"""Benchmark: cells·timesteps/second of the full projection step.

Runs the uniform-grid solver at the north-star size (8192^2 f32, the
driver target in BASELINE.json: >= 1 step/s on v5e) from an initial
state with O(1) velocity and real divergence content, so the Poisson
solve iterates at the reference's production tolerances every step —
round 1's bench measured a solver at 0 iterations (VERDICT.md Weak #1)
because Taylor-Green keeps the undivided residual under the absolute
tolerance at large N.

Reports, besides cells*steps/s: Poisson iters/step and ms/iter (timed
separately on the captured RHS), advection ms/step, and model-based MFU
and HBM-bandwidth utilization from an explicit per-cell flop/byte count
(the step is memory-bound stencil work — HBM utilization is the number
that says how close to the roof we are; MFU is reported for
completeness).

Prints ONE JSON line (driver contract). BENCH_SIZE/BENCH_STEPS/
BENCH_WARMUP env vars override the defaults.
"""

from __future__ import annotations

import json
import os
import sys
import time

# the kernel_curve's sharded-tier arm needs >= 2 devices; on CPU-only
# boxes force 2 virtual host devices BEFORE jax initializes. The flag
# only affects the host (CPU) platform, so a real accelerator's device
# count wins; an existing forcing (e.g. the test harness's 8) is kept.
if "--xla_force_host_platform_device_count" not in os.environ.get(
        "XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=2").strip()

import jax
import jax.numpy as jnp
import numpy as np


BASELINE_CELLS_STEPS_PER_SEC = 8192.0 * 8192.0  # 1 step/s @ 8192^2 target

# v5e single chip, public specs: 197 TFLOPS bf16 -> ~1/2 for f32 MXU work,
# and 819 GB/s HBM. The stencil path is VPU/HBM work, so HBM is the roof.
PEAK_F32_TFLOPS = 98.5
PEAK_HBM_GBPS = 819.0

# --- per-cell work model (counted from cup2d_tpu/ops/stencil.py) ---------
# advect_diffuse_rhs per component per direction: WENO5 plus+minus
# (~2x45 flops incl. smoothness indicators) + upwind select + diffusion
# 5-point (~10) -> ~110; x2 directions x2 components x2 Heun stages ~ 880
# plus penalization/projection/divergence epilogue ~ 60.
FLOPS_STEP_PER_CELL = 940.0
# BiCGSTAB iteration: 2 laplacians (6) + 2 block-precond GEMV rows
# (2*BS^2 MAC/cell = 256) + ~8 axpy/dot sweeps (~16) -> ~290.
FLOPS_ITER_PER_CELL = 290.0
# bytes: advection reads vel(2f) x2 stages + writes, penalization, rhs,
# projection: ~22 f32 field sweeps; Krylov iteration touches ~12 arrays.
BYTES_STEP_PER_CELL = 22 * 4.0
BYTES_ITER_PER_CELL = 12 * 4.0
# one Heun SUBSTAGE (the kernel_curve unit, PR 9): half the advection
# work above (~440/cell: WENO5 x2 directions x2 components ~440) plus
# the 3-flop state update — documented estimate, shared by every tier
# so the MFU column is comparable across them.
FLOPS_SUBSTAGE_PER_CELL = 443.0


def bench_state(grid):
    """O(1) velocity with genuine multi-scale divergence: a shear-layer
    pair, a mid-scale mode, and a non-solenoidal mode at a FIXED 64
    cells/wavelength. The last one makes the Poisson load
    resolution-invariant (undivided divergence ~ A^2 * h * k stays
    constant when k grows with N) — with physical-wavenumber-only
    content the absolute 1e-3 tolerance becomes trivially satisfied at
    large N and the bench degenerates to advection-only (round 1's
    failure, VERDICT.md Weak #1). Free-slip-compatible normal components
    (sin -> 0 at walls) keep the box BCs consistent."""
    x, y = grid.cell_centers()
    lx, ly = grid.cfg.extents
    xs, ys = np.pi * x / lx, np.pi * y / ly
    m = max(grid.nx // 64, 32)
    u = (np.sin(xs) * np.cos(ys)
         + 0.25 * np.sin(8 * xs) * np.cos(8 * ys)
         + 0.3 * np.sin(m * xs) * np.sin(m * ys))
    v = (-np.cos(xs) * np.sin(ys)
         + 0.25 * np.sin(16 * ys) * np.sin(16 * xs)
         + 0.3 * np.sin(m * ys) * np.sin(m * xs))
    vel = jnp.asarray(np.stack([u, v]), dtype=grid.dtype)
    return grid.zero_state()._replace(vel=vel)


def _fence(x) -> float:
    """Force completion of x's producer chain via a host scalar read.
    jax.block_until_ready is NOT a reliable completion fence on remote
    device tunnels (measured: returns in 0.02 ms while the queued
    computation still runs); a data-dependent scalar transfer is."""
    return float(x.reshape(-1)[0])


def _latency_floor(probe) -> float:
    """Per-readback host<->device round-trip cost, to subtract from
    fenced wall times (measured ~100 ms on the tunneled TPU)."""
    _fence(probe)
    ts = []
    for _ in range(3):
        t0 = time.perf_counter()
        _fence(probe)
        ts.append(time.perf_counter() - t0)
    return min(ts)


def run_size(size: int, n_warmup: int, n_steps: int):
    from cup2d_tpu.config import SimConfig
    from cup2d_tpu.uniform import UniformGrid

    level = int(np.log2(size // 8))
    cfg = SimConfig(bpdx=1, bpdy=1, level_max=1, level_start=0,
                    extent=1.0, nu=4e-5, cfl=0.5, dtype="float32")
    grid = UniformGrid(cfg, level=level)
    state = bench_state(grid)

    # obstacle_terms=False: the bench case has no shapes; the step
    # statically drops the identically-zero penalization/udef terms
    # (see UniformGrid.step; the obstacle-free driver does the same)
    import functools
    step = jax.jit(
        functools.partial(grid.step, obstacle_terms=False),
        donate_argnums=(0,), static_argnames=("exact_poisson",))
    dt = jnp.asarray(0.5 * grid.h, grid.dtype)  # CFL 0.5 at umax ~ 1

    for _ in range(n_warmup):
        state, diag = step(state, dt)
    _fence(state.vel)
    lat = _latency_floor(dt)

    # full-step throughput; one fence (its latency subtracted), no other
    # host syncs inside the timed region. The window auto-extends until
    # it dwarfs the fence latency — a window at or below the latency
    # floor would otherwise report pure jitter as throughput.
    latency_bound = False
    while True:
        diags = []
        t0 = time.perf_counter()
        for _ in range(n_steps):
            state, diag = step(state, dt)
            diags.append(diag)
        _fence(state.vel)
        t1 = time.perf_counter()
        if (t1 - t0) >= 5.0 * lat or n_steps >= 640:
            latency_bound = (t1 - t0) < 5.0 * lat
            break
        n_steps *= 4
    wall = max(t1 - t0 - lat, 1e-9)
    # ONE batched pull of every step's whole diag dict, outside the
    # timed window (the per-scalar int() pulls this replaces cost one
    # round trip each)
    diags = jax.device_get(diags)
    iters = [int(d["poisson_iters"]) for d in diags]
    iters_total = sum(iters)

    # advection stage alone (the non-Poisson bulk of the step); extra
    # reps at small sizes so the fence latency (~100 ms on the tunneled
    # TPU) stays small against the measured window
    adv = jax.jit(grid.advect_heun)
    _fence(adv(state.vel, dt))
    n_adv = max(3, n_steps, (2048 // max(size // 8, 1)) * n_steps)
    t2 = time.perf_counter()
    out = state.vel
    for _ in range(n_adv):
        out = adv(out, dt)
    _fence(out)
    advect_ms = max(
        (time.perf_counter() - t2 - lat) / n_adv * 1e3, 0.0)

    # Poisson stage alone, on a HARD solve: the t=0 RHS (cold pressure,
    # full divergence content) at a tight relative tolerance, so ms/iter
    # averages over a real iteration train even when the production
    # steps above coast at 0-1 iterations thanks to the MG preconditioner
    from cup2d_tpu.ops.stencil import divergence_rhs
    from cup2d_tpu.poisson import bicgstab
    from cup2d_tpu.uniform import pad_vector
    state0 = bench_state(grid)
    b = divergence_rhs(pad_vector(state0.vel, 1),
                       pad_vector(state0.udef, 1),
                       state0.chi, 1, grid.h, dt)
    psolve = jax.jit(lambda bb: bicgstab(
        grid.laplacian, bb, M=grid.mg, tol=0.0, tol_rel=1e-4,
        max_iter=100))
    res = psolve(b)
    _fence(res.x)
    t3 = time.perf_counter()
    res = psolve(b)
    _fence(res.x)
    psolve_wall = max(time.perf_counter() - t3 - lat, 0.0)
    psolve_iters = int(res.iters)
    poisson_ms_per_iter = psolve_wall / max(psolve_iters, 1) * 1e3

    # the timed steps as run-telemetry records in the SAME schema a
    # production run streams to metrics.jsonl (profiling.METRICS_KEYS)
    # — BENCH_*.json and run telemetry are one trajectory. t/step are
    # synthetic (the bench holds dt fixed and restarts from warmup);
    # wall_ms is the per-step mean of the fenced window.
    from cup2d_tpu.profiling import MetricsRecorder, summarize_metrics
    rec = MetricsRecorder(sink=None)
    step_ms_mean = wall / n_steps * 1e3
    records = [
        rec.record_step(step=i + 1, t=float(dt) * (i + 1),
                        dt=float(dt), diag=d, wall_ms=step_ms_mean)
        for i, d in enumerate(diags)]
    telemetry = {
        "summary": summarize_metrics(records),
        "last_records": records[-8:],
    }

    cells = grid.nx * grid.ny
    cells_steps_per_sec = cells * n_steps / wall
    iters_per_step = iters_total / n_steps
    flops = cells * (FLOPS_STEP_PER_CELL * n_steps
                     + FLOPS_ITER_PER_CELL * iters_total)
    bytes_ = cells * (BYTES_STEP_PER_CELL * n_steps
                      + BYTES_ITER_PER_CELL * iters_total)
    return {
        "telemetry": telemetry,
        "grid": f"{size}x{size}",
        "cells_steps_per_sec": round(cells_steps_per_sec, 1),
        "steps": n_steps,
        "wall_s": round(wall, 3),
        "step_ms": round(wall / n_steps * 1e3, 3),
        "iters_per_step": round(iters_per_step, 2),
        "poisson_iters_total": iters_total,
        "poisson_ms_per_iter": round(poisson_ms_per_iter, 3),
        "poisson_solve_iters": psolve_iters,
        "advect_ms_per_step": round(advect_ms, 3),
        "mfu_pct": round(flops / wall / (PEAK_F32_TFLOPS * 1e12) * 100, 3),
        "hbm_util_pct": round(bytes_ / wall / (PEAK_HBM_GBPS * 1e9) * 100, 1),
        "latency_bound": latency_bound,
        **_profiled_step(step, state, dt, cells),
    }


def _profiled_step(step, state, dt, cells: int) -> dict:
    """Profiler-measured step time (VERDICT r2 #3: measured, not
    modeled): capture a short jax.profiler trace of the warmed step and
    read the XLA-module device time from the xplane dump. The HBM
    figure divides the IDEAL traffic floor (the same per-cell byte
    model) by the MEASURED device time — i.e. it is an upper bound on
    achievable utilization; the gap to 100% is arithmetic (VPU), op
    overhead, or redundant traffic. Skipped silently where the profiler
    or its protobufs are unavailable."""
    import glob
    import shutil
    import tempfile
    d = tempfile.mkdtemp(prefix="cup2d_bench_trace_")
    try:
        reps = 8
        with jax.profiler.trace(d):
            s = state
            for _ in range(reps):
                s, _diag = step(s, dt)
            _fence(s.vel)
        from tensorflow.tsl.profiler.protobuf import xplane_pb2
        paths = glob.glob(os.path.join(
            d, "plugins", "profile", "*", "*.xplane.pb"))
        xs = xplane_pb2.XSpace()
        xs.ParseFromString(open(paths[0], "rb").read())
        plane = next(p for p in xs.planes
                     if p.name.startswith("/device:"))
        durs = sorted(ev.duration_ps for line in plane.lines
                      if line.name == "XLA Modules"
                      for ev in line.events)
        if not durs:
            return {}
        # median execution: per-rep Poisson iteration counts vary
        dev_s = durs[len(durs) // 2] / 1e12
        mean_s = sum(durs) / len(durs) / 1e12
        floor_bytes = cells * BYTES_STEP_PER_CELL
        return {
            "device_step_ms_profiled": round(dev_s * 1e3, 3),
            "device_step_ms_profiled_mean": round(mean_s * 1e3, 3),
            "device_cells_steps_per_sec": round(cells / mean_s, 1),
            "hbm_util_profiled_pct": round(
                floor_bytes / dev_s / (PEAK_HBM_GBPS * 1e9) * 100, 1),
        }
    except Exception:
        return {}
    finally:
        shutil.rmtree(d, ignore_errors=True)


def run_adaptive(n_warm_steps: int = 40, chain: int = 15):
    """The CANONICAL adaptive case as a first-class bench number
    (VERDICT r4 #2): the reference's own run.sh two-fish configuration
    (levelMax 8, finest cap 2048x1024 — /root/reference/run.sh:1-22),
    warmed through real driver steps + regrids, then timed as chained
    frozen-input megasteps with a profiler trace (device time, not
    tunnel wall). Reports active-cell throughput AND the
    finest-equivalent throughput (steps/s x finest-cap cells — the
    number that says what the AMR compression buys on the case the
    reference exists for)."""
    import glob
    import shutil
    import tempfile

    from validation.canonical import build_canonical_sim

    sim = build_canonical_sim(levelmax=8)
    cfg = sim.cfg
    t0 = time.perf_counter()
    sim.initialize()
    init_s = time.perf_counter() - t0
    for _ in range(n_warm_steps):
        if sim.step_count <= 10 or sim.step_count % cfg.adapt_steps == 0:
            sim.adapt()
        sim.step_once()
    sim._refresh()
    ordf = sim._ordered_state()
    inputs = sim._shape_inputs()
    f = sim.forest
    prescribed = jnp.asarray(
        [[s.u, s.v, s.omega] for s in sim.shapes], dtype=f.dtype)
    dt = jnp.asarray(sim._next_dt or sim.compute_dt(), f.dtype)
    hmin = jnp.asarray(
        cfg.h_at(int(f.level[sim._order].max())), f.dtype)

    def mega(vel, pres):
        return sim._mega_jit(
            vel, pres, inputs, prescribed, dt, hmin,
            sim._h, sim._hsq_flat, sim._maskv, sim._xc, sim._yc,
            sim._tables["vec3"], sim._tables["vec1"],
            sim._tables["sca1"], sim._tables["pois"],
            sim._tables.get("vec4t"), sim._tables.get("sca4t"),
            sim._corr, sim._use_coarse(False),
            exact_poisson=False, with_forces=False)

    vel, pres = ordf["vel"], ordf["pres"]
    out = mega(vel, pres)
    _fence(out[0])
    lat = _latency_floor(dt)
    best = None
    for _ in range(3):
        v, p = vel, pres
        t1 = time.perf_counter()
        for _ in range(chain):
            v, p = mega(v, p)[:2]
        _fence(v)
        w = time.perf_counter() - t1 - lat
        best = w if best is None else min(best, w)
    wall_ms = best / chain * 1e3

    dev_ms = None
    d = tempfile.mkdtemp(prefix="cup2d_bench_adapt_")
    try:
        with jax.profiler.trace(d):
            v, p = vel, pres
            for _ in range(chain):
                v, p = mega(v, p)[:2]
            _fence(v)
        from tensorflow.tsl.profiler.protobuf import xplane_pb2
        paths = glob.glob(os.path.join(
            d, "plugins", "profile", "*", "*.xplane.pb"))
        xs = xplane_pb2.XSpace()
        xs.ParseFromString(open(paths[0], "rb").read())
        plane = next(p_ for p_ in xs.planes
                     if p_.name.startswith("/device:"))
        mod_ps = sum(ev.duration_ps for line in plane.lines
                     if line.name == "XLA Modules" for ev in line.events)
        if mod_ps:
            dev_ms = mod_ps / 1e9 / chain
    except Exception:
        pass
    finally:
        shutil.rmtree(d, ignore_errors=True)

    # the one megastep pull carries the production iteration count
    scal = jax.device_get(mega(vel, pres)[3])
    diag = scal[5]
    piters = int(diag["poisson_iters"])
    n_blocks = len(f.blocks)
    cells = n_blocks * cfg.bs * cfg.bs
    finest_cells = (cfg.bpdx * cfg.bs << (cfg.level_max - 1)) \
        * (cfg.bpdy * cfg.bs << (cfg.level_max - 1))
    ms = dev_ms if dev_ms is not None else wall_ms
    steps_per_sec = 1e3 / ms
    return {
        "case": "run.sh two-fish levelMax=8 (canonical adaptive)",
        "device_derived": dev_ms is not None,
        "n_blocks": n_blocks,
        "n_pad": int(sim._npad_hwm),
        "init_s": round(init_s, 1),
        "device_ms_per_megastep": (
            round(dev_ms, 3) if dev_ms is not None else None),
        "wall_ms_per_megastep": round(wall_ms, 3),
        "poisson_iters_per_step": piters,
        # UPPER BOUND: whole megastep / iterations (at the canonical
        # case's 1-5 iters/step the solve is a fraction of the step;
        # the uniform hard-solve figure above isolates a real train)
        "poisson_ms_per_iter_upper": (
            round(ms / piters, 3) if piters else None),
        "steps_per_sec_device": round(steps_per_sec, 2),
        "cells_steps_per_sec_active": round(cells * steps_per_sec, 1),
        "cells_steps_per_sec_finest_equiv": round(
            finest_cells * steps_per_sec, 1),
        "finest_cap_cells": finest_cells,
    }


def run_fleet(size: int, members_list, n_steps: int = 40,
              n_warmup: int = 3):
    """Fleet-batching throughput curve (fleet.FleetSim): member-steps/s
    of the DRIVER loop (one fused dispatch + one batched diag pull per
    step — the product-level stepping cost) at B = 1, 2, 4, 8 on one
    small grid. Small grids are dispatch-bound — the regime the fleet
    exists for: stepping B cases in one dispatch amortizes the fixed
    per-step dispatch+pull overhead over B members, so member-steps/s
    climbs with B while a single case leaves the device idle. Each
    member is seeded at its own Taylor-Green amplitude (per-member dt,
    no lockstep); the warmup runs the executable hot and the window is
    fenced once with the readback latency subtracted (same methodology
    as run_size)."""
    from cup2d_tpu.config import SimConfig
    from cup2d_tpu.fleet import FleetSim, taylor_green_fleet

    level = int(np.log2(size // 8))
    cfg = SimConfig(bpdx=1, bpdy=1, level_max=1, level_start=0,
                    extent=1.0, nu=4e-5, cfl=0.5, dtype="float32")
    points = []
    for b in members_list:
        sim = FleetSim(cfg, level=level, members=b)
        sim.state = taylor_green_fleet(sim.grid, b)
        sim.step_count = 20    # production regime (skip the exact-mode
        #                        startup solves — a second executable)
        for _ in range(n_warmup):
            sim.step_once()
        _fence(sim.state.vel)
        lat = _latency_floor(sim.state.pres)
        t0 = time.perf_counter()
        for _ in range(n_steps):
            sim.step_once()
        _fence(sim.state.vel)
        wall = max(time.perf_counter() - t0 - lat, 1e-9)
        points.append({
            "members": b,
            "step_ms": round(wall / n_steps * 1e3, 3),
            "member_steps_per_s": round(b * n_steps / wall, 1),
        })
    # the headline: dispatch amortization at the largest B, against
    # the ACTUAL B=1 point (a BENCH_FLEET spec without 1 must not
    # mislabel a B=2 baseline as B=1 — the field is null then)
    b1 = next((pt for pt in points if pt["members"] == 1), None)
    return {
        "grid": f"{size}x{size}",
        "steps": n_steps,
        "points": points,
        "speedup_vs_b1": (round(
            points[-1]["member_steps_per_s"]
            / b1["member_steps_per_s"], 2) if b1 else None),
        "note": ("member-steps/s of the sync driver loop (one fused "
                 "dispatch + one batched diag pull per step); the "
                 "curve IS the dispatch-amortization win — per-member "
                 "compute is B-invariant"),
    }


def run_fleet_serving(size: int, members: int = 8, n_steps: int = 60,
                      n_warmup: int = 3):
    """Continuous-batching serving curve (fleet.FleetServer, PR 11):
    occupancy-weighted member-steps/s of a CHURN workload — sessions
    with staggered horizons retiring and admitting INSIDE the timed
    window — against the static fixed-B FleetSim loop of run_fleet on
    the same pool size. The ratio is the cost of the serving machinery
    (mask-frozen dead lanes, device-indexed slot scatter on admit,
    host-side queue/retire bookkeeping); the zero-recompile contract is
    measured, not assumed: the warmup exercises every serving
    executable (masked step, admit scatter, retire re-zero, fresh-dt
    reduce), then the jax.monitoring compile counter must stay FLAT
    through the whole churn window (``recompiles_after_warmup`` — the
    CI smoke pins it at 0)."""
    from cup2d_tpu.config import SimConfig
    from cup2d_tpu.fleet import (FleetRequest, FleetServer, FleetSim,
                                 taylor_green_fleet)
    from cup2d_tpu.profiling import HostCounters
    from cup2d_tpu.tracing import ServingLatency
    from cup2d_tpu.uniform import FlowState

    level = int(np.log2(size // 8))
    cfg = SimConfig(bpdx=1, bpdy=1, level_max=1, level_start=0,
                    extent=1.0, nu=4e-5, cfl=0.5, dtype="float32")

    # --- static baseline: the fixed-B fleet loop, full pool, no churn
    sim = FleetSim(cfg, level=level, members=members)
    sim.state = taylor_green_fleet(sim.grid, members)
    sim.step_count = 20    # production regime, as in run_fleet
    for _ in range(n_warmup):
        sim.step_once()
    _fence(sim.state.vel)
    lat = _latency_floor(sim.state.pres)
    t0 = time.perf_counter()
    for _ in range(n_steps):
        sim.step_once()
    _fence(sim.state.vel)
    wall = max(time.perf_counter() - t0 - lat, 1e-9)
    static_msps = members * n_steps / wall

    # --- serving pool: same B, sessions flowing through the queue.
    # No session_dir/clients_dir: the timed window measures stepping +
    # slot churn, not checkpoint I/O (that cost is per-retire and
    # reported by the production run's phase timers instead).
    sim2 = FleetSim(cfg, level=level, members=members)
    sim2.step_count = 20
    # latency histograms (tracing.ServingLatency) ride the server's
    # existing submit/admit/step boundaries — pure host clocks, so the
    # instrument itself costs nothing the timed window can see
    server = FleetServer(sim2, latency=ServingLatency())
    ens = taylor_green_fleet(sim2.grid, members)   # session state bank
    n_req = 0
    queued_msteps = 0

    def submit(horizon_steps: int):
        # amplitude ladder member -> Taylor-Green umax = amp, so the
        # session's CFL dt ~ cfl*h/amp and a t_end of horizon_steps
        # such dts retires it after ~horizon_steps steps (the horizon
        # stagger below is what makes the churn continuous rather than
        # one synchronized retirement wave). queued_msteps accounts the
        # demand in MEMBER-STEPS — dt-invariant, so the window
        # provisioning below holds across the 5x dt spread of the
        # ladder
        nonlocal n_req, queued_msteps
        i = n_req % members
        amp = 0.8 ** i
        dt_est = cfg.cfl * sim2.grid.h / amp
        server.submit(FleetRequest(
            client_id=f"b{n_req:04d}",
            state=FlowState(*(a[i] for a in ens)),
            t_end=horizon_steps * dt_est))
        n_req += 1
        queued_msteps += horizon_steps

    # warmup: every serving executable compiles here — fill the pool,
    # step under the (array-form) mask, retire the short-horizon
    # sessions, admit replacements through the slot scatter
    counters = HostCounters().install()
    try:
        for _ in range(members):
            submit(2)
        for _ in range(max(n_warmup, 6)):
            submit(2)
            server.step()

        # the churn window: enough staggered-horizon demand queued that
        # the pool never idles, retirements interleaving throughout
        # (1.3x over-provision absorbs dt drift as the vortices decay;
        # leftover sessions just stay queued). Sessions average about
        # half the window — roughly one full pool turnover of churn
        # inside the timed region
        span = max(n_steps // 2, 2)
        queued_msteps = 0
        while queued_msteps < 1.3 * n_steps * members:
            submit(span + (n_req % 7))
        # roll the pool ONTO window sessions before the clock starts:
        # the warmup's short-horizon leftovers retire here, outside the
        # timed region, so the window's churn is the staggered-horizon
        # workload itself and not a warmup artifact wave
        for _ in range(4):
            server.step()
        _fence(sim2.state.vel)
        compiles_warm = counters.jit_compiles
        member_steps = 0
        t1 = time.perf_counter()
        for _ in range(n_steps):
            server.step()
            # occupants DURING the fused step (active[] is already
            # post-retire here — a member retiring at the end of this
            # very cycle still did a full step of work)
            member_steps += sum(c is not None
                                for c in server.step_clients)
        _fence(sim2.state.vel)
        wall2 = max(time.perf_counter() - t1 - lat, 1e-9)
        recompiles = counters.jit_compiles - compiles_warm
    finally:
        counters.uninstall()
    serving_msps = member_steps / wall2
    return {
        "grid": f"{size}x{size}",
        "members": members,
        "steps": n_steps,
        "static_member_steps_per_s": round(static_msps, 1),
        "serving_member_steps_per_s": round(serving_msps, 1),
        "throughput_ratio": round(serving_msps / static_msps, 3),
        "occupancy_mean": round(
            member_steps / (n_steps * members), 3),
        "admitted": server.admitted,
        "retired": server.retired,
        "evicted": server.evicted,
        "recompiles_after_warmup": recompiles,
        # pool-wide latency distributions of the whole churn run
        # (warmup included — queue_wait/admit percentiles need the
        # admission waves, not just the steady window)
        "serving_latency": server.latency.report()["pool"],
        "note": ("serving member-steps/s is occupancy-weighted (sum "
                 "of live members over the churn window / wall); the "
                 "ratio vs the static fixed-B loop prices the serving "
                 "machinery, and recompiles_after_warmup pins the "
                 "zero-steady-state-recompile contract; "
                 "serving_latency is the pool-wide queue-wait/"
                 "admit-to-first-step/per-step histogram report "
                 "(log2 buckets, tracing.LatencyHistogram)"),
    }


def run_mirror_overhead(size: int, n_iters: int = 30, n_warmup: int = 3):
    """Host-redundant mirror tier overhead (PR 17): enqueue-side cost
    of capturing a device snapshot WITH the neighbor mirror (one
    shard_map ppermute + on-device per-block checksums, io.py) vs the
    plain snapshot — the per-capture tax the ``-mirror`` flag adds to
    a guarded elastic run. Runs on the full local device set grouped
    into 2 "hosts" (the minimal ring); both loops are fenced with the
    readback latency subtracted (run_size methodology). The number to
    watch is mirror_overhead_ms staying a small fraction of a step —
    the mirror is enqueue-only and overlaps the next dispatch, so the
    exposed cost in a real run is lower still."""
    from cup2d_tpu.config import SimConfig
    from cup2d_tpu.io import (mirror_nbytes, mirror_snapshot,
                              snapshot_nbytes, snapshot_state_device)
    from cup2d_tpu.parallel.mesh import ShardedUniformSim, make_mesh
    from cup2d_tpu.uniform import taylor_green_state

    level = int(np.log2(size // 8))
    cfg = SimConfig(bpdx=1, bpdy=1, level_max=1, level_start=0,
                    extent=1.0, nu=4e-5, cfl=0.5, dtype="float32")
    mesh = make_mesh()
    n_hosts = 2
    sim = ShardedUniformSim(cfg, mesh, level=level)
    sim.set_state(taylor_green_state(sim.grid))
    for _ in range(n_warmup):        # compile ppermute + checksum jits
        snap = snapshot_state_device(sim)
        m = mirror_snapshot(snap, mesh, n_hosts)
        if m is None:
            raise RuntimeError("mirror_snapshot refused the uniform "
                               "payload — bench rig mismatch")
    _fence(m.payload["vel"])
    lat = _latency_floor(sim.state.pres)
    t0 = time.perf_counter()
    for _ in range(n_iters):
        snap = snapshot_state_device(sim)
    _fence(snap.payload["vel"])
    plain = max(time.perf_counter() - t0 - lat, 1e-9)
    t0 = time.perf_counter()
    for _ in range(n_iters):
        snap = snapshot_state_device(sim)
        m = mirror_snapshot(snap, mesh, n_hosts)
    _fence(m.payload["vel"])
    mirrored = max(time.perf_counter() - t0 - lat, 1e-9)
    snap = snap._replace(mirror=m)
    return {
        "grid": f"{size}x{size}",
        "devices": mesh.devices.size,
        "hosts": n_hosts,
        "iters": n_iters,
        "snap_ms": round(plain / n_iters * 1e3, 3),
        "snap_mirror_ms": round(mirrored / n_iters * 1e3, 3),
        "mirror_overhead_ms": round(
            max(mirrored - plain, 0.0) / n_iters * 1e3, 3),
        "snapshot_bytes": int(snapshot_nbytes(snap)),
        "mirror_bytes": int(mirror_nbytes(snap)),
        "note": ("per-capture cost of the neighbor mirror (ppermute + "
                 "device checksums) over the plain device snapshot; "
                 "enqueue-side — in a guarded run the collective "
                 "overlaps the next dispatch"),
    }


def run_poisson_curve(size: int, tol_rel: float = 1e-3,
                      n_rep: int = 3):
    """Poisson solver micro-curve (PR 6): iterations-to-tolerance and
    ms/solve PER SOLVE PATH on one cold RHS at a FIXED relative
    residual target, so the solver trajectory is tracked across rounds
    in the BENCH JSON instead of living only in ad-hoc probes.

    Paths: the reference's block-Jacobi-preconditioned Krylov
    (bicgstab_jacobi — the AMR smoother's scaling baseline), the
    production uniform default (bicgstab_mg), and the FAS multigrid
    full solver in V-cycle and FMG-opening form (fas_v / fas_f,
    poisson.mg_solve — the CUP2D_POIS=fas path). Iteration counts are
    platform-independent; ms figures carry the usual host-fence
    methodology (latency floor subtracted).

    The 1e-3 target is the deepest one every path can HONESTLY reach
    in f32: mg_solve converges on the true residual b - A(x), whose
    f32 evaluation floor on this case is ~2e-4 relative (eps * |x|
    amplified through the undivided Laplacian — measured, f64 cycles
    sail through to any target), while BiCGSTAB's recursive residual
    drifts optimistically below that floor. Comparing at 1e-4 would
    pit an honest residual against a drifted one.

    Memory-tiered arms (ISSUE 19) + the kernel_curve roofline fields:
    fas_v+strip runs the same f32 hierarchy with the sweep chains
    fused to one strip pipeline each; fas_v+bf16leg additionally
    stores the cycle legs bf16 (mg_solve's outer loop keeps the f32
    true residual, so all fas arms converge by the SAME Linf
    criterion). Each arm carries modeled f32-equivalent HBM passes
    per iteration (1 pass = one size^2 f32 field), the modeled bytes,
    and the derived HBM-util% / MFU% against the v5e peaks — the
    kernel_curve r04-anchor methodology.

    Bytes model, per V(2,2) cycle (1 Jacobi sweep = read e + read r +
    write e = 3 passes, 2 when the first sweep starts from zero; one
    level visit = pre-chain + residual 3 + restrict 1.25 + prolong
    2.25 + post-chain; the level ladder sums to 4/3 of the finest;
    mg_solve's outer true-residual + correction add ~4 f32 passes):
      fas_v / fas_f   : (5 + 3 + 1.25 + 2.25 + 6) * 4/3 + 4 ~ 27.3
      fas_v+strip     : chains at 1 read (e, r) + 1 write -> level
                        (2 + 3 + 1.25 + 2.25 + 3) * 4/3 + 4 ~ 19.3
      fas_v+bf16leg   : same strip passes at bf16 width on the legs
                        (x 0.5), f32 outer -> 15.33 * 0.5 + 4 ~ 11.7
      bicgstab_jacobi : per iter, 2 A (3 each) + 2 block-precond
                        (2 each) + ~12 Krylov vector passes ~ 22
      bicgstab_mg     : block-precond -> one bf16 V(2,2) cycle
                        (23.3 * 0.5 each) + 2 A + vectors ~ 41.3
    The flops model is equally coarse (laps/cycle x ~7 flops/cell +
    sweep updates) — the fields track cross-round MOVEMENT, and the
    pinned acceptance is the fas_v : fas_v+bf16leg byte ratio >= 2 at
    iters within +1. util percentages are meaningless in
    interpret_mode (flagged), exactly like run_kernel_curve.

    Direct arms (ISSUE 20): fftd_periodic (doubly-periodic box, pure
    spectral divide) and fftd_channel (periodic-x/no-slip-y, per-mode
    Thomas systems) time poisson.fft_diag_solve on their own periodic
    grids + cold mean-free RHS at the same relative criterion —
    iters == 1 by contract, and the round-14 acceptance pins
    fftd_periodic ms_per_solve below the best fas arm's."""
    from cup2d_tpu.config import SimConfig
    from cup2d_tpu.ops.stencil import divergence_rhs
    from cup2d_tpu.poisson import (MultigridPreconditioner, bicgstab,
                                   mg_solve)
    from cup2d_tpu.uniform import UniformGrid, pad_vector

    level = int(np.log2(size // 8))
    cfg = SimConfig(bpdx=1, bpdy=1, level_max=1, level_start=0,
                    extent=1.0, nu=4e-5, cfl=0.5, dtype="float32")
    grid = UniformGrid(cfg, level=level)
    state0 = bench_state(grid)
    dt = jnp.asarray(0.5 * grid.h, grid.dtype)
    b = divergence_rhs(pad_vector(state0.vel, 1),
                       pad_vector(state0.udef, 1),
                       state0.chi, 1, grid.h, dt)

    # EVERY arm's hierarchy is built EXPLICITLY with its own
    # cycle_dtype/leg_dtype/smoother rather than reusing grid.mg: that
    # one's tier follows the CUP2D_POIS/CUP2D_PREC/CUP2D_PALLAS
    # latches, so a bench run under any env latch would silently time
    # a mislabeled arm and break cross-round curve comparison (the
    # PR-6 contamination fix, extended to the ISSUE-19 tiers).
    from cup2d_tpu.ops.pallas_kernels import _on_accel
    mgp = MultigridPreconditioner(grid.ny, grid.nx, grid.dtype)
    mgf = MultigridPreconditioner(grid.ny, grid.nx, grid.dtype,
                                  cycle_dtype=grid.dtype)
    mgs = MultigridPreconditioner(grid.ny, grid.nx, grid.dtype,
                                  cycle_dtype=grid.dtype,
                                  smoother="strip")
    mgb = MultigridPreconditioner(grid.ny, grid.nx, grid.dtype,
                                  cycle_dtype=grid.dtype,
                                  leg_dtype=jnp.bfloat16,
                                  smoother="strip")
    solvers = {
        "bicgstab_jacobi": lambda bb: bicgstab(
            grid.laplacian, bb, M=grid.precond, tol=0.0,
            tol_rel=tol_rel, max_iter=2000),
        "bicgstab_mg": lambda bb: bicgstab(
            grid.laplacian, bb, M=mgp, tol=0.0,
            tol_rel=tol_rel, max_iter=200),
        "fas_v": lambda bb: mg_solve(
            grid.laplacian, bb, mgf, tol=0.0,
            tol_rel=tol_rel, max_cycles=200),
        "fas_f": lambda bb: mg_solve(
            grid.laplacian, bb, mgf, tol=0.0,
            tol_rel=tol_rel, max_cycles=200, fmg=True),
        "fas_v+strip": lambda bb: mg_solve(
            grid.laplacian, bb, mgs, tol=0.0,
            tol_rel=tol_rel, max_cycles=200),
        "fas_v+bf16leg": lambda bb: mg_solve(
            grid.laplacian, bb, mgb, tol=0.0,
            tol_rel=tol_rel, max_cycles=200),
    }
    # modeled f32-equivalent HBM passes and flops per ITERATION (see
    # docstring; 1 pass = one size^2 f32 field, flops/cell coarse)
    hbm_model = {
        "bicgstab_jacobi": (22.0, 24.0),
        "bicgstab_mg": (41.3, 75.0),
        "fas_v": (27.3, 60.0),
        "fas_f": (27.3, 60.0),
        "fas_v+strip": (19.3, 60.0),
        "fas_v+bf16leg": (11.7, 60.0),
    }
    tier_label = {
        "fas_v": mgf.smoother_tier, "fas_f": mgf.smoother_tier,
        "fas_v+strip": mgs.smoother_tier,
        "fas_v+bf16leg": mgb.smoother_tier,
    }
    fb = float(size * size) * 4.0
    cells = float(size * size)
    lat = None
    paths = {}
    norm0 = float(jnp.max(jnp.abs(b)))
    for name, solve in solvers.items():
        js = jax.jit(solve)
        res = js(b)
        _fence(res.x)
        if lat is None:
            lat = _latency_floor(dt)
        t0 = time.perf_counter()
        for _ in range(n_rep):
            res = js(b)
            _fence(res.x)
        wall = max((time.perf_counter() - t0 - n_rep * lat) / n_rep,
                   1e-9)
        iters = int(res.iters)
        ms_iter = wall / max(iters, 1) * 1e3
        passes, flops_cell = hbm_model[name]
        sec_iter = ms_iter * 1e-3
        paths[name] = {
            "iters": iters,
            "ms_per_solve": round(wall * 1e3, 3),
            "ms_per_iter": round(ms_iter, 3),
            "residual_rel": float(res.residual) / norm0,
            "converged": bool(res.converged),
            "hbm_passes": passes,
            "hbm_bytes": passes * fb,
            "hbm_util_pct": round(
                passes * fb / sec_iter / (PEAK_HBM_GBPS * 1e9)
                * 100.0, 3),
            "mfu_pct": round(
                flops_cell * cells / sec_iter
                / (PEAK_F32_TFLOPS * 1e12) * 100.0, 3),
        }
        if name in tier_label:
            paths[name]["smoother_tier"] = tier_label[name]

    # FFT-diagonalized direct arms (ISSUE 20): each gets its OWN
    # periodic grid and cold RHS — the wall-table RHS above belongs to
    # a different operator — under the SAME fence methodology and
    # relative Linf criterion. The plan is constructed EXPLICITLY
    # (not via the CUP2D_POIS latch), the PR-6 contamination rule.
    # iters == 1 is the direct-solve contract; the acceptance compares
    # ms_per_solve against the best fas arm above. The bytes model is
    # as coarse as the others': rfft2+divide+irfft2 ~ 2 passes per 1-D
    # transform stage + the pointwise stage; the tridiag arm swaps one
    # transform pair for the two first-order Thomas scans.
    from cup2d_tpu.cases import periodic_channel_table, periodic_table
    from cup2d_tpu.poisson import FFTDiagPlan, fft_diag_solve

    for name, table in (("fftd_periodic", periodic_table()),
                        ("fftd_channel", periodic_channel_table())):
        gp = UniformGrid(cfg, level=level, bc=table)
        sp = bench_state(gp)
        bp = gp.poisson_rhs(sp.vel, None, sp.udef, dt)
        bp = bp - jnp.mean(bp)       # cold mean-free RHS (the
        #                              projection pipeline's contract)
        px, py = gp._paxes
        plan = FFTDiagPlan(gp.ny, gp.nx, gp.dtype, px, py, gp._psigns)
        solve = lambda bb, gp=gp, plan=plan: fft_diag_solve(
            gp.laplacian, bb, plan, tol=0.0, tol_rel=tol_rel)
        js = jax.jit(solve)
        res = js(bp)
        _fence(res.x)
        t0 = time.perf_counter()
        for _ in range(n_rep):
            res = js(bp)
            _fence(res.x)
        wall = max((time.perf_counter() - t0 - n_rep * lat) / n_rep,
                   1e-9)
        passes, flops_cell = ((10.0, 120.0) if name == "fftd_periodic"
                              else (12.0, 80.0))
        norm0p = float(jnp.max(jnp.abs(bp)))
        sec = max(wall, 1e-12)
        paths[name] = {
            "iters": int(res.iters),
            "ms_per_solve": round(wall * 1e3, 3),
            "ms_per_iter": round(wall * 1e3, 3),
            "residual_rel": float(res.residual) / norm0p,
            "converged": bool(res.converged),
            "bc_table": table.token,
            "hbm_passes": passes,
            "hbm_bytes": passes * fb,
            "hbm_util_pct": round(
                passes * fb / sec / (PEAK_HBM_GBPS * 1e9) * 100.0, 3),
            "mfu_pct": round(
                flops_cell * cells / sec
                / (PEAK_F32_TFLOPS * 1e12) * 100.0, 3),
        }
    return {"grid": f"{size}x{size}", "tol_rel": tol_rel,
            "interpret_mode": not _on_accel(),
            "anchors_r04": {"mfu_pct": 0.95, "hbm_util_pct": 12.0},
            "paths": paths,
            "forest": run_poisson_forest(n_rep=n_rep),
            "note": ("cold-RHS solves at a fixed relative target; "
                     "iters are platform-independent, ms carries the "
                     "fence methodology of run_size; hbm_passes/bytes "
                     "are MODELED per-iteration f32-equivalent field "
                     "passes (docstring), util/mfu derived against "
                     "the v5e peaks and meaningless in "
                     "interpret_mode")}


def run_poisson_forest(n_rep: int = 3):
    """Composite-forest solve-path micro-curve (PR 13): the SAME
    iters-to-tolerance + ms/solve contract as the uniform curve above,
    but on a genuinely multi-level forest (validation.poisson_ab's
    vortex-tagged topology) and through the REAL production entry
    point — each arm times a jitted AMRSim._pressure_project on the
    cold deltap RHS, so the figure includes the RHS assembly and
    projection every production solve pays. Arms:

      krylov_jacobi  block-Jacobi-preconditioned BiCGSTAB (the
                     trigger-off structured default)
      krylov_fft     mg2-cycle-preconditioned BiCGSTAB (the
                     CUP2D_POIS=fft production form)
      forest_fas     forest-native FAS multigrid as the full solver
                     (CUP2D_POIS=fas; iters are mg_solve CYCLES)

    One fresh sim per arm: _pois_mode is latched and read at trace
    time, so arms must not share a traced callable. Tolerances are the
    forest production defaults (tol 1e-3 / tol_rel 1e-2) rather than
    the uniform curve's 1e-3 relative target — the acceptance claim is
    about PRODUCTION solves."""
    from validation.poisson_ab import build_multilevel_sim

    arms = {
        "krylov_jacobi": (None, False),
        "krylov_fft": ("fft", True),
        "forest_fas": ("fas", True),
    }
    lat = None
    paths = {}
    meta = {}
    for name, (mode, coarse) in arms.items():
        sim = build_multilevel_sim(dtype="float32")
        sim._refresh()
        if mode is not None:
            sim._pois_mode = mode
        sim._coarse_on = coarse
        tc = sim._use_coarse(False) if coarse else None
        t = sim._tables
        ordf = sim._ordered_state()
        dtv = jnp.asarray(sim.compute_dt(), sim.forest.dtype)

        def solve(v, p, sim=sim, t=t, tc=tc, dtv=dtv):
            _, _, res, _ = sim._pressure_project(
                v, p, dtv, sim._h, sim._hsq_flat, t["vec1"],
                t["sca1"], t["pois"], sim._corr, tc, False,
                sim._maskv)
            return res

        js = jax.jit(solve)
        res = js(ordf["vel"], ordf["pres"])
        _fence(res.x)
        if lat is None:
            lat = _latency_floor(dtv)
        t0 = time.perf_counter()
        for _ in range(n_rep):
            res = js(ordf["vel"], ordf["pres"])
            _fence(res.x)
        wall = max((time.perf_counter() - t0 - n_rep * lat) / n_rep,
                   1e-9)
        iters = int(res.iters)
        if not meta:
            meta = {"n_blocks": int(sim._n_real),
                    "tol": sim.cfg.poisson_tol,
                    "tol_rel": sim.cfg.poisson_tol_rel}
        paths[name] = {
            "iters": iters,
            "ms_per_solve": round(wall * 1e3, 3),
            "ms_per_iter": round(wall / max(iters, 1) * 1e3, 3),
            "residual": float(res.residual),
            "converged": bool(res.converged),
        }
    return {**meta, "paths": paths,
            "note": ("cold-RHS _pressure_project solves on the "
                     "multi-level vortex forest at the production "
                     "tolerances; forest_fas iters are mg_solve "
                     "cycles, the Krylov arms' are BiCGSTAB "
                     "iterations")}


def run_kernel_curve(size: int, n_rep: int = 3):
    """Advection kernel-tier micro-curve (PR 9): ms per Heun SUBSTAGE
    for the XLA op chain vs the fused Pallas megakernel (f32 and bf16
    storage), with the MODELED HBM bytes per substage and the derived
    HBM-util% / MFU% against the v5e peaks — so acceptance is roofline
    movement against the r04 anchors (0.95% MFU / 12% HBM util), not
    just wall-clock. Timing covers one full Heun (both substages)
    divided by 2, apples-to-apples across tiers.

    Bytes model (per substage, field = 2 * N^2 * itemsize; the modeled
    pass counts are the asserted ISSUE-9 acceptance — XLA's chain
    re-reads the field >= 3x where the megakernel reads it once):
      xla   : 3 field reads (vel by pad; lab + vold by the fused
              RHS+update kernel) + 2 writes (lab, vel) = 5 f32 passes
      fused : stage 1 reads vel ONCE, writes once (2 passes); stage 2
              adds the vold read (3 passes) -> 2.5 f32 passes/substage
      bf16  : same passes at bf16 width, plus the once-per-step f32
              state <-> bf16 cast (1 f32 read + 1 bf16 write) and the
              stage-2 f32 final write -> 2 f32 + 5 bf16 passes per
              STEP = 2.25 f32-equivalent passes/substage. Halo bytes
              (<0.1% at bench sizes) ignored.

    On non-TPU hosts the fused tiers run in Pallas interpret mode: the
    ms/util columns are then NOT kernel performance (interpret_mode
    says so) but the bytes model and tier plumbing are
    platform-independent, so the smoke can pin the schema."""
    from cup2d_tpu.config import SimConfig
    from cup2d_tpu.ops.pallas_kernels import (_on_accel,
                                              fused_advect_heun,
                                              fused_tier_supported)
    from cup2d_tpu.ops.stencil import advect_diffuse_rhs, heun_substage
    from cup2d_tpu.uniform import UniformGrid, pad_vector

    level = int(np.log2(size // 8))
    cfg = SimConfig(bpdx=1, bpdy=1, level_max=1, level_start=0,
                    extent=1.0, nu=4e-5, cfl=0.5, dtype="float32")
    grid = UniformGrid(cfg, level=level)
    vel0 = bench_state(grid).vel
    h, nu = grid.h, cfg.nu
    ih2 = 1.0 / (h * h)
    dt = jnp.asarray(0.5 * h, jnp.float32)

    def xla_heun(v):
        vold = v
        for c in (0.5, 1.0):
            lab = pad_vector(v, 3)
            rhs = advect_diffuse_rhs(lab, 3, h, nu, dt)
            v = heun_substage(vold, c, rhs, ih2)
        return v

    def measure(fn):
        f = jax.jit(fn)
        out = f(vel0)
        _fence(out)                       # compile + warm
        lat = _latency_floor(dt)
        t0 = time.perf_counter()
        for _ in range(n_rep):
            out = f(out)
        _fence(out)
        wall = max(time.perf_counter() - t0 - lat, 1e-9)
        return wall / n_rep / 2.0 * 1e3   # ms per SUBSTAGE

    fb4 = 2.0 * size * size * 4.0         # one f32 velocity field
    cells = float(size * size)

    def derived(ms, passes_f32_equiv):
        hbm = passes_f32_equiv * fb4
        sec = ms * 1e-3
        return {
            "hbm_passes": passes_f32_equiv,
            "hbm_bytes": hbm,
            "hbm_util_pct": round(
                hbm / sec / (PEAK_HBM_GBPS * 1e9) * 100.0, 3),
            "mfu_pct": round(
                FLOPS_SUBSTAGE_PER_CELL * cells / sec
                / (PEAK_F32_TFLOPS * 1e12) * 100.0, 3),
        }

    tiers = {}
    ms = measure(xla_heun)
    tiers["xla"] = {
        "ms_per_substage": round(ms, 4),
        "adv_field_reads": 3, "adv_field_writes": 2,
        "storage_dtype": "f32", **derived(ms, 5.0)}
    if fused_tier_supported(grid.ny, grid.nx, prec="f32"):
        ms = measure(lambda v: fused_advect_heun(v, h, nu, dt))
        tiers["pallas_fused"] = {
            "ms_per_substage": round(ms, 4),
            "adv_field_reads": 1, "adv_field_writes": 1,
            "storage_dtype": "f32", **derived(ms, 2.5)}
    if fused_tier_supported(grid.ny, grid.nx, prec="bf16"):
        ms = measure(lambda v: fused_advect_heun(v, h, nu, dt,
                                                 bf16=True))
        tiers["pallas_fused_bf16"] = {
            "ms_per_substage": round(ms, 4),
            "adv_field_reads": 1, "adv_field_writes": 1,
            "storage_dtype": "bf16", **derived(ms, 2.25)}
        # BC'd arms (ISSUE 16): the validation workloads that used to
        # fall back to the XLA chain — lid-driven cavity and parabolic
        # channel tables — now run the same 2.25-pass bf16 tier; the
        # ghost synthesis is in-VMEM affine arithmetic, so the bytes
        # model is UNCHANGED and any ms delta vs pallas_fused_bf16 is
        # pure compute
        from cup2d_tpu.cases import cavity_table, channel_table
        for name, table in (
                ("pallas_fused_cavity", cavity_table(1.0)),
                ("pallas_fused_channel",
                 channel_table(1.0, profile="parabolic"))):
            ms = measure(lambda v, t=table: fused_advect_heun(
                v, h, nu, dt, bc=t, bf16=True))
            tiers[name] = {
                "ms_per_substage": round(ms, 4),
                "adv_field_reads": 1, "adv_field_writes": 1,
                "storage_dtype": "bf16", "bc_token": table.token,
                **derived(ms, 2.25)}
        # sharded-tier point (ISSUE 16): 2-device x-split mesh (virtual
        # host devices on CPU boxes — forced at import, top of file);
        # the 3-wide WENO halo moves by edge-column ppermutes before
        # the strip pipeline, so the per-device bytes model is the same
        # 2.25 passes (halo bytes < 0.1% at bench sizes, ignored as in
        # the bf16 model above)
        if jax.device_count() >= 2 and grid.nx % 2 == 0:
            from cup2d_tpu.parallel.mesh import make_mesh
            from cup2d_tpu.parallel.shard_halo import (
                fused_advect_heun_sharded)
            mesh2 = make_mesh(2)
            ms = measure(lambda v: fused_advect_heun_sharded(
                v, h, nu, dt, mesh2, bc=channel_table(
                    1.0, profile="parabolic"), bf16=True))
            tiers["pallas_fused_sharded"] = {
                "ms_per_substage": round(ms, 4),
                "adv_field_reads": 1, "adv_field_writes": 1,
                "storage_dtype": "bf16",
                "bc_token": channel_table(1.0,
                                          profile="parabolic").token,
                "mesh": "x:2", **derived(ms, 2.25)}
    return {
        "grid": f"{size}x{size}",
        "interpret_mode": not _on_accel(),
        "flops_substage_per_cell": FLOPS_SUBSTAGE_PER_CELL,
        "tiers": tiers,
        "anchors_r04": {"mfu_pct": 0.95, "hbm_util_pct": 12.0},
        "note": ("ms = one full Heun (jit, fenced, latency floor "
                 "subtracted) / 2 substages; reads/writes are MODELED "
                 "full-field HBM passes per substage (see "
                 "run_kernel_curve docstring for the bytes model); "
                 "util percentages use the v5e peak constants and are "
                 "meaningless in interpret_mode"),
    }


def _init_platform() -> str:
    """Initialize an available backend. On boxes without the configured
    accelerator, jax's first device probe dies with RuntimeError
    ('Unable to initialize backend ...') — which used to surface as an
    rc=1 stack-trace tail in BENCH_*.json (BENCH_r04/r05). Fall back to
    whatever platform initializes (CPU always does) and report it in
    the JSON instead: a bench that says 'platform: cpu' is honest; a
    crashed bench measures nothing.

    The probe runs a TINY REAL OP, not just jax.devices(): the axon
    backend registers devices eagerly and defers the actual failure to
    the first computation (RuntimeError at convert_element_type), so a
    devices()-only probe passes and the bench then dies at its first
    jnp call (the BENCH_r05 rc=1 tail). Anything the backend throws —
    RuntimeError, the bare AssertionError jax 0.4.37 raises for
    registered-but-deviceless platforms — takes the CPU fallback.

    The fallback must CLEAR the backend cache before retrying: the
    probe op populates xla_bridge's `_backends`/default-backend cache
    with the broken platform, and `jax.config.update("jax_platforms")`
    has no update hook in this jax line — `backends()` early-returns
    the populated cache, so without the clear the retry dispatches on
    the same broken backend and dies identically."""
    try:
        return _probe_platform()
    except Exception as e:   # noqa: BLE001 — see docstring
        print(f"bench: {type(e).__name__}: {e}; falling back to cpu",
              file=sys.stderr)
        try:
            from jax.extend.backend import clear_backends
        except ImportError:   # older spelling
            from jax._src.xla_bridge import _clear_backends \
                as clear_backends
        clear_backends()
        jax.config.update("jax_platforms", "cpu")
        return _probe_platform()


def _probe_platform() -> str:
    """One real tiny dispatch + the platform name (see _init_platform;
    module-level so the fallback drill can stub a deferred failure)."""
    jnp.zeros(1).block_until_ready()
    return jax.devices()[0].platform


def main():
    from cup2d_tpu.cache import enable_compilation_cache
    platform = _init_platform()
    enable_compilation_cache()
    size = int(os.environ.get("BENCH_SIZE", "8192"))
    n_warmup = int(os.environ.get("BENCH_WARMUP", "3"))
    n_steps = int(os.environ.get("BENCH_STEPS", "10"))
    extra_sizes = [int(s) for s in
                   os.environ.get("BENCH_EXTRA_SIZES", "").split(",") if s]

    primary = run_size(size, n_warmup, n_steps)
    secondary = {s: run_size(s, n_warmup, n_steps) for s in extra_sizes}
    adaptive = None
    if os.environ.get("BENCH_ADAPTIVE", "1") != "0":
        try:
            adaptive = run_adaptive(
                n_warm_steps=int(os.environ.get("BENCH_ADAPT_WARM", "40")),
                chain=int(os.environ.get("BENCH_ADAPT_CHAIN", "15")))
        except Exception as e:           # noqa: BLE001 - bench must print
            adaptive = {"error": f"{type(e).__name__}: {e}"}
    # fleet-batching curve (BENCH_FLEET="1,2,4,8" default; "0" skips;
    # BENCH_FLEET_SIZE picks the small-grid case — 16^2 default, the
    # dispatch-bound regime on every platform incl. the CPU CI box)
    fleet = None
    fleet_spec = os.environ.get("BENCH_FLEET", "1,2,4,8")
    if fleet_spec not in ("", "0"):
        try:
            fleet = run_fleet(
                int(os.environ.get("BENCH_FLEET_SIZE", "16")),
                [int(b) for b in fleet_spec.split(",") if b],
                n_steps=int(os.environ.get("BENCH_FLEET_STEPS", "40")))
        except Exception as e:           # noqa: BLE001 - bench must print
            fleet = {"error": f"{type(e).__name__}: {e}"}
    # continuous-batching serving curve (BENCH_SERVE=0 skips;
    # BENCH_SERVE_MEMBERS picks the pool size — 8 default, the ISSUE-11
    # acceptance point; BENCH_SERVE_SIZE/BENCH_SERVE_STEPS size the
    # grid and churn window like the fleet knobs above)
    serving = None
    if os.environ.get("BENCH_SERVE", "1") != "0":
        try:
            serving = run_fleet_serving(
                int(os.environ.get("BENCH_SERVE_SIZE", "16")),
                members=int(os.environ.get("BENCH_SERVE_MEMBERS", "8")),
                n_steps=int(os.environ.get("BENCH_SERVE_STEPS", "60")))
        except Exception as e:           # noqa: BLE001 - bench must print
            serving = {"error": f"{type(e).__name__}: {e}"}
    # mirror-overhead point (BENCH_MIRROR=0 skips; BENCH_MIRROR_SIZE
    # picks the grid — 256^2 default keeps the CPU CI point cheap
    # while still big enough that the permute cost is visible)
    mirror = None
    if os.environ.get("BENCH_MIRROR", "1") != "0":
        try:
            mirror = run_mirror_overhead(
                int(os.environ.get("BENCH_MIRROR_SIZE", "256")),
                n_iters=int(os.environ.get("BENCH_MIRROR_ITERS", "30")))
        except Exception as e:           # noqa: BLE001 - bench must print
            mirror = {"error": f"{type(e).__name__}: {e}"}
    # Poisson solve-path micro-curve (BENCH_POISSON=0 skips;
    # BENCH_POISSON_SIZE picks the grid — 1024^2 default keeps the
    # block-Jacobi baseline arm's iteration train bounded)
    poisson = None
    if os.environ.get("BENCH_POISSON", "1") != "0":
        try:
            poisson = run_poisson_curve(
                int(os.environ.get("BENCH_POISSON_SIZE", "1024")))
        except Exception as e:           # noqa: BLE001 - bench must print
            poisson = {"error": f"{type(e).__name__}: {e}"}
    # advection kernel-tier micro-curve (BENCH_KERNEL=0 skips;
    # BENCH_KERNEL_SIZE defaults to the primary size so the rig
    # re-measure against the r04 roofline anchors is one command)
    kernel = None
    if os.environ.get("BENCH_KERNEL", "1") != "0":
        try:
            kernel = run_kernel_curve(
                int(os.environ.get("BENCH_KERNEL_SIZE", str(size))),
                n_rep=int(os.environ.get("BENCH_KERNEL_REPS", "3")))
        except Exception as e:           # noqa: BLE001 - bench must print
            kernel = {"error": f"{type(e).__name__}: {e}"}

    # PRIMARY metric: DEVICE-derived throughput (profiler module time
    # over chained steps). The fenced-wall number carries host/tunnel
    # dispatch overhead that varies with the rig (r03: 25% of wall was
    # non-device time, invisible drift in the headline — VERDICT r3
    # weak #1); the device number is what the chip does and reproduces
    # to a few % against device_step_ms_profiled by construction.
    # Wall-clock throughput stays as a secondary field with the
    # wall/device divergence called out explicitly.
    have_device = "device_cells_steps_per_sec" in primary
    uni_value = (primary["device_cells_steps_per_sec"] if have_device
                 else primary["cells_steps_per_sec"])
    wall_ms = primary["step_ms"]
    dev_ms = primary.get("device_step_ms_profiled_mean")
    if adaptive and "error" not in adaptive:
        # PRIMARY metric since round 5: the CANONICAL adaptive case
        # (VERDICT r4 #2 — the uniform 8192^2 number flattered both the
        # advection share and the solver). The value is the
        # finest-equivalent throughput (device steps/s x the case's
        # finest-cap cell count): the driver target of 1 step/s applied
        # to the run.sh case makes the baseline finest_cap_cells
        # cells*steps/s, so vs_baseline is literally the achieved
        # steps/s on the reference's own case.
        value = adaptive["cells_steps_per_sec_finest_equiv"]
        # the wall-fallback must not masquerade as a device measurement
        # (same contract as the uniform metric below)
        metric = ("adaptive_cells_steps_per_sec_finest_equiv"
                  if adaptive["device_derived"]
                  else "adaptive_cells_steps_per_sec_finest_equiv"
                  "_wall_fallback")
        vs_baseline = round(value / adaptive["finest_cap_cells"], 4)
    else:
        value = uni_value
        metric = ("device_cells_steps_per_sec" if have_device
                  else "cells_steps_per_sec_wall_fallback")
        vs_baseline = round(value / BASELINE_CELLS_STEPS_PER_SEC, 4)
    out = {
        # the metric label must say what the number IS: on rigs where
        # the profiler is unavailable the fallback is wall-derived and
        # must not masquerade as a device measurement
        "metric": metric,
        "value": value,
        "unit": "cells*steps/s",
        "vs_baseline": vs_baseline,
        "backend": jax.default_backend(),
        "platform": platform,
        "dtype": "float32",
        ("uniform_8192_device_cells_steps_per_sec" if have_device
         else "uniform_8192_cells_steps_per_sec_wall_fallback"): uni_value,
        "uniform_8192_vs_1steps_target": round(
            uni_value / BASELINE_CELLS_STEPS_PER_SEC, 4),
        "wall_minus_device_ms": (
            round(wall_ms - dev_ms, 3) if dev_ms else None),
        "wall_overhead_note": (
            "step_ms(wall) - device_step_ms_profiled_mean is host/tunnel "
            "dispatch overhead, not solver time; primary value is "
            "device-derived (VERDICT r3 weak #1)"),
        "peak_assumed": {"f32_tflops": PEAK_F32_TFLOPS,
                         "hbm_gbps": PEAK_HBM_GBPS},
        **primary,
    }
    if adaptive:
        out["adaptive_canonical"] = adaptive
    if fleet:
        out["fleet"] = fleet
    if serving:
        out["fleet_serving"] = serving
    if mirror:
        out["mirror"] = mirror
    if poisson:
        out["poisson_curve"] = poisson
    if kernel:
        out["kernel_curve"] = kernel
    if secondary:
        out["secondary"] = secondary
    print(json.dumps(out))


if __name__ == "__main__":
    sys.exit(main())
