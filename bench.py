"""Benchmark: cells·timesteps/second of the full projection step.

Runs the flagship uniform-grid solver (Taylor–Green initial condition, the
reference's Poisson tolerances from run.sh) for a timed batch of steps on
whatever backend JAX finds (real TPU chip under the driver; CPU locally)
and prints ONE JSON line.

Baseline: the reference publishes no numbers (BASELINE.md); the
driver-defined north star is >= 1 full timestep/sec at 8192^2 on v5e-8
(/root/repo/BASELINE.json), i.e. 8192^2 = 67.1M cells·steps/s.
``vs_baseline`` is measured throughput / that target.
"""

from __future__ import annotations

import json
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np


BASELINE_CELLS_STEPS_PER_SEC = 8192.0 * 8192.0  # 1 step/s @ 8192^2 target


def main():
    size = int(os.environ.get("BENCH_SIZE", "1024"))
    n_warmup = int(os.environ.get("BENCH_WARMUP", "3"))
    n_steps = int(os.environ.get("BENCH_STEPS", "10"))

    from cup2d_tpu.config import SimConfig
    from cup2d_tpu.uniform import UniformGrid, taylor_green_state

    # square domain of size x size cells: bpdx=bpdy=1, level = log2(size/bs)
    level = int(np.log2(size // 8))
    cfg = SimConfig(bpdx=1, bpdy=1, level_max=1, level_start=0,
                    extent=1.0, nu=4e-5, cfl=0.5, dtype="float32")
    grid = UniformGrid(cfg, level=level)
    state = taylor_green_state(grid)

    step = jax.jit(grid.step, static_argnames=("exact_poisson",))
    dt = jnp.asarray(0.25 * grid.h, grid.dtype)

    for _ in range(n_warmup):
        state, diag = step(state, dt)
    jax.block_until_ready(state.vel)

    # no host sync inside the timed loop — iteration counts are read after
    diags = []
    t0 = time.perf_counter()
    for _ in range(n_steps):
        state, diag = step(state, dt)
        diags.append(diag["poisson_iters"])
    jax.block_until_ready(state.vel)
    t1 = time.perf_counter()
    iters_total = int(sum(int(d) for d in diags))

    wall = t1 - t0
    cells = grid.nx * grid.ny
    cells_steps_per_sec = cells * n_steps / wall
    poisson_ms_per_iter = (wall / max(iters_total, 1)) * 1e3

    print(json.dumps({
        "metric": "cells_steps_per_sec",
        "value": round(cells_steps_per_sec, 1),
        "unit": "cells*steps/s",
        "vs_baseline": round(
            cells_steps_per_sec / BASELINE_CELLS_STEPS_PER_SEC, 4
        ),
        "grid": f"{size}x{size}",
        "steps": n_steps,
        "wall_s": round(wall, 3),
        "poisson_ms_per_iter": round(poisson_ms_per_iter, 3),
        "poisson_iters_total": iters_total,
        "backend": jax.default_backend(),
    }))


if __name__ == "__main__":
    sys.exit(main())
