"""FFT-diagonalized direct Poisson solve + periodic-case tests
(ISSUE 20, CUP2D_POIS=fftd).

Contracts pinned here:

- Latch + attribution: "fftd" rides the sanctioned UniformGrid
  CUP2D_POIS read (construct-once — a post-construction env mutation
  is inert) and reports poisson_mode "fftd" (doubly periodic, pure
  spectral divide) or "fftd+tridiag" (one periodic axis, per-mode
  Thomas systems on the wall axis).
- Direct-solve correctness: one application reaches the production
  Linf criterion (iters == 1, converged) on the doubly-periodic box
  AND both mixed channels; the solution agrees with converged
  BiCGSTAB and FAS on the same operator to tight tolerance; the
  fully-periodic / all-Neumann nullspace is handled by the mean-zero
  pin (solution mean == 0, residual unaffected for mean-free RHS).
- Fleet batching: member_axis=True pushes B systems through ONE
  transform — batched == solo per member, iters == 1 for every
  member (the freeze contract is trivially inert: no member can
  observe another's iteration count).
- Loud refusal everywhere the diagonalization cannot go: wall-only
  tables (nothing to diagonalize), the device-mesh x-split (it shards
  the transform or scan axis), AMRSim (uniform-family token), the
  Pallas megakernel tier and the strip smoother on periodic tokens
  (no wrap-ghost variants) — silent free-slip fallback is impossible.
- Physics: the doubly-periodic Taylor-Green vortex's kinetic energy
  decays as exp(-4 nu k^2 t) within 1% at 128^2 (the catalog's
  analytic anchor), and a served periodic fleet pool runs its
  steady-state churn with jit_compiles == 0.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from cup2d_tpu.bc import BCTable, no_slip, periodic
from cup2d_tpu.cases import (make_sim, periodic_channel_table,
                             periodic_table)
from cup2d_tpu.config import SimConfig


def _cfg(**kw):
    base = dict(bpdx=1, bpdy=1, level_max=1, level_start=0, extent=1.0,
                nu=1e-3, cfl=0.4, lam=1e6, dtype="float64",
                max_poisson_iterations=200)
    base.update(kw)
    return SimConfig(**base)


def _grid(bc, monkeypatch, pois="fftd", level=3, **kw):
    from cup2d_tpu.uniform import UniformGrid
    if pois:
        monkeypatch.setenv("CUP2D_POIS", pois)
    else:
        monkeypatch.delenv("CUP2D_POIS", raising=False)
    return UniformGrid(_cfg(**kw), level=level, bc=bc)


def _mean_free(shape, seed):
    rng = np.random.default_rng(seed)
    b = rng.standard_normal(shape)
    return jnp.asarray(b - b.mean(axis=(-2, -1), keepdims=True))


def _py_channel_table():
    return BCTable(no_slip(), no_slip(), periodic(), periodic())


# ---------------------------------------------------------------------------
# latch + poisson_mode attribution
# ---------------------------------------------------------------------------

def test_fftd_latch_and_mode_strings(monkeypatch):
    g = _grid(periodic_table(), monkeypatch)
    assert g.solver_mode == "fftd"
    assert g.poisson_mode == "fftd"
    # construct-once: a mid-run env mutation is inert (ADVICE r5)
    monkeypatch.delenv("CUP2D_POIS", raising=False)
    assert g.solver_mode == "fftd" and g.poisson_mode == "fftd"

    gx = _grid(periodic_channel_table(), monkeypatch)   # periodic x
    assert gx.poisson_mode == "fftd+tridiag"
    gy = _grid(_py_channel_table(), monkeypatch)        # periodic y
    assert gy.poisson_mode == "fftd+tridiag"


def test_fftd_refuses_wall_only_box(monkeypatch):
    from cup2d_tpu.cases import cavity_table
    with pytest.raises(ValueError, match="at least one periodic"):
        _grid(cavity_table(), monkeypatch)
    with pytest.raises(ValueError, match="at least one periodic"):
        _grid(None, monkeypatch)   # default free-slip box


# ---------------------------------------------------------------------------
# direct-solve correctness: 1 iteration at the production criterion
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("table", [periodic_table(),
                                   periodic_channel_table(),
                                   _py_channel_table()],
                         ids=["doubly-periodic", "periodic-x",
                              "periodic-y"])
def test_fftd_one_application_converges(table, monkeypatch):
    g = _grid(table, monkeypatch)
    rhs = _mean_free((g.ny, g.nx), 11)
    res = g.pressure_solve(rhs)
    assert int(res.iters) == 1
    assert bool(res.converged) and not bool(res.stalled)
    # f64 direct solve: the true residual sits at transform rounding,
    # far below the production criterion it is judged against
    lin = float(jnp.max(jnp.abs(rhs - g.laplacian(res.x))))
    assert lin < 1e-10, lin
    # nullspace pin on the fully-periodic box: zeroing the (0,0) mode
    # IS the mean-zero solution. (The tridiag channels pin one VALUE
    # of the singular k=0 system instead — any mean offset is removed
    # downstream by the projection's standing mean-free contract,
    # exactly as for the Krylov solvers.)
    if table == periodic_table():
        assert abs(float(jnp.mean(res.x))) < 1e-12


def test_fftd_f32_production_criterion(monkeypatch):
    """The acceptance probe's tier-1 twin: cold mean-free RHS in f32 at
    128^2 meets the production Linf criterion in the single direct
    application (the 1024^2 version is bench.py's fftd_periodic arm)."""
    g = _grid(periodic_table(), monkeypatch, level=4, dtype="float32")
    rhs = _mean_free((g.ny, g.nx), 12).astype(jnp.float32)
    res = g.pressure_solve(rhs)
    assert int(res.iters) == 1
    assert bool(res.converged), float(res.residual)


# ---------------------------------------------------------------------------
# agreement with the iterative solvers on the same operator
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("table", [periodic_channel_table(),
                                   _py_channel_table()],
                         ids=["periodic-x", "periodic-y"])
def test_fftd_matches_bicgstab_and_fas(table, monkeypatch):
    """Mixed periodic/no-slip channels: the per-mode direct solve, the
    MG-preconditioned Krylov solve and the FAS full solver are three
    implementations of ONE operator — converged answers must agree to
    tight (mean-adjusted) tolerance."""
    rhs = _mean_free((64, 64), 13)

    def demean(a):
        return np.asarray(a) - float(jnp.mean(a))

    xf = demean(_grid(table, monkeypatch).pressure_solve(rhs).x)
    gb = _grid(table, monkeypatch, pois=None)
    rb = gb.pressure_solve(rhs, exact=True)       # tol-0 Krylov
    assert bool(rb.converged) or bool(rb.stalled)  # precision floor
    np.testing.assert_allclose(xf, demean(rb.x), atol=5e-9)

    gf = _grid(table, monkeypatch, pois="fas")
    rf = gf.pressure_solve(rhs, exact=True)
    np.testing.assert_allclose(xf, demean(rf.x), atol=5e-9)


def test_fftd_periodic_box_matches_bicgstab(monkeypatch):
    """Fully-periodic box (pure spectral divide, true nullspace): both
    solvers produce the SAME mean-free solution."""
    rhs = _mean_free((64, 64), 14)
    xf = _grid(periodic_table(), monkeypatch).pressure_solve(rhs).x
    gb = _grid(periodic_table(), monkeypatch, pois=None)
    rb = gb.pressure_solve(rhs, exact=True)
    xb = np.asarray(rb.x) - float(jnp.mean(rb.x))
    np.testing.assert_allclose(np.asarray(xf), xb, atol=5e-9)


# ---------------------------------------------------------------------------
# fleet batching: B systems through one transform
# ---------------------------------------------------------------------------

def test_fftd_member_batched_matches_solo(monkeypatch):
    from cup2d_tpu.poisson import fft_diag_solve
    g = _grid(periodic_channel_table(), monkeypatch)
    B = 3
    rhs = _mean_free((B, g.ny, g.nx), 15)
    # a dead slot (zero RHS) rides along: its direct solve is exact
    rhs = rhs.at[1].set(0.0)
    batched = fft_diag_solve(g.laplacian, rhs, g._fft_plan,
                             tol=1e-4, tol_rel=1e-3, member_axis=True)
    # freeze contract trivially inert: iters == 1 for EVERY member
    # (dead slots included) — no member observes another's count
    np.testing.assert_array_equal(np.asarray(batched.iters),
                                  np.ones(B, np.int32))
    assert bool(jnp.all(batched.converged))
    assert batched.residual.shape == (B,)
    for m in range(B):
        solo = fft_diag_solve(g.laplacian, rhs[m], g._fft_plan,
                              tol=1e-4, tol_rel=1e-3)
        np.testing.assert_allclose(np.asarray(batched.x[m]),
                                   np.asarray(solo.x), atol=1e-12)


def test_fftd_fleet_trajectory_matches_solo(monkeypatch):
    """A member-batched periodic fleet steps bit-close to the solo sim
    under fftd: same IC in every slot, one fused dispatch."""
    monkeypatch.setenv("CUP2D_POIS", "fftd")
    fs = make_sim("tgv_periodic", level=2, members=3, dtype="float64")
    solo = make_sim("tgv_periodic", level=2, dtype="float64")
    dt = 1e-3
    for _ in range(3):
        fs.step_once(dt)
        solo.step_once(dt)
    vs = np.asarray(solo.state.vel)
    vf = np.asarray(fs.state.vel)
    for m in range(3):
        np.testing.assert_allclose(vf[m], vs, atol=1e-12)


# ---------------------------------------------------------------------------
# refusal matrix: every tier that cannot honor periodic/fftd says so
# ---------------------------------------------------------------------------

def test_attach_mesh_refuses_fftd(monkeypatch):
    g = _grid(periodic_table(), monkeypatch)
    with pytest.raises(ValueError, match="fftd cannot attach"):
        g.attach_mesh(object())


def test_amr_refuses_fftd_token(monkeypatch):
    from cup2d_tpu.amr import AMRSim
    monkeypatch.setenv("CUP2D_POIS", "fftd")
    cfg = SimConfig(bpdx=1, bpdy=1, level_max=2, level_start=1,
                    extent=1.0, dtype="float64")
    with pytest.raises(ValueError, match="uniform-family"):
        AMRSim(cfg, shapes=[])


def test_amr_refuses_periodic_table(monkeypatch):
    from cup2d_tpu.amr import AMRSim
    monkeypatch.delenv("CUP2D_POIS", raising=False)
    cfg = SimConfig(bpdx=1, bpdy=1, level_max=2, level_start=1,
                    extent=1.0, dtype="float64")
    with pytest.raises(ValueError, match="does not support"):
        AMRSim(cfg, shapes=[], bc=periodic_table())


def test_pallas_megakernel_refuses_periodic(monkeypatch):
    """CUP2D_PALLAS=1 + a periodic table refuses AT CONSTRUCTION,
    naming the face/kind/token (the PR-16 capability-gate pattern) —
    a silent free-slip fallback is impossible."""
    from cup2d_tpu.uniform import UniformGrid
    monkeypatch.setenv("CUP2D_PALLAS", "1")
    monkeypatch.delenv("CUP2D_POIS", raising=False)
    cfg = _cfg(dtype="float32")
    with pytest.raises(ValueError, match="periodic"):
        UniformGrid(cfg, level=4, bc=periodic_table())
    with pytest.raises(ValueError, match="pd"):
        UniformGrid(cfg, level=4, bc=periodic_channel_table())


def test_strip_smoother_refuses_periodic():
    from cup2d_tpu.poisson import MultigridPreconditioner
    with pytest.raises(ValueError, match="strip smoother"):
        MultigridPreconditioner(
            64, 64, jnp.float32, edge_signs=(0.0, 0.0, 1.0, 1.0),
            smoother="strip", periodic=(True, False))


def test_mg_periodic_needs_edge_signs():
    from cup2d_tpu.poisson import MultigridPreconditioner
    with pytest.raises(ValueError, match="edge_signs"):
        MultigridPreconditioner(64, 64, jnp.float64,
                                periodic=(True, True))


# ---------------------------------------------------------------------------
# MG cycles on the wrapped operator (the bicgstab/fas arms' engine)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("table", [periodic_table(),
                                   periodic_channel_table()],
                         ids=["doubly-periodic", "periodic-x"])
def test_bicgstab_mg_converges_on_periodic(table, monkeypatch):
    """The ITERATIVE path must also honor wrap stencils (periodicity
    persists under coarsening) — it is the fftd A/B baseline and the
    only sharded-periodic option."""
    g = _grid(table, monkeypatch, pois=None)
    rhs = _mean_free((g.ny, g.nx), 16)
    res = g.pressure_solve(rhs)
    assert bool(res.converged)
    lin = float(jnp.max(jnp.abs(rhs - g.laplacian(res.x))))
    tgt = max(g.cfg.poisson_tol,
              g.cfg.poisson_tol_rel * float(jnp.max(jnp.abs(rhs))))
    assert lin <= 1.01 * tgt, (lin, tgt)


# ---------------------------------------------------------------------------
# telemetry: the v12 vocabulary on a REAL record
# ---------------------------------------------------------------------------

def test_fftd_metrics_record_attribution(monkeypatch):
    from cup2d_tpu.profiling import MetricsRecorder
    monkeypatch.setenv("CUP2D_POIS", "fftd")
    sim = make_sim("tgv_periodic", level=2, dtype="float64")
    sim.step_count = 20     # production regime: the startup exact
    #                         (tol-0) override reports stalled, not
    #                         converged — same semantics as bicgstab
    rec = MetricsRecorder()
    rec.prime(sim)
    r = rec.record(sim, sim.step_once(1e-3))
    assert r["poisson_mode"] == "fftd"
    assert r["bc_table"] == "pd,pd,pd,pd"
    assert r["case"] == "tgv_periodic"
    assert r["poisson_iters"] == 1
    assert r["precond_cycles"] == 0
    assert r["poisson_converged"] is True


# ---------------------------------------------------------------------------
# physics: the analytic anchor + the serving contract
# ---------------------------------------------------------------------------

def test_tgv_periodic_ke_decay_within_1pct(monkeypatch):
    """Acceptance (ISSUE 20): tgv_periodic at 128^2 under fftd — KE
    decays as exp(-4 nu k^2 t), k = 2 pi, within 1%."""
    nu = 1e-3
    monkeypatch.setenv("CUP2D_POIS", "fftd")
    sim = make_sim("tgv_periodic", level=4, nu=nu, dtype="float64")
    ke0 = float(jnp.mean(sim.state.vel ** 2))
    t_end = 0.1
    sim.advance(n_steps=10_000, tend=t_end)
    assert sim.time >= t_end
    ke = float(jnp.mean(sim.state.vel ** 2))
    k = 2.0 * np.pi
    expected = np.exp(-4.0 * nu * k * k * sim.time)
    measured = ke / ke0
    assert abs(measured - expected) / expected < 0.01, (measured,
                                                       expected)


@pytest.mark.slow   # developed-regime trajectory (O(300) steps at
#                     128^2 through roll-up, t=0.8). The tier-1
#                     physics anchor for the periodic stack is the 1%
#                     TGV KE-decay test above — this pins the CATALOG
#                     case qualitatively (perturbation growth +
#                     bounded, decaying invariants), which needs the
#                     developed regime by definition.
def test_shear_layer_rolls_up(monkeypatch):
    """Double shear layer under fftd: the delta*sin(2pi x) seed grows
    into the roll-up (v-energy rises an order of magnitude), while KE
    decays monotonically-in-aggregate and the fields stay finite —
    the classic BCG sanity on the periodic advection + projection."""
    monkeypatch.setenv("CUP2D_POIS", "fftd")
    sim = make_sim("shear_layer", level=4, dtype="float64")
    v2_0 = float(jnp.mean(sim.state.vel[1] ** 2))
    ke0 = float(jnp.mean(sim.state.vel ** 2))
    sim.advance(n_steps=10_000, tend=0.8)   # roll-up developed:
    #                                         measured v-energy growth
    #                                         ~x110 by t=0.8 (x5 at
    #                                         0.4 — still linear)
    vel = sim.state.vel
    assert bool(jnp.all(jnp.isfinite(vel)))
    ke = float(jnp.mean(vel ** 2))
    v2 = float(jnp.mean(vel[1] ** 2))
    assert ke < ke0                        # dissipative
    assert v2 > 10.0 * v2_0, (v2, v2_0)   # roll-up grew the seed


@pytest.mark.slow   # seeded-spectrum decay trajectory at 128^2 (same
#                     developed-regime justification as the
#                     shear-layer test; tier-1 already pins turb2d's
#                     build + solve contracts via the fftd tests
#                     above)
def test_turb2d_selective_decay(monkeypatch):
    """Decaying 2D turbulence under fftd: energy and enstrophy both
    decay (selective decay — enstrophy faster), deterministically per
    seed."""
    monkeypatch.setenv("CUP2D_POIS", "fftd")
    sim = make_sim("turb2d", level=4, seed=7, dtype="float64")
    g = sim.grid

    def invariants():
        w = g.vorticity_field(sim.state.vel)
        return (float(jnp.mean(sim.state.vel ** 2)),
                float(jnp.mean(w ** 2)))

    ke0, ens0 = invariants()
    sim.advance(n_steps=10_000, tend=0.2)
    ke1, ens1 = invariants()
    assert bool(jnp.all(jnp.isfinite(sim.state.vel)))
    assert ke1 < ke0
    assert ens1 < ens0
    # enstrophy decays FASTER than energy (2D selective decay)
    assert ens1 / ens0 < ke1 / ke0


def test_zero_recompile_served_periodic_pool(monkeypatch, tmp_path):
    """Acceptance (ISSUE 20): a served periodic case runs its
    steady-state churn with jit_compiles == 0 — the fftd direct solve
    and wrap stencils compile once in the warm phase and the slot-pool
    executables are reused through admit/retire churn."""
    from cup2d_tpu.fleet import FleetRequest, FleetServer, FleetSim
    from cup2d_tpu.profiling import HostCounters
    from cup2d_tpu.resilience import EventLog

    monkeypatch.setenv("CUP2D_POIS", "fftd")
    cfg = _cfg(lam=1e6)
    sim = FleetSim(cfg, level=2, members=3, bc=periodic_table())
    sim.step_count = 20          # production regime (serving steady state)
    log = EventLog(str(tmp_path / "events.jsonl"))
    server = FleetServer(sim, event_log=log)
    g = sim.grid
    x, y = g.cell_centers()
    k = 2.0 * np.pi
    n_req = 0

    def submit(horizon_steps):
        nonlocal n_req
        amp = 0.8 ** (n_req % 3)
        st = g.zero_state()._replace(vel=jnp.asarray(np.stack([
            amp * np.sin(k * x) * np.cos(k * y),
            -amp * np.cos(k * x) * np.sin(k * y)]), dtype=g.dtype))
        dt0 = float(sim._member_dt(st.vel))
        server.submit(FleetRequest(
            client_id=f"c{n_req:03d}", state=st,
            t_end=(horizon_steps - 0.1) * dt0))
        n_req += 1

    # warm phase: fill the pool, retire, refill — every executable the
    # measured window touches compiles here
    for _ in range(3):
        submit(2)
    for _ in range(6):
        submit(2)
        server.step()

    c = HostCounters().install()
    try:
        retired0, admitted0 = server.retired, server.admitted
        for _ in range(6):
            submit(3)
            server.step()
    finally:
        c.uninstall()
    snap = c.snapshot()
    assert server.retired > retired0 and server.admitted > admitted0
    assert snap["jit_compiles"] == 0, snap
    log.close()
