"""Fused RK-substage megakernel (PR 9): equivalence + composition pins.

Everything here runs the REAL Pallas kernels in interpret mode (tier-1,
CPU box) — interpret executes the same kernel body, DMA schedule and
value-level halo construction as the TPU lowering, so kernel-logic bugs
(ring-slot collisions, wrong ghost mirror signs, per-member scale-row
mixups) fail HERE, not on the first TPU drive. What interpret cannot
check — Mosaic lowering, real DMA overlap — is test_pallas.py's
TPU-only job.

Measured error bounds (pinned with ~16x headroom, CPU interpret):

- full-Heun f32 vs the XLA op chain: max-abs 1.1920928955078125e-07 on
  the 32x64 unit-scale operand — NOT bit-exact because XLA contracts
  `a*b+c` into FMAs differently inside vs outside the kernel body; the
  prior single-op probe measured 2.9e-11 per RHS evaluation, and the
  Heun update multiplies the RHS by ih2 = 4096, giving exactly ~1 ulp
  at unit scale. Asserted <= 2e-6.
- forest-block fused_lab_rhs vs advect_diffuse_rhs: bit-exact (0.0) —
  no ih2 amplification on the raw RHS, identical contraction.
- fused projection-correction vs the XLA epilogue: 2.4e-7 (uniform) /
  4.8e-7 (fleet) — the mean-subtract reassociates. Asserted <= 5e-6.
- bf16 storage tier vs the f32 reference trajectory: ~3.2e-3 after one
  step (bf16 mantissa 2^-8), drifting with step count. The Taylor-
  Green golden asserts <= 2e-2 after 10 steps.
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cup2d_tpu.config import SimConfig
from cup2d_tpu.ops.pallas_kernels import (HAVE_PALLAS, fused_advect_heun,
                                          fused_lab_rhs,
                                          fused_tier_supported)
from cup2d_tpu.ops.stencil import advect_diffuse_rhs, heun_substage
from cup2d_tpu.poisson import project_correct
from cup2d_tpu.uniform import (UniformGrid, UniformSim, pad_vector,
                               taylor_green_state)

pytestmark = pytest.mark.skipif(
    not HAVE_PALLAS, reason="needs jax.experimental.pallas")

NY, NX = 32, 64
H = 1.0 / NX
NU = 4e-5
FULL_HEUN_BOUND = 2e-6     # measured 1.19e-07 (see module docstring)
CORRECTION_BOUND = 5e-6    # measured 2.4e-7 / 4.8e-7


def _rand(shape, seed):
    return jnp.asarray(
        np.random.default_rng(seed).standard_normal(shape), jnp.float32)


def _xla_heun(vel, h, nu, dt):
    """The pre-PR-9 XLA op chain, verbatim (uniform: scalar dt; fleet:
    dt [B] broadcast exactly like FleetSim._step_impl's dt4)."""
    ih2 = 1.0 / (h * h)
    dt_b = dt[:, None, None, None] if jnp.ndim(dt) == 1 else dt
    vold = vel
    v = vel
    for c in (0.5, 1.0):
        lab = pad_vector(v, 3)
        rhs = advect_diffuse_rhs(lab, 3, h, nu, dt_b)
        v = heun_substage(vold, c, rhs, ih2)
    return v


def _cfg32(**kw):
    base = dict(bpdx=1, bpdy=1, level_max=1, level_start=0, extent=1.0,
                nu=NU, cfl=0.4, dtype="float32",
                max_poisson_iterations=60)
    base.update(kw)
    return SimConfig(**base)


# ---------------------------------------------------------------------------
# f32 equivalence vs the XLA chain: all three operand families
# ---------------------------------------------------------------------------

def test_fused_heun_matches_xla_uniform():
    """UniformSim's operand family: vel [2,Ny,Nx], scalar dt."""
    vel = _rand((2, NY, NX), 0)
    dt = jnp.float32(0.5 * H)
    ref = _xla_heun(vel, H, NU, dt)
    got = fused_advect_heun(vel, H, NU, dt)
    err = float(jnp.max(jnp.abs(got - ref)))
    assert err <= FULL_HEUN_BOUND, err


def test_fused_heun_matches_xla_member_batched():
    """FleetSim's operand family: vel [B,2,Ny,Nx] with DISTINCT
    per-member dt — pins the kernel's per-member (afac, dfac) scale
    rows (a transposed or broadcast-shared row would blow the ~1-ulp
    bound by the dt ratio)."""
    vel = _rand((3, 2, NY, NX), 1)
    dt = jnp.asarray([0.5 * H, 0.35 * H, 0.27 * H], jnp.float32)
    ref = _xla_heun(vel, H, NU, dt)
    got = fused_advect_heun(vel, H, NU, dt)
    err = float(jnp.max(jnp.abs(got - ref)))
    assert err <= FULL_HEUN_BOUND, err


def test_fused_lab_rhs_bitexact_forest_blocks():
    """AMRSim's operand family: pre-assembled labs [N,2,BS+6,BS+6] with
    PER-BLOCK h [N,1,1,1] (the forest mixes levels in one batch). The
    raw RHS has no ih2 amplification, so this one is bit-exact."""
    n, bs, g = 5, 8, 3
    lab = _rand((n, 2, bs + 2 * g, bs + 2 * g), 2)
    hb = jnp.asarray([H, H / 2, H, H / 4, H / 2],
                     jnp.float32).reshape(n, 1, 1, 1)
    dt = jnp.float32(0.5 * H)
    # both sides jitted — the production configuration (AMRSim's step
    # is one jit); eagerly the op-by-op dispatch contracts FMAs
    # differently and the match is ~1 ulp (1.5e-10) instead of exact
    ref = jax.jit(lambda l: advect_diffuse_rhs(l, g, hb, NU, dt))(lab)
    got = jax.jit(lambda l: fused_lab_rhs(l, hb, NU, dt))(lab)
    assert float(jnp.max(jnp.abs(got - ref))) == 0.0


def test_fused_correction_matches_xla():
    """project_correct: the fused single-kernel epilogue vs the
    historical XLA chain, uniform (scalar means) and fleet (per-member
    means, per-member dt) operands."""
    x = _rand((NY, NX), 3)
    pold = _rand((NY, NX), 4)
    vel = _rand((2, NY, NX), 5)
    dt = jnp.float32(0.5 * H)
    vr, pr = project_correct(x, pold, vel, H, dt, tier="xla")
    vf, pf = project_correct(x, pold, vel, H, dt, tier="pallas-fused")
    assert float(jnp.max(jnp.abs(vf - vr))) <= CORRECTION_BOUND
    assert float(jnp.max(jnp.abs(pf - pr))) <= CORRECTION_BOUND

    xb = _rand((3, NY, NX), 6)
    pb = _rand((3, NY, NX), 7)
    vb = _rand((3, 2, NY, NX), 8)
    dtb = jnp.asarray([0.5 * H, 0.35 * H, 0.27 * H], jnp.float32)
    vr, pr = project_correct(xb, pb, vb, H, dtb,
                             mean_axes=(-2, -1), tier="xla")
    vf, pf = project_correct(xb, pb, vb, H, dtb,
                             mean_axes=(-2, -1), tier="pallas-fused")
    assert float(jnp.max(jnp.abs(vf - vr))) <= CORRECTION_BOUND
    assert float(jnp.max(jnp.abs(pf - pr))) <= CORRECTION_BOUND


# ---------------------------------------------------------------------------
# BC-aware kernel (ISSUE 16): the four ghost kinds, corner composition
# and the parabolic clamp vs bc.py's XLA chain, all four face tables
# ---------------------------------------------------------------------------

def _xla_bc_heun(vel, h, nu, dt, bc):
    """The BC'd XLA op chain (uniform.py's fallback path, verbatim):
    bc.py ghost paint -> WENO RHS -> Heun substage."""
    from cup2d_tpu.bc import pad_vector_bc
    ih2 = 1.0 / (h * h)
    dt_b = dt[:, None, None, None] if jnp.ndim(dt) == 1 else dt
    vold = vel
    v = vel
    for c in (0.5, 1.0):
        # dt_b: the member-batched path broadcasts dt like fleet.py's
        # dt4 so the outflow extrapolation speed is per-member
        lab = pad_vector_bc(v, 3, bc, h, dt_b)
        rhs = advect_diffuse_rhs(lab, 3, h, nu, dt_b)
        v = heun_substage(vold, c, rhs, ih2)
    return v


def _bc_tables():
    from cup2d_tpu.bc import (BCTable, convective_outflow,
                              dirichlet_inflow, no_slip)
    from cup2d_tpu.cases import cavity_table, channel_table
    return {
        # four no-slip walls + moving lid: 2*uw - edge on every face,
        # corners compose x-ghosts from the y-painted columns
        "cavity": cavity_table(1.0),
        # uniform Dirichlet inflow + convective outflow on the x faces
        # (the dt-dependent extrapolation speed, clipped to [0,1])
        "channel_uniform": channel_table(1.0),
        # parabolic inflow: the 4s(1-s) profile along the x_lo face's
        # PADDED rows, s clipped outside the interior band
        "channel_parabolic": channel_table(1.0, profile="parabolic"),
        # y-face inflow/outflow: the parabolic profile along a y face
        # (tangent = global column index) and outflow at y_hi, with
        # no-slip x walls reading the y-painted corners
        "outflow_y": BCTable(no_slip(), no_slip(),
                             dirichlet_inflow(0.0, 1.0,
                                              profile="parabolic"),
                             convective_outflow()),
    }


@pytest.mark.parametrize("name", sorted(_bc_tables()))
def test_fused_heun_matches_xla_bc(name):
    """Every supported ghost kind, ~1-ulp f32 equivalence vs the bc.py
    XLA chain (the same FMA-contraction bound as the free-slip pin)."""
    bc = _bc_tables()[name]
    vel = _rand((2, NY, NX), 11)
    dt = jnp.float32(0.5 * H)
    ref = _xla_bc_heun(vel, H, NU, dt, bc)
    got = fused_advect_heun(vel, H, NU, dt, bc=bc)
    err = float(jnp.max(jnp.abs(got - ref)))
    assert err <= FULL_HEUN_BOUND, (name, err)


def test_fused_heun_bc_member_batched():
    """BC'd kernel under the fleet's operand family: distinct
    per-member dt rides the widened facs row (col 2 feeds the outflow
    extrapolation speed per member)."""
    from cup2d_tpu.cases import channel_table
    bc = channel_table(1.0, profile="parabolic")
    vel = _rand((3, 2, NY, NX), 12)
    dt = jnp.asarray([0.5 * H, 0.35 * H, 0.27 * H], jnp.float32)
    ref = _xla_bc_heun(vel, H, NU, dt, bc)
    got = fused_advect_heun(vel, H, NU, dt, bc=bc)
    err = float(jnp.max(jnp.abs(got - ref)))
    # measured 2.03e-6: the outflow speed c = clip(s*en*dt/h, 0, 1) is
    # associated differently inside the kernel ((s*en)*dtf)/h and the
    # ~1-ulp difference in c rides (edge - inner) through the same
    # ih2 = 4096 amplification as the base bound — a few extra ulp,
    # not a logic error (the solo BC'd arms above stay <= 2e-6)
    assert err <= 1e-5, err


def test_free_slip_table_normalizes_to_base_kernel():
    """The ISSUE-16 acceptance pin: an explicit all-free-slip table
    normalizes to bc=None inside fused_advect_heun, so the default
    table stays BIT-identical to the PR-9 kernel (same executable, not
    merely close)."""
    from cup2d_tpu.bc import BCTable
    vel = _rand((2, NY, NX), 13)
    dt = jnp.float32(0.5 * H)
    base = fused_advect_heun(vel, H, NU, dt)
    got = fused_advect_heun(vel, H, NU, dt, bc=BCTable())
    assert float(jnp.max(jnp.abs(got - base))) == 0.0


def test_fused_correction_carries_pressure_signs():
    """The fused projection epilogue with a Dirichlet (outflow) face:
    the kernel's edge-gradient coefficients take bc.py's derived
    pressure-row signs and match the XLA chain; the default signs stay
    bit-identical to explicit all-Neumann (1,1,1,1)."""
    from cup2d_tpu.bc import pressure_signs
    from cup2d_tpu.cases import channel_table
    gs = pressure_signs(channel_table(1.0))
    assert gs == (1.0, -1.0, 1.0, 1.0)     # x_hi outflow -> Dirichlet
    x = _rand((NY, NX), 14)
    pold = _rand((NY, NX), 15)
    vel = _rand((2, NY, NX), 16)
    dt = jnp.float32(0.5 * H)
    vr, pr = project_correct(x, pold, vel, H, dt, tier="xla",
                             grad_signs=gs)
    vf, pf = project_correct(x, pold, vel, H, dt, tier="pallas-fused",
                             grad_signs=gs)
    assert float(jnp.max(jnp.abs(vf - vr))) <= CORRECTION_BOUND
    assert float(jnp.max(jnp.abs(pf - pr))) <= CORRECTION_BOUND
    v0, p0 = project_correct(x, pold, vel, H, dt, tier="pallas-fused")
    v1, p1 = project_correct(x, pold, vel, H, dt, tier="pallas-fused",
                             grad_signs=(1.0, 1.0, 1.0, 1.0))
    assert float(jnp.max(jnp.abs(v1 - v0))) == 0.0
    assert float(jnp.max(jnp.abs(p1 - p0))) == 0.0


def test_sharded_kernel_matches_solo_kernel():
    """The halo-mode kernel on a 2-device x-split vs the solo kernel,
    same BC table: the per-shard ghost synthesis reads global position
    from the info row and edge columns from the ppermuted halo operand,
    so the split must be invisible (observed bit-identical in
    interpret mode; asserted <= 1e-11)."""
    from cup2d_tpu.cases import channel_table
    from cup2d_tpu.parallel.mesh import make_mesh
    from cup2d_tpu.parallel.shard_halo import fused_advect_heun_sharded
    bc = channel_table(1.0, profile="parabolic")
    vel = _rand((2, NY, NX), 17)
    dt = jnp.float32(0.5 * H)
    solo = fused_advect_heun(vel, H, NU, dt, bc=bc)
    shard = fused_advect_heun_sharded(vel, H, NU, dt, make_mesh(2),
                                      bc=bc)
    assert float(jnp.max(jnp.abs(shard - solo))) <= 1e-11


@pytest.mark.slow
def test_sharded_sim_trajectory_matches_solo(monkeypatch):
    """End-to-end ISSUE-16 acceptance: ShardedUniformSim on the fused
    tier (2-device x-split, halo-mode kernel — the configuration the
    pre-16 tier REFUSED at construction) tracks the solo spmd_safe sim
    step for step to <= 1e-11 over 5 steps, and the tier string names
    the boundary table.

    slow, like PR 13's sharded FAS trajectory drill: full-sim sharded
    trajectories pay two interpret-mode shard_map compiles (~18 s on
    one CPU core).  The tier-1 pin for sharded == solo is the
    kernel-level bit-identity test above, which exercises the same
    halo-mode kernel without the sim scaffolding."""
    from cup2d_tpu.cases import channel_table
    from cup2d_tpu.parallel.mesh import ShardedUniformSim, make_mesh
    monkeypatch.setenv("CUP2D_PALLAS", "1")
    monkeypatch.delenv("CUP2D_PREC", raising=False)
    bc = channel_table(1.0, profile="parabolic")
    cfg = _cfg32()
    solo = UniformSim(cfg, level=2, spmd_safe=True, bc=bc)
    solo.state = taylor_green_state(solo.grid)
    sh = ShardedUniformSim(cfg, make_mesh(2), level=2, bc=bc)
    assert sh.kernel_tier == \
        "pallas-fused+bc(in(1,0)[parabolic],out,fs,fs)"
    sh.set_state(taylor_green_state(sh.grid))
    dt = 0.25 * solo.grid.h
    for _ in range(5):
        solo.step_once(dt)
        sh.step_once(dt)
    dv = np.abs(np.asarray(sh.state.vel)
                - np.asarray(solo.state.vel)).max()
    assert dv <= 1e-11, dv


# ---------------------------------------------------------------------------
# tier latch + composition pins (the use_pallas composition gap, closed
# LOUDLY — ISSUE 9 satellite)
# ---------------------------------------------------------------------------

def test_sharded_x_split_constructs_fused_tier(monkeypatch):
    """ISSUE 16 retired the PR-9 construction refusal: the sharded
    x-split now routes to the halo-mode kernel (edge-column ppermutes
    feed a per-shard halo operand before the strip pipeline), so
    spmd_safe construction with the tier requested SUCCEEDS and latches
    pallas-fused — the pre-16 ValueError("sharded ...") is gone."""
    monkeypatch.setenv("CUP2D_PALLAS", "1")
    monkeypatch.delenv("CUP2D_PREC", raising=False)
    g = UniformGrid(_cfg32(), level=2, spmd_safe=True)
    assert g.kernel_tier == "pallas-fused"


def test_tier_activates_for_spatial_fleet(monkeypatch):
    """The fleet's spatial placement is a mesh caller: big grids fall
    back to the x-split, and with the fused tier requested that now
    takes the SAME halo-mode kernel instead of the pre-16 loud
    refusal."""
    from cup2d_tpu.fleet import FleetSim
    from cup2d_tpu.parallel.mesh import make_mesh
    monkeypatch.setenv("CUP2D_PALLAS", "1")
    monkeypatch.delenv("CUP2D_PREC", raising=False)
    fleet = FleetSim(_cfg32(), level=3, members=2, mesh=make_mesh(8),
                     member_cells_cap=0)   # force the spatial branch
    assert fleet.placement == "spatial"
    assert fleet.kernel_tier == "pallas-fused"


def test_kernel_supports_refuses_unknown_kind_naming_the_token():
    """The ONE remaining refusal (kernel_supports): a ghost kind with
    no in-VMEM synthesis fails at construction time, loudly, naming
    the offending face, kind and the full table token."""
    from cup2d_tpu.bc import BCTable, FaceBC
    from cup2d_tpu.ops.pallas_kernels import kernel_supports
    bad = BCTable(FaceBC("periodic"), FaceBC(), FaceBC(), FaceBC())
    with pytest.raises(ValueError) as ei:
        kernel_supports(bad)
    msg = str(ei.value)
    assert "x_lo" in msg and "periodic" in msg and bad.token in msg


def test_tier_activates_for_member_batched_fleet(monkeypatch):
    """Member placement keeps spatial axes whole, so the fleet gets the
    fused tier — the kernel is leading-dim agnostic by construction."""
    from cup2d_tpu.fleet import FleetSim
    monkeypatch.setenv("CUP2D_PALLAS", "1")
    monkeypatch.delenv("CUP2D_PREC", raising=False)
    fleet = FleetSim(_cfg32(), level=2, members=2)
    assert fleet.kernel_tier == "pallas-fused"
    assert fleet.prec_mode == "f32"


def test_bf16_requires_the_fused_tier(monkeypatch):
    """bf16 is a storage property of the megakernel's HBM operands —
    meaningless without the tier, so requesting it tier-less is loud."""
    monkeypatch.delenv("CUP2D_PALLAS", raising=False)
    monkeypatch.setenv("CUP2D_PREC", "bf16")
    with pytest.raises(ValueError, match="CUP2D_PALLAS"):
        UniformGrid(_cfg32(), level=2)


def test_bf16_refuses_unsupported_shape(monkeypatch):
    """An explicit precision request must never silently degrade: the
    bf16 tier needs ny % 16 strips, and an 8-row grid gets a ValueError
    where the f32 tier's shape miss keeps the historical silent-XLA
    fallback (asserted below)."""
    monkeypatch.setenv("CUP2D_PALLAS", "1")
    monkeypatch.setenv("CUP2D_PREC", "bf16")
    with pytest.raises(ValueError, match="bf16"):
        UniformGrid(_cfg32(), level=0)     # ny = 8


def test_bad_prec_token_is_loud(monkeypatch):
    monkeypatch.setenv("CUP2D_PALLAS", "1")
    monkeypatch.setenv("CUP2D_PREC", "fp8")
    with pytest.raises(ValueError, match="f32|bf16"):
        UniformGrid(_cfg32(), level=2)


def test_f32_shape_miss_keeps_silent_xla_fallback(monkeypatch):
    """The f32 tier is an optimization, not a semantic: a dtype/shape
    miss falls back to the XLA chain exactly like pre-PR-9 CUP2D_PALLAS
    behavior (only EXPLICIT bf16 requests refuse)."""
    monkeypatch.setenv("CUP2D_PALLAS", "1")
    monkeypatch.delenv("CUP2D_PREC", raising=False)
    g = UniformGrid(_cfg32(dtype="float64"), level=2)
    assert g.kernel_tier == "xla" and not g.use_pallas
    assert g.prec_mode == "f64"


def test_telemetry_carries_kernel_tier(monkeypatch):
    """Schema v6: the record's kernel_tier/prec_mode come from the
    sim's latch (the xla/f64 side is pinned in test_telemetry.py)."""
    from cup2d_tpu.profiling import MetricsRecorder
    monkeypatch.setenv("CUP2D_PALLAS", "1")
    monkeypatch.delenv("CUP2D_PREC", raising=False)
    sim = UniformSim(_cfg32(), level=2)
    assert sim.kernel_tier == "pallas-fused"
    sim.state = taylor_green_state(sim.grid)
    rec = MetricsRecorder()
    rec.prime(sim)
    diag = sim.step_once(0.25 * sim.grid.h)
    r = rec.record(sim, diag)
    assert r["kernel_tier"] == "pallas-fused"
    assert r["prec_mode"] == "f32"


# ---------------------------------------------------------------------------
# bf16 storage tier: Taylor-Green tolerance golden, watchdog armed
# ---------------------------------------------------------------------------

def test_bf16_taylor_green_watchdog_golden(tmp_path, monkeypatch):
    """10 guarded steps of the 32x32 Taylor-Green on the bf16 tier vs
    the f32 XLA reference at the SAME fixed dt: the trajectory stays in
    the bf16 band (<= 2e-2; the one-step measurement is ~3.2e-3) and
    the for_prec('bf16') watchdog — widened settle ratios, doubled
    div_factor — arms on the settled flow WITHOUT a false trip (a trip
    would show as a recovery event and a forked trajectory)."""
    from cup2d_tpu.resilience import EventLog, PhysicsWatchdog, StepGuard
    monkeypatch.delenv("CUP2D_PALLAS", raising=False)
    monkeypatch.delenv("CUP2D_PREC", raising=False)
    cfg = _cfg32()
    ref = UniformSim(cfg, level=2)         # xla tier, f32
    ref.state = taylor_green_state(ref.grid)

    monkeypatch.setenv("CUP2D_PALLAS", "1")
    monkeypatch.setenv("CUP2D_PREC", "bf16")
    sim = UniformSim(cfg, level=2)
    assert sim.kernel_tier == "pallas-fused-bf16"
    assert sim.prec_mode == "bf16"
    sim.state = taylor_green_state(sim.grid)

    wd = PhysicsWatchdog.for_prec(sim.prec_mode, window=4)
    assert (wd.div_factor, wd.div_settle) == (100.0, 8.0)  # bf16 band
    log = EventLog(str(tmp_path / "events.jsonl"))
    guard = StepGuard(sim, watchdog=wd, event_log=log)
    dt = 0.25 * sim.grid.h                 # fixed: same clock both runs
    for _ in range(10):
        guard.step(dt)
        ref.step_once(dt)
    guard.drain()
    assert sim.step_count == 10

    # armed, and no false trip
    assert wd._armed(wd.umax, wd.umax_settle) is not None
    with open(tmp_path / "events.jsonl") as f:
        evs = [json.loads(ln) for ln in f if ln.strip()]
    assert not [e for e in evs if e.get("event") == "recovery"], evs

    dv = np.abs(np.asarray(sim.state.vel)
                - np.asarray(ref.state.vel)).max()
    assert 0.0 < dv <= 2e-2, dv            # really bf16, inside band
    assert np.all(np.isfinite(np.asarray(sim.state.vel)))


def test_fused_tier_supported_strip_rules():
    """The support predicate the constructors latch on: sublane-tile
    strip heights (8 rows f32, 16 rows bf16), lane alignment enforced
    only on real accelerators (interpret mode has no lane tiling)."""
    assert fused_tier_supported(32, 64, prec="f32")
    assert fused_tier_supported(8, 64, prec="f32")
    assert not fused_tier_supported(12, 64, prec="f32")   # ny % 8
    assert fused_tier_supported(32, 64, prec="bf16")
    assert not fused_tier_supported(8, 64, prec="bf16")   # ny % 16
