"""Collision golden-trajectory regression (VERDICT r3 #8).

The collision invariant tests (tests/test_collision_forces.py) pass
under any SYMMETRIC sign error; this pins the actual two-disk
trajectory through contact — approach, e=1 impulse exchange, rebound —
against numbers recorded by `python -m validation.golden_collision
--write` (CPU f64). Regenerate consciously after legitimate numerics
changes, like the canonical golden."""

import json
import os

import numpy as np
import pytest

from validation.golden_collision import GOLDEN_PATH, N_STEPS, \
    run_trajectory


@pytest.mark.skipif(not os.path.exists(GOLDEN_PATH),
                    reason="golden_collision.json not generated")
def test_golden_collision_trajectory():
    with open(GOLDEN_PATH) as f:
        want = json.load(f)
    got = run_trajectory()
    assert len(got["steps"]) == len(want["steps"]) == N_STEPS
    for i, (g, w) in enumerate(zip(got["steps"], want["steps"])):
        np.testing.assert_allclose(g["time"], w["time"], rtol=1e-12)
        for k, (bg, bw) in enumerate(zip(g["bodies"], w["bodies"])):
            np.testing.assert_allclose(
                bg["com"], bw["com"], rtol=0, atol=1e-7,
                err_msg=f"step {i} body {k} com")
            for q in ("u", "v", "omega"):
                np.testing.assert_allclose(
                    bg[q], bw[q], rtol=1e-6, atol=1e-9,
                    err_msg=f"step {i} body {k} {q}")
    np.testing.assert_allclose(got["min_gap"], want["min_gap"],
                               rtol=0, atol=1e-7)
    # the pinned window must actually contain the impulse: body 0 flips
    # from approaching (+u) to receding (-u) across step 0 -> 1
    assert want["steps"][0]["bodies"][0]["u"] > 0.1
    assert want["steps"][1]["bodies"][0]["u"] < -0.01
