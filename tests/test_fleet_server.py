"""Continuous-batching fleet serving (fleet.FleetServer, PR 11):

- Slot invariance: a live member's trajectory AND clock are
  bit-identical regardless of co-member churn — sessions retiring,
  admitting and parking around it change values only in lanes it never
  reads (the select-freeze + frozen-Poisson-lane contracts).
- The masked trace at full occupancy is bit-identical to the unmasked
  historical trace (``where(True, new, old)`` selects new verbatim),
  and a parked slot is FROZEN bit-exact — state, pressure, clock and
  diag lane — however many fused steps its co-members take.
- Admit-from-checkpoint resumes a parked session bit-exact: state,
  clock and the chained per-member dt all round-trip through
  ``io.save_member_checkpoint``, so split serving == uninterrupted.
- The guard's eviction rung: an exhausted per-member ladder EVICTS the
  bad member (slot freed, fleet lives on) while the healthy members'
  trajectories and clocks stay bit-identical to an unfaulted twin.
- Zero steady-state recompiles: once every serving executable is warm
  (masked step, slot scatter, fresh-dt admit, eviction ladder), an
  arbitrary admit/retire/evict churn — a SECOND eviction included —
  compiles nothing (jax.monitoring compile counter flat).
- Shaped membership: per-member frozen obstacles (disk chi + nonzero
  solid velocity) ride the member axis; each member matches the solo
  ``UniformGrid.step(obstacle_terms=True)`` trajectory to <= 1e-12.
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np

from cup2d_tpu.config import SimConfig
from cup2d_tpu.faults import FaultPlan
from cup2d_tpu.fleet import (FleetRequest, FleetServer, FleetSim,
                             stack_states, taylor_green_fleet)
from cup2d_tpu.profiling import HostCounters
from cup2d_tpu.resilience import EventLog, FleetStepGuard
from cup2d_tpu.uniform import taylor_green_state


# 32^2 grid: the serving contracts are size-independent (tier-1 budget)
LVL = 2


def _cfg(**kw):
    base = dict(bpdx=1, bpdy=1, level_max=1, level_start=0, extent=1.0,
                nu=1e-3, cfl=0.4, lam=1e6, dtype="float64",
                max_poisson_iterations=100)
    base.update(kw)
    return SimConfig(**base)


def _pool(members=3):
    """A production-regime slot pool (exact-mode startup skipped, as in
    tests/test_fleet.py — the serving loop is a steady-state machine)."""
    sim = FleetSim(_cfg(), level=LVL, members=members)
    sim.step_count = 20
    return sim


def _session_state(grid, m):
    """Session m's admission state: the amplitude-laddered Taylor-Green
    vortex (distinct umax -> distinct per-member dt, as in the fleet
    tests — identical sessions would hide cross-lane leaks)."""
    st = taylor_green_state(grid)
    return st._replace(vel=st.vel * (0.8 ** m))


def _events(path):
    with open(path) as f:
        return [json.loads(ln) for ln in f if ln.strip()]


# ---------------------------------------------------------------------------
# slot invariance under churn
# ---------------------------------------------------------------------------

def test_member_trajectory_bit_identical_under_co_member_churn():
    """THE serving contract: client "keep"'s trajectory through n
    serving cycles is bit-identical whether it runs alone in the pool
    or surrounded by a full churn of co-sessions (two retirement waves
    + refills from the queue). Its lane's arithmetic is elementwise
    independent, its Poisson lane select-frozen once converged — dead
    or alive co-lanes change nothing it reads, clocks included."""
    n = 8

    def run(churn):
        sim = _pool(3)
        server = FleetServer(sim)
        g = sim.grid

        def req(cid, m, t_end=np.inf):
            return FleetRequest(client_id=cid,
                                state=_session_state(g, m),
                                t_end=float(t_end))

        # short horizons measured in the session's OWN first dt, so the
        # retirement points are robust to the slow CFL drift
        dt1 = float(sim._member_dt(_session_state(g, 1).vel))
        dt2 = float(sim._member_dt(_session_state(g, 2).vel))
        server.submit(req("keep", 0))
        if churn:
            server.submit(req("s1", 1, 1.9 * dt1))   # retires ~cycle 2
            server.submit(req("s2", 2, 2.9 * dt2))   # retires ~cycle 3
        for k in range(n):
            if churn and k == 4:
                # second wave through the freed slots
                server.submit(req("s3", 1, 1.9 * dt1))
                server.submit(req("s4", 2, 2.9 * dt2))
            assert server.step() is not None
        return (np.asarray(sim.member_state(0).vel),
                np.asarray(sim.member_state(0).pres),
                float(sim.times[0]), server)

    v_a, p_a, t_a, srv_a = run(False)
    v_b, p_b, t_b, srv_b = run(True)
    # the churn was real: both waves retired, the pool refilled
    assert srv_a.retired == 0 and srv_a.admitted == 1
    assert srv_b.admitted == 5 and srv_b.retired >= 3
    assert srv_b.client_of(0) == "keep"
    assert np.array_equal(v_a, v_b)
    assert np.array_equal(p_a, p_b)
    assert t_a == t_b


def test_all_true_mask_bit_identical_and_parked_slot_frozen():
    """Two halves of the mask contract. (1) The masked trace at full
    occupancy is bit-identical to the historical unmasked trace —
    where(True, new, old) selects new verbatim, so flipping a fixed-B
    fleet to serving mode costs no trajectory change. (2) A parked
    slot is frozen BIT-EXACT: state, pressure and clock unchanged over
    further fused steps, its diag lane inert (zero dt/div, converged
    at iteration zero)."""
    n = 3
    plain = _pool(3)
    plain.state = taylor_green_fleet(plain.grid, 3)
    masked = _pool(3)
    masked.state = taylor_green_fleet(masked.grid, 3)
    masked.set_active(np.ones(3, dtype=bool))
    dp = dm = None
    for _ in range(n):
        dp = plain.step_once()
        dm = masked.step_once()
    assert np.array_equal(np.asarray(plain.state.vel),
                          np.asarray(masked.state.vel))
    assert np.array_equal(np.asarray(plain.state.pres),
                          np.asarray(masked.state.pres))
    assert np.array_equal(plain.times, masked.times)
    assert np.array_equal(np.asarray(dp["poisson_iters"]),
                          np.asarray(dm["poisson_iters"]))

    # park slot 2 and keep stepping the others
    v2 = np.asarray(masked.member_state(2).vel)
    p2 = np.asarray(masked.member_state(2).pres)
    t2 = float(masked.times[2])
    v0 = np.asarray(masked.member_state(0).vel)
    masked.set_active(np.array([True, True, False]))
    diag = None
    for _ in range(3):
        diag = masked.step_once()
    assert np.array_equal(np.asarray(masked.member_state(2).vel), v2)
    assert np.array_equal(np.asarray(masked.member_state(2).pres), p2)
    assert float(masked.times[2]) == t2
    # the live members genuinely advanced
    assert not np.array_equal(np.asarray(masked.member_state(0).vel), v0)
    # the dead lane's diag is inert: it costs the solver nothing and
    # never pollutes the fold aggregates
    assert int(np.asarray(diag["poisson_iters"])[2]) == 0
    assert bool(np.asarray(diag["poisson_converged"])[2])
    assert float(np.asarray(diag["dt"])[2]) == 0.0
    assert float(np.asarray(diag["div_linf"])[2]) == 0.0
    # fleet time reads min over LIVE slots only
    assert masked.time == min(float(masked.times[0]),
                              float(masked.times[1]))


# ---------------------------------------------------------------------------
# admit-from-checkpoint: bit-exact session resume
# ---------------------------------------------------------------------------

def test_admit_from_checkpoint_bit_exact_resume(tmp_path):
    """A session parked mid-flight (retire -> member checkpoint) and
    re-admitted from that checkpoint lands EXACTLY where the
    uninterrupted run lands: the state, the clock and the chained
    per-member dt all round-trip losslessly, so the split trajectory's
    dt sequence is the uninterrupted one."""
    from cup2d_tpu.io import load_member_checkpoint

    probe = _pool(2)
    dt0 = float(probe._member_dt(
        _session_state(probe.grid, 0).vel))
    T = 4.6 * dt0        # ~5 steps total
    t_mid = 2.6 * dt0    # parked after ~3 steps

    def serve(sdir, horizons):
        sim = _pool(2)
        server = FleetServer(sim, session_dir=str(sdir))
        ckpt, times = None, []
        for t_end in horizons:
            server.submit(FleetRequest(
                client_id="X", checkpoint=ckpt,
                state=None if ckpt else _session_state(sim.grid, 0),
                t_end=t_end))
            assert server.drain() > 0
            ckpt = os.path.join(str(sdir), "X")
            # the leg's parked clock, read between legs: proves the
            # split run really parked mid-flight before resuming
            times.append(load_member_checkpoint(ckpt, sim.grid)[1]["time"])
        return sim, ckpt, times

    sim_ref, ck_ref, t_ref = serve(tmp_path / "ref", [T])
    sim_spl, ck_spl, t_spl = serve(tmp_path / "split", [t_mid, T])
    assert t_mid <= t_spl[0] < T           # a genuine mid-flight park

    st_r, meta_r = load_member_checkpoint(ck_ref, sim_ref.grid)
    st_s, meta_s = load_member_checkpoint(ck_spl, sim_spl.grid)
    assert meta_r["time"] >= T and meta_s["time"] >= T
    for name, a, b in zip(st_r._fields, st_r, st_s):
        assert np.array_equal(np.asarray(a), np.asarray(b)), name
    assert meta_r["time"] == meta_s["time"]
    assert meta_r["next_dt"] == meta_s["next_dt"]


# ---------------------------------------------------------------------------
# the eviction rung: bad member out, fleet lives, healthy members pinned
# ---------------------------------------------------------------------------

def test_eviction_pins_healthy_members_bit_identical(tmp_path):
    """A member whose per-member ladder exhausts (nan_vel re-poisoned
    through retry AND escalate: *3) is EVICTED — slot freed and
    zeroed, fleet stepping on — instead of the fleet dying. The
    surviving members' trajectories and clocks stay bit-identical to
    an unfaulted twin, through the recovery AND the post-eviction
    masked steps."""
    n = 7

    def run(spec):
        sim = _pool(3)
        log = EventLog(str(tmp_path / f"ev_{bool(spec)}.jsonl"))
        guard = FleetStepGuard(
            sim, event_log=log,
            faults=FaultPlan(spec) if spec else None)
        server = FleetServer(sim, guard=guard, event_log=log)
        for m in range(3):
            server.submit(FleetRequest(
                client_id=f"c{m}", state=_session_state(sim.grid, m)))
        for _ in range(n):
            assert server.step() is not None
        log.close()
        return sim, server

    sim_t, srv_t = run(None)
    sim_f, srv_f = run("nan_vel@24*3")     # faults.py poisons member 0

    assert srv_t.evicted == 0
    assert srv_f.evicted == 1 and srv_f.guard.evictions == 1
    assert not srv_f.active[0] and srv_f.client_of(0) is None
    assert srv_f.active[1] and srv_f.active[2]
    vt = np.asarray(sim_t.state.vel)
    vf = np.asarray(sim_f.state.vel)
    for m in (1, 2):                       # healthy members NEVER rewind
        assert np.array_equal(vt[m], vf[m]), m
        assert sim_t.times[m] == sim_f.times[m], m
    # the evicted slot was zeroed (a NaN corpse would poison the
    # masked step's member_health diag rows) and the shared counter
    # kept advancing: the fleet survived the eviction
    assert np.all(np.asarray(sim_f.member_state(0).vel) == 0.0)
    assert sim_f.step_count == sim_t.step_count == 20 + n
    evs = _events(tmp_path / "ev_True.jsonl")
    aborted = [e for e in evs if e.get("event") == "member_aborted"]
    evicted = [e for e in evs if e.get("event") == "member_evict"]
    assert len(aborted) == 1 and aborted[0]["member"] == 0
    assert aborted[0]["action"] == "evict"
    assert len(evicted) == 1 and evicted[0]["client"] == "c0"
    # the ladder was climbed before giving up: retry then escalate
    recs = [e for e in evs if e.get("event") == "recovery"]
    assert [e["action"] for e in recs] == ["retry", "escalate"]


# ---------------------------------------------------------------------------
# zero steady-state recompiles
# ---------------------------------------------------------------------------

def test_zero_recompile_steady_state_churn(tmp_path):
    """The perf contract the whole slot-pool design exists for: once
    the serving executables are warm (masked fused step, slot scatter
    with the device-int32 index, fresh-CFL-dt admit, the eviction
    ladder's solo retry/escalate pair), an arbitrary admit/retire/
    evict churn — including a SECOND eviction — compiles NOTHING. The
    jax.monitoring compile counter is the measurement, as in the
    telemetry steady-state test."""
    sim = _pool(3)
    log = EventLog(str(tmp_path / "events.jsonl"))
    guard = FleetStepGuard(
        sim, event_log=log,
        faults=FaultPlan("nan_vel@26*3,nan_vel@33*3"))
    server = FleetServer(sim, guard=guard, event_log=log)
    g = sim.grid
    n_req = 0

    def submit(horizon_steps):
        nonlocal n_req
        st = _session_state(g, n_req % 3)
        dt0 = float(sim._member_dt(st.vel))
        server.submit(FleetRequest(
            client_id=f"c{n_req:03d}", state=st,
            t_end=(horizon_steps - 0.1) * dt0))
        n_req += 1

    # warm phase: full pool, short-horizon retires + refills, then the
    # first ladder exhaustion (fault at shared step 26) — every
    # executable the churn below touches compiles HERE
    for _ in range(3):
        submit(2)
    for _ in range(9):                     # steps 20..28, evict at 26
        submit(2)
        server.step()
    assert server.evicted == 1             # warm ladder really ran

    # measured churn: more sessions, retires, admits and the SECOND
    # eviction (step 33) — with zero compiles
    c = HostCounters().install()
    try:
        retired0, admitted0 = server.retired, server.admitted
        for _ in range(8):                 # steps 29..36, evict at 33
            submit(3)
            server.step()
    finally:
        c.uninstall()
    snap = c.snapshot()
    assert server.evicted == 2 and guard.evictions == 2
    assert server.retired > retired0       # churn happened in-window
    assert server.admitted > admitted0
    assert snap["jit_compiles"] == 0, snap
    log.close()


def test_zero_recompile_bc_pallas_pool_churn(tmp_path, monkeypatch):
    """ISSUE-16 acceptance: the zero-recompile contract extends to a
    BC'd fused-kernel pool. All BC coefficients are trace-time
    constants (one executable per BCTable token) and the kernel_tier
    suffix lives on the host-side property only — so a cavity-table
    pool on the pallas tier (f32 state, the tier's dtype contract)
    serves a measured admit/retire churn window with jit_compiles ==
    0, exactly like the XLA pool above."""
    from cup2d_tpu.cases import cavity_table
    monkeypatch.setenv("CUP2D_PALLAS", "1")
    monkeypatch.delenv("CUP2D_PREC", raising=False)
    sim = FleetSim(_cfg(dtype="float32", nu=4e-5), level=LVL,
                   members=3, bc=cavity_table(1.0))
    assert sim.kernel_tier == "pallas-fused+bc(ns,ns,ns,ns(1,0))"
    sim.step_count = 20
    log = EventLog(str(tmp_path / "events.jsonl"))
    server = FleetServer(sim, event_log=log)
    g = sim.grid
    n_req = 0

    def submit(horizon_steps):
        nonlocal n_req
        st = _session_state(g, n_req % 3)
        dt0 = float(sim._member_dt(st.vel))
        server.submit(FleetRequest(
            client_id=f"b{n_req:03d}", state=st,
            t_end=(horizon_steps - 0.1) * dt0))
        n_req += 1

    # warm phase: fill, retire, refill — every executable the measured
    # window touches compiles here
    for _ in range(3):
        submit(2)
    for _ in range(5):
        submit(2)
        server.step()

    c = HostCounters().install()
    try:
        retired0, admitted0 = server.retired, server.admitted
        for _ in range(6):
            submit(3)
            server.step()
    finally:
        c.uninstall()
    snap = c.snapshot()
    assert server.retired > retired0       # churn happened in-window
    assert server.admitted > admitted0
    assert snap["jit_compiles"] == 0, snap
    log.close()


# ---------------------------------------------------------------------------
# shaped membership: per-member frozen obstacles
# ---------------------------------------------------------------------------

def _shaped_state(grid, m):
    """Member m's shaped session: amplitude-laddered Taylor-Green flow
    around a frozen disk (chi) translating at a nonzero solid velocity
    (us), with a small divergence-bearing deformation field (udef) so
    the chi*div(u_def) RHS term is exercised for real."""
    g = grid
    xs = (np.arange(g.nx) + 0.5) * g.h
    ys = (np.arange(g.ny) + 0.5) * g.h
    X, Y = np.meshgrid(xs, ys)
    cx = 0.35 + 0.1 * m                    # per-member disk position
    chi = (((X - cx) ** 2 + (Y - 0.5) ** 2) < 0.15 ** 2)
    chi = chi.astype(np.float64)
    us = np.stack([0.2 * chi, 0.05 * chi])
    udef = 0.02 * np.stack([chi * np.sin(2 * np.pi * Y),
                            chi * np.cos(2 * np.pi * X)])
    base = taylor_green_state(grid)
    return base._replace(
        vel=base.vel * (0.8 ** m),
        chi=jnp.asarray(chi, g.dtype),
        us=jnp.asarray(us, g.dtype),
        udef=jnp.asarray(udef, g.dtype))


def test_shaped_fleet_members_match_solo_obstacle_step():
    """``FleetSim(shaped=True)``: per-member obstacle fields ride the
    member axis as frozen solids — Brinkman penalization and the
    chi-weighted divergence RHS batched over B. Each member matches
    the solo ``UniformGrid.step(obstacle_terms=True)`` trajectory to
    <= 1e-12 (the documented MG FMA-contraction bound), per-member dt
    chains included."""
    B, n = 2, 3
    sim = FleetSim(_cfg(), level=LVL, members=B, shaped=True)
    sim.step_count = 20
    g = sim.grid
    sim.state = stack_states([_shaped_state(g, m) for m in range(B)])
    diag = None
    for _ in range(n):
        diag = sim.step_once()

    solo_step = jax.jit(g.step,
                        static_argnames=("exact_poisson",
                                         "obstacle_terms"))
    for m in range(B):
        st = _shaped_state(g, m)
        dt = float(sim._member_dt(st.vel))
        t = 0.0
        for _ in range(n):
            st, d = solo_step(st, jnp.asarray(dt, g.dtype),
                              exact_poisson=False, obstacle_terms=True)
            t += dt
            dt = float(d["dt_next"])
        vs = np.asarray(st.vel)
        vf = np.asarray(sim.state.vel)[m]
        scale = max(1.0, np.abs(vs).max())
        assert np.abs(vs - vf).max() <= 1e-12 * scale, m
        assert np.abs(np.asarray(st.pres)
                      - np.asarray(sim.state.pres)[m]).max() \
            <= 1e-12, m
        assert abs(sim.times[m] - t) <= 1e-12, m
        # penalization really bit: the solid region moves with us
        assert float(np.asarray(diag["umax"])[m]) > 0
    # the disk broke the symmetry: members' solves differ
    assert int(np.asarray(diag["poisson_iters"])[0]) >= 1
