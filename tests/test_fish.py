"""Host-side fish kinematics unit tests (reference main.cpp:111-161
if2d_solve, 3476-3547 interpolation, 3991-4207 ongrid kinematics)."""

import numpy as np

from cup2d_tpu.models.fish import (
    FishShape,
    cubic_interp,
    if2d_solve,
    natural_cubic_spline,
)


def test_natural_cubic_spline_reproduces_line():
    x = np.array([0.0, 0.3, 0.7, 1.0])
    y = 2.0 * x + 1.0
    xx = np.linspace(0, 1, 17)
    yy = natural_cubic_spline(x, y, xx)
    assert np.allclose(yy, 2.0 * xx + 1.0, atol=1e-12)


def test_cubic_interp_endpoints_and_derivative():
    y, dy = cubic_interp(0.0, 1.0, 0.0, 3.0, 7.0, dy0=0.5)
    assert np.isclose(y, 3.0) and np.isclose(dy, 0.5)
    y, dy = cubic_interp(0.0, 1.0, 1.0, 3.0, 7.0, dy0=0.5)
    assert np.isclose(y, 7.0) and np.isclose(dy, 0.0)


def test_if2d_solve_straight_line_when_curvature_zero():
    rs = np.linspace(0.0, 1.0, 33)
    z = np.zeros_like(rs)
    rX, rY, vX, vY, norX, norY, vNorX, vNorY = if2d_solve(rs, z, z)
    assert np.allclose(rX, rs) and np.allclose(rY, 0.0)
    assert np.allclose(norX, 0.0) and np.allclose(norY, 1.0)
    assert np.allclose(vX, 0.0) and np.allclose(vY, 0.0)


def test_if2d_solve_arc_length_preserved():
    """Frenet integration is an isometry: |r_{i+1}-r_i| == ds even for a
    strongly curved midline (the renormalization keeps |ksi| = 1)."""
    rs = np.linspace(0.0, 1.0, 65)
    curv = 3.0 * np.sin(2 * np.pi * rs)
    rX, rY, *_ = if2d_solve(rs, curv, np.zeros_like(rs))
    seg = np.hypot(np.diff(rX), np.diff(rY))
    assert np.allclose(seg, np.diff(rs), rtol=1e-10)


def _fish():
    return FishShape(0.2, 0.5, 0.5, 0.0, min_h=0.2 / 32)


def test_fish_discretization():
    f = _fish()
    assert f.nm == len(f.rS) == len(f.width)
    assert f.rS[0] == 0.0 and np.isclose(f.rS[-1], f.length)
    assert np.all(np.diff(f.rS) >= 0)
    assert np.all(f.width >= 0)
    assert f.width[0] == 0.0 and np.isclose(f.width[-1], 0.0)
    # head width profile: sqrt(2 wh s - s^2) with wh = 0.04 L
    s = f.rS[1]
    wh = 0.04 * f.length
    assert np.isclose(f.width[1], np.sqrt(2 * wh * s - s * s))


def test_midline_internal_momentum_removed():
    """After the de-meaning pass the midline's own linear momentum
    integral is ~0 (self-propulsion consistency, main.cpp:4094-4184)."""
    f = _fish()
    f.midline(0.37)
    ds = np.empty(f.nm)
    ds[0] = f.rS[1] - f.rS[0]
    ds[-1] = f.rS[-1] - f.rS[-2]
    ds[1:-1] = f.rS[2:] - f.rS[:-2]
    fac1 = 2.0 * f.width
    lmx = np.sum(f.vX * fac1 * ds / 2.0)
    lmy = np.sum(f.vY * fac1 * ds / 2.0)
    scale = max(np.max(np.abs(f.vX)), np.max(np.abs(f.vY))) * f.area
    # fac2/fac3 width^3 terms are dropped here, so only near-zero
    assert abs(lmx) < 0.05 * scale and abs(lmy) < 0.05 * scale


def test_midline_moves_with_time():
    f = _fish()
    f.midline(0.1)
    r1 = f.rY.copy()
    f.midline(0.35)
    assert not np.allclose(r1, f.rY)
    assert np.max(np.abs(f.rY)) > 1e-3  # undulation has real amplitude


def test_surface_polygon_closed_and_transformed():
    f = FishShape(0.2, 1.0, 0.75, 90.0, min_h=0.2 / 32)
    f.midline(0.2)
    poly = f.surface_polygon()
    assert poly.shape == (2 * f.nm, 2)
    # 90 deg: fish extends along +y from its center, stays near x=1
    assert np.ptp(poly[:, 1]) > np.ptp(poly[:, 0])
    assert abs(np.mean(poly[:, 0]) - 1.0) < 0.05


def test_kinematic_dt_cap_bounds_gait_advance():
    """The gait-period dt cap (shapes_host._kinematic_dt_cap, a
    deliberate deviation from the reference's pure CFL control,
    main.cpp:6579-6595): on a coarse quiescent grid the CFL/diffusive
    dt exceeds the swimming period — one step would advance the midline
    by O(period), which is kinematic nonsense (the body teleports
    through a full gait cycle between two penalization solves). The cap
    must (a) actually bind in that regime at 1/20 of the fastest
    period, (b) stay out of the way for rigid shapes."""
    from cup2d_tpu.amr import AMRSim
    from cup2d_tpu.config import SimConfig
    from cup2d_tpu.models import DiskShape, FishShape

    cfg = SimConfig(bpdx=2, bpdy=1, level_max=3, level_start=1,
                    extent=1.0, dtype="float64", nu=4e-5, lam=1e6,
                    rtol=2.0, ctol=1.0)
    fish = FishShape(0.12, 0.55, 0.25, 0.0, cfg.min_h, period=0.8)
    sim = AMRSim(cfg, shapes=[fish])
    sim.compute_forces_every = 0
    sim.initialize()
    # quiescent flow: umax ~ 0 -> uncapped CFL dt is huge
    uncapped = sim.compute_dt()
    cap = sim._kinematic_dt_cap()
    assert cap == 0.05 * 0.8
    assert uncapped > cap, (uncapped, cap)
    sim.step_once()
    # the step really advanced by the cap, not the CFL dt
    assert abs(sim.time - cap) < 1e-12, sim.time

    rigid = AMRSim(cfg, shapes=[DiskShape(0.08, 0.55, 0.25)])
    assert rigid._kinematic_dt_cap() == float("inf")
