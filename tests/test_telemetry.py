"""Run-telemetry subsystem tests (profiling.py + the PR-3 resilience
additions): the frozen metrics schema, the zero-extra-sync contract
(metrics-on bit-identical to metrics-off with EQUAL device_get counts —
the PR-2 trace-count harness extended), the physics-invariant watchdog
against injected wrong-but-finite corruption, the steady-state
recompile/transfer-count guard, phase-timer fencing, and the windowed
trace driver."""

import glob
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cup2d_tpu.config import SimConfig
from cup2d_tpu.faults import FaultPlan
from cup2d_tpu.models import DiskShape
from cup2d_tpu.profiling import (METRICS_KEYS, HostCounters,
                                 MetricsRecorder, NULL_TIMERS,
                                 PhaseTimers, TraceWindow, load_metrics,
                                 summarize_metrics)
from cup2d_tpu.resilience import (EventLog, PhysicsWatchdog, StepGuard)
from cup2d_tpu.sim import Simulation


def _cfg(**kw):
    base = dict(bpdx=1, bpdy=1, level_max=1, level_start=0, extent=1.0,
                nu=1e-3, cfl=0.4, lam=1e6, dtype="float64",
                max_poisson_iterations=100)
    base.update(kw)
    return SimConfig(**base)


def _sim():
    disk = DiskShape(0.1, 0.4, 0.5, prescribed=(0.2, 0.0))
    return Simulation(_cfg(), shapes=[disk], level=3)


def _amr_sim():
    from cup2d_tpu.amr import AMRSim
    cfg = SimConfig(bpdx=1, bpdy=1, level_max=3, level_start=1,
                    extent=1.0, dtype="float64", nu=1e-3, lam=1e5,
                    rtol=0.5, ctol=0.05, max_poisson_iterations=40,
                    poisson_tol=1e-4, poisson_tol_rel=1e-3)
    sim = AMRSim(cfg, shapes=[DiskShape(0.08, 0.4, 0.5,
                                        prescribed=(0.2, 0.0))])
    sim.compute_forces_every = 0
    return sim


# ---------------------------------------------------------------------------
# schema stability (golden key set): every producer emits the SAME keys
# ---------------------------------------------------------------------------

# the LITERAL schema-v8 key set: METRICS_KEYS is the producers' truth,
# this tuple is the consumers' — any drift between them (a key renamed,
# dropped, or added without bumping the schema) fails here on purpose.
# v3 added the fleet-batching fields (fleet_members / member_steps_per_s
# / member_health, fleet.py); v4 the solve-path attribution pair
# (poisson_mode — the active CUP2D_POIS latch + trigger state — and the
# per-step preconditioner-cycle count, PR 6); v5 the elastic-topology
# group (topology_epoch / remesh_count / remesh_ms — the TopologyGuard
# + StepGuard.elastic_recover subsystem, PR 7); v6 the kernel-tier
# attribution pair (kernel_tier — the active CUP2D_PALLAS megakernel
# latch — and prec_mode, the CUP2D_PREC storage-precision contract,
# PR 9); v7 the continuous-batching serving gauges (active_members /
# occupancy / admitted / evicted / queue_depth — the FleetServer
# slot-pool lifecycle, fleet.py); v8 the boundary-condition attribution
# pair (bc_table — the driver's BCTable token, e.g. "fs,fs,fs,fs" —
# and case, the case-registry tag or null for ad-hoc runs, bc.py +
# cases.py); v9 the host-redundant mirror-tier group (mirror_bytes /
# mirror_ms / restore_source — the neighbor-mirrored snapshot ring and
# the rung attribution of elastic recoveries, PR 17); v10 the
# flight-recorder gauges (span_count / compile_ms_total /
# hbm_exec_bytes — the tracing.FlightRecorder span ring and
# compile/memory ledger, PR 18); v11 the smoother-tier attribution
# (smoother_tier — the pressure hierarchy's sweep-chain latch, xla |
# strip | strip+bf16 with "+bf16" suffixing whatever base the shape
# gate left armed, ISSUE 19); v12 a VALUE-vocabulary rev, no key
# moved (ISSUE 20): poisson_mode gains the uniform-family direct
# tokens "fftd" / "fftd+tridiag" (FFT-diagonalized per-mode solves,
# poisson_iters == 1 by contract, precond_cycles == 0) and bc_table
# gains the "pd" periodic face token ("pd,pd,pd,pd" turbulence box,
# "pd,pd,ns,ns" periodic channel).
_SCHEMA_V12_KEYS = (
    "schema", "step", "t", "dt", "wall_ms",
    "umax", "dt_next",
    "poisson_iters", "poisson_residual",
    "poisson_converged", "poisson_stalled",
    "poisson_mode", "precond_cycles",
    "kernel_tier", "prec_mode",
    "smoother_tier",
    "bc_table", "case",
    "energy", "div_linf",
    "n_blocks", "blocks_per_level", "refines", "coarsens",
    "halo_real_bytes", "halo_padded_bytes",
    "jit_compiles", "device_gets", "state_gathers", "hbm_peak_bytes",
    "snap_ring_bytes", "replayed_steps",
    "topology_epoch", "remesh_count", "remesh_ms",
    "mirror_bytes", "mirror_ms", "restore_source",
    "fleet_members", "member_steps_per_s", "member_health",
    "active_members", "occupancy", "admitted", "evicted",
    "queue_depth",
    "span_count", "compile_ms_total", "hbm_exec_bytes",
    "phase_ms",
)


def test_metrics_schema_v12_key_set_pinned():
    from cup2d_tpu.profiling import METRICS_SCHEMA_VERSION
    assert METRICS_SCHEMA_VERSION == 12
    assert METRICS_KEYS == _SCHEMA_V12_KEYS


@pytest.mark.slow   # ~17 s; duplicative tier-1 coverage: the frozen key
#                     SET is pinned as a literal tuple in
#                     test_metrics_schema_v12_key_set_pinned and the
#                     uniform producer stream (every record, key-exact)
#                     in test_cli_metrics_stream_and_post_report; the
#                     AMR/bench records drilled here ride the identical
#                     MetricsRecorder.record_step path
def test_metrics_schema_stable_uniform_amr_bench():
    gold = set(METRICS_KEYS)

    # uniform driver path
    sim = _sim()
    rec = MetricsRecorder()
    rec.prime(sim)
    r = rec.record(sim, sim.step_once())
    assert set(r) == gold
    # the dt baseline was primed: the first record carries a real dt
    assert r["dt"] is not None and r["dt"] > 0
    assert r["energy"] > 0 and r["div_linf"] >= 0
    assert r["n_blocks"] is None        # uniform: AMR fields null
    # schema v4 solve-path attribution: the driver's latch string and
    # the cycle count riding the same diag (BiCGSTAB applies the MG
    # preconditioner twice per iteration)
    assert r["poisson_mode"] == "bicgstab+mg"
    assert r["precond_cycles"] == 2 * r["poisson_iters"]
    # schema v6 kernel-tier attribution: the driver's constructor
    # latches ride the same pull (default environment: XLA tier, and
    # prec_mode reports the f64 state dtype of _cfg)
    assert r["kernel_tier"] == "xla"
    assert r["prec_mode"] == "f64"
    # schema v8 BC attribution: the default table's token, and no case
    # tag on an ad-hoc (non-registry) run
    assert r["bc_table"] == "fs,fs,fs,fs"
    assert r["case"] is None

    # forest driver path
    asim = _amr_sim()
    asim.initialize()
    arec = MetricsRecorder()
    arec.prime(asim)
    ar = arec.record(asim, asim.step_once())
    assert set(ar) == gold
    assert ar["n_blocks"] > 0
    assert sum(ar["blocks_per_level"].values()) == ar["n_blocks"]
    assert ar["energy"] > 0
    # forest attribution: default latch, exact first step = two-level
    # coarse operand on, 2 M-applies/iter + the x0 = M(b) application
    assert ar["poisson_mode"] == "bicgstab+jacobi"
    assert ar["precond_cycles"] == 2 * ar["poisson_iters"] + 1

    # bench path (record_step without a sim): same key set, so a
    # BENCH_*.json telemetry block and a run's metrics.jsonl are one
    # schema
    host_diag = {k: r[k] for k in ("umax", "dt_next", "poisson_iters",
                                   "poisson_residual",
                                   "poisson_converged",
                                   "poisson_stalled", "energy",
                                   "div_linf")}
    br = MetricsRecorder().record_step(step=1, t=0.1, dt=0.1,
                                       diag=host_diag, wall_ms=2.0)
    assert set(br) == gold


def test_metrics_forest_fas_mode_strings(monkeypatch):
    """Schema v8 KEY set is frozen, but PR 13 grew the poisson_mode
    VALUE vocabulary: a fas/fas-f-latched forest driver must stamp
    "fas+forest" / "fas-f+forest" on its records (the "+forest" suffix
    keeps the forest FAS hierarchy distinguishable from the uniform
    path's plain "fas"/"fas-f" in merged fleet streams), and the FAS
    full-solver convention precond_cycles == poisson_iters (one cycle
    per outer iteration — no Krylov wrapper doubling) must ride the
    diag unchanged. Recorder-level: the driver-side cycle accounting
    itself is pinned by test_forest_fas_matches_krylov_pressure."""
    from cup2d_tpu.amr import AMRSim
    cfg = SimConfig(bpdx=1, bpdy=1, level_max=2, level_start=1,
                    extent=1.0, dtype="float64")
    for tok, mode in (("fas", "fas+forest"), ("fas-f", "fas-f+forest")):
        monkeypatch.setenv("CUP2D_POIS", tok)
        sim = AMRSim(cfg, shapes=[])
        r = MetricsRecorder().record_step(
            step=1, t=0.1, dt=0.1, sim=sim,
            diag={"poisson_iters": 3, "precond_cycles": 3,
                  "poisson_converged": True})
        assert set(r) == set(METRICS_KEYS)      # no new keys rode in
        assert r["poisson_mode"] == mode
        assert r["precond_cycles"] == r["poisson_iters"] == 3
    # the fft latch keeps its pre-PR-13 string: the vocabulary grew,
    # existing values did not move
    monkeypatch.setenv("CUP2D_POIS", "fft")
    sim = AMRSim(cfg, shapes=[])
    assert sim.poisson_mode == "bicgstab+fft"


def test_metrics_kernel_tier_bc_suffix(monkeypatch):
    """Schema v8 KEY set is frozen, but ISSUE 16 grew the kernel_tier
    VALUE vocabulary: a BC'd sim on the fused tier stamps the literal
    "pallas-fused+bc(<token>)" — captured at DISPATCH via the guard's
    _Pending slot (PR-6 pattern: the tier the step actually RAN with,
    immune to a drain-time latch change) and mirrored by the recorder's
    diag-first pull — alongside the v8 bc_table token it suffixes. The
    default free-slip table keeps the bare PR-9 string (pinned above in
    test_metrics_schema_stable_uniform_amr_bench)."""
    from cup2d_tpu.cases import cavity_table
    from cup2d_tpu.uniform import UniformSim, taylor_green_state
    monkeypatch.setenv("CUP2D_PALLAS", "1")
    monkeypatch.delenv("CUP2D_PREC", raising=False)
    cfg = _cfg(dtype="float32", nu=4e-5, max_poisson_iterations=60)
    sim = UniformSim(cfg, level=2, bc=cavity_table(1.0))
    assert sim.kernel_tier == "pallas-fused+bc(ns,ns,ns,ns(1,0))"
    sim.state = taylor_green_state(sim.grid)
    rec = MetricsRecorder()
    rec.prime(sim)
    r = rec.record(sim, sim.step_once(0.25 * sim.grid.h))
    assert r["kernel_tier"] == "pallas-fused+bc(ns,ns,ns,ns(1,0))"
    assert r["prec_mode"] == "f32"
    assert r["bc_table"] == "ns,ns,ns,ns(1,0)"


def test_metrics_jsonl_stream_and_summary(tmp_path):
    sink = EventLog(str(tmp_path / "metrics.jsonl"))
    sim = _sim()
    rec = MetricsRecorder(sink=sink)
    rec.prime(sim)
    for _ in range(3):
        rec.record(sim, sim.step_once(), wall_ms=1.5)
    sink.close()
    recs = load_metrics(str(tmp_path / "metrics.jsonl"))
    ms = [r for r in recs if r.get("event") == "metrics"]
    assert [r["step"] for r in ms] == [1, 2, 3]
    # the stream carries the schema keys plus the EventLog envelope
    assert set(ms[0]) - {"event", "wall"} == set(METRICS_KEYS)
    s = summarize_metrics(recs)
    assert s["steps"] == 3
    assert s["t_final"] == pytest.approx(sim.time)
    assert s["poisson_iters"]["max"] >= s["poisson_iters"]["mean"] > 0
    assert s["energy_last"] > 0
    assert s["wall_ms"]["mean"] == pytest.approx(1.5)


# ---------------------------------------------------------------------------
# physics-invariant watchdog
# ---------------------------------------------------------------------------

def test_watchdog_policy_unit():
    wd = PhysicsWatchdog(window=3, energy_factor=4.0, div_factor=50.0)
    # warm-up: no verdicts until the window is full of good steps
    assert wd.check({"energy": 100.0, "div_linf": 100.0}) is None
    for _ in range(3):
        wd.observe({"umax": 2.0, "energy": 1.0, "div_linf": 0.1})
    assert wd.check({"umax": 2.1, "energy": 1.2,
                     "div_linf": 0.12}) is None
    # umax jump flags first (it is the earliest-armed invariant)
    assert wd.check({"umax": 20.0, "energy": 1.0}) == "invariant_umax"
    # energy jump and collapse both flag
    assert wd.check({"energy": 5.0}) == "invariant_energy"
    assert wd.check({"energy": 0.2}) == "invariant_energy"
    # divergence blow-up flags; inside the bound does not
    assert wd.check({"energy": 1.0, "div_linf": 6.0}) \
        == "invariant_divergence"
    assert wd.check({"energy": 1.0, "div_linf": 4.0}) is None
    # a flagged step must never enter its own baseline: the window
    # still describes the good history
    assert wd.check({"energy": 1.1}) is None
    wd.reset()
    assert wd.check({"energy": 50.0}) is None   # cleared = warm-up again


def test_watchdog_unsettled_signal_stays_dormant():
    """Relative drift bounds on an unsettled invariant are meaningless
    (spin-up from rest legitimately multiplies the energy per step —
    a dt/2 retry measured 8x the window max on the fish case), so an
    invariant whose window is not settled must NOT arm: a full window
    of exponential growth never fires, while the settled umax band
    still catches the same corruption."""
    wd = PhysicsWatchdog(window=4, energy_settle=2.0)
    for k in range(4):
        wd.observe({"energy": 10.0 ** k, "umax": 1.0})
    # energy window spans 1..1000 (ratio 1000 > settle 2): dormant even
    # for a 100x jump...
    assert wd.check({"energy": 1e5}) is None
    # ...but the settled umax band catches the same corrupted step
    assert wd.check({"energy": 1e5, "umax": 10.0}) == "invariant_umax"


def test_watchdog_catches_injected_finite_corruption(tmp_path):
    """faults.scale_vel multiplies the velocity x10 — finite
    everywhere, invisible to the isfinite verdict — and the watchdog
    flags it within its window; the ladder's rewind-retry recovers."""
    sim = _sim()
    log = EventLog(str(tmp_path / "events.jsonl"))
    guard = StepGuard(sim, watchdog=PhysicsWatchdog(window=4),
                      faults=FaultPlan("scale_vel@6"), event_log=log)
    for _ in range(8):
        guard.step()
    with open(tmp_path / "events.jsonl") as f:
        evs = [json.loads(line) for line in f if line.strip()]
    recov = [e for e in evs if e.get("event") == "recovery"]
    assert [e["action"] for e in recov] == ["retry"]
    assert recov[0]["step"] == 6
    assert recov[0]["verdict"].startswith("invariant_")
    assert sim.step_count == 8
    assert np.all(np.isfinite(np.asarray(sim.state.vel)))


def test_fault_plan_scale_vel_parse():
    p = FaultPlan("scale_vel@4*2")
    assert p.vel_scale[4] == [10.0, 2]
    assert bool(p)
    with pytest.raises(ValueError):
        FaultPlan("scale_vel")             # step is required


# ---------------------------------------------------------------------------
# zero-extra-sync contract: metrics-on == metrics-off, bit for bit,
# with EQUAL device_get counts (the PR-2 harness extended to the full
# telemetry stack: recorder + counters + watchdog)
# ---------------------------------------------------------------------------

def test_metrics_on_bit_identical_equal_pulls(tmp_path, monkeypatch):
    traces = {"n": 0}
    orig_impl = Simulation._flow_step_impl

    def counted_impl(self, *a, **k):
        traces["n"] += 1
        return orig_impl(self, *a, **k)

    monkeypatch.setattr(Simulation, "_flow_step_impl", counted_impl)

    def run(telemetry):
        sim = _sim()
        counters = guard = rec = None
        if telemetry:
            counters = HostCounters().install()
            sink = EventLog(str(tmp_path / "metrics.jsonl"))
            rec = MetricsRecorder(sink=sink, counters=counters)
            rec.prime(sim)
            guard = StepGuard(sim, watchdog=PhysicsWatchdog())
        pulls = {"n": 0}
        real_get = jax.device_get

        def counting_get(x):
            pulls["n"] += 1
            return real_get(x)

        t0 = traces["n"]
        try:
            with monkeypatch.context() as m:
                m.setattr(jax, "device_get", counting_get)
                for _ in range(5):
                    if telemetry:
                        rec.record(sim, guard.step())
                    else:
                        sim.step_once()
        finally:
            if counters is not None:
                counters.uninstall()
        return (np.asarray(sim.state.vel), np.asarray(sim.state.pres),
                sim.time, pulls["n"], traces["n"] - t0)

    va, pa, ta, pulls_a, traces_a = run(False)
    vb, pb, tb, pulls_b, traces_b = run(True)
    assert np.array_equal(va, vb)
    assert np.array_equal(pa, pb)
    assert ta == tb
    # the whole telemetry stack rides the step's existing batched pull:
    # no extra device_get, no extra trace of the step function
    assert pulls_b == pulls_a
    assert traces_b == traces_a


@pytest.mark.slow   # ~13 s; duplicative tier-1 coverage: the no-extra-
#                     device_get contract is pinned on the Simulation
#                     family by test_metrics_on_bit_identical_equal_
#                     pulls, and the lagged AMR path's pull accounting
#                     by test_snapshot_ring (device_gets == n,
#                     state_gathers == 0 on every record)
def test_metrics_no_second_pull_on_device_diag(monkeypatch):
    """The obstacle-free AMR step deliberately keeps its diag scalars
    ON DEVICE; the guard's LAGGED verdict pulls them once (batched,
    after the next step's dispatch), and the guard must hand those host
    values to the recorder — metrics-on must not re-pull what the
    verdict already fetched (code review PR 3; lagged since PR 4)."""
    from cup2d_tpu.amr import AMRSim
    cfg = SimConfig(bpdx=1, bpdy=1, level_max=2, level_start=1,
                    extent=1.0, dtype="float64", nu=1e-3,
                    max_poisson_iterations=40)
    def run(metrics):
        rng = np.random.default_rng(0)
        sim = AMRSim(cfg, shapes=[])
        f = sim.forest
        f.fields["vel"] = f.fields["vel"] + jnp.asarray(
            0.1 * rng.standard_normal(f.fields["vel"].shape))
        guard = StepGuard(sim)
        rec = MetricsRecorder(guard=guard) if metrics else None
        pulls = {"n": 0}
        real_get = jax.device_get

        def counting_get(x):
            pulls["n"] += 1
            return real_get(x)

        def record(r):
            if rec is not None and r is not None:
                rec.record_step(step=r["step"], t=r["t"], dt=r["dt"],
                                diag=r, sim=sim)

        with monkeypatch.context() as m:
            m.setattr(jax, "device_get", counting_get)
            for _ in range(3):
                record(guard.step())
            for r in guard.drain():     # the final lagged verdict
                record(r)
        assert sim.step_count == 3
        return pulls["n"]

    assert run(True) == run(False)


# ---------------------------------------------------------------------------
# CI guard: steady-state steps compile NOTHING and pull a bounded count
# ---------------------------------------------------------------------------

def test_steady_state_zero_recompiles_bounded_transfers():
    sim = _sim()
    sim.compute_forces_every = 0
    for _ in range(3):
        sim.step_once()                    # warm every executable
    c = HostCounters().install()
    try:
        n = 4
        for _ in range(n):
            sim.step_once()
    finally:
        c.uninstall()
    # a steady-state step must be a pure cache hit: one XLA compile
    # here means a shape/static-arg leak (the r1 per-count retrace bug
    # class) — and it would cost minutes per occurrence through the
    # remote-compile tunnel
    assert c.jit_compiles == 0
    # the hot-path pull discipline: exactly TWO batched device_gets per
    # shaped uniform step (the rasterize scalar sync + the step's one
    # diag/uvw pull); anything above means a new per-step round trip
    # leaked in (~100 ms each through the TPU tunnel)
    assert c.device_gets == 2 * n


# ---------------------------------------------------------------------------
# phase timers: fence exists, attributes, and the report covers phases
# ---------------------------------------------------------------------------

def test_phase_timers_fence_and_report():
    sim = _sim()
    sim.timers = PhaseTimers()          # pre-PR3 this crashed: only
    sim.step_once()                     # _NullTimers had fence()
    rep = sim.timers.report()
    for phase in ("rasterize", "flow"):
        assert phase in rep and rep[phase]["count"] == 1
    # fence passes arrays through unchanged (same contract as
    # NULL_TIMERS) and accepts pytrees
    x = jnp.ones(3)
    out = sim.timers.fence("x", x, {"a": x})
    assert out[0] is x
    assert NULL_TIMERS.fence("x", x)[0] is x


@pytest.mark.slow   # ~11-25 s (fresh AMR init); the fence mechanism
#                     itself is tier-1-covered by the uniform test above
def test_phase_timers_fence_amr():
    sim = _amr_sim()
    sim.timers = PhaseTimers()
    sim.initialize()
    sim.adapt()
    sim.step_once()
    rep = sim.timers.report()
    assert "tables" in rep and "flow" in rep


# ---------------------------------------------------------------------------
# windowed device tracing
# ---------------------------------------------------------------------------

def test_trace_window_parse(monkeypatch, tmp_path):
    monkeypatch.delenv("CUP2D_TRACE", raising=False)
    assert TraceWindow.from_env() is None
    monkeypatch.setenv("CUP2D_TRACE", f"2:4:{tmp_path}/tr")
    tw = TraceWindow.from_env()
    assert (tw.start, tw.stop, tw.logdir) == (2, 4, f"{tmp_path}/tr")
    monkeypatch.setenv("CUP2D_TRACE", "7:9")
    assert TraceWindow.from_env().logdir == "trace"
    for bad in ("5", "4:2", "a:b", "-1:3"):
        monkeypatch.setenv("CUP2D_TRACE", bad)
        with pytest.raises(ValueError):
            TraceWindow.from_env()


def test_trace_window_wraps_exact_steps(tmp_path):
    logdir = str(tmp_path / "trace")
    tw = TraceWindow(1, 3, logdir)
    f = jax.jit(lambda x: x * 2.0)
    x = jnp.ones(16)
    seen = []
    for step in range(5):
        tw.maybe_start(step)
        seen.append(tw.active)
        x = f(x)
        tw.maybe_stop(step + 1)
    tw.close()
    # active exactly while stepping steps 1 and 2
    assert seen == [False, True, True, False, False]
    assert tw.done and not tw.active
    # the trace actually materialized (TensorBoard xplane dump)
    assert glob.glob(os.path.join(logdir, "plugins", "profile",
                                  "*", "*")), \
        "trace window left no profile dump"


# ---------------------------------------------------------------------------
# CLI end-to-end (in-process): metrics stream + post --metrics report
# ---------------------------------------------------------------------------

def test_cli_metrics_stream_and_post_report(tmp_path, monkeypatch,
                                            capsys):
    from cup2d_tpu import post
    from cup2d_tpu.__main__ import main

    monkeypatch.delenv("CUP2D_FAULTS", raising=False)
    monkeypatch.delenv("CUP2D_TRACE", raising=False)
    out = tmp_path / "run"
    rc = main([
        "-bpdx", "1", "-bpdy", "1", "-levelMax", "1", "-levelStart", "0",
        "-Rtol", "2", "-Ctol", "1", "-extent", "1", "-CFL", "0.4",
        "-tend", "1", "-lambda", "1e6", "-nu", "0.001",
        "-poissonTol", "1e-3", "-poissonTolRel", "1e-2",
        "-maxPoissonRestarts", "0", "-maxPoissonIterations", "100",
        "-AdaptSteps", "20", "-tdump", "0", "-level", "3",
        "-dtype", "float64",
        "-shapes", "angle=0 L=0.25 xpos=0.5 ypos=0.5",
        "-output", str(out), "-maxSteps", "3",
    ])
    assert rc == 0
    recs = load_metrics(str(out / "metrics.jsonl"))
    ms = [r for r in recs if r.get("event") == "metrics"]
    assert [r["step"] for r in ms] == [1, 2, 3]
    assert set(ms[0]) - {"event", "wall"} == set(METRICS_KEYS)
    # per-step counters came through the CLI's HostCounters install
    assert all(r["device_gets"] is not None for r in ms)
    assert ms[-1]["jit_compiles"] == 0     # steady state by step 3
    capsys.readouterr()
    rc = post.main(["--metrics", str(out / "metrics.jsonl")])
    assert rc == 0
    summary = json.loads(
        capsys.readouterr().out.strip().splitlines()[-1])
    assert summary["steps"] == 3
    assert summary["source"].endswith("metrics.jsonl")
