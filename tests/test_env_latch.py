"""Static guard for the env-latching convention (ADVICE r5 / PR 1).

Every CUP2D_* environment gate must be LATCHED — read exactly once at a
sanctioned construction/enable point and stored — never consulted
mid-run: a read inside a jitted body or a per-refresh helper means a
mid-run env mutation silently flips an operator/preconditioner form at
the next retrace or regrid (the hazard class CUP2D_SHARD_EXCHANGE and
CUP2D_POIS/CUP2D_TWOLEVEL were each fixed for). This test walks the
package AST and fails on any CUP2D_* read outside the sanctioned latch
sites below — adding a new gate means adding a new latch site HERE, on
purpose, with a reason.
"""

import ast
import os

PKG = os.path.normpath(
    os.path.join(os.path.dirname(__file__), "..", "cup2d_tpu"))

# files where ANY CUP2D_* read is a sanctioned latch:
#   config.py — the typed-config construction point
SANCTIONED_FILES = {"config.py"}

# (file, enclosing scope) -> allowed vars. Each is a construct-once /
# enable-once latch, grandfathered with its reason:
SANCTIONED_SITES = {
    # A/B gates latched per-sim in the constructor (ADVICE r5).
    # CUP2D_POIS mode values: structured|tables|fft|fas|fas-f on the
    # forest (AMRSim validates; fas/fas-f select the forest-native FAS
    # full solver since PR 13), and fas|fas-f on the uniform family —
    # the UniformGrid constructor is the ONE uniform-side latch;
    # fleet.py and the parallel/ modules read the GRID's stored latch
    # and stay env-read-free (this walk enforces it).
    # CUP2D_PALLAS (PR 9): the forest's own fused-tier latch — the
    # lab-mode megakernel dispatch in _advect_rk2 reads the stored
    # self._kernel_tier, never the env
    ("amr.py", "AMRSim.__init__"): {"CUP2D_POIS", "CUP2D_TWOLEVEL",
                                    "CUP2D_PALLAS"},
    # per-grid constructor latches (stored as self._kernel_tier /
    # self.solver_mode+self.fas_fmg). CUP2D_PREC (PR 9) is the
    # storage-precision contract of the fused tier: ONE read site in
    # the whole package — fleet/mesh/bench consume the grid's stored
    # tier string, so a mid-run env mutation can never flip the
    # precision of a compiled step
    ("uniform.py", "UniformGrid.__init__"): {"CUP2D_PALLAS",
                                             "CUP2D_POIS",
                                             "CUP2D_PREC"},
    # the fault-injection latch (PR 7 tightened faults.py from a
    # whole-file sanction to this one scope): every injector —
    # including the elastic host_exit/host_hang tokens — parses from
    # the ONE plan FaultPlan.from_env constructs; consumers (StepGuard,
    # TopologyGuard, io's crash window) read the plan object, never the
    # env
    ("faults.py", "FaultPlan.from_env"): {"CUP2D_FAULTS"},
    # read once from ShardedAMRSim.__init__, stored as self._exchange
    ("parallel/forest_mesh.py", "_exchange_mode"):
        {"CUP2D_SHARD_EXCHANGE"},
    # windowed device tracing: latched once by the CLI before the run
    # loop (a mid-run mutation must not re-arm a finished window)
    ("profiling.py", "TraceWindow.from_env"): {"CUP2D_TRACE"},
    # enable-once process knobs (cache paths, not numerics gates)
    ("cache.py", "enable_compilation_cache"): {"CUP2D_CACHE"},
    ("native/__init__.py", "_load"): {"CUP2D_NATIVE_CACHE"},
}


def _env_var_of(node):
    """Return the env var name a node reads, or None. Catches
    os.environ[...] / os.environ.get|pop|setdefault(...) / os.getenv(...)
    (and the bare `environ`/`getenv` import-form spellings)."""
    def is_environ(n):
        return (isinstance(n, ast.Attribute) and n.attr == "environ") \
            or (isinstance(n, ast.Name) and n.id == "environ")

    def const(n):
        return n.value if (isinstance(n, ast.Constant)
                           and isinstance(n.value, str)) else "<dynamic>"

    if isinstance(node, ast.Subscript) and is_environ(node.value):
        return const(node.slice)
    if isinstance(node, ast.Call):
        f = node.func
        envget = (isinstance(f, ast.Attribute)
                  and f.attr in ("get", "pop", "setdefault")
                  and is_environ(f.value))
        getenv = ((isinstance(f, ast.Attribute) and f.attr == "getenv")
                  or (isinstance(f, ast.Name) and f.id == "getenv"))
        if envget or getenv:
            return const(node.args[0]) if node.args else "<dynamic>"
    return None


def _cup2d_env_reads(path):
    """(scope, var, lineno) for every constant CUP2D_* env read."""
    with open(path) as f:
        tree = ast.parse(f.read(), filename=path)
    out = []

    def visit(node, scope):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            scope = scope + [node.name]
        var = _env_var_of(node)
        if var is not None and var.startswith("CUP2D_"):
            out.append((".".join(scope) or "<module>", var, node.lineno))
        for child in ast.iter_child_nodes(node):
            visit(child, scope)

    visit(tree, [])
    return out


def test_cup2d_env_reads_only_at_latch_points():
    violations = []
    for dirpath, dirnames, filenames in os.walk(PKG):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            full = os.path.join(dirpath, fn)
            rel = os.path.relpath(full, PKG).replace(os.sep, "/")
            if rel in SANCTIONED_FILES:
                continue
            allowed_by_scope = {scope: vars_
                                for (f, scope), vars_
                                in SANCTIONED_SITES.items() if f == rel}
            for scope, var, line in _cup2d_env_reads(full):
                if var in allowed_by_scope.get(scope, ()):
                    continue
                violations.append(
                    f"cup2d_tpu/{rel}:{line} reads {var} in {scope}")
    assert not violations, (
        "CUP2D_* env vars must be read ONCE at a sanctioned latch point "
        "(config.py / AMRSim.__init__ / faults.py / the grandfathered "
        "sites in tests/test_env_latch.py), never mid-run:\n  "
        + "\n  ".join(violations))


def test_latch_allowlist_matches_reality():
    """The sanctioned-site table must not rot: every grandfathered
    (file, scope, var) entry still exists — a refactor that moves a
    latch must move its allowlist row too, keeping the table an
    accurate map of where gates live."""
    for (rel, scope), vars_ in SANCTIONED_SITES.items():
        reads = _cup2d_env_reads(os.path.join(PKG, rel))
        found = {v for s, v, _ in reads if s == scope}
        assert vars_ <= found, (
            f"cup2d_tpu/{rel} scope {scope}: expected latched reads of "
            f"{sorted(vars_)}, found {sorted(found)}")
