"""Env gates are latched ONCE at sanctioned sites (thin wrapper).

The bespoke AST walk that lived here since PR 2 moved into the
graftlint framework (``cup2d_tpu.analysis``): the sanctioned-site
table is now ``analysis/policy.py`` data (the single source of truth
— there is deliberately no second copy in this file), the walk is the
``env-latch`` rule, and this test just asserts the rule runs clean on
the package. The old reality check (every allowlist row still names a
real latch) is the rule's finalize pass: a stale row IS a finding, so
the clean assertion covers it; the monkeypatch test below proves the
detector actually fires.
"""

from cup2d_tpu.analysis import lint_package, policy


def test_cup2d_env_reads_only_at_latch_points():
    report = lint_package(only=["env-latch"])
    assert report.clean, "\n".join(str(f) for f in report.findings)


def test_latch_allowlist_matches_reality(monkeypatch):
    # the finalize pass flags policy rows that stopped matching the
    # code — prove it by planting a row for a latch that doesn't exist
    bogus = dict(policy.ENV_LATCH_SITES)
    bogus[("cache.py", "enable_compilation_cache")] = (
        bogus[("cache.py", "enable_compilation_cache")]
        | {"CUP2D_NO_SUCH_GATE"})
    monkeypatch.setattr(policy, "ENV_LATCH_SITES", bogus)
    report = lint_package(only=["env-latch"])
    stale = [f for f in report.findings if "stale policy row" in f.message]
    assert stale, "planted stale allowlist row was not detected"
    assert any("CUP2D_NO_SUCH_GATE" in f.message for f in stale)
