"""Supervised run loop tests (resilience.py + faults.py): every rung of
the recovery ladder exercised by fault injection, the crash-mid-save
window, SIGTERM preemption through the CLI, coordinator connect
backoff, and the zero-overhead contract of the health verdict (an
unfaulted guarded run is bit-identical and adds no device pulls or
retraces)."""

import json
import os
import subprocess
import sys

import jax
import numpy as np
import pytest

from cup2d_tpu import faults as faults_mod
from cup2d_tpu.config import SimConfig
from cup2d_tpu.faults import FaultPlan, InjectedCrash
from cup2d_tpu.io import load_checkpoint, save_checkpoint
from cup2d_tpu.models import DiskShape
from cup2d_tpu.resilience import (EventLog, ResilienceAbort, StepGuard,
                                  health_verdict, set_event_log)
from cup2d_tpu.sim import Simulation


def _cfg(**kw):
    base = dict(bpdx=1, bpdy=1, level_max=1, level_start=0, extent=1.0,
                nu=1e-3, cfl=0.4, lam=1e6, dtype="float64",
                max_poisson_iterations=100)
    base.update(kw)
    return SimConfig(**base)


def _sim():
    disk = DiskShape(0.1, 0.4, 0.5, prescribed=(0.2, 0.0))
    return Simulation(_cfg(), shapes=[disk], level=3)


def _amr_cfg():
    return SimConfig(bpdx=1, bpdy=1, level_max=3, level_start=1,
                     extent=1.0, dtype="float64", nu=1e-3, lam=1e5,
                     rtol=0.5, ctol=0.05, max_poisson_iterations=40,
                     poisson_tol=1e-4, poisson_tol_rel=1e-3)


def _amr_sim():
    from cup2d_tpu.amr import AMRSim
    sim = AMRSim(_amr_cfg(), shapes=[DiskShape(0.08, 0.4, 0.5,
                                               prescribed=(0.2, 0.0))])
    sim.compute_forces_every = 0
    return sim


def _recoveries(path):
    with open(path) as f:
        evs = [json.loads(line) for line in f if line.strip()]
    return [e for e in evs if e.get("event") == "recovery"]


def _guard(sim, tmp_path, plan=None, **kw):
    log = EventLog(str(tmp_path / "events.jsonl"))
    return StepGuard(sim, event_log=log, faults=plan, **kw), \
        str(tmp_path / "events.jsonl")


# ---------------------------------------------------------------------------
# verdict policy (pure unit)
# ---------------------------------------------------------------------------

def test_health_verdict_policy():
    ok = dict(finite=True, umax=1.0, poisson_converged=True,
              poisson_stalled=False, poisson_residual=1e-5)
    assert health_verdict(ok).ok
    # Inf/NaN anywhere in vel/pres -> nonfinite (the old inline driver
    # check umax != umax missed Inf)
    assert health_verdict({**ok, "finite": False}).reason == "nonfinite"
    # no finite flag at all: fall back to umax self-check
    assert health_verdict({"umax": float("inf")}).reason == "nonfinite"
    assert health_verdict({"umax": float("nan")}).reason == "nonfinite"
    assert health_verdict({"umax": 1.0}).ok
    # nonfinite residual is a solver failure even with finite fields
    bad_res = {**ok, "poisson_converged": False,
               "poisson_residual": float("nan")}
    assert health_verdict(bad_res).reason == "poisson_nonfinite"
    # neither converged nor stalled = give-up / exhaustion
    exh = {**ok, "poisson_converged": False, "poisson_stalled": False,
           "poisson_residual": 10.0}
    assert health_verdict(exh).reason == "poisson_exhausted"
    # ... unless the residual already sits near target (budget-capped
    # solve, reference-parity behavior)
    assert health_verdict({**exh, "poisson_residual": 1e-5},
                          residual_ok=1e-3).ok
    # a stalled exit is the precision floor, not a failure
    assert health_verdict({**ok, "poisson_converged": False,
                           "poisson_stalled": True}).ok


def test_fault_plan_parse():
    p = FaultPlan("nan_vel@3, poisson_giveup@5*2, sigterm@7,"
                  "crash_in_save")
    assert p.vel_poison[3][1] == 1
    assert np.isnan(p.vel_poison[3][0])
    assert p.giveup[5] == 2
    assert 7 in p.sigterm_steps
    assert p.crash_points["checkpoint_install"] == 1
    assert bool(p) and not bool(FaultPlan(""))
    assert p.poisson_giveup_at(5) and p.poisson_giveup_at(5)
    assert not p.poisson_giveup_at(5)      # count exhausted
    with pytest.raises(ValueError):
        FaultPlan("tyop_fault@3")          # typos must not silently arm
    with pytest.raises(ValueError):
        FaultPlan("nan_vel")               # step is required


# ---------------------------------------------------------------------------
# zero-overhead contract: bit-identical, no extra pulls, no retraces
# ---------------------------------------------------------------------------

@pytest.mark.slow   # ~15 s; STRICT SUPERSET stays tier-1:
#                     test_telemetry.test_metrics_on_bit_identical_
#                     equal_pulls runs the same shaped driver with
#                     guard + recorder + counters + watchdog and
#                     asserts the same bit-identity/pull/trace set
def test_guard_unfaulted_bit_identical_uniform(tmp_path, monkeypatch):
    traces = {"n": 0}
    orig_impl = Simulation._flow_step_impl

    def counted_impl(self, *a, **k):
        traces["n"] += 1
        return orig_impl(self, *a, **k)

    monkeypatch.setattr(Simulation, "_flow_step_impl", counted_impl)

    def run(guarded):
        sim = _sim()
        guard = StepGuard(sim) if guarded else None
        pulls = {"n": 0}
        real_get = jax.device_get

        def counting_get(x):
            pulls["n"] += 1
            return real_get(x)

        t0 = traces["n"]
        with monkeypatch.context() as m:
            m.setattr(jax, "device_get", counting_get)
            for _ in range(5):
                guard.step() if guarded else sim.step_once()
        return (np.asarray(sim.state.vel), np.asarray(sim.state.pres),
                sim.time, pulls["n"], traces["n"] - t0)

    va, pa, ta, pulls_a, traces_a = run(False)
    vb, pb, tb, pulls_b, traces_b = run(True)
    assert np.array_equal(va, vb)
    assert np.array_equal(pa, pb)
    assert ta == tb
    # the verdict rides the step's existing batched pull: no extra
    # device_get, no extra trace of the step function
    assert pulls_b == pulls_a
    assert traces_b == traces_a


@pytest.mark.slow   # ~43 s; the zero-overhead contract stays tier-1 on
#                     the uniform path (above + the telemetry-stack
#                     variant in test_telemetry.py)
def test_guard_unfaulted_bit_identical_amr(tmp_path, monkeypatch):
    from cup2d_tpu.amr import AMRSim

    traces = {"n": 0}
    orig_impl = AMRSim._megastep_impl

    def counted_impl(self, *a, **k):
        traces["n"] += 1
        return orig_impl(self, *a, **k)

    monkeypatch.setattr(AMRSim, "_megastep_impl", counted_impl)

    def run(guarded):
        sim = _amr_sim()
        sim.initialize()
        guard = StepGuard(sim) if guarded else None
        pulls = {"n": 0}
        real_get = jax.device_get

        def counting_get(x):
            pulls["n"] += 1
            return real_get(x)

        t0 = traces["n"]
        with monkeypatch.context() as m:
            m.setattr(jax, "device_get", counting_get)
            for _ in range(3):
                guard.step() if guarded else sim.step_once()
        vel = np.asarray(sim.fields()["vel"][sim.forest.order()])
        return vel, sim.time, pulls["n"], traces["n"] - t0

    va, ta, pulls_a, traces_a = run(False)
    vb, tb, pulls_b, traces_b = run(True)
    assert np.array_equal(va, vb)
    assert ta == tb
    assert pulls_b == pulls_a
    assert traces_b == traces_a


# ---------------------------------------------------------------------------
# the recovery ladder, rung by rung
# ---------------------------------------------------------------------------

def _drive_to(sim, tend, stepper):
    """Advance to EXACTLY tend (last dt clipped) so faulted and
    unfaulted runs are compared at the same physical time — a dt/2
    recovery step otherwise offsets the whole time grid."""
    while sim.time < tend:
        if sim._next_dt is not None:
            dt = min(sim._next_dt, sim._kinematic_dt_cap())
        else:
            dt = min(float(sim._dt(sim.state.vel)),
                     sim._kinematic_dt_cap())
        stepper(min(dt, tend - sim.time + 1e-15))


@pytest.mark.parametrize("directive", [
    "nan_vel@3",
    # ~29 s dup of the same rung: Inf-vs-NaN differs only inside
    # health_verdict, unit-covered by test_health_verdict_policy
    pytest.param("inf_vel@3", marks=pytest.mark.slow),
])
def test_rung1_poison_recovers_via_rewind(tmp_path, directive):
    tend = 0.3
    ref = _sim()
    _drive_to(ref, tend, lambda dt: ref.step_once(dt=dt))

    sim = _sim()
    guard, evpath = _guard(sim, tmp_path, plan=FaultPlan(directive),
                           ckpt_dir=None)
    _drive_to(sim, tend, lambda dt: guard.step(dt=dt))

    evs = _recoveries(evpath)
    assert [e["action"] for e in evs] == ["retry"]
    assert evs[0]["step"] == 3
    assert evs[0]["verdict"] == "nonfinite"
    vel = np.asarray(sim.state.vel)
    assert np.all(np.isfinite(vel))
    assert abs(sim.time - ref.time) < 1e-12
    # recovered trajectory lands inside the golden-trajectory-style
    # tolerances of the unfaulted run (test_golden pins umax at rtol
    # 1e-3 mid-trajectory; measured here: ~7e-4). The full field keeps
    # a coarse bound only — the Brinkman-penalized body interior is
    # genuinely dt-sensitive (alpha = 1/(1+lam dt)), so one dt/2 step
    # legitimately perturbs it at the percent level while the flow
    # outside stays aligned.
    ref_v = np.asarray(ref.state.vel)
    assert abs(np.abs(vel).max() - np.abs(ref_v).max()) \
        <= 2e-3 * np.abs(ref_v).max()
    rel = np.linalg.norm(vel - ref_v) / max(np.linalg.norm(ref_v), 1e-30)
    assert rel < 0.05, rel


def test_rung2_escalates_to_exact_poisson(tmp_path):
    sim = _sim()
    # two consecutive forced give-ups at step 2: the rewind-retry rung
    # fails once, the exact-Poisson escalation clears it
    guard, evpath = _guard(sim, tmp_path,
                           plan=FaultPlan("poisson_giveup@2*2"))
    for _ in range(5):
        guard.step()
    evs = _recoveries(evpath)
    assert [e["action"] for e in evs] == ["retry", "escalate"]
    assert all(e["step"] == 2 for e in evs)
    assert all(e["verdict"] == "poisson_giveup(injected)" for e in evs)
    assert sim.step_count == 5
    assert not sim._force_exact        # restored after the escalation


def test_rung3_disk_restore_replays_bit_exactly(tmp_path):
    tend = 0.3
    ref = _sim()
    while ref.time < tend:
        ref.step_once()

    ck = str(tmp_path / "ck")
    sim = _sim()
    guard, evpath = _guard(sim, tmp_path,
                           plan=FaultPlan("poisson_giveup@4*3"),
                           ckpt_dir=ck)
    while sim.time < tend:
        guard.step()
        if sim.step_count == 2:
            save_checkpoint(ck, sim)
    evs = _recoveries(evpath)
    assert [e["action"] for e in evs] == \
        ["retry", "escalate", "disk_restore"]
    # after the disk restore the run replays steps 2..4 on the normal
    # path (the give-up budget is spent) — the bit-exact resume
    # contract makes the final state EQUAL to the unfaulted run
    assert np.allclose(np.asarray(sim.state.vel),
                       np.asarray(ref.state.vel), atol=1e-12)
    assert abs(sim.time - ref.time) < 1e-12


def test_rung4_abort_leaves_postmortem(tmp_path):
    sim = _sim()
    sim.force_log = open(tmp_path / "forces.csv", "w")
    pm = str(tmp_path / "postmortem")
    # re-poisoned on every attempt: nothing recovers, no disk rung
    guard, evpath = _guard(sim, tmp_path, plan=FaultPlan("nan_vel@1*4"),
                           postmortem_dir=pm)
    guard.step()
    with pytest.raises(ResilienceAbort):
        guard.step()
    evs = _recoveries(evpath)
    assert [e["action"] for e in evs] == ["retry", "escalate", "abort"]
    assert evs[-1]["postmortem"] == pm
    # the dead run left a loadable post-mortem checkpoint and a closed
    # force log (the old __main__ NaN abort leaked both)
    assert sim.force_log.closed
    fresh = _sim()
    load_checkpoint(pm, fresh)
    assert fresh.step_count == sim.step_count


@pytest.mark.slow   # ~30 s (deforming-fish init dominates); the
#                     ring-seed-after-blend ordering it pins is also
#                     load-bearing for every tier-1 rung test above
def test_first_step_failure_keeps_chi_blend(tmp_path):
    """The ring seed must be captured AFTER the lazy chi-blend
    initialization: restoring a pre-initialize snapshot marks the sim
    initialized (shapes restore), so a rewind after a FIRST-step
    failure would silently skip the blend — for a deforming fish the
    recovered trajectory forks from t=0 (code-review PR 2)."""
    from cup2d_tpu.models import FishShape

    def mk():
        cfg = _cfg()
        return Simulation(cfg, shapes=[FishShape(0.2, 0.5, 0.5, 0.0,
                                                 cfg.min_h)], level=3)

    sim = mk()
    guard, evpath = _guard(sim, tmp_path,
                           plan=FaultPlan("poisson_giveup@0"))
    guard.step()
    evs = _recoveries(evpath)
    assert [e["action"] for e in evs] == ["retry"] and evs[0]["step"] == 0
    # a fresh run, initialized then stepped once at the SAME (halved)
    # dt, must match the recovered state bit-for-bit
    ref = mk()
    ref.step_once(dt=sim.time)
    assert np.allclose(np.asarray(sim.state.vel),
                       np.asarray(ref.state.vel), atol=1e-14)


@pytest.mark.slow   # ~19 s; -noSupervise abort semantics stay tier-1
#                     end-to-end via test_cli_nan_abort_via_guard
def test_verdict_only_mode_aborts_first_failure(tmp_path):
    sim = _sim()
    pm = str(tmp_path / "postmortem")
    guard, evpath = _guard(sim, tmp_path, plan=FaultPlan("nan_vel@1"),
                           postmortem_dir=pm, recover=False)
    guard.step()
    with pytest.raises(ResilienceAbort):
        guard.step()
    evs = _recoveries(evpath)
    assert [e["action"] for e in evs] == ["abort"]
    assert os.path.exists(os.path.join(pm, "meta.json"))


# ---------------------------------------------------------------------------
# crash-mid-save window + .old fallback (io.py satellites)
# ---------------------------------------------------------------------------

def test_crash_mid_save_restores_old_bitexact(tmp_path, capsys):
    ck = str(tmp_path / "ck")
    sim = _sim()
    sim.step_once()
    sim.step_once()
    save_checkpoint(ck, sim)                     # v1, the survivor
    with np.load(os.path.join(ck, "fields.npz")) as d:
        v1 = {k: np.array(d[k]) for k in d.files}
    sim.step_once()
    faults_mod.install(FaultPlan("crash_in_save"))
    try:
        with pytest.raises(InjectedCrash):
            save_checkpoint(ck, sim)             # dies park->install
    finally:
        faults_mod.install(None)
    # the crash window: dirpath gone, the parked .old is complete
    assert not os.path.exists(os.path.join(ck, "meta.json"))
    assert os.path.exists(os.path.join(ck + ".old", "meta.json"))

    log = EventLog(str(tmp_path / "events.jsonl"))
    set_event_log(log)
    try:
        fresh = _sim()
        load_checkpoint(ck, fresh)
    finally:
        set_event_log(None)
        log.close()
    # loud fallback: stderr warning + resilience event
    assert "falling back" in capsys.readouterr().err
    with open(tmp_path / "events.jsonl") as f:
        evs = [json.loads(line) for line in f]
    assert any(e.get("event") == "checkpoint_fallback_old" for e in evs)
    # ... and the restored state is the parked copy, bit-exactly
    assert fresh.step_count == 2
    restored = {k: np.asarray(v)
                for k, v in fresh.state._asdict().items()}
    for k, v in v1.items():
        assert np.array_equal(restored[k], v), k


# ---------------------------------------------------------------------------
# SIGTERM preemption through the CLI (+ restart from its checkpoint)
# ---------------------------------------------------------------------------

def _cli_cmd(outdir, extra):
    return [
        sys.executable, "-m", "cup2d_tpu",
        "-bpdx", "1", "-bpdy", "1", "-levelMax", "1", "-levelStart", "0",
        "-Rtol", "2", "-Ctol", "1", "-extent", "1", "-CFL", "0.4",
        "-tend", "1", "-lambda", "1e6", "-nu", "0.001",
        "-poissonTol", "1e-3", "-poissonTolRel", "1e-2",
        "-maxPoissonRestarts", "0", "-maxPoissonIterations", "100",
        "-AdaptSteps", "20", "-tdump", "0", "-level", "3",
        "-dtype", "float64",
        "-shapes", "angle=0 L=0.25 xpos=0.5 ypos=0.5",
        "-output", str(outdir),
    ] + extra


def _run_cli(outdir, extra, fault=None):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PYTHONPATH", None)
    env.pop("CUP2D_FAULTS", None)
    if fault:
        env["CUP2D_FAULTS"] = fault
    return subprocess.run(_cli_cmd(outdir, extra), cwd="/root/repo",
                          env=env, timeout=400, capture_output=True,
                          text=True)


@pytest.mark.slow   # ~30 s, three CLI subprocesses (the smoke class
#                     the PR-3 satellite moves out of tier-1);
#                     test_cli_nan_abort_via_guard keeps a supervised
#                     CLI subprocess in tier-1
def test_sigterm_checkpoints_and_restart_resumes(tmp_path):
    out1 = tmp_path / "run1"
    out2 = tmp_path / "run2"
    out3 = tmp_path / "run3"

    # preempted run: SIGTERM after step 3 -> clean exit 0 + checkpoint
    r1 = _run_cli(out1, ["-maxSteps", "8"], fault="sigterm@3")
    assert r1.returncode == 0, r1.stderr[-2000:]
    assert "SIGTERM" in r1.stderr
    assert os.path.exists(out1 / "checkpoint" / "meta.json")
    with open(out1 / "events.jsonl") as f:
        evs = [json.loads(line) for line in f]
    sig = [e for e in evs if e.get("event") == "sigterm_checkpoint"]
    assert len(sig) == 1 and sig[0]["step"] == 3

    # resumed run continues to step 6 and checkpoints there
    r2 = _run_cli(out2, ["-maxSteps", "6", "-checkpointEvery", "6",
                         "-restart", str(out1 / "checkpoint")])
    assert r2.returncode == 0, r2.stderr[-2000:]
    # uninterrupted twin of the same case
    r3 = _run_cli(out3, ["-maxSteps", "6", "-checkpointEvery", "6"])
    assert r3.returncode == 0, r3.stderr[-2000:]

    with open(out2 / "checkpoint" / "meta.json") as f:
        m2 = json.load(f)
    with open(out3 / "checkpoint" / "meta.json") as f:
        m3 = json.load(f)
    assert m2["step_count"] == m3["step_count"] == 6
    assert m2["time"] == m3["time"]
    with np.load(out2 / "checkpoint" / "fields.npz") as a, \
            np.load(out3 / "checkpoint" / "fields.npz") as b:
        assert set(a.files) == set(b.files)
        for k in a.files:
            assert np.array_equal(a[k], b[k]), k


def test_cli_nan_abort_via_guard(tmp_path):
    """The old __main__ NaN check (missed Inf, leaked the force log,
    left no state behind) is routed through the guard's abort rung: a
    persistent Inf with supervision disabled exits 1 AND leaves a
    post-mortem checkpoint + abort event."""
    out = tmp_path / "run"
    r = _run_cli(out, ["-maxSteps", "6", "-noSupervise"],
                 fault="inf_vel@2")
    assert r.returncode == 1, r.stderr[-2000:]
    assert "unrecoverable" in r.stderr
    assert os.path.exists(out / "postmortem" / "meta.json")
    with open(out / "events.jsonl") as f:
        evs = [json.loads(line) for line in f]
    aborts = [e for e in evs if e.get("event") == "recovery"
              and e.get("action") == "abort"]
    assert len(aborts) == 1 and aborts[0]["verdict"] == "nonfinite"


# ---------------------------------------------------------------------------
# coordinator connect backoff (launch.py)
# ---------------------------------------------------------------------------

def test_connect_backoff_bounded_and_logged(tmp_path):
    from cup2d_tpu.parallel.launch import _connect_with_retry

    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise RuntimeError("connection refused")

    log = EventLog(str(tmp_path / "events.jsonl"))
    set_event_log(log)
    try:
        _connect_with_retry(flaky, attempts=5, backoff=0.001)
    finally:
        set_event_log(None)
        log.close()
    assert calls["n"] == 3
    with open(tmp_path / "events.jsonl") as f:
        evs = [json.loads(line) for line in f]
    retries = [e for e in evs if e.get("event") == "coordinator_retry"]
    assert [e["attempt"] for e in retries] == [1, 2]

    def dead():
        raise RuntimeError("unreachable")

    with pytest.raises(RuntimeError, match="unreachable"):
        _connect_with_retry(dead, attempts=3, backoff=0.0)
