"""Hilbert SFC tests — the oracle role `tool/curve.cpp` plays for the
reference (forward/inverse identity, curve continuity, encode ordering)."""

import numpy as np
import pytest

from cup2d_tpu.curve import SpaceCurve, _xy2d, _d2xy


@pytest.mark.parametrize("order", [0, 1, 2, 3, 5])
def test_xy2d_roundtrip(order):
    n = 1 << order
    ii, jj = np.meshgrid(np.arange(n), np.arange(n), indexing="ij")
    d = _xy2d(order, ii.ravel(), jj.ravel())
    # bijective onto [0, n^2)
    assert sorted(d.tolist()) == list(range(n * n))
    x, y = _d2xy(order, d)
    np.testing.assert_array_equal(x, ii.ravel())
    np.testing.assert_array_equal(y, jj.ravel())


def test_hilbert_continuity():
    """Consecutive curve indices are grid neighbors (locality — the property
    load balancing relies on)."""
    order = 4
    n = 1 << order
    x, y = _d2xy(order, np.arange(n * n))
    step = np.abs(np.diff(x)) + np.abs(np.diff(y))
    assert np.all(step == 1)


@pytest.mark.parametrize("bpdx,bpdy", [(1, 1), (2, 1), (2, 2), (3, 2), (4, 1)])
def test_forward_inverse_identity(bpdx, bpdy):
    sc = SpaceCurve(bpdx, bpdy, level_max=4)
    for level in range(3):
        nx, ny = sc.blocks_at(level)
        ii, jj = np.meshgrid(np.arange(nx), np.arange(ny), indexing="ij")
        z = sc.forward(level, ii.ravel(), jj.ravel())
        # bijective onto [0, nx*ny)
        assert sorted(z.tolist()) == list(range(nx * ny))
        x, y = sc.inverse(z, level)
        np.testing.assert_array_equal(x, ii.ravel())
        np.testing.assert_array_equal(y, jj.ravel())


def test_nonsquare_compaction():
    sc = SpaceCurve(2, 1, level_max=4)
    assert not sc.is_regular
    sc2 = SpaceCurve(2, 2, level_max=4)
    assert sc2.is_regular


def test_encode_unique_and_level_aware():
    """encode() must give globally unique keys; children must sort after
    their parent but before the parent's successor (depth-first curve
    ordering, reference main.cpp:422-445)."""
    sc = SpaceCurve(2, 1, level_max=4)
    keys = []
    for level in range(3):
        nx, ny = sc.blocks_at(level)
        ii, jj = np.meshgrid(np.arange(nx), np.arange(ny), indexing="ij")
        k = sc.encode(np.full(ii.size, level), ii.ravel(), jj.ravel())
        keys.extend(k.tolist())
    assert len(set(keys)) == len(keys)

    # Mixed-level forest ordering: take level-1 blocks, refine one into its
    # 4 children; children's keys must fall between the parent's neighbors.
    level = 1
    nx, ny = sc.blocks_at(level)
    ii, jj = np.meshgrid(np.arange(nx), np.arange(ny), indexing="ij")
    z = sc.forward(level, ii.ravel(), jj.ravel())
    order = np.argsort(z)
    i_sorted, j_sorted = ii.ravel()[order], jj.ravel()[order]
    k_parent = sc.encode(np.full(i_sorted.size, level), i_sorted, j_sorted)
    # refine the 3rd block along the curve
    pi, pj = int(i_sorted[2]), int(j_sorted[2])
    ci = np.array([2 * pi, 2 * pi + 1, 2 * pi, 2 * pi + 1])
    cj = np.array([2 * pj, 2 * pj, 2 * pj + 1, 2 * pj + 1])
    k_children = sc.encode(np.full(4, level + 1), ci, cj)
    assert k_children.min() > k_parent[1]
    assert k_children.max() < k_parent[3]
