"""Device-side obstacle pipeline tests: SDF kernel, chi mollification,
penalization, momentum solve (reference main.cpp:3911-3969, 4271-4463,
6643-6704, 6944-6979)."""

import jax.numpy as jnp
import numpy as np
import pytest

from cup2d_tpu.config import SimConfig
from cup2d_tpu.models import DiskShape, FishShape
from cup2d_tpu.ops.obstacle import polygon_sdf, solve_rigid_momentum
from cup2d_tpu.sim import Simulation


def test_polygon_sdf_circle():
    th = np.linspace(0, 2 * np.pi, 256, endpoint=False)
    poly = jnp.asarray(np.stack([0.5 * np.cos(th), 0.5 * np.sin(th)], 1))
    px = jnp.asarray([0.0, 0.3, 0.49, 0.51, 0.8, -0.7])
    py = jnp.zeros(6)
    d = polygon_sdf(px, py, poly)
    expected = 0.5 - np.abs(np.asarray(px))
    assert np.allclose(np.asarray(d), expected, atol=1e-3)


def test_polygon_sdf_square_signs():
    poly = jnp.asarray([[0.0, 0.0], [1.0, 0.0], [1.0, 1.0], [0.0, 1.0]])
    d_in = float(polygon_sdf(jnp.asarray([0.5]), jnp.asarray([0.5]), poly)[0])
    d_out = float(polygon_sdf(jnp.asarray([1.5]), jnp.asarray([0.5]), poly)[0])
    assert np.isclose(d_in, 0.5, atol=1e-6)
    assert np.isclose(d_out, -0.5, atol=1e-6)


def test_solve_rigid_momentum_identity():
    # PM=2, no offset: plain translation u = UM/PM
    u = solve_rigid_momentum(2.0, 1.0, 0.0, 0.0, 1.0, 0.5, 0.25)
    assert np.allclose(np.asarray(u), [0.5, 0.25, 0.25], atol=1e-6)


def _cfg(**kw):
    base = dict(bpdx=1, bpdy=1, level_max=1, level_start=0, extent=1.0,
                nu=1e-3, cfl=0.4, lam=1e6, dtype="float64",
                max_poisson_iterations=200)
    base.update(kw)
    return SimConfig(**base)


def test_disk_chi_mass_matches_area():
    disk = DiskShape(0.1, 0.5, 0.5)
    sim = Simulation(_cfg(), shapes=[disk], level=4)
    sim.initialize()
    m = float(jnp.sum(sim.state.chi)) * sim.grid.h**2
    assert abs(m - np.pi * 0.01) < 0.002 * np.pi * 0.01
    assert abs(disk.M - np.pi * 0.01) < 0.002 * np.pi * 0.01


def test_towed_disk_penalization():
    """Prescribed-motion disk: interior fluid velocity is driven to the
    prescribed velocity by the implicit penalization update."""
    disk = DiskShape(0.1, 0.35, 0.5, prescribed=(0.2, 0.0))
    sim = Simulation(_cfg(), shapes=[disk], level=4)
    for _ in range(10):
        sim.step_once()
    x, y = sim.grid.cell_centers()
    inside = (x - disk.com[0]) ** 2 + (y - disk.com[1]) ** 2 \
        < (0.7 * disk.radius) ** 2
    uin = float(jnp.sum(jnp.where(inside, sim.state.vel[0], 0.0))) \
        / inside.sum()
    assert abs(uin - 0.2) < 0.02
    # wake: fluid behind the disk is dragged forward
    assert float(jnp.max(sim.state.vel[0])) > 0.1


def test_free_disk_stays_at_rest():
    disk = DiskShape(0.1, 0.5, 0.5)
    sim = Simulation(_cfg(), shapes=[disk], level=4)
    for _ in range(5):
        sim.step_once()
    assert disk.u == 0.0 and disk.v == 0.0 and disk.omega == 0.0
    assert float(jnp.max(jnp.abs(sim.state.vel))) < 1e-10


def test_fish_simulation_runs_finite():
    """Swimming fish end-to-end: fields stay finite, chi mass tracks the
    analytic midline area, tail beat produces body rotation rate."""
    fish = FishShape(0.25, 0.5, 0.5, 0.0, min_h=1 / 64)
    sim = Simulation(_cfg(max_poisson_iterations=100), shapes=[fish],
                     level=4)
    for _ in range(8):
        diag = sim.step_once()
    assert np.isfinite(fish.u) and np.isfinite(fish.v)
    assert float(jnp.all(jnp.isfinite(sim.state.vel)))
    assert fish.M > 0.2 * fish.area  # coarse grid: lax bound
    assert float(diag["umax"]) < 10.0


def test_two_fish_reference_case_shapes():
    """The run.sh two-fish configuration parses into two FishShapes via
    the reference flag path (run.sh:19-22)."""
    cfg = _cfg()
    cfg.shapes = "angle=0 L=0.2 xpos=0.35 ypos=0.5 T=1\nangle=180 L=0.2 xpos=0.65 ypos=0.5 T=1"
    from cup2d_tpu.sim import make_shapes
    shapes = make_shapes(cfg)
    assert len(shapes) == 2
    assert isinstance(shapes[0], FishShape)
    sim = Simulation(cfg, shapes=shapes, level=4)
    for _ in range(3):
        sim.step_once()
    assert float(jnp.all(jnp.isfinite(sim.state.vel)))
