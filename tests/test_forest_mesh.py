"""Sharded forest execution: an 8-device ShardedAMRSim must reproduce
the single-device AMRSim trajectory (the multi-rank == 1-rank invariant
the reference can only test on a cluster; here on 8 virtual CPU devices
via conftest's forced host device count)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cup2d_tpu.amr import AMRSim
from cup2d_tpu.config import SimConfig
from cup2d_tpu.parallel.forest_mesh import ShardedAMRSim
from cup2d_tpu.parallel.mesh import make_mesh


def _mixed_cfg():
    return SimConfig(bpdx=2, bpdy=2, level_max=3, level_start=1,
                     extent=1.0, dtype="float64", nu=1e-3,
                     rtol=0.8, ctol=0.05)


def _seed_vortex(sim):
    f = sim.forest
    cfg = sim.cfg
    order = f.order()
    bs = cfg.bs
    vals = np.zeros((f.capacity, 2, bs, bs))
    for s in order:
        l = int(f.level[s])
        h = cfg.h_at(l)
        i, j = int(f.bi[s]), int(f.bj[s])
        x = (i * bs + np.arange(bs) + 0.5) * h
        y = (j * bs + np.arange(bs) + 0.5) * h
        X, Y = np.meshgrid(x, y, indexing="xy")
        vals[s, 0] = np.sin(np.pi * X) * np.cos(np.pi * Y)
        vals[s, 1] = -np.cos(np.pi * X) * np.sin(np.pi * Y)
    f.fields["vel"] = jnp.asarray(vals, f.dtype)


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 devices")
def test_sharded_forest_obstacle_matches_single_device():
    """Sharded forest WITH an immersed body: rasterization, chi
    tagging, penalization and the Poisson closure all run under the
    mesh and reproduce the single-device trajectory."""
    from cup2d_tpu.models import DiskShape

    def cfg():
        return SimConfig(bpdx=2, bpdy=1, level_max=3, level_start=1,
                         extent=1.0, dtype="float64", nu=4e-5, lam=1e6,
                         rtol=2.0, ctol=1.0)

    mesh = make_mesh(8)
    ref = AMRSim(cfg(), shapes=[DiskShape(0.08, 0.55, 0.25)])
    sh = ShardedAMRSim(cfg(), mesh, shapes=[DiskShape(0.08, 0.55, 0.25)])
    for sim in (ref, sh):
        sim.compute_forces_every = 0
        sim.initialize()
        _seed_vortex(sim)
    assert set(ref.forest.blocks) == set(sh.forest.blocks)
    for _ in range(2):
        ref.step_once(dt=1e-3)
        sh.step_once(dt=1e-3)
    ref.sync_fields()
    sh.sync_fields()
    a = np.asarray(ref.forest.fields["vel"][ref.forest.order()])
    b = np.asarray(sh.forest.fields["vel"][sh.forest.order()])
    assert np.abs(a - b).max() < 1e-11, np.abs(a - b).max()
    assert len(sh._ordered_state()["vel"].sharding.device_set) == 8


@pytest.mark.slow   # ~36 s; the OBSTACLE sharded==single equality above
#                     covers the superset step (raster + collisions +
#                     forces on the mesh) and stays tier-1
@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 devices")
@pytest.mark.parametrize("ndev", [8, 4])
def test_sharded_forest_matches_single_device(ndev):
    """ndev=4 exercises the per-device table splitter at a different
    shard width (B = n_pad/D) and surface-set layout than the 8 the
    rest of CI uses."""
    mesh = make_mesh(ndev)
    ref = AMRSim(_mixed_cfg())
    sh = ShardedAMRSim(_mixed_cfg(), mesh)
    for sim in (ref, sh):
        _seed_vortex(sim)
        sim.adapt()                      # real mixed-level topology
    assert len(ref.forest.blocks) == len(sh.forest.blocks) > 16

    for n in range(3):
        ref.step_once(dt=1e-3)
        sh.step_once(dt=1e-3)
    ref.sync_fields()
    sh.sync_fields()
    a = np.asarray(ref.forest.fields["vel"][ref.forest.order()])
    b = np.asarray(sh.forest.fields["vel"][sh.forest.order()])
    assert np.abs(a - b).max() < 1e-11, np.abs(a - b).max()

    # the sharded working state really is distributed over the mesh
    # (guards the silent replicated fallback ShardedAMRSim takes when
    # n_pad stops dividing by the mesh size)
    vel = sh._ordered_state()["vel"]
    assert len(vel.sharding.device_set) == ndev

    # regrid mid-run (resharding path), then keep stepping
    sh.adapt()
    ref.adapt()
    ref.step_once(dt=1e-3)
    sh.step_once(dt=1e-3)
    ref.sync_fields()
    sh.sync_fields()
    a = np.asarray(ref.forest.fields["vel"][ref.forest.order()])
    b = np.asarray(sh.forest.fields["vel"][sh.forest.order()])
    assert np.abs(a - b).max() < 1e-11


def _mixed_three_level_forest():
    """Walls, same-level faces/corners, coarse and fine interfaces —
    the same topology zoo tests/test_flux.py pins the single-device
    fast ops on."""
    from cup2d_tpu.forest import Forest

    cfg = SimConfig(bpdx=2, bpdy=3, level_max=4, level_start=1,
                    extent=1.0, dtype="float64")
    f = Forest(cfg)
    f.release(1, 0, 0)
    for a in (0, 1):
        for b in (0, 1):
            f.allocate(2, a, b)
    f.release(2, 0, 0)
    for a in (0, 1):
        for b in (0, 1):
            f.allocate(3, a, b)
    f.release(1, 3, 5)
    for a in (0, 1):
        for b in (0, 1):
            f.allocate(2, 6 + a, 10 + b)
    return cfg, f


@pytest.mark.slow   # ~26 s; duplicative tier-1 coverage: the
#                     single-device paint keeps its bit-exact bar in
#                     test_flux.py::test_fast_face_copy_assembly_
#                     matches_tables, and the sharded paint is
#                     exercised end-to-end by the tier-1 sharded ==
#                     single-device trajectory/operator equalities in
#                     this file (obstacle case + ShardPoissonOp +
#                     wires-fast-ops) — slow-marked to fund the PR-7
#                     elastic drill within the 870 s cap
@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 devices")
def test_shard_fast_paint_matches_table_assembly():
    """The shard-local FastHalo paint must reproduce the gather-table
    assembly BIT-EXACTLY on a mixed three-level forest — the same bar
    tests/test_flux.py sets for the single-device paint (round-5 fast
    path on the mesh)."""
    from cup2d_tpu.halo import (
        assemble_labs_ordered,
        build_face_copy,
        build_tables,
        pad_tables,
    )
    from cup2d_tpu.parallel.shard_halo import shard_tables

    cfg, f = _mixed_three_level_forest()
    order = f.order()
    n = len(order)
    n_pad = 40                                 # divides the 8-mesh
    assert n < n_pad
    mesh = make_mesh(8)
    nb, mask = build_face_copy(f, order, n_pad)
    assert mask.sum() > 0
    rng = np.random.default_rng(5)
    for (g, tensorial, dim, corners) in ((3, True, 2, True),
                                         (1, False, 2, False),
                                         (1, True, 1, True)):
        x = rng.standard_normal((n_pad, dim, cfg.bs, cfg.bs))
        x[n:] = 0.0
        xj = jnp.asarray(x)
        t = build_tables(f, order, g, tensorial, dim)
        want = np.asarray(assemble_labs_ordered(
            xj, jax.device_put(pad_tables(t, n_pad))))
        st = shard_tables(t, n_pad, mesh, fc=(nb, mask),
                          corners=corners)
        # the paint actually engages on at least one shard
        assert float(np.asarray(st.fc_mask).sum()) > 0
        got = np.asarray(st.assemble(xj))
        np.testing.assert_array_equal(
            got[:n], want[:n],
            err_msg=f"g={g} tensorial={tensorial} dim={dim}")


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 devices")
def test_shard_poisson_structured_matches_single_device():
    """The sharded structured PoissonOp closure must match the
    single-device structured operator to <= 1e-12 on a mixed-level
    forest (it is bit-identical by construction: shared strip math,
    per-face matmuls reduce over BS only)."""
    from cup2d_tpu.flux import build_poisson_structured, \
        poisson_apply_structured
    from cup2d_tpu.parallel.shard_halo import ShardPoissonOp, \
        shard_poisson_op

    cfg, f = _mixed_three_level_forest()
    order = f.order()
    n = len(order)
    n_pad = 40
    mesh = make_mesh(8)
    rng = np.random.default_rng(11)
    x = rng.standard_normal((n_pad, cfg.bs, cfg.bs))
    x[n:] = 0.0
    xj = jnp.asarray(x)
    op = build_poisson_structured(f, order, n_pad)
    want = np.asarray(poisson_apply_structured(xj, op))
    sop = shard_poisson_op(op, n_pad, mesh)
    assert isinstance(sop, ShardPoissonOp)
    assert sop.S < n_pad            # surface stays boundary-sized
    got = np.asarray(poisson_apply_structured(xj, sop))
    np.testing.assert_allclose(got[:n], want[:n], rtol=0, atol=1e-12)


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 devices")
def test_sharded_sim_wires_fast_ops():
    """ShardedAMRSim must actually WIRE the round-5 fast operators into
    its hot-loop tables (a silent fallback to the round-4 lab-table
    forms would erase the per-device speedup without failing anything),
    and CUP2D_POIS=tables must restore the table form for A/B runs."""
    from cup2d_tpu.parallel.shard_halo import ShardPoissonOp, ShardTables

    mesh = make_mesh(8)
    sh = ShardedAMRSim(_mixed_cfg(), mesh)
    sh._refresh()
    assert isinstance(sh._tables["pois"], ShardPoissonOp)
    for k, corners in sh._FAST_SETS.items():
        t = sh._tables.get(k)
        if t is None:
            continue
        assert isinstance(t, ShardTables), k
        assert t.n_regions == (8 if corners else 4), (k, t.n_regions)


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 devices")
def test_sharded_pois_tables_env_fallback(monkeypatch):
    from cup2d_tpu.parallel.shard_halo import ShardTables

    monkeypatch.setenv("CUP2D_POIS", "tables")
    mesh = make_mesh(8)
    sh = ShardedAMRSim(_mixed_cfg(), mesh)
    sh._refresh()
    assert isinstance(sh._tables["pois"], ShardTables)


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 devices")
def test_overlap_block_jacobi_matches_unoverlapped():
    """The comm/compute-overlapped forest smoother
    (shard_halo.overlap_block_jacobi_sweeps, PR 13) must be TERMWISE
    identical to the unoverlapped per-sweep composition
    e + P_inv (r - A e): the sweep body runs the same
    flux._structured_lap strip math over the same [own ++ received]
    gather space and the same GEMM, only the issue order changes —
    pinned <= 1e-12 over multiple sweeps on a mixed-level forest."""
    from cup2d_tpu.flux import build_poisson_structured, \
        poisson_apply_structured
    from cup2d_tpu.parallel.shard_halo import shard_poisson_op, \
        overlap_block_jacobi_sweeps
    from cup2d_tpu.poisson import apply_block_precond_blocks, \
        block_precond_matrix

    cfg, f = _mixed_three_level_forest()
    order = f.order()
    n = len(order)
    n_pad = 40
    mesh = make_mesh(8)
    rng = np.random.default_rng(23)
    r = rng.standard_normal((n_pad, cfg.bs, cfg.bs))
    r[n:] = 0.0
    rj = jnp.asarray(r)
    op = build_poisson_structured(f, order, n_pad)
    sop = shard_poisson_op(op, n_pad, mesh)
    p_inv = jnp.asarray(block_precond_matrix(cfg.bs))
    # unoverlapped reference: n sweeps of the plain composition on
    # the single-device structured operator
    want = apply_block_precond_blocks(rj, p_inv)
    for _ in range(3):
        want = want + apply_block_precond_blocks(
            rj - poisson_apply_structured(want, op), p_inv)
    got = overlap_block_jacobi_sweeps(
        apply_block_precond_blocks(rj, p_inv), rj, p_inv, sop, 3)
    np.testing.assert_allclose(np.asarray(got)[:n],
                               np.asarray(want)[:n],
                               rtol=0, atol=1e-12)


@pytest.mark.slow   # ~50 s: full sharded-vs-single TRAJECTORY drill
#                     under CUP2D_POIS=fas — duplicative composition:
#                     the overlapped smoother's termwise identity is
#                     tier-1 above, the sharded==single step equality
#                     is tier-1 for the default path, and the fas
#                     solve itself is tier-1 in test_solver_modes; this
#                     drill only pins their composition end-to-end
@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 devices")
def test_sharded_fas_trajectory_matches_single_device(monkeypatch):
    from validation.poisson_ab import build_multilevel_sim

    monkeypatch.setenv("CUP2D_POIS", "fas")
    mesh = make_mesh(8)
    a = build_multilevel_sim()
    b = build_multilevel_sim(
        sim_cls=lambda cfg: ShardedAMRSim(cfg, mesh))
    assert a._pois_mode == "fas" and b._pois_mode == "fas"
    for s in (a, b):
        s._refresh()
        s._coarse_on = True
        s._last_iters = 0
        s._last_iters_dev = None
    da = a.step_once(1e-3)
    db = b.step_once(1e-3)
    assert bool(da["poisson_converged"]) and bool(db["poisson_converged"])
    assert int(da["poisson_iters"]) == int(db["poisson_iters"])
    va = a._ordered_state()
    vb = b._ordered_state()
    nr = a._n_real
    dv = float(jnp.max(jnp.abs(va["vel"][:nr] - vb["vel"][:nr])))
    dp = float(jnp.max(jnp.abs(va["pres"][:nr] - vb["pres"][:nr])))
    assert dv < 1e-11, dv
    assert dp < 1e-11, dp
