"""Sharded forest execution: an 8-device ShardedAMRSim must reproduce
the single-device AMRSim trajectory (the multi-rank == 1-rank invariant
the reference can only test on a cluster; here on 8 virtual CPU devices
via conftest's forced host device count)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cup2d_tpu.amr import AMRSim
from cup2d_tpu.config import SimConfig
from cup2d_tpu.parallel.forest_mesh import ShardedAMRSim
from cup2d_tpu.parallel.mesh import make_mesh


def _mixed_cfg():
    return SimConfig(bpdx=2, bpdy=2, level_max=3, level_start=1,
                     extent=1.0, dtype="float64", nu=1e-3,
                     rtol=0.8, ctol=0.05)


def _seed_vortex(sim):
    f = sim.forest
    cfg = sim.cfg
    order = f.order()
    bs = cfg.bs
    vals = np.zeros((f.capacity, 2, bs, bs))
    for s in order:
        l = int(f.level[s])
        h = cfg.h_at(l)
        i, j = int(f.bi[s]), int(f.bj[s])
        x = (i * bs + np.arange(bs) + 0.5) * h
        y = (j * bs + np.arange(bs) + 0.5) * h
        X, Y = np.meshgrid(x, y, indexing="xy")
        vals[s, 0] = np.sin(np.pi * X) * np.cos(np.pi * Y)
        vals[s, 1] = -np.cos(np.pi * X) * np.sin(np.pi * Y)
    f.fields["vel"] = jnp.asarray(vals, f.dtype)


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 devices")
def test_sharded_forest_obstacle_matches_single_device():
    """Sharded forest WITH an immersed body: rasterization, chi
    tagging, penalization and the Poisson closure all run under the
    mesh and reproduce the single-device trajectory."""
    from cup2d_tpu.models import DiskShape

    def cfg():
        return SimConfig(bpdx=2, bpdy=1, level_max=3, level_start=1,
                         extent=1.0, dtype="float64", nu=4e-5, lam=1e6,
                         rtol=2.0, ctol=1.0)

    mesh = make_mesh(8)
    ref = AMRSim(cfg(), shapes=[DiskShape(0.08, 0.55, 0.25)])
    sh = ShardedAMRSim(cfg(), mesh, shapes=[DiskShape(0.08, 0.55, 0.25)])
    for sim in (ref, sh):
        sim.compute_forces_every = 0
        sim.initialize()
        _seed_vortex(sim)
    assert set(ref.forest.blocks) == set(sh.forest.blocks)
    for _ in range(2):
        ref.step_once(dt=1e-3)
        sh.step_once(dt=1e-3)
    ref.sync_fields()
    sh.sync_fields()
    a = np.asarray(ref.forest.fields["vel"][ref.forest.order()])
    b = np.asarray(sh.forest.fields["vel"][sh.forest.order()])
    assert np.abs(a - b).max() < 1e-11, np.abs(a - b).max()
    assert len(sh._ordered_state()["vel"].sharding.device_set) == 8


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 devices")
@pytest.mark.parametrize("ndev", [8, 4])
def test_sharded_forest_matches_single_device(ndev):
    """ndev=4 exercises the per-device table splitter at a different
    shard width (B = n_pad/D) and surface-set layout than the 8 the
    rest of CI uses."""
    mesh = make_mesh(ndev)
    ref = AMRSim(_mixed_cfg())
    sh = ShardedAMRSim(_mixed_cfg(), mesh)
    for sim in (ref, sh):
        _seed_vortex(sim)
        sim.adapt()                      # real mixed-level topology
    assert len(ref.forest.blocks) == len(sh.forest.blocks) > 16

    for n in range(3):
        ref.step_once(dt=1e-3)
        sh.step_once(dt=1e-3)
    ref.sync_fields()
    sh.sync_fields()
    a = np.asarray(ref.forest.fields["vel"][ref.forest.order()])
    b = np.asarray(sh.forest.fields["vel"][sh.forest.order()])
    assert np.abs(a - b).max() < 1e-11, np.abs(a - b).max()

    # the sharded working state really is distributed over the mesh
    # (guards the silent replicated fallback ShardedAMRSim takes when
    # n_pad stops dividing by the mesh size)
    vel = sh._ordered_state()["vel"]
    assert len(vel.sharding.device_set) == ndev

    # regrid mid-run (resharding path), then keep stepping
    sh.adapt()
    ref.adapt()
    ref.step_once(dt=1e-3)
    sh.step_once(dt=1e-3)
    ref.sync_fields()
    sh.sync_fields()
    a = np.asarray(ref.forest.fields["vel"][ref.forest.order()])
    b = np.asarray(sh.forest.fields["vel"][sh.forest.order()])
    assert np.abs(a - b).max() < 1e-11
