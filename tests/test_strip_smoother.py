"""Memory-tiered FAS tests (ISSUE 19): the fused Pallas strip
smoother vs the XLA sweep chain (~1-ulp, all operand families), the
bf16-leg cycle tier (same f32 true-residual criterion, iters within
+1), the fused forest block-Jacobi update, the sharded halo strip
form, the driver latch composition with loud refusals, and the
for_prec watchdog band on the bf16-leg cavity case.

CPU boxes run every Pallas kernel in interpret mode (the real kernel
body through the interpreter) — parity bounds are identical there by
construction; only ms figures need hardware."""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cup2d_tpu.config import SimConfig
from cup2d_tpu.ops.pallas_kernels import (block_update_supported,
                                          fused_block_jacobi_update,
                                          fused_jacobi_sweeps,
                                          jacobi_strip_supported)
from cup2d_tpu.ops.stencil import (_edge_ones, laplacian5_bc,
                                   laplacian5_neumann)
from cup2d_tpu.poisson import (MultigridPreconditioner,
                               apply_block_precond_blocks,
                               block_precond_matrix, mg_solve)

SIGNED = (1.0, -1.0, 1.0, 1.0)


def _xla_chain(e, r, omega, n, edge_signs=None, from_zero=False):
    """The exact _smooth arithmetic: stencil laplacian + the fori-body
    grouping e + omega*(r - lap)*inv_d, from_zero shortcut included."""
    ny, nx = r.shape[-2:]
    if edge_signs is None:
        ey, ex = _edge_ones(ny, r.dtype), _edge_ones(nx, r.dtype)
        lap = laplacian5_neumann
    else:
        sx_lo, sx_hi, sy_lo, sy_hi = edge_signs
        ey = _edge_ones(ny, r.dtype, lo=sy_lo, hi=sy_hi)
        ex = _edge_ones(nx, r.dtype, lo=sx_lo, hi=sx_hi)
        lap = lambda p: laplacian5_bc(p, *edge_signs)
    inv_d = 1.0 / (ey[:, None] + ex[None, :] - 4.0)
    if from_zero and n > 0:
        e = omega * r * inv_d
        n -= 1
    for _ in range(n):
        e = e + omega * (r - lap(e)) * inv_d
    return e


def _rand(shape, seed, dtype=jnp.float32):
    return jnp.asarray(np.random.default_rng(seed).normal(size=shape),
                       dtype)


# ---------------------------------------------------------------------------
# f32 parity: all three operand families, chains 1..6, both BC signs
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("shape", [(32, 128),      # solo grid
                                   (4, 32, 128),   # fleet member batch
                                   (2, 2, 16, 256)])  # nested lead
def test_strip_parity_f32_operand_families(shape):
    """~1-ulp vs the XLA sweep chain (the only allowed delta is FMA
    contraction inside the compiled stencil), every chain depth the
    cycle uses, from_zero both ways, Neumann and signed walls."""
    omega = 0.8
    for n in (1, 2, 3, 6):
        for fz in (False, True):
            for signs in (None, SIGNED):
                r = _rand(shape, 7 * n + fz)
                e = _rand(shape, 100 + n)
                ref = _xla_chain(e, r, omega, n, signs, fz)
                got = fused_jacobi_sweeps(e, r, omega, n,
                                          edge_signs=signs,
                                          from_zero=fz)
                assert got.shape == ref.shape
                assert got.dtype == ref.dtype
                tol = 1e-6 * float(jnp.max(jnp.abs(ref)))
                assert float(jnp.max(jnp.abs(got - ref))) <= tol, \
                    (shape, n, fz, signs)


def test_strip_gate():
    """The optimization gate: f32/bf16 only, sublane-aligned strips,
    bounded chain depth. A False is a silent XLA fallback by design
    (MultigridPreconditioner demotes truthfully, below)."""
    f32, bf16 = jnp.float32, jnp.bfloat16
    assert jacobi_strip_supported(32, 128, f32, 3)
    assert jacobi_strip_supported(16, 128, bf16, 3)
    assert not jacobi_strip_supported(33, 128, f32, 1)   # ny % by
    assert not jacobi_strip_supported(8, 128, bf16, 1)   # ny < by
    assert not jacobi_strip_supported(32, 128, f32, 7)   # depth cap
    assert not jacobi_strip_supported(32, 128, f32, 0)
    assert not jacobi_strip_supported(32, 128, jnp.float64, 2)


def test_strip_bf16_storage_f32_accumulate():
    """bf16 legs: storage dtype rides the operands, one rounding per
    sweep — the result tracks the f32 chain to bf16 resolution."""
    r = _rand((32, 128), 3).astype(jnp.bfloat16)
    e = _rand((32, 128), 4).astype(jnp.bfloat16)
    got = fused_jacobi_sweeps(e, r, 0.8, 2)
    assert got.dtype == jnp.bfloat16
    ref = _xla_chain(e.astype(jnp.float32), r.astype(jnp.float32),
                     0.8, 2)
    err = float(jnp.max(jnp.abs(got.astype(jnp.float32) - ref)))
    assert err <= 2e-2 * float(jnp.max(jnp.abs(ref)))


# ---------------------------------------------------------------------------
# hierarchy integration: cycle parity, truthful tier label, demotion
# ---------------------------------------------------------------------------

def test_mg_cycle_strip_matches_xla():
    """One full V-cycle with the strip smoother vs the XLA chain, and
    the truthful smoother_tier labels (including the shape-gate
    demotion and the leg-suffix composition)."""
    b = _rand((128, 256), 11)
    mgx = MultigridPreconditioner(128, 256, jnp.float32,
                                  cycle_dtype=jnp.float32)
    mgs = MultigridPreconditioner(128, 256, jnp.float32,
                                  cycle_dtype=jnp.float32,
                                  smoother="strip")
    assert (mgx.smoother_tier, mgs.smoother_tier) == ("xla", "strip")
    cx, cs = mgx(b), mgs(b)
    tol = 2e-6 * float(jnp.max(jnp.abs(cx)))
    assert float(jnp.max(jnp.abs(cs - cx))) <= tol
    # unsupported finest shape: truthful demotion, identical results
    mgd = MultigridPreconditioner(36, 36, jnp.float32,
                                  cycle_dtype=jnp.float32,
                                  smoother="strip")
    assert mgd.smoother_tier == "xla"
    # bf16 legs survive a demotion in the label (no hidden tier)
    mgdb = MultigridPreconditioner(36, 36, jnp.float32,
                                   cycle_dtype=jnp.float32,
                                   leg_dtype=jnp.bfloat16,
                                   smoother="strip")
    assert mgdb.smoother_tier == "xla+bf16"
    mgb = MultigridPreconditioner(128, 256, jnp.float32,
                                  cycle_dtype=jnp.float32,
                                  leg_dtype=jnp.bfloat16,
                                  smoother="strip")
    assert mgb.smoother_tier == "strip+bf16"
    assert mgb(b).dtype == jnp.float32      # out_dtype restored


def test_bf16_leg_mg_solve_same_criterion():
    """The tentpole's convergence contract: bf16 legs under mg_solve's
    f32 true-residual outer loop converge by the SAME Linf criterion
    with iters within +1 of the f32-leg arm (iterative refinement —
    the legs only shape the correction). The probe is the REALISTIC
    bench RHS (vortex-field divergence at production tol_rel): on a
    white-noise RHS at tol_rel 1e-4 the bf16 correction's resolution
    floor costs 29-vs-19 cycles — the +1 claim is a claim about
    production solves, not adversarial spectra."""
    from cup2d_tpu.ops.stencil import divergence_rhs
    from cup2d_tpu.uniform import UniformGrid, pad_vector
    from bench import bench_state

    cfg = SimConfig(bpdx=1, bpdy=1, level_max=1, level_start=0,
                    extent=1.0, nu=4e-5, cfl=0.5, dtype="float32")
    grid = UniformGrid(cfg, level=4)        # 128^2 probe
    st = bench_state(grid)
    dt = jnp.asarray(0.5 * grid.h, grid.dtype)
    b = divergence_rhs(pad_vector(st.vel, 1), pad_vector(st.udef, 1),
                       st.chi, 1, grid.h, dt)
    arms = {}
    for name, kw in (("f32", {}),
                     ("bf16leg", {"leg_dtype": jnp.bfloat16})):
        mg = MultigridPreconditioner(grid.ny, grid.nx, grid.dtype,
                                     cycle_dtype=grid.dtype,
                                     smoother="strip", **kw)
        res = mg_solve(grid.laplacian, b, mg, tol=0.0, tol_rel=1e-3,
                       max_cycles=100)
        assert bool(res.converged), name
        arms[name] = int(res.iters)
    assert arms["bf16leg"] <= arms["f32"] + 1, arms


# ---------------------------------------------------------------------------
# fused forest block-Jacobi update
# ---------------------------------------------------------------------------

def test_block_jacobi_update_parity():
    assert block_update_supported(jnp.float32)
    assert not block_update_supported(jnp.float64)
    bs = 16
    p_inv = jnp.asarray(block_precond_matrix(bs), jnp.float32)
    for N in (1, 7, 130):
        e = _rand((N, bs, bs), N)
        r = _rand((N, bs, bs), N + 1)
        lap = _rand((N, bs, bs), N + 2)
        ref = e + apply_block_precond_blocks(r - lap, p_inv)
        got = fused_block_jacobi_update(e, r, lap, p_inv)
        tol = 2e-6 * float(jnp.max(jnp.abs(ref)))
        assert float(jnp.max(jnp.abs(got - ref))) <= tol, N


# ---------------------------------------------------------------------------
# sharded halo strip (8 forced host devices, conftest)
# ---------------------------------------------------------------------------

def test_sharded_strip_matches_gspmd_overlap():
    """The tier="strip" form of overlap_jacobi_sweeps (edge-column
    ppermutes FIRST, then the per-sweep halo strip kernel) against the
    pinned GSPMD overlap body — the in-kernel device-masked wall
    diagonal reproduces it exactly."""
    from jax.sharding import Mesh
    from cup2d_tpu.parallel.shard_halo import overlap_jacobi_sweeps

    if jax.device_count() < 8:
        pytest.skip("needs the 8 forced host devices")
    mesh = Mesh(np.array(jax.devices()[:8]), ("x",))
    ny, nx = 32, 1024
    e, r = _rand((ny, nx), 21), _rand((ny, nx), 22)
    ey, ex = _edge_ones(ny, r.dtype), _edge_ones(nx, r.dtype)
    inv_d = 1.0 / (ey[:, None] + ex[None, :] - 4.0)
    for n in (1, 3):
        ref = overlap_jacobi_sweeps(e, r, inv_d, 0.8, n, mesh,
                                    tier="xla")
        got = overlap_jacobi_sweeps(e, r, inv_d, 0.8, n, mesh,
                                    tier="strip")
        tol = 1e-6 * float(jnp.max(jnp.abs(ref)))
        assert float(jnp.max(jnp.abs(got - ref))) <= tol, n


# ---------------------------------------------------------------------------
# driver latch composition + loud refusals
# ---------------------------------------------------------------------------

def test_uniform_latch_composition(monkeypatch):
    from cup2d_tpu.uniform import UniformGrid

    cfg = SimConfig(bpdx=1, bpdy=1, level_max=1, level_start=0,
                    extent=1.0, nu=4e-5, cfl=0.5, dtype="float32")
    monkeypatch.delenv("CUP2D_PALLAS", raising=False)
    monkeypatch.delenv("CUP2D_PREC", raising=False)
    monkeypatch.setenv("CUP2D_POIS", "fas")
    assert UniformGrid(cfg, level=4).smoother_tier == "xla"
    monkeypatch.setenv("CUP2D_PALLAS", "1")
    g = UniformGrid(cfg, level=4)
    assert g.smoother_tier == "strip" and g.mg.leg_dtype is None
    monkeypatch.setenv("CUP2D_PREC", "bf16")
    g = UniformGrid(cfg, level=4)
    assert g.smoother_tier == "strip+bf16"
    assert g.mg.leg_dtype == jnp.bfloat16
    # non-fas: the strip/leg tier stays off (preconditioner cycles
    # keep their pinned bf16-storage default under Krylov)
    monkeypatch.setenv("CUP2D_POIS", "")
    monkeypatch.delenv("CUP2D_PREC", raising=False)
    assert UniformGrid(cfg, level=4).smoother_tier == "xla"


def test_forest_latch_composition_and_refusals(monkeypatch):
    from cup2d_tpu.amr import AMRSim

    cfg = SimConfig(bpdx=2, bpdy=2, level_max=3, level_start=1,
                    extent=1.0, nu=4e-5, cfl=0.5, dtype="float32")
    monkeypatch.delenv("CUP2D_PALLAS", raising=False)
    monkeypatch.setenv("CUP2D_POIS", "fas")
    monkeypatch.setenv("CUP2D_PREC", "bf16")
    sim = AMRSim(cfg, shapes=[])
    assert sim._fas_leg_dtype == jnp.bfloat16
    assert sim.smoother_tier == "xla+bf16"
    monkeypatch.setenv("CUP2D_PALLAS", "1")
    assert AMRSim(cfg, shapes=[]).smoother_tier == "strip+bf16"
    monkeypatch.delenv("CUP2D_PREC", raising=False)
    assert AMRSim(cfg, shapes=[]).smoother_tier == "strip"
    # refusals are LOUD: a latch that cannot route must not relabel
    monkeypatch.setenv("CUP2D_PREC", "bf16")
    monkeypatch.setenv("CUP2D_POIS", "structured")
    with pytest.raises(ValueError, match="CUP2D_POIS"):
        AMRSim(cfg, shapes=[])
    monkeypatch.setenv("CUP2D_POIS", "fas")
    cfg64 = SimConfig(bpdx=2, bpdy=2, level_max=3, level_start=1,
                      extent=1.0, nu=4e-5, cfl=0.5, dtype="float64")
    with pytest.raises(ValueError, match="f32 solver state"):
        AMRSim(cfg64, shapes=[])
    monkeypatch.setenv("CUP2D_PREC", "bf32")
    with pytest.raises(ValueError, match="CUP2D_PREC"):
        AMRSim(cfg, shapes=[])


def test_forest_bf16_leg_solve_iters(monkeypatch):
    """Forest FAS with bf16 ladder legs: a production step's solve
    converges with cycles within +1 of the f32-leg arm (the
    poisson_ab fas-bf16leg arm, tier-1-sized)."""
    from cup2d_tpu.amr import AMRSim

    monkeypatch.setenv("CUP2D_POIS", "fas")
    monkeypatch.delenv("CUP2D_PALLAS", raising=False)
    cfg = SimConfig(bpdx=2, bpdy=2, level_max=3, level_start=1,
                    extent=1.0, nu=4e-5, cfl=0.5, dtype="float32")
    iters = {}
    for prec in ("f32", "bf16"):
        if prec == "bf16":
            monkeypatch.setenv("CUP2D_PREC", "bf16")
        else:
            monkeypatch.delenv("CUP2D_PREC", raising=False)
        sim = AMRSim(cfg, shapes=[])
        sim.step_count = 20        # production regime (no exact mode)
        d = sim.step_once()
        assert bool(d["poisson_converged"]), prec
        iters[prec] = int(d["poisson_iters"])
    assert iters["bf16"] <= iters["f32"] + 1, iters


def test_forest_strip_block_smoother_dispatch(monkeypatch):
    """CUP2D_PALLAS=1 + fas routes the composite smoother's update
    tail through fused_block_jacobi_update; the step's solve agrees
    with the XLA form to solver tolerance."""
    from cup2d_tpu.amr import AMRSim

    monkeypatch.setenv("CUP2D_POIS", "fas")
    monkeypatch.delenv("CUP2D_PREC", raising=False)
    cfg = SimConfig(bpdx=2, bpdy=2, level_max=3, level_start=1,
                    extent=1.0, nu=4e-5, cfl=0.5, dtype="float32")
    press = {}
    for tier in ("xla", "strip"):
        if tier == "strip":
            monkeypatch.setenv("CUP2D_PALLAS", "1")
        else:
            monkeypatch.delenv("CUP2D_PALLAS", raising=False)
        sim = AMRSim(cfg, shapes=[])
        sim.step_count = 20
        d = sim.step_once()
        assert bool(d["poisson_converged"]), tier
        press[tier] = np.asarray(sim.forest.fields["pres"])
    scale = np.max(np.abs(press["xla"])) or 1.0
    assert np.max(np.abs(press["strip"] - press["xla"])) <= 1e-4 * scale


# ---------------------------------------------------------------------------
# watchdog band on the bf16-leg cavity case
# ---------------------------------------------------------------------------

def test_bf16_leg_cavity_watchdog(tmp_path, monkeypatch):
    """Guarded lid-driven cavity on the full bf16 composition
    (advection tier + FAS bf16 legs): the for_prec('bf16') band arms
    on the settling flow WITHOUT a false trip, and the telemetry
    record carries the smoother_tier latch."""
    from cup2d_tpu.cases import cavity_table
    from cup2d_tpu.profiling import MetricsRecorder
    from cup2d_tpu.resilience import (EventLog, PhysicsWatchdog,
                                      StepGuard)
    from cup2d_tpu.uniform import UniformSim

    monkeypatch.setenv("CUP2D_PALLAS", "1")
    monkeypatch.setenv("CUP2D_PREC", "bf16")
    monkeypatch.setenv("CUP2D_POIS", "fas")
    cfg = SimConfig(bpdx=1, bpdy=1, level_max=1, level_start=0,
                    extent=1.0, nu=1e-3, cfl=0.4, dtype="float32",
                    max_poisson_iterations=60)
    sim = UniformSim(cfg, level=2, bc=cavity_table(1.0))
    assert sim.prec_mode == "bf16"
    assert sim.smoother_tier == "strip+bf16"

    wd = PhysicsWatchdog.for_prec(sim.prec_mode, window=4)
    assert (wd.div_factor, wd.div_settle) == (100.0, 8.0)
    log = EventLog(str(tmp_path / "events.jsonl"))
    guard = StepGuard(sim, watchdog=wd, event_log=log)
    dt = 0.25 * sim.grid.h                 # fixed clock, as the golden
    for _ in range(10):
        guard.step(dt)
    guard.drain()
    assert sim.step_count == 10
    # the v11 telemetry latch rides the record
    rec = MetricsRecorder()
    rec.prime(sim)
    r = rec.record(sim, sim.step_once(dt))
    assert r["smoother_tier"] == "strip+bf16"
    assert wd._armed(wd.umax, wd.umax_settle) is not None
    with open(tmp_path / "events.jsonl") as f:
        evs = [json.loads(ln) for ln in f if ln.strip()]
    assert not [e for e in evs if e.get("event") == "recovery"], evs
