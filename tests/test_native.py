"""Native AMR host kernels vs the pure-Python fallback.

The C fix_states (cup2d_tpu/native/amr_host.c) must be bit-equal to
AMRSim._fix_states_py on randomized forests and state assignments —
the same oracle discipline the reference applies to its SFC test bed
(tool/curve.cpp)."""

import copy

import numpy as np
import pytest

from cup2d_tpu import native
from cup2d_tpu.amr import AMRSim
from cup2d_tpu.config import SimConfig
from cup2d_tpu.forest import Forest


def _random_forest(rng, level_max=4):
    cfg = SimConfig(bpdx=2, bpdy=1, level_max=level_max, level_start=1,
                    extent=1.0, dtype="float64")
    f = Forest(cfg)
    # random refinement, two rounds (any partition is a valid input)
    for _ in range(2):
        for key in list(f.blocks):
            l, i, j = key
            if l < level_max - 1 and rng.random() < 0.35:
                f.release(l, i, j)
                for a in (0, 1):
                    for b in (0, 1):
                        f.allocate(l + 1, 2 * i + a, 2 * j + b)
    return cfg, f


def _random_states(rng, f, level_max):
    state = {}
    for (l, i, j) in f.blocks:
        if l == level_max - 1:
            state[(l, i, j)] = int(rng.choice([-1, 0]))
        else:
            state[(l, i, j)] = int(rng.choice([-1, 0, 1]))
    return state


@pytest.mark.skipif(native._load() is None,
                    reason="no C compiler / native build unavailable")
def test_fix_states_native_matches_python():
    rng = np.random.default_rng(7)
    for trial in range(8):
        cfg, f = _random_forest(rng)
        sim = AMRSim.__new__(AMRSim)   # only forest/cfg used by the fix
        sim.forest = f
        sim.cfg = cfg
        base = _random_states(rng, f, cfg.level_max)

        st_py = copy.deepcopy(base)
        sim._fix_states_py(st_py)

        keys = list(base.keys())
        n = len(keys)
        lvl = np.fromiter((k[0] for k in keys), np.int32, n)
        bi = np.fromiter((k[1] for k in keys), np.int32, n)
        bj = np.fromiter((k[2] for k in keys), np.int32, n)
        st = np.fromiter((base[k] for k in keys), np.int8, n)
        ok = native.fix_states(lvl, bi, bj, st, cfg.level_max,
                               cfg.bpdx, cfg.bpdy)
        assert ok
        st_c = dict(zip(keys, st.tolist()))
        assert st_c == st_py, trial


@pytest.mark.skipif(native._load() is None,
                    reason="no C compiler / native build unavailable")
def test_fix_states_native_wired_into_adapt():
    """The AMRSim path uses the native kernel transparently: a full
    adapt() on a seeded forest produces a 2:1-balanced result."""
    import jax.numpy as jnp

    cfg = SimConfig(bpdx=2, bpdy=2, level_max=3, level_start=1,
                    extent=1.0, dtype="float64", nu=1e-3,
                    rtol=0.6, ctol=0.05)
    sim = AMRSim(cfg)
    f = sim.forest
    order = f.order()
    bs = cfg.bs
    vals = np.zeros((f.capacity, 2, bs, bs))
    for s in order:
        l = int(f.level[s])
        h = cfg.h_at(l)
        i, j = int(f.bi[s]), int(f.bj[s])
        x = (i * bs + np.arange(bs) + 0.5) * h
        y = (j * bs + np.arange(bs) + 0.5) * h
        X, Y = np.meshgrid(x, y, indexing="xy")
        vals[s, 0] = np.sin(np.pi * X) * np.cos(np.pi * Y)
        vals[s, 1] = -np.cos(np.pi * X) * np.sin(np.pi * Y)
    f.fields["vel"] = jnp.asarray(vals)
    assert sim.adapt()
    # face neighbors never differ by more than one level
    for (l, i, j) in f.blocks:
        nbx, nby = f.nblocks_at(l)
        for cx, cy in ((-1, 0), (1, 0), (0, -1), (0, 1)):
            ni, nj = i + cx, j + cy
            if 0 <= ni < nbx and 0 <= nj < nby:
                assert f.owner_relation(l, ni, nj) != -3, (l, i, j)
