"""Test harness: run every test on CPU with 8 virtual devices.

This is the mechanism the reference never had for testing "multi-node
without a cluster" (SURVEY.md §4): XLA's forced host platform device count
stands in for a TPU v5e-8 slice, so `shard_map`/`pjit` paths are exercised
for real (collectives and all) on any machine.

Must run before `import jax` — hence top of conftest.
"""

import os

# The image's sitecustomize pre-registers the TPU platform and pins
# JAX_PLATFORMS — plain env setdefault does not win. jax.config.update
# before first backend use does.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# float64 on CPU: validates discretization order of accuracy at reference
# precision (the reference is float64 throughout, main.cpp:24). The TPU
# production path runs float32 — precision-sensitive tests assert both.
jax.config.update("jax_enable_x64", True)
