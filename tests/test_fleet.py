"""Fleet batching (fleet.py + the member-masked Poisson loop + the
per-member FleetStepGuard):

- B=1 contract: FleetSim is BIT-IDENTICAL to UniformSim — same
  trajectory through the exact-mode startup solves, same clocks, equal
  device_get counts.
- B>1 contract: each member's trajectory matches its solo run to
  <= 1e-12 (bit-exact everywhere except the documented MG
  FMA-contraction noise — see the fleet.py module docstring), with
  IDENTICAL per-member dt sequences and solver iteration counts.
- Poisson member mask: a member that converges early is FROZEN — its
  solution is bit-equal to its solo solve even while the fused loop
  keeps sweeping for the slowest member.
- Per-member supervision: a nan_vel fault in one member rewinds ONLY
  that member (restore-slice + solo replay under a snapshot cadence);
  the other members' trajectories stay bit-identical to an unfaulted
  run, through the library guard AND the full CLI.
- Sharding: member-parallel placement over the 8-virtual-device mesh
  (whole members on devices) matches the single-device fleet; big
  grids fall back to the spatial x-split.
- Checkpoint round-trip carries the per-member clocks.
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cup2d_tpu.config import SimConfig
from cup2d_tpu.faults import FaultPlan
from cup2d_tpu.fleet import FleetSim, stack_states, taylor_green_fleet
from cup2d_tpu.poisson import bicgstab
from cup2d_tpu.profiling import HostCounters, MetricsRecorder
from cup2d_tpu.resilience import EventLog, FleetStepGuard, PhysicsWatchdog
from cup2d_tpu.uniform import UniformSim, taylor_green_state


# 32^2 grid (tier-1 budget: the contracts under test are all
# size-independent, and Nx=32 still divides the 8-device mesh)
LVL = 2


def _cfg(**kw):
    base = dict(bpdx=1, bpdy=1, level_max=1, level_start=0, extent=1.0,
                nu=1e-3, cfl=0.4, lam=1e6, dtype="float64",
                max_poisson_iterations=100)
    base.update(kw)
    return SimConfig(**base)


def _fleet(members=3, production=True, **kw):
    sim = FleetSim(_cfg(), level=LVL, members=members, **kw)
    sim.state = taylor_green_fleet(sim.grid, members)
    if production:
        # skip the exact-mode startup branch (a second executable that
        # grinds to the precision floor); the B=1 test covers it
        sim.step_count = 20
    return sim


def _solo(member, production=True):
    sim = UniformSim(_cfg(), level=LVL)
    st = taylor_green_state(sim.grid)
    sim.state = st._replace(vel=st.vel * (0.8 ** member))
    if production:
        sim.step_count = 20
    return sim


def _recoveries(path):
    with open(path) as f:
        return [e for e in map(json.loads, filter(str.strip, f))
                if e.get("event") == "recovery"]


# ---------------------------------------------------------------------------
# B=1: bit-identical to UniformSim, equal pulls
# ---------------------------------------------------------------------------

def test_fleet_b1_bit_identical_to_uniformsim_equal_pulls():
    n = 6

    def run(fleet):
        if fleet:
            sim = FleetSim(_cfg(), level=LVL, members=1)
            sim.state = stack_states([taylor_green_state(sim.grid)])
        else:
            sim = UniformSim(_cfg(), level=LVL)
            sim.state = taylor_green_state(sim.grid)
        c = HostCounters().install()
        try:
            for _ in range(n):       # incl. the exact startup solves
                sim.step_once()
        finally:
            c.uninstall()
        vel = np.asarray(sim.state.vel)
        return (vel[0] if fleet else vel,
                np.asarray(sim.state.pres)[0] if fleet
                else np.asarray(sim.state.pres),
                sim.time, c.snapshot())

    v_u, p_u, t_u, c_u = run(False)
    v_f, p_f, t_f, c_f = run(True)
    assert np.array_equal(v_u, v_f)
    assert np.array_equal(p_u, p_f)
    assert t_u == t_f
    # the fused fleet dispatch pays the SAME one batched diag pull per
    # step the solo driver pays — batching is free at B=1
    assert c_f["device_gets"] == c_u["device_gets"] == n
    assert c_f["state_gathers"] == 0


# ---------------------------------------------------------------------------
# B>1: members match their solo runs; per-member dt is real
# ---------------------------------------------------------------------------

def test_fleet_members_match_solo_runs():
    B, n = 2, 6
    fleet = _fleet(B)
    solos = [_solo(m) for m in range(B)]
    fleet_diag = solo_diags = None
    for _ in range(n):
        fleet_diag = fleet.step_once()
        solo_diags = [s.step_once() for s in solos]
    for m in range(B):
        vs = np.asarray(solos[m].state.vel)
        vf = np.asarray(fleet.state.vel)[m]
        # <= 1e-12: bit-exact except the documented MG FMA-contraction
        # noise (fleet.py module docstring) — advection, projection and
        # every reduction are bit-equal per member
        dev = np.abs(vs - vf).max()
        assert dev <= 1e-12, (m, dev)
        # each member integrated at ITS OWN dt — the solo clock, not a
        # fleet lockstep. The clock can differ from solo by an ulp:
        # the <=1e-12 state deviation may perturb the umax cell and
        # hence dt_next in its last bit.
        assert abs(fleet.times[m] - solos[m].time) <= 1e-12
        # solver health matches solo exactly (same iteration counts —
        # production solves are short warm-start solves, robust to the
        # preconditioner's rounding noise)
        assert int(np.asarray(fleet_diag["poisson_iters"])[m]) \
            == int(solo_diags[m]["poisson_iters"])
    # the amplitude ladder produced genuinely distinct clocks
    assert len({float(t) for t in fleet.times}) == B


# ---------------------------------------------------------------------------
# Poisson member mask: converged members freeze bit-exactly
# ---------------------------------------------------------------------------

def test_converged_member_frozen_under_extra_iterations():
    """A member whose solve converges early must return EXACTLY its
    solo solution: the fused loop keeps sweeping for the slow member,
    and the per-member mask makes those sweeps identity for the
    converged one (the lax.select freeze in poisson.bicgstab)."""
    fleet = _fleet(2)
    g = fleet.grid
    rng = np.random.default_rng(7)
    # member 0: near-trivial RHS (converges at iteration ~0);
    # member 1: rough full-amplitude RHS (needs many more iterations)
    easy = 1e-4 * np.ones((g.ny, g.nx))
    easy -= easy.mean()
    hard = rng.standard_normal((g.ny, g.nx))
    b = jnp.asarray(np.stack([easy, hard]))

    kw = dict(tol=1e-3, tol_rel=1e-2, max_iter=100, max_restarts=0,
              sum_dtype=g.sum_dtype)
    solve = jax.jit(lambda bb: bicgstab(
        g.laplacian, bb, M=g.mg, member_axis=True, **kw))
    both = solve(b)
    iters = np.asarray(both.iters)
    assert iters[0] < iters[1], iters   # the mask had work to do

    # THE invariance claim: the easy member's pressure must be
    # BIT-IDENTICAL whether its co-member converges instantly (loop
    # exits with it) or grinds on for many more sweeps (loop keeps
    # running, the frozen member riding along) — the extra iterations
    # are exact identity for a converged member
    short = solve(jnp.asarray(np.stack([easy, easy])))
    assert int(np.asarray(short.iters)[0]) == int(iters[0])
    assert np.array_equal(np.asarray(both.x[0]),
                          np.asarray(short.x[0]))

    # the EASY member also agrees with its solo solve (short solve —
    # robust to the MG FMA-contraction noise). The HARD member's long
    # rough solve is deliberately NOT compared iteration-for-iteration:
    # ~50 Krylov iterations compound the preconditioner's ~1-ulp
    # rounding into a genuinely different (equally converged) path;
    # the production-regime solo equivalence is pinned by
    # test_fleet_members_match_solo_runs.
    solo = jax.jit(lambda bb: bicgstab(
        g.laplacian, bb, M=g.mg, **kw))(b[0])
    assert int(iters[0]) == int(solo.iters)
    assert bool(np.asarray(both.converged)[0]) == bool(solo.converged)
    scale = max(1.0, float(np.abs(np.asarray(solo.x)).max()))
    assert np.abs(np.asarray(both.x[0])
                  - np.asarray(solo.x)).max() <= 1e-12 * scale
    assert bool(np.asarray(both.converged)[1])   # hard member converged


# ---------------------------------------------------------------------------
# per-member supervision
# ---------------------------------------------------------------------------

@pytest.mark.slow   # ~8 s; the unguarded-equal-pulls contract is
#                     tier-1 via test_fleet_b1..., and the guarded
#                     healthy-member bit-identity via the fault drill
def test_fleet_guard_unfaulted_bit_identical_equal_pulls():
    n = 6

    def run(guarded):
        sim = _fleet(3)
        guard = FleetStepGuard(sim, watchdog=PhysicsWatchdog()) \
            if guarded else None
        c = HostCounters().install()
        try:
            for _ in range(n):
                guard.step() if guarded else sim.step_once()
            if guarded:
                guard.drain()
        finally:
            c.uninstall()
        return np.asarray(sim.state.vel), np.array(sim.times), c.snapshot()

    va, ta, ca = run(False)
    vb, tb, cb = run(True)
    assert np.array_equal(va, vb)
    assert np.array_equal(ta, tb)
    # the vectorized verdict rides the driver's one batched pull
    assert cb["device_gets"] == ca["device_gets"] == n
    assert cb["state_gathers"] == 0


def test_fleet_member_fault_rewinds_only_that_member(tmp_path):
    """The acceptance drill: nan_vel in ONE member (faults.py poisons
    member 0 on a fleet) under a snapshot cadence — recovery restores
    only that member's slice, replays it solo, retries at dt/2; the
    OTHER members' trajectories stay bit-identical to an unfaulted
    twin, clocks included."""
    n = 6
    twin = _fleet(3)
    twin_diag = None
    for _ in range(n):
        twin_diag = twin.step_once()

    sim = _fleet(3)
    log = EventLog(str(tmp_path / "events.jsonl"))
    guard = FleetStepGuard(sim, event_log=log, snap_every=3,
                           faults=FaultPlan("nan_vel@24"))
    for _ in range(n):
        guard.step()
    guard.drain()

    evs = _recoveries(tmp_path / "events.jsonl")
    assert [(e["step"], e["member"], e["action"]) for e in evs] \
        == [(24, 0, "retry")]
    assert evs[0]["replayed"] == 1      # anchor post-23, replay 23->24
    assert guard.replayed_steps == 1
    vt = np.asarray(twin.state.vel)
    vf = np.asarray(sim.state.vel)
    for m in (1, 2):                    # healthy members NEVER rewind
        assert np.array_equal(vt[m], vf[m]), m
        assert twin.times[m] == sim.times[m]
    # the faulted member recovered (dt/2 -> its clock legitimately
    # differs from the twin's)
    assert np.all(np.isfinite(vf[0]))
    assert sim.times[0] < twin.times[0]
    assert sim.step_count == twin.step_count == 26

    # schema-v3 fleet record off the twin's last diag (no extra
    # compiles): per-member detail + conservative aggregates
    rec = MetricsRecorder()
    rec.prime(twin)
    r = rec.record_step(step=twin.step_count, t=twin.time,
                        dt=twin_diag["dt"], diag=twin_diag, sim=twin,
                        wall_ms=2.0)
    assert r["fleet_members"] == 3
    assert r["member_steps_per_s"] == pytest.approx(3 / 2e-3, rel=1e-6)
    mh = r["member_health"]
    assert len(mh["umax"]) == 3
    assert r["umax"] == max(mh["umax"])
    assert r["dt_next"] == min(mh["dt_next"])
    assert r["poisson_iters"] == max(mh["poisson_iters"])
    assert r["energy"] == pytest.approx(sum(mh["energy"]))
    assert r["dt"] == min(mh["dt"])


@pytest.mark.slow   # ~9 s; the step-keyed fault-lookup mechanism it
#                     pins is exercised tier-1 by the single-fault
#                     drill (same code path, one rung)
def test_fleet_guard_consecutive_member_faults(tmp_path):
    """Faults at two consecutive steps are both caught at their OWN
    steps (the retry's fault lookup is keyed on the step being
    retried, not the already-advanced shared counter)."""
    sim = _fleet(3)
    log = EventLog(str(tmp_path / "events.jsonl"))
    guard = FleetStepGuard(sim, event_log=log,
                           faults=FaultPlan("nan_vel@24,nan_vel@25"))
    for _ in range(6):
        guard.step()
    guard.drain()
    evs = _recoveries(tmp_path / "events.jsonl")
    assert [(e["step"], e["member"], e["action"]) for e in evs] \
        == [(24, 0, "retry"), (25, 0, "retry")]
    assert np.all(np.isfinite(np.asarray(sim.state.vel)))


@pytest.mark.slow   # ~9 s; duplicative product-surface pass over the
#                     tier-1 library drill + telemetry record test
#                     (the CLI plumbing itself is tier-1 in test_io's
#                     CLI smoke for the non-fleet path)
def test_cli_fleet_drill(tmp_path, monkeypatch):
    """The full product surface: -fleet 3 with an injected nan in one
    member — supervised recovery, schema-v3 per-member telemetry, and
    per-member dumps."""
    from cup2d_tpu.__main__ import main
    from cup2d_tpu.profiling import load_metrics, summarize_metrics

    monkeypatch.setenv("CUP2D_FAULTS", "nan_vel@5")
    monkeypatch.delenv("CUP2D_TRACE", raising=False)
    out = tmp_path / "run"
    rc = main([
        "-bpdx", "1", "-bpdy", "1", "-levelMax", "1", "-levelStart", "0",
        "-Rtol", "2", "-Ctol", "1", "-extent", "1", "-CFL", "0.4",
        "-tend", "1", "-lambda", "1e6", "-nu", "0.001",
        "-poissonTol", "1e-3", "-poissonTolRel", "1e-2",
        "-maxPoissonRestarts", "0", "-maxPoissonIterations", "100",
        "-AdaptSteps", "20", "-tdump", "0", "-level", "3",
        "-dtype", "float64", "-output", str(out),
        "-maxSteps", "8", "-fleet", "3",
    ])
    assert rc == 0
    evs = _recoveries(out / "events.jsonl")
    assert [(e["step"], e["member"], e["action"]) for e in evs] \
        == [(5, 0, "retry")]
    recs = load_metrics(str(out / "metrics.jsonl"))
    ms = [r for r in recs if r.get("event") == "metrics"]
    assert [r["step"] for r in ms] == list(range(1, 9))
    assert all(r["fleet_members"] == 3 for r in ms)
    mh = ms[-1]["member_health"]
    assert len(mh["poisson_iters"]) == 3
    assert all(mh["finite"])
    assert all(len(v) == 3 for v in mh.values())
    s = summarize_metrics(recs)
    assert s["fleet_members"] == 3
    assert s["member_steps_per_s"]["mean"] > 0
    # -fleet with shapes is refused
    assert main(["-bpdx", "1", "-bpdy", "1", "-levelMax", "1",
                 "-levelStart", "0", "-Rtol", "2", "-Ctol", "1",
                 "-extent", "1", "-CFL", "0.4", "-tend", "1",
                 "-lambda", "1e6", "-nu", "0.001", "-poissonTol", "1e-3",
                 "-poissonTolRel", "1e-2", "-maxPoissonRestarts", "0",
                 "-maxPoissonIterations", "100", "-AdaptSteps", "20",
                 "-tdump", "0", "-level", "3", "-fleet", "2",
                 "-shapes", "angle=0 L=0.25 xpos=0.5 ypos=0.5",
                 "-output", str(tmp_path / "bad")]) == 2


# ---------------------------------------------------------------------------
# sharding placement
# ---------------------------------------------------------------------------

def _seed_sharded(sim, members):
    sim.state = type(sim.state)(*(
        jax.device_put(np.asarray(a), b.sharding)
        for a, b in zip(taylor_green_fleet(sim.grid, members),
                        sim.state)))
    sim.step_count = 20    # production regime, like _fleet()


@pytest.mark.slow   # ~8 s; sharded-equality machinery is tier-1 via
#                     test_mesh.py — this adds the member-axis spec
#                     assertion on top
def test_fleet_member_parallel_sharding_matches_single_device():
    """Member-parallel placement: whole members along the mesh axis —
    every member's stencils and reductions stay shard-local (zero
    per-step halo collectives), and the trajectory matches the
    single-device fleet to the 1e-12 sharded-equality bound (the GSPMD
    executable's codegen differs by ~1 ulp, same as the
    ShardedUniformSim contract in test_mesh.py)."""
    from cup2d_tpu.parallel.mesh import make_mesh
    B, n = 8, 3
    mesh = make_mesh(8)
    ref = _fleet(B)
    sharded = FleetSim(_cfg(), level=LVL, members=B, mesh=mesh)
    assert sharded.placement == "member"
    assert not sharded.grid.spmd_safe      # spatial axes unsharded
    _seed_sharded(sharded, B)
    for _ in range(n):
        ref.step_once()
        sharded.step_once()
    assert np.abs(np.asarray(ref.state.vel)
                  - np.asarray(sharded.state.vel)).max() <= 1e-12
    assert np.abs(ref.times - sharded.times).max() <= 1e-12
    # the member axis is actually what is sharded, across all devices
    spec = sharded.state.vel.sharding.spec
    assert spec[0] == "x"
    assert len(sharded.state.vel.sharding.device_set) == 8


@pytest.mark.slow   # ~25 s (GSPMD-partitioned compile of the big
#                     batched step); the placement decision logic is
#                     cheap but the executable is not — the
#                     member-parallel test covers the mesh plumbing
def test_fleet_spatial_fallback_for_big_grids():
    """Grids above member_cells_cap fall back to the spatial x-split
    (the ShardedUniformSim layout, spmd_safe stencils), member axis
    replicated."""
    from cup2d_tpu.parallel.mesh import make_mesh
    mesh = make_mesh(8)
    sim = FleetSim(_cfg(), level=LVL, members=2, mesh=mesh,
                   member_cells_cap=0)     # force the big-grid branch
    assert sim.placement == "spatial"
    assert sim.grid.spmd_safe
    _seed_sharded(sim, 2)
    ref = _fleet(2)
    for _ in range(2):
        sim.step_once()
        ref.step_once()
    # the spatial axis is sharded (member axis replicated)
    assert sim.state.vel.sharding.spec[-1] == "x"
    # 1e-12: the ShardedUniformSim sharded-equality bound
    dv = np.abs(np.asarray(sim.state.vel)
                - np.asarray(ref.state.vel)).max()
    assert dv <= 1e-12, dv


# ---------------------------------------------------------------------------
# checkpoint round-trip carries per-member clocks
# ---------------------------------------------------------------------------

@pytest.mark.slow   # ~4 s; checkpoint machinery is tier-1 via
#                     test_io — this adds only the fleet times/members
#                     meta round-trip
def test_fleet_checkpoint_roundtrip_times(tmp_path):
    from cup2d_tpu.io import load_checkpoint, save_checkpoint
    sim = _fleet(3)
    for _ in range(3):
        sim.step_once()
    times = np.array(sim.times)
    vel = np.asarray(sim.state.vel)
    save_checkpoint(str(tmp_path / "ck"), sim)
    other = FleetSim(_cfg(), level=LVL, members=3)
    load_checkpoint(str(tmp_path / "ck"), other)
    assert np.array_equal(other.times, times)
    assert other.time == times.min()
    assert np.array_equal(np.asarray(other.state.vel), vel)
    # member-count mismatch is refused loudly
    with pytest.raises(ValueError):
        load_checkpoint(str(tmp_path / "ck"),
                        FleetSim(_cfg(), level=LVL, members=2))


