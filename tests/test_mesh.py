"""Multi-device uniform path: sharded trajectory == single-device.

This is the test the reference could never write (its multi-rank story
needed a cluster, SURVEY.md §4): conftest.py forces 8 virtual CPU
devices, so the x-split `NamedSharding` execution — XLA-inserted halo
collective-permutes, cross-device reductions and all — runs for real
and must reproduce the single-device trajectory (the reference's
implicit contract that rank count never changes physics,
main.cpp:909-2142).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cup2d_tpu.config import SimConfig
from cup2d_tpu.parallel.mesh import ShardedUniformSim, make_mesh
from cup2d_tpu.uniform import UniformSim, taylor_green_state


def _cfg():
    return SimConfig(bpdx=2, bpdy=1, level_max=1, level_start=0,
                     extent=2.0, nu=1e-3, cfl=0.4, dtype="float64")


def test_eight_devices_available():
    assert len(jax.devices()) >= 8, (
        "conftest.py must force 8 virtual CPU devices"
    )


def test_make_mesh_sizes():
    mesh = make_mesh(8)
    assert mesh.devices.size == 8
    with pytest.raises(ValueError):
        make_mesh(len(jax.devices()) + 1)


def test_sharded_matches_single_device_trajectory():
    cfg = _cfg()
    level = 3  # 128 x 64 cells; Nx=128 divides 8
    ref = UniformSim(cfg, level=level)
    ref.state = taylor_green_state(ref.grid)

    mesh = make_mesh(8)
    sh = ShardedUniformSim(cfg, mesh, level=level)
    sh.set_state(taylor_green_state(sh.grid))

    # both advance under their own CFL dt — identical states must derive
    # identical dt, so the trajectories stay comparable step-for-step
    for _ in range(3):
        ref.advance(1)
        sh.advance(1)

    a = np.asarray(ref.state.vel)
    b = np.asarray(sh.state.vel)
    # identical numerics; tolerance covers reduction-order differences
    assert np.max(np.abs(a - b)) < 1e-12
    # the state really is laid out across all 8 devices
    assert len(sh.state.vel.sharding.device_set) == 8


@pytest.mark.slow   # ~31 s; sharded Krylov coverage stays tier-1 via
#                     the sharded trajectory test above + the forest
#                     ShardPoissonOp equality (test_forest_mesh)
def test_sharded_poisson_iterates():
    """The Krylov loop itself must run sharded (collectives inside
    lax.while_loop), not just the stencils."""
    cfg = _cfg()
    mesh = make_mesh(8)
    sh = ShardedUniformSim(cfg, mesh, level=3)
    state = taylor_green_state(sh.grid)
    # non-solenoidal kick so the projection has real work
    vel = state.vel.at[0].add(
        0.1 * jnp.sin(jnp.linspace(0, 3.0, sh.grid.nx))[None, :])
    sh.set_state(state._replace(vel=vel))
    diag = sh.advance(1)
    assert int(diag["poisson_iters"]) > 0
    assert bool(jnp.all(jnp.isfinite(sh.state.vel)))


def test_launch_single_host_noop_and_global_mesh():
    """init_distributed on a single-host run is a no-op returning
    process 0; global_mesh covers all (virtual) devices and plugs
    straight into ShardedUniformSim."""
    import jax
    from cup2d_tpu.parallel import global_mesh, init_distributed

    assert init_distributed() == 0
    mesh = global_mesh()
    assert mesh.devices.size == len(jax.devices())
    assert mesh.axis_names == ("x",)
