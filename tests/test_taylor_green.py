"""End-to-end validation on the Taylor-Green vortex in a free-slip box —
the analytic case SURVEY.md §4 prescribes for the test pyramid the
reference lacks. u = sin(pi x) cos(pi y) F(t), v = -cos(pi x) sin(pi y) F(t)
satisfies free-slip walls exactly on [0,1]^2 and decays with
F(t) = exp(-2 nu pi^2 t)."""

import jax.numpy as jnp
import numpy as np
import pytest

from cup2d_tpu.config import SimConfig
from cup2d_tpu.uniform import UniformSim


def _tg_sim(level, nu=1e-3):
    cfg = SimConfig(
        bpdx=1, bpdy=1, level_max=level + 1, level_start=level, extent=1.0,
        nu=nu, cfl=0.4, lam=0.0, poisson_tol=1e-11, poisson_tol_rel=0.0,
        dtype="float64",
    )
    sim = UniformSim(cfg)
    x, y = sim.grid.cell_centers()
    u = np.sin(np.pi * x) * np.cos(np.pi * y)
    v = -np.cos(np.pi * x) * np.sin(np.pi * y)
    sim.state = sim.state._replace(vel=jnp.asarray(np.stack([u, v])))
    return sim


def test_taylor_green_decay():
    nu = 1e-3
    sim = _tg_sim(level=3, nu=nu)  # 64^2
    w0 = float(jnp.max(jnp.abs(sim.grid.vorticity_field(sim.state.vel))))
    t_end = 0.2
    sim.advance(n_steps=10_000, tend=t_end)
    assert sim.time >= t_end
    w1 = float(jnp.max(jnp.abs(sim.grid.vorticity_field(sim.state.vel))))
    expected = np.exp(-2 * nu * np.pi**2 * sim.time)
    measured = w1 / w0
    assert abs(measured - expected) / expected < 0.02, (measured, expected)


def test_divergence_free_after_projection():
    sim = _tg_sim(level=3)
    sim.advance(n_steps=5)
    from cup2d_tpu.ops.stencil import divergence_rhs
    from cup2d_tpu.uniform import pad_vector

    div = divergence_rhs(
        pad_vector(sim.state.vel, 1),
        pad_vector(sim.state.udef, 1),
        sim.state.chi, 1, sim.grid.h, 1.0,
    )
    # The central (+-1) divergence of a centrally-projected field is zero
    # only to discretization error O(h^2) — the compact 5-point Laplacian
    # is not the composition div∘grad (same property as the reference).
    # Physical div ~ 2.4e-4 at 64^2; the rhs here carries a 0.5*h scaling.
    assert float(jnp.max(jnp.abs(div))) < 1e-5


def test_velocity_stays_bounded():
    """Free-slip box + projection: energy cannot grow."""
    sim = _tg_sim(level=2)
    e0 = float(jnp.sum(sim.state.vel**2))
    sim.advance(n_steps=20)
    e1 = float(jnp.sum(sim.state.vel**2))
    assert e1 <= e0 * 1.001
