"""Table-build scalability: the regrid-time host cost VERDICT r1 flagged.

The reference rebuilds its MPI synchronizer plans after every regrid
(main.cpp:5425-5437) for O(1e4-1e5) blocks; our equivalent is
build_tables. The pattern-memoized builder must stay in seconds at
thousands of blocks (the naive per-ghost-cell path measured 12.7 s for
ONE table at 4.3k blocks on this 1-core host).
"""

import time

import numpy as np

from cup2d_tpu.config import SimConfig
from cup2d_tpu.forest import Forest
from cup2d_tpu.halo import build_tables


def _adapted_forest(levels=3):
    cfg = SimConfig(bpdx=2, bpdy=1, level_max=4 + levels, level_start=4,
                    extent=4.0, dtype="float32")
    f = Forest(cfg, capacity=100000)
    for _ in range(levels - 1):
        for (l, i, j) in list(f.blocks.keys()):
            if l >= cfg.level_max - 1:
                continue
            nbx, nby = f.nblocks_at(l)
            x, y = (i + 0.5) / nbx, (j + 0.5) / nby
            if abs(x - y * 2 % 1.0) < 0.5 * (0.5 ** (l - cfg.level_start)):
                f.release(l, i, j)
                for a in (0, 1):
                    for b in (0, 1):
                        f.allocate(l + 1, 2 * i + a, 2 * j + b)
    return f


def test_build_tables_at_scale():
    f = _adapted_forest()
    order = f.order()
    assert len(order) >= 4000, f"forest too small: {len(order)}"
    t0 = time.perf_counter()
    tables = {
        "vec3": build_tables(f, order, 3, True, 2),
        "vec1": build_tables(f, order, 1, False, 2),
        "sca1": build_tables(f, order, 1, False, 1),
        "vec1t": build_tables(f, order, 1, True, 2),
        "sca1t": build_tables(f, order, 1, True, 1),
    }
    wall = time.perf_counter() - t0
    # all 5 per-regrid tables; generous bound (CI hosts vary) that still
    # catches a fallback to per-ghost-cell construction (~60 s here)
    assert wall < 30.0, f"table build too slow: {wall:.1f}s"
    # the split must hold: copy-type rows dominate interpolation rows
    t = tables["vec3"]
    assert t.dest_s.shape[0] > 5 * t.dest.shape[0]
    # every ghost row lands inside the lab arrays
    L = t.L
    n = len(order)
    assert int(np.max(np.asarray(t.dest_s))) < n * L * L
    assert int(np.min(np.asarray(t.dest_s))) >= 0


def test_build_tables_at_1e4_blocks():
    """The 1e4-block regime (SURVEY §6's fully developed canonical
    case; measured on-chip in the round-3 scale proof at 0.39 s/build).
    The template memo must keep WARM rebuilds — the steady-state
    regrid path — in single-digit seconds at this size on a 1-core CI
    host; a scaling regression to per-pattern rebuilds shows up as
    minutes here."""
    f = _adapted_forest(levels=4)
    order = f.order()
    assert len(order) >= 10000, f"forest too small: {len(order)}"
    build_tables(f, order, 3, True, 2)      # cold: fills the memo
    t0 = time.perf_counter()
    t = build_tables(f, order, 3, True, 2)  # warm: the per-regrid cost
    warm = time.perf_counter() - t0
    assert warm < 10.0, f"warm rebuild too slow at 1e4: {warm:.1f}s"
    n = len(order)
    assert int(np.max(np.asarray(t.dest_s))) < n * t.L * t.L
