"""Golden-trajectory regression (VERDICT r2 missing #4 / next #5).

The invariant-style suite (conservation, convergence, equality across
paths) passes even if the physics silently drifts; this test pins the
actual trajectory of a small canonical two-fish run — fish CoM and
rigid-body state, umax, block count at fixed steps — against numbers
recorded in golden_canonical.json by `python -m validation.golden
--write`. A legitimate numerics change (new discretization, tolerance
change) must consciously re-golden; anything else that moves these
values is a regression."""

import json
import os

import numpy as np
import pytest

from validation.golden import CHECK_STEPS, GOLDEN_PATH, MID_STEP, \
    run_trajectory


@pytest.mark.skipif(not os.path.exists(GOLDEN_PATH),
                    reason="golden_canonical.json not generated")
def test_golden_canonical_trajectory():
    with open(GOLDEN_PATH) as f:
        want = json.load(f)
    got = run_trajectory()
    assert set(want) == {str(s) for s in CHECK_STEPS}
    last = str(max(CHECK_STEPS))
    for step, w in want.items():
        g = got[step]
        # topology and solver behavior: exact / near-exact
        assert g["n_blocks"] == w["n_blocks"], \
            (step, g["n_blocks"], w["n_blocks"])
        assert abs(g["poisson_iters"] - w["poisson_iters"]) <= 1, \
            (step, g["poisson_iters"], w["poisson_iters"])
        np.testing.assert_allclose(g["time"], w["time"], rtol=1e-12)
        if step == last:
            # the final step pins COARSE invariants only: by t=1.5 the
            # two-fish state is chaotic enough that tight tolerances on
            # it churn on every benign numerics tweak while carrying
            # little discriminating power vs a real bug (ADVICE r4).
            # The windows below still catch sign errors, wrong-field
            # bugs, and O(1) trajectory forks.
            np.testing.assert_allclose(g["umax"], w["umax"],
                                       rtol=0.5, atol=1e-6)
            for k, (fg, fw) in enumerate(zip(g["fish"], w["fish"])):
                np.testing.assert_allclose(
                    fg["com"], fw["com"], rtol=0, atol=5e-3,
                    err_msg=f"step {step} fish {k} CoM (coarse)")
                # rigid state keeps a wide window (not none): a sign
                # flip or zeroing of an O(1) omega still fails, while
                # re-golden churn of the chaotic state (~0.3 between
                # benign numerics tweaks, ADVICE r4) passes
                for name, tol in (("u", 0.05), ("v", 0.05),
                                  ("omega", 0.8)):
                    assert abs(fg[name] - fw[name]) <= tol, \
                        (f"step {step} fish {k} {name} (coarse): "
                         f"{fg[name]} vs {fw[name]}")
            continue
        if step == str(MID_STEP):
            # mid-trajectory (pre-chaotic, just after the impulse):
            # INTERMEDIATE tolerances — 4+ orders tighter than the
            # final-step windows, so a late-window trajectory fork
            # still fails here, but loose enough that benign
            # instruction-order changes across XLA releases pass
            # without a re-golden (ADVICE r5)
            np.testing.assert_allclose(g["umax"], w["umax"],
                                       rtol=1e-3, atol=1e-9)
            for k, (fg, fw) in enumerate(zip(g["fish"], w["fish"])):
                np.testing.assert_allclose(
                    fg["com"], fw["com"], rtol=0, atol=1e-4,
                    err_msg=f"step {step} fish {k} CoM (mid)")
                for name, tol in (("u", 5e-3), ("v", 5e-3),
                                  ("omega", 5e-2)):
                    assert abs(fg[name] - fw[name]) <= tol, \
                        (f"step {step} fish {k} {name} (mid): "
                         f"{fg[name]} vs {fw[name]}")
            continue
        # early steps: f64 on CPU is deterministic; the loose-ish floors
        # absorb benign instruction-order changes across XLA releases
        np.testing.assert_allclose(g["umax"], w["umax"],
                                   rtol=1e-7, atol=1e-12)
        for k, (fg, fw) in enumerate(zip(g["fish"], w["fish"])):
            np.testing.assert_allclose(
                fg["com"], fw["com"], rtol=0, atol=1e-8,
                err_msg=f"step {step} fish {k} CoM")
            np.testing.assert_allclose(
                [fg["u"], fg["v"], fg["omega"]],
                [fw["u"], fw["v"], fw["omega"]],
                rtol=1e-6, atol=1e-10,
                err_msg=f"step {step} fish {k} rigid state")
