"""Pallas WENO kernel: bit-parity with the XLA path.

Runs only where the Pallas TPU backend exists (the CI environment is
CPU with the interpreter unavailable for the DMA idioms used); the same
comparison is part of the TPU verification drives.
"""

import jax
import numpy as np
import pytest

from cup2d_tpu.ops.pallas_kernels import HAVE_PALLAS


def _on_tpu():
    try:
        return jax.devices()[0].platform in ("tpu", "axon")
    except Exception:
        return False


@pytest.mark.skipif(not (HAVE_PALLAS and _on_tpu()),
                    reason="needs a Pallas TPU backend")
def test_pallas_advect_matches_xla():
    import jax.numpy as jnp

    from cup2d_tpu.ops.pallas_kernels import advect_diffuse_rhs_pallas
    from cup2d_tpu.ops.stencil import advect_diffuse_rhs
    from cup2d_tpu.uniform import pad_vector

    ny, nx = 128, 256
    vel = jnp.asarray(
        np.random.default_rng(0).standard_normal((2, ny, nx)), jnp.float32)
    lab = pad_vector(vel, 3)
    h, nu, dt = 1.0 / nx, 4e-5, 1e-3
    ref = advect_diffuse_rhs(lab, 3, h, nu, dt)
    got = advect_diffuse_rhs_pallas(lab, h, nu, dt, nx)
    assert float(jnp.max(jnp.abs(got - ref))) == 0.0
