"""Flight-recorder subsystem tests (tracing.py, PR 18).

- Span timeline: a guarded run with an injected fault produces the
  full hierarchy (step > dispatch/snapshot/verdict + recover > rung
  actions) in the flushed JSONL stream, and the Perfetto export is a
  structurally valid Chrome trace with correct nesting.
- Compile attribution + HBM memory ledger: every named_jit compile
  lands on its label with a duration, memory_analysis bytes and the
  Poisson components observed at trace time; the ledger's own
  re-lower compile is suppressed from HostCounters (the
  equal-compile-count contract).
- THE zero-overhead contract: a tracing-on run is bit-identical to a
  tracing-off run with EQUAL device_gets and EQUAL jit_compiles, on
  the guarded UniformSim hot loop and on FleetServer churn.
- Serving latency histograms: log2 bucket math, percentile ordering,
  the submit/admit/step collector flow.
- Log rotation (EventLog + ClientStreams) and the torn-tail-tolerant
  metrics reader (satellites).
"""

import json
import math
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cup2d_tpu import tracing
from cup2d_tpu.config import SimConfig
from cup2d_tpu.faults import FaultPlan
from cup2d_tpu.profiling import (HostCounters, load_metrics,
                                 load_metrics_report)
from cup2d_tpu.resilience import EventLog, StepGuard
from cup2d_tpu.tracing import (FlightRecorder, LatencyHistogram,
                               ServingLatency, spans_to_perfetto)
from cup2d_tpu.uniform import UniformSim, taylor_green_state


def _cfg(**kw):
    base = dict(bpdx=1, bpdy=1, level_max=1, level_start=0, extent=1.0,
                nu=1e-3, cfl=0.4, lam=1e6, dtype="float64",
                max_poisson_iterations=100)
    base.update(kw)
    return SimConfig(**base)


def _usim(level=1):
    """16^2 production-regime uniform sim (exact startup skipped) with
    a Taylor-Green state — the instruments are size-independent."""
    sim = UniformSim(_cfg(), level=level)
    sim.state = taylor_green_state(sim.grid)
    sim.step_count = 20
    return sim


@pytest.fixture(autouse=True)
def _no_leaked_recorder():
    """Every test leaves the module recorder uninstalled (a leaked
    recorder would silently turn every later test tracing-on)."""
    yield
    r = tracing.recorder()
    if r is not None:
        r.uninstall()


# ---------------------------------------------------------------------------
# latency histogram / serving collector units
# ---------------------------------------------------------------------------

def test_latency_histogram_buckets_and_percentiles():
    h = LatencyHistogram()
    assert h.report() == {"count": 0}
    assert h.percentile(0.5) is None
    for us in (1, 3, 5, 100, 1000, 10_000, 100_000):
        h.add(us / 1e6)
    rep = h.report()
    assert rep["count"] == 7
    # percentiles are bucket upper edges clamped to the max — ordered,
    # positive, and never above the observed maximum
    assert 0 < rep["p50_ms"] <= rep["p90_ms"] <= rep["p99_ms"] \
        <= rep["max_ms"]
    assert rep["max_ms"] == pytest.approx(100.0)
    # conservative within one bucket: the true p50 (100 us) maps into
    # [64, 128) us, so the reported edge is 128 us = 0.128 ms
    assert rep["p50_ms"] == pytest.approx(0.128)
    # negative / zero durations clamp into bucket 0, never raise
    h.add(-1.0)
    h.add(0.0)
    assert h.report()["count"] == 9


def test_latency_histogram_overflow_bucket():
    h = LatencyHistogram()
    h.add(2e6)            # ~23 days: beyond the 40-bucket (2^40 us) range
    assert h.counts[-1] == 1
    assert h.report()["p99_ms"] == pytest.approx(2e9)  # clamped to max


def test_serving_latency_collector_flow():
    lat = ServingLatency()
    lat.on_submit("a")
    lat.on_submit("b")
    lat.on_admit("a")
    lat.on_step(["a", None], 0.002)      # None slots are skipped
    lat.on_step(["a", None], 0.002)
    rep = lat.report()
    pool = rep["pool"]
    assert pool["queue_wait"]["count"] == 1
    # admit_to_first_step observes exactly ONCE (popped at first step)
    assert pool["admit_to_first_step"]["count"] == 1
    assert pool["step"]["count"] == 2
    assert rep["clients"]["a"]["step"]["count"] == 2
    assert "b" not in rep["clients"]     # submitted, never admitted
    assert "untracked_clients" not in rep


def test_serving_latency_client_cap(monkeypatch):
    monkeypatch.setattr(ServingLatency, "MAX_CLIENTS", 2)
    lat = ServingLatency()
    for cid in ("a", "b", "c"):
        lat.on_step([cid], 0.001)
    rep = lat.report()
    # pool-wide keeps counting; the overflow id is reported, not lost
    assert rep["pool"]["step"]["count"] == 3
    assert set(rep["clients"]) == {"a", "b"}
    assert rep["untracked_clients"] == 1


# ---------------------------------------------------------------------------
# span timeline + Perfetto export (fault -> recovery rungs on the path)
# ---------------------------------------------------------------------------

def test_span_timeline_and_perfetto_export(tmp_path):
    sink = EventLog(str(tmp_path / "spans.jsonl"))
    flight = FlightRecorder(capture_memory=False, sink=sink).install()
    try:
        sim = _usim()
        guard = StepGuard(sim, faults=FaultPlan("nan_vel@22"))
        for _ in range(4):
            guard.step()
        guard.drain()
        flight.flush()
    finally:
        flight.uninstall()
        sink.close()
    rows = [json.loads(ln)
            for ln in open(tmp_path / "spans.jsonl") if ln.strip()]
    assert rows and all(r["event"] == "span" for r in rows)
    names = {r["name"] for r in rows}
    # the full guarded hierarchy, recovery rungs included
    assert {"step", "dispatch", "snapshot", "verdict",
            "recover", "retry"} <= names
    rec = next(r for r in rows if r["name"] == "recover")
    assert rec["verdict"] == "nonfinite" and rec["depth"] >= 1
    rungs = [r for r in rows if r["name"] in ("retry", "escalate")]
    assert all(isinstance(r["rung"], int) for r in rungs)
    # every row is a positive-duration interval with a step attribute
    assert all(r["dur_us"] >= 1 and isinstance(r["ts_us"], int)
               for r in rows)

    # Perfetto export: valid trace-event JSON, nested intervals
    trace = spans_to_perfetto(rows)
    evs = [e for e in trace["traceEvents"] if e["ph"] == "X"]
    meta = [e for e in trace["traceEvents"] if e["ph"] == "M"]
    assert evs and any(m["name"] == "process_name" for m in meta)
    step_ev = max((e for e in evs if e["name"] == "step"),
                  key=lambda e: e["dur"])
    inner = [e for e in evs
             if e["name"] in ("dispatch", "snapshot")
             and e["ts"] >= step_ev["ts"]
             and e["ts"] + e["dur"] <= step_ev["ts"] + step_ev["dur"]]
    assert inner, "no span nests inside the longest step interval"
    json.dumps(trace)   # serializable end-to-end


def test_span_ring_bounded_without_sink():
    flight = FlightRecorder(max_spans=16, sink=None,
                            capture_memory=False).install()
    try:
        for i in range(50):
            with tracing.span("s", i=i):
                pass
    finally:
        flight.uninstall()
    assert flight.span_count == 50
    assert len(flight._buf) == 16          # ring capped
    assert flight.spans_dropped == 34      # accounted, not silent


def test_spans_off_returns_shared_nullcontext():
    # library default (no recorder): span() must not allocate
    assert tracing.span("x") is tracing.span("y")


def test_post_trace_export_cli(tmp_path):
    from cup2d_tpu.post import main as post_main, trace_export
    sink = EventLog(str(tmp_path / "spans.jsonl"))
    flight = FlightRecorder(capture_memory=False, sink=sink).install()
    try:
        with tracing.span("step", step=1):
            with tracing.span("dispatch", step=1):
                pass
        flight.flush()
    finally:
        flight.uninstall()
        sink.close()
    out = trace_export(str(tmp_path / "spans.jsonl"))
    assert out == str(tmp_path / "trace.json")
    trace = json.load(open(out))
    assert any(e["name"] == "dispatch" for e in trace["traceEvents"])
    assert post_main(["--trace", str(tmp_path / "spans.jsonl")]) == 0


# ---------------------------------------------------------------------------
# compile attribution + HBM memory ledger
# ---------------------------------------------------------------------------

def test_compile_ledger_attribution_memory_and_suppression():
    # the operand exists BEFORE any instrument: an eager fill op can
    # itself fire a backend compile, which belongs to neither twin
    x = jnp.ones((8, 8), jnp.float32)
    x.block_until_ready()
    flight = FlightRecorder(spans=False).install()
    counters = HostCounters().install()
    try:
        def impl(a, b):
            tracing.note_component("unit.component")
            return a * 2.0 + b

        tracing.note_step(7)
        tracing.note_token("unit-token")
        fn = tracing.named_jit("unit.fn", jax.jit(impl))
        fn(x, x)
        fn(x, x)      # cache hit: no second compile
    finally:
        counters.uninstall()
    flight.uninstall()
    # ONE countable compile: the memory ledger's re-lower is hidden
    # from HostCounters and from the ledger (suppression contract)
    assert counters.jit_compiles == 1
    rep = flight.ledger_report()
    assert rep["compiles"] == 1
    assert rep["compile_ms_total"] > 0
    (row,) = rep["executables"]
    assert row["label"] == "unit.fn"
    assert row["compiles"] == 1 and row["ms"] > 0
    assert row["first_step"] == row["last_step"] == 7
    assert row["token"] == "unit-token"
    assert row["components"] == ["unit.component"]
    mem = row["memory"]
    assert mem and "error" not in mem
    assert mem["argument_bytes"] == 2 * 8 * 8 * 4
    assert mem["output_bytes"] == 8 * 8 * 4
    assert rep["hbm_exec_bytes"] == flight.hbm_exec_bytes() > 0


def test_named_jit_variant_label_and_passthrough():
    x = jnp.ones((4,), jnp.float32)    # built before the recorder
    x.block_until_ready()
    flight = FlightRecorder(spans=False, capture_memory=False).install()
    try:
        fn = tracing.named_jit(
            "unit.var",
            jax.jit(lambda v, flag=False: v + (1.0 if flag else 0.0),
                    static_argnames=("flag",)),
            variant=("flag",))
        fn(x, flag=True)
        fn(x, flag=False)
    finally:
        flight.uninstall()
    labels = {r["label"] for r in flight.ledger_report()["executables"]}
    assert labels == {"unit.var[flag=True]", "unit.var[flag=False]"}
    # attribute access passes through to the wrapped jit
    assert hasattr(tracing.named_jit("l", jax.jit(lambda x: x)),
                   "lower")


def test_uniform_sim_compiles_fully_attributed():
    """The acceptance criterion's attribution half on the solo driver:
    with the recorder on, every jit compile of a fresh UniformSim run
    lands in the ledger with a duration, and the driver's own
    executables carry their names + the Poisson component tag."""
    flight = FlightRecorder(spans=False, capture_memory=False).install()
    counters = HostCounters().install()
    try:
        sim = _usim()
        for _ in range(2):
            sim.step_once()
    finally:
        counters.uninstall()
    flight.uninstall()
    rep = flight.ledger_report()
    # nothing escapes: the ledger total equals the CI counter
    assert rep["compiles"] == counters.jit_compiles > 0
    by_label = {r["label"]: r for r in rep["executables"]}
    step_rows = [r for lbl, r in by_label.items()
                 if lbl.startswith("uniform.step")]
    assert step_rows and all(r["ms"] > 0 for r in step_rows)
    assert any("poisson.bicgstab" in (r["components"] or ())
               or "poisson.mg_solve" in (r["components"] or ())
               for r in step_rows)
    assert "uniform.dt" in by_label


# ---------------------------------------------------------------------------
# THE zero-overhead contract (acceptance-pinned): tracing-on is
# bit-identical with equal device_gets AND equal jit_compiles
# ---------------------------------------------------------------------------

def test_tracing_zero_overhead_uniform(tmp_path, monkeypatch):
    def run(traced, tag):
        flight = None
        if traced:
            sink = EventLog(str(tmp_path / f"spans_{tag}.jsonl"))
            flight = FlightRecorder(sink=sink).install()
        counters = HostCounters().install()
        pulls = {"n": 0}
        real_get = jax.device_get

        def counting_get(x):
            pulls["n"] += 1
            return real_get(x)

        try:
            with monkeypatch.context() as m:
                m.setattr(jax, "device_get", counting_get)
                sim = _usim()
                guard = StepGuard(sim)
                for _ in range(4):
                    guard.step()
                guard.drain()
        finally:
            counters.uninstall()
            if flight is not None:
                flight.close()
        return (np.asarray(sim.state.vel), np.asarray(sim.state.pres),
                sim.time, pulls["n"], counters.jit_compiles,
                counters.device_gets)

    # throwaway warmup: jax's HLO-level compile cache spans runs in
    # one process, so the FIRST run of a fresh program pays compiles
    # its twin would inherit — warm it once, then compare twins in the
    # same cache regime
    run(False, "warm")
    va, pa, ta, pulls_a, compiles_a, gets_a = run(False, "off")
    vb, pb, tb, pulls_b, compiles_b, gets_b = run(True, "on")
    assert np.array_equal(va, vb)
    assert np.array_equal(pa, pb)
    assert ta == tb
    assert pulls_b == pulls_a          # raw jax.device_get calls
    assert gets_b == gets_a            # the counted CI metric
    assert compiles_b == compiles_a    # memory re-lowers suppressed


def test_tracing_zero_overhead_fleet_churn(tmp_path):
    """The serving half of the contract: a FleetServer churn run
    (admit/step/retire/refill) under the full recorder — spans,
    compile attribution, memory ledger, latency histograms — is
    bit-identical to the untraced twin with equal counted pulls and
    compiles."""
    from cup2d_tpu.fleet import FleetRequest, FleetServer, FleetSim
    from cup2d_tpu.uniform import taylor_green_state

    def run(traced, tag):
        flight = None
        if traced:
            sink = EventLog(str(tmp_path / f"fspans_{tag}.jsonl"))
            flight = FlightRecorder(sink=sink).install()
        counters = HostCounters().install()
        try:
            sim = FleetSim(_cfg(), level=1, members=2)
            sim.step_count = 20
            server = FleetServer(
                sim, latency=ServingLatency() if traced else None)
            g = sim.grid

            def req(cid, m, t_end=np.inf):
                st = taylor_green_state(g)
                return FleetRequest(client_id=cid,
                                    state=st._replace(
                                        vel=st.vel * (0.8 ** m)),
                                    t_end=float(t_end))

            server.submit(req("keep", 0))
            dt1 = float(sim._member_dt(taylor_green_state(g).vel
                                       * 0.8))
            server.submit(req("s1", 1, 1.9 * dt1))  # retires mid-run
            for k in range(5):
                if k == 3:
                    server.submit(req("s2", 1, 1.9 * dt1))
                server.step()
        finally:
            counters.uninstall()
            if flight is not None:
                flight.close()
        assert server.retired >= 1 and server.admitted >= 3
        return (np.asarray(sim.member_state(0).vel),
                float(sim.times[0]), counters.jit_compiles,
                counters.device_gets)

    run(False, "warm")     # HLO-cache warmup — see the uniform twin
    v_a, t_a, compiles_a, gets_a = run(False, "off")
    v_b, t_b, compiles_b, gets_b = run(True, "on")
    assert np.array_equal(v_a, v_b)
    assert t_a == t_b
    assert gets_b == gets_a
    assert compiles_b == compiles_a


# ---------------------------------------------------------------------------
# satellites: size-capped rotation + torn-tail-tolerant reader
# ---------------------------------------------------------------------------

def test_eventlog_rotation_and_segmented_read(tmp_path):
    path = str(tmp_path / "metrics.jsonl")
    log = EventLog(path, rotate_mb=0.001)     # ~1 KiB per segment
    n = 60
    for i in range(n):
        log.emit(event="metrics", i=i, pad="x" * 40)
    log.close()
    segs = sorted(f for f in os.listdir(tmp_path)
                  if f.startswith("metrics.jsonl."))
    assert len(segs) >= 2                      # rotation actually fired
    assert all(os.path.getsize(tmp_path / s) < 2048 for s in segs)
    # the reader folds segments back in write order
    recs = load_metrics(path)
    assert [r["i"] for r in recs] == list(range(n))


def test_eventlog_rotation_resumes_numbering(tmp_path):
    # a restarted run must append segments AFTER the existing ones
    path = str(tmp_path / "m.jsonl")
    for _ in range(2):
        log = EventLog(path, rotate_mb=0.0001)   # ~105 bytes
        for i in range(4):
            log.emit(event="metrics", i=i, pad="y" * 80)
        log.close()
    recs = load_metrics(path)
    assert len(recs) == 8                      # nothing overwritten


def test_client_streams_rotation(tmp_path):
    from cup2d_tpu.profiling import ClientStreams
    cs = ClientStreams(str(tmp_path), rotate_mb=0.001)
    for i in range(60):
        cs.emit("c1", {"i": i, "pad": "z" * 40})
    cs.close()
    segs = [f for f in os.listdir(tmp_path)
            if f.startswith("c1.jsonl.")]
    assert segs
    recs = load_metrics(str(tmp_path / "c1.jsonl"))
    assert [r["i"] for r in recs] == list(range(60))


def test_metrics_reader_tolerates_torn_and_empty(tmp_path):
    p = tmp_path / "torn.jsonl"
    with open(p, "w") as f:
        for i in range(3):
            f.write(json.dumps({"event": "metrics", "i": i}) + "\n")
        f.write('{"event": "metrics", "i": 3, "tr')   # SIGKILL tail
    recs, torn = load_metrics_report(str(p))
    assert [r["i"] for r in recs] == [0, 1, 2]
    assert torn == 1

    empty = tmp_path / "empty.jsonl"
    empty.touch()
    assert load_metrics_report(str(empty)) == ([], 0)

    with pytest.raises(FileNotFoundError):
        load_metrics_report(str(tmp_path / "missing.jsonl"))


def test_post_metrics_summary_reports_truncated(tmp_path):
    from cup2d_tpu.post import metrics_summary
    p = tmp_path / "metrics.jsonl"
    with open(p, "w") as f:
        f.write(json.dumps({"event": "serving_latency",
                            "pool": {"step": {"count": 5}}}) + "\n")
        f.write(json.dumps({"event": "compile_ledger", "compiles": 3,
                            "executables": []}) + "\n")
        f.write('{"torn')
    out = metrics_summary(str(p))
    assert out["truncated_records"] == 1
    assert out["steps"] == 0                   # no metrics rows: no crash
    # the run-report rows surface verbatim in the summary
    assert out["serving_latency"]["pool"]["step"]["count"] == 5
    assert out["compile_ledger"]["compiles"] == 3
