"""Fish-fish contact golden regression (VERDICT r4 #5).

The disk golden pins the impulse math on rigid bodies; this pins the
canonical event — a deforming two-fish head-on encounter through the
chi-overlap impulse — including per-shape surface forces, against
numbers recorded by `python -m validation.golden_fish_contact --write`
(CPU f64). Regenerate consciously after legitimate numerics changes."""

import json
import os

import numpy as np
import pytest

from validation.golden_fish_contact import GOLDEN_PATH, N_STEPS, \
    run_trajectory


@pytest.mark.skipif(not os.path.exists(GOLDEN_PATH),
                    reason="golden_fish_contact.json not generated")
@pytest.mark.slow   # ~76 s; the canonical two-fish golden and the
#                     collision golden keep trajectory pinning in tier-1
def test_golden_fish_contact_trajectory():
    with open(GOLDEN_PATH) as f:
        want = json.load(f)
    got = run_trajectory()
    assert len(got["steps"]) == len(want["steps"]) == N_STEPS
    assert got["impulse_step"] == want["impulse_step"]
    for i, (g, w) in enumerate(zip(got["steps"], want["steps"])):
        np.testing.assert_allclose(g["time"], w["time"], rtol=1e-12)
        for k, (bg, bw) in enumerate(zip(g["bodies"], w["bodies"])):
            np.testing.assert_allclose(
                bg["com"], bw["com"], rtol=0, atol=1e-7,
                err_msg=f"step {i} body {k} com")
            for q in ("u", "v", "omega"):
                np.testing.assert_allclose(
                    bg[q], bw[q], rtol=1e-6, atol=1e-9,
                    err_msg=f"step {i} body {k} {q}")
            for q in ("fx", "fy", "torque"):
                np.testing.assert_allclose(
                    bg[q], bw[q], rtol=1e-5, atol=1e-10,
                    err_msg=f"step {i} body {k} {q}")
    # the pinned window must actually contain the impulse: the closing
    # velocity reverses sign across impulse_step (same style as
    # test_golden_collision.py) — body 0 closes (u < 0, it sits on the
    # right) then recedes (u > 0)
    s = want["impulse_step"]
    assert want["steps"][s - 1]["bodies"][0]["u"] < -0.05
    assert want["steps"][s]["bodies"][0]["u"] > 0.05
