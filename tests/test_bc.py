"""Boundary-condition engine tests (cup2d_tpu/bc.py, ISSUE 12).

Four contracts:

- Ghost-paint correctness: ``pad_vector_bc`` matches hand-rolled
  transcriptions of the per-kind ghost formulas (mirror / 2*uw - edge /
  convective extrapolation), including the corner composition and the
  clamped parabolic inflow profile.
- Operator-tier correctness: the per-face fused-BC stencil forms
  (laplacian5_bc / divergence_bc / pressure_gradient_update_bc) match
  explicit ghost-padded references, and collapse to the legacy
  free-slip/Neumann forms at the legacy coefficients.
- Default-table BIT-identity: every driver built with ``bc=FREE_SLIP``
  (or no bc at all) produces bitwise the trajectories of rounds 1-11 —
  the table is a dispatch, not a reimplementation.
- Loud refusal at every tier that cannot honor a table: the Pallas
  megakernel (in-VMEM mirror synthesis), the AMR forest (sign-flip
  gather rows) and the FleetServer admit path (pool executables are
  table-specific).

Plus the standing physics sanity: a coarse lid-driven cavity develops
the lid-following shear layer, and a uniform inflow/outflow channel
transports the exact plug flow unchanged (the Dirichlet-pressure
machinery's null test). The full Ghia et al. comparison is
@pytest.mark.slow (validation/cavity.py runs it standalone too).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cup2d_tpu.bc import (BCTable, FREE_SLIP, convective_outflow,
                          dirichlet_inflow, divergence_affine_bc,
                          divergence_coeffs, free_slip, no_slip,
                          pad_vector_bc, periodic, periodic_axes,
                          pressure_signs)
from cup2d_tpu.cases import (cavity_table, channel_table, make_sim,
                             periodic_channel_table, periodic_table)
from cup2d_tpu.config import SimConfig
from cup2d_tpu.ops.stencil import (divergence_bc, divergence_freeslip,
                                   laplacian5_bc, laplacian5_neumann,
                                   pressure_gradient_update_bc,
                                   pressure_gradient_update_fused)
from cup2d_tpu.uniform import (UniformGrid, UniformSim, pad_vector,
                               taylor_green_state)


def _cfg(**kw):
    base = dict(bpdx=1, bpdy=1, level_max=1, level_start=0, extent=1.0,
                nu=1e-3, cfl=0.4, lam=1e6, dtype="float64",
                max_poisson_iterations=100)
    base.update(kw)
    return SimConfig(**base)


def _rand(shape, seed, dtype=jnp.float64):
    return jax.random.normal(jax.random.PRNGKey(seed), shape, dtype)


# ---------------------------------------------------------------------------
# table semantics (token / flags / validation)
# ---------------------------------------------------------------------------

def test_table_tokens_flags_and_validation():
    assert FREE_SLIP.token == "fs,fs,fs,fs"
    assert FREE_SLIP.is_free_slip and FREE_SLIP.all_neumann

    cav = cavity_table()
    assert cav.token == "ns,ns,ns,ns(1,0)"
    assert not cav.is_free_slip and cav.all_neumann

    chan = channel_table(0.2)
    assert chan.token == "in(0.2,0),out,fs,fs"
    assert not chan.is_free_slip and not chan.all_neumann
    par = channel_table(0.2, profile="parabolic")
    assert par.token == "in(0.2,0)[parabolic],out,fs,fs"

    # hashable + comparable: the FleetServer admit check and executable
    # keying depend on value semantics
    assert cavity_table() == cavity_table()
    assert hash(cavity_table()) == hash(cavity_table())
    assert cavity_table() != cavity_table(lid_u=2.0)

    with pytest.raises(ValueError, match="unknown kind"):
        BCTable(x_lo=free_slip()._replace(kind="bogus")).validate()
    # periodic is a valid kind since ISSUE 20 — but only PAIRED
    with pytest.raises(ValueError, match="paired"):
        BCTable(x_lo=periodic()).validate()
    with pytest.raises(ValueError, match="paired"):
        BCTable(y_hi=periodic()).validate()
    with pytest.raises(ValueError, match="uniform|parabolic"):
        dirichlet_inflow(1.0, profile="plug")


def test_derived_coefficients():
    # signs: +1 Neumann everywhere except outflow (-1 Dirichlet)
    assert pressure_signs(FREE_SLIP) == (1.0, 1.0, 1.0, 1.0)
    assert pressure_signs(cavity_table()) == (1.0, 1.0, 1.0, 1.0)
    assert pressure_signs(channel_table(0.2)) == (1.0, -1.0, 1.0, 1.0)

    # divergence edge coefficients: legacy (+1, -1) except outflow flips
    assert divergence_coeffs(FREE_SLIP) == (1.0, -1.0, 1.0, -1.0)
    assert divergence_coeffs(channel_table(0.2)) == (1.0, 1.0, 1.0, -1.0)

    # affine term: the cavity's walls move only TANGENTIALLY -> no
    # divergence source at all (identical to free-slip)
    assert divergence_affine_bc(cavity_table(), 8, 8, jnp.float64) is None
    # a uniform inflow at x_lo sources -2*u_in on the first column
    aff = divergence_affine_bc(channel_table(0.2), 4, 6, jnp.float64)
    ref = np.zeros((4, 6))
    ref[:, 0] = -2.0 * 0.2
    np.testing.assert_array_equal(np.asarray(aff), ref)


# ---------------------------------------------------------------------------
# ghost paint vs hand-rolled edge stencils (all four kinds)
# ---------------------------------------------------------------------------

def test_pad_free_slip_table_dispatches_bitwise():
    v = _rand((2, 6, 9), 0)
    np.testing.assert_array_equal(np.asarray(pad_vector_bc(v, 3, FREE_SLIP, 0.1)),
                                  np.asarray(pad_vector(v, 3)))


def test_pad_no_slip_moving_lid_with_corners():
    """All-no_slip cavity: ghost = 2*u_wall - edge for BOTH components
    on every face; the x strips read the y-painted columns, so a corner
    ghost composes both walls' formulas exactly like the legacy mirror
    paint composes its reflections."""
    g, ny, nx = 2, 5, 7
    v = _rand((2, ny, nx), 1)
    lid = (0.7, 0.0)
    bc = BCTable(no_slip(), no_slip(), no_slip(), no_slip(*lid))
    out = np.asarray(pad_vector_bc(v, g, bc, 0.1))
    vn = np.asarray(v)

    # interior untouched
    np.testing.assert_array_equal(out[:, g:-g, g:-g], vn)
    # y faces (interior columns): stationary floor, moving lid
    for k in range(g):
        np.testing.assert_allclose(out[:, k, g:-g], -vn[:, 0, :])
        np.testing.assert_allclose(out[0, ny + g + k, g:-g],
                                   2.0 * lid[0] - vn[0, -1, :])
        np.testing.assert_allclose(out[1, ny + g + k, g:-g],
                                   -vn[1, -1, :])
    # x faces (FULL rows, reading the y-painted edge columns)
    for k in range(g):
        np.testing.assert_allclose(out[:, :, k], -out[:, :, g])
        np.testing.assert_allclose(out[:, :, nx + g + k],
                                   -out[:, :, nx + g - 1])
    # spot-check one corner ghost explicitly: (lid ghost) then mirrored
    # through the x_lo wall -> -(2*lid - edge)
    np.testing.assert_allclose(out[0, ny + g, 0],
                               -(2.0 * lid[0] - vn[0, -1, 0]))


def test_pad_parabolic_inflow_profile_clamped():
    g, ny, nx = 2, 8, 6
    v = _rand((2, ny, nx), 2)
    u_in = 0.4
    bc = BCTable(dirichlet_inflow(u_in, profile="parabolic"),
                 convective_outflow(), free_slip(), free_slip())
    out = np.asarray(pad_vector_bc(v, g, bc, 0.1))

    # the y faces are free-slip: v mirrored, u copied
    edge_u_col = out[0, :, g]          # y-padded edge column
    s = (np.arange(ny + 2 * g) - g + 0.5) / ny
    s = np.clip(s, 0.0, 1.0)           # profile closes at the corners
    prof = 4.0 * s * (1.0 - s)
    for k in range(g):
        np.testing.assert_allclose(out[0, :, k],
                                   2.0 * u_in * prof - edge_u_col,
                                   rtol=1e-12)
        np.testing.assert_allclose(out[1, :, k], -out[1, :, g])


def test_pad_convective_outflow_local_speed():
    g, ny, nx = 2, 5, 6
    v = _rand((2, ny, nx), 3)
    h, dt = 0.1, 0.04
    bc = BCTable(free_slip(), convective_outflow(), free_slip(),
                 free_slip())
    out = np.asarray(pad_vector_bc(v, g, bc, h, dt=dt))

    edge = np.asarray(pad_vector_bc(v, g, bc, h, dt=dt))[:, :, nx + g - 1]
    inner = out[:, :, nx + g - 2]
    c = np.clip(out[0, :, nx + g - 1] * dt / h, 0.0, 1.0)
    for k in range(g):
        np.testing.assert_allclose(out[:, :, nx + g + k],
                                   edge + c * (edge - inner), rtol=1e-12)
    # dt=None (diagnostic paint) degrades to zeroth-order extrapolation
    out0 = np.asarray(pad_vector_bc(v, g, bc, h))
    for k in range(g):
        np.testing.assert_allclose(out0[:, :, nx + g + k],
                                   out0[:, :, nx + g - 1])


# ---------------------------------------------------------------------------
# periodic faces (ISSUE 20): wrap paint + derived coefficients + wrapped
# operator stencils, each against a hand-rolled torus reference
# ---------------------------------------------------------------------------

def test_periodic_table_tokens_and_coefficients():
    per = periodic_table()
    assert per.token == "pd,pd,pd,pd"
    assert not per.is_free_slip
    # the operator is still all-Neumann-singular on the torus: the
    # mean-removal contract stays on
    assert per.all_neumann
    assert pressure_signs(per) == (0.0, 0.0, 0.0, 0.0)
    assert divergence_coeffs(per) == (0.0, 0.0, 0.0, 0.0)
    assert periodic_axes(per) == (True, True)
    assert divergence_affine_bc(per, 6, 8, jnp.float64) is None

    chan = periodic_channel_table()
    assert chan.token == "pd,pd,ns,ns"
    assert periodic_axes(chan) == (True, False)
    assert pressure_signs(chan) == (0.0, 0.0, 1.0, 1.0)
    assert periodic_axes(FREE_SLIP) == (False, False)


def test_pad_periodic_wrap_vs_roll_reference():
    """All-periodic box: the padded array IS the torus — every ghost
    cell (corners included) equals np.pad(..., mode='wrap')."""
    g, ny, nx = 2, 5, 7
    v = _rand((2, ny, nx), 7)
    out = np.asarray(pad_vector_bc(v, g, periodic_table(), 0.1))
    ref = np.pad(np.asarray(v), ((0, 0), (g, g), (g, g)), mode="wrap")
    np.testing.assert_array_equal(out, ref)


def test_pad_periodic_mixed_channel_corners():
    """Periodic-x + no-slip-y: y ghosts paint first on interior
    columns, then the x wrap copies FULL rows — so a corner ghost is
    the wrapped image of the y-painted wall ghost (y-then-x
    composition, same order as the wall-only corner rule)."""
    g, ny, nx = 2, 5, 6
    v = _rand((2, ny, nx), 8)
    out = np.asarray(pad_vector_bc(v, g, periodic_channel_table(), 0.1))
    vn = np.asarray(v)

    # reference: y no-slip paint on the unpadded columns...
    ye = np.zeros((2, ny + 2 * g, nx))
    ye[:, g:-g, :] = vn
    for k in range(g):
        ye[:, k, :] = -vn[:, 0, :]
        ye[:, ny + g + k, :] = -vn[:, -1, :]
    # ...then the x wrap of the painted rows (torus in x only)
    ref = np.pad(ye, ((0, 0), (0, 0), (g, g)), mode="wrap")
    np.testing.assert_array_equal(out, ref)


def test_periodic_operators_vs_torus_reference():
    """laplacian5_bc / divergence_bc / pressure_gradient_update_bc
    with periodic axes equal the hand-rolled np.roll torus stencils
    (signs/coefficients are 0 on periodic faces — no edge terms)."""
    ny, nx = 6, 8
    p = np.asarray(_rand((ny, nx), 9))
    v = _rand((2, ny, nx), 10)
    h, dt = 0.1, 0.03

    def roll(a, dy, dx):
        return np.roll(a, shift=(-dy, -dx), axis=(-2, -1))

    # fully periodic
    got = np.asarray(laplacian5_bc(jnp.asarray(p), 0.0, 0.0, 0.0, 0.0,
                                   px=True, py=True))
    ref = (roll(p, 0, 1) + roll(p, 0, -1) + roll(p, 1, 0)
           + roll(p, -1, 0) - 4.0 * p)
    np.testing.assert_allclose(got, ref, rtol=1e-13)

    u, w = np.asarray(v[0]), np.asarray(v[1])
    got = np.asarray(divergence_bc(v, 0.0, 0.0, 0.0, 0.0,
                                   px=True, py=True))
    ref = (roll(u, 0, 1) - roll(u, 0, -1)) + (roll(w, 1, 0)
                                              - roll(w, -1, 0))
    np.testing.assert_allclose(got, ref, rtol=1e-13)

    got = np.asarray(pressure_gradient_update_bc(
        jnp.asarray(p), h, dt, 0.0, 0.0, 0.0, 0.0, px=True, py=True))
    pfac = -0.5 * dt * h
    ref = pfac * np.stack([roll(p, 0, 1) - roll(p, 0, -1),
                           roll(p, 1, 0) - roll(p, -1, 0)])
    np.testing.assert_allclose(got, ref, rtol=1e-13)

    # mixed channel: wrap in x, no-slip walls in y (Neumann pressure)
    got = np.asarray(laplacian5_bc(jnp.asarray(p), 0.0, 0.0, 1.0, 1.0,
                                   px=True, py=False))
    pe = np.pad(p, ((1, 1), (0, 0)), mode="edge")   # Neumann y ghosts
    pe = np.pad(pe, ((0, 0), (1, 1)), mode="wrap")  # periodic x
    ref = (pe[1:-1, 2:] + pe[1:-1, :-2] + pe[2:, 1:-1]
           + pe[:-2, 1:-1] - 4.0 * p)
    np.testing.assert_allclose(got, ref, rtol=1e-13)


# ---------------------------------------------------------------------------
# fused-BC operator forms vs ghost-padded references
# ---------------------------------------------------------------------------

def _ref_lap(p, signs):
    sx_lo, sx_hi, sy_lo, sy_hi = signs
    ny, nx = p.shape
    pe = np.zeros((ny + 2, nx + 2), p.dtype)
    pe[1:-1, 1:-1] = p
    pe[1:-1, 0] = sx_lo * p[:, 0]
    pe[1:-1, -1] = sx_hi * p[:, -1]
    pe[0, 1:-1] = sy_lo * p[0, :]
    pe[-1, 1:-1] = sy_hi * p[-1, :]
    return (pe[1:-1, 2:] + pe[1:-1, :-2] + pe[2:, 1:-1] + pe[:-2, 1:-1]
            - 4.0 * p)


def test_laplacian5_bc_vs_ghost_padded_reference():
    p = np.asarray(_rand((7, 9), 4))
    legacy = laplacian5_bc(jnp.asarray(p), 1.0, 1.0, 1.0, 1.0)
    np.testing.assert_array_equal(np.asarray(legacy),
                                  np.asarray(laplacian5_neumann(jnp.asarray(p))))
    for signs in ((1.0, -1.0, 1.0, 1.0), (-1.0, -1.0, 1.0, -1.0)):
        got = laplacian5_bc(jnp.asarray(p), *signs)
        np.testing.assert_allclose(np.asarray(got), _ref_lap(p, signs),
                                   rtol=1e-13)


def test_divergence_bc_vs_reference_and_legacy():
    v = _rand((2, 6, 8), 5)
    legacy = divergence_bc(v, 1.0, -1.0, 1.0, -1.0)
    np.testing.assert_array_equal(np.asarray(legacy),
                                  np.asarray(divergence_freeslip(v)))

    # outflow at x_hi: ghost u = edge u -> edge coefficient flips
    got = np.asarray(divergence_bc(v, 1.0, 1.0, 1.0, -1.0))
    u, w = np.asarray(v[0]), np.asarray(v[1])
    ue = np.zeros((u.shape[0], u.shape[1] + 2))
    ue[:, 1:-1] = u
    ue[:, 0] = -u[:, 0]        # mirror ghost
    ue[:, -1] = u[:, -1]       # extrapolated ghost
    we = np.zeros((w.shape[0] + 2, w.shape[1]))
    we[1:-1, :] = w
    we[0, :] = -w[0, :]
    we[-1, :] = -w[-1, :]
    ref = (ue[:, 2:] - ue[:, :-2]) + (we[2:, :] - we[:-2, :])
    np.testing.assert_allclose(got, ref, rtol=1e-13)


def test_pressure_gradient_bc_vs_reference_and_legacy():
    p = _rand((6, 8), 6)
    h, dt = 0.1, 0.03
    legacy = pressure_gradient_update_bc(p, h, dt, 1.0, 1.0, 1.0, 1.0)
    np.testing.assert_array_equal(
        np.asarray(legacy),
        np.asarray(pressure_gradient_update_fused(p, h, dt)))

    # Dirichlet x_hi: the gradient differences against the reflected
    # ghost (-edge) instead of the copied one
    got = np.asarray(pressure_gradient_update_bc(p, h, dt,
                                                 1.0, -1.0, 1.0, 1.0))
    pn = np.asarray(p)
    signs = (1.0, -1.0, 1.0, 1.0)
    ny, nx = pn.shape
    pe = np.zeros((ny + 2, nx + 2))
    pe[1:-1, 1:-1] = pn
    pe[1:-1, 0] = signs[0] * pn[:, 0]
    pe[1:-1, -1] = signs[1] * pn[:, -1]
    pe[0, 1:-1] = signs[2] * pn[0, :]
    pe[-1, 1:-1] = signs[3] * pn[-1, :]
    pfac = -0.5 * dt * h
    ref = pfac * np.stack([pe[1:-1, 2:] - pe[1:-1, :-2],
                           pe[2:, 1:-1] - pe[:-2, 1:-1]])
    np.testing.assert_allclose(got, ref, rtol=1e-13)


# ---------------------------------------------------------------------------
# default-table BIT-identity on every driver (the dispatch contract)
# ---------------------------------------------------------------------------

def _run_steps(sim, n=4):
    for _ in range(n):
        sim.step_once()
    return np.asarray(sim.state.vel), np.asarray(sim.state.pres)


def test_default_table_bit_identical_uniform():
    a = UniformSim(_cfg(), level=2)
    b = UniformSim(_cfg(), level=2, bc=FREE_SLIP)
    # distinct state objects: the stepping jits donate their buffers
    a.state = taylor_green_state(a.grid)
    b.state = taylor_green_state(b.grid)
    va, pa = _run_steps(a)
    vb, pb = _run_steps(b)
    np.testing.assert_array_equal(va, vb)
    np.testing.assert_array_equal(pa, pb)
    assert a.bc_table == b.bc_table == "fs,fs,fs,fs"


@pytest.mark.slow
def test_default_table_bit_identical_shaped():
    # slow: ~12 s of shaped-step compiles. The bit-identity CONTRACT is
    # pinned tier-1 on UniformSim above — every driver routes the
    # default-table dispatch through the same grid-level is_free_slip
    # selection, so this drills the obstacle-step COMPOSITION of that
    # already-pinned dispatch (PR-6 duplicative-heavyweight precedent).
    from cup2d_tpu.models import DiskShape
    from cup2d_tpu.sim import Simulation

    def build(**kw):
        s = Simulation(_cfg(), shapes=[DiskShape(0.12, 0.4, 0.5,
                                                 prescribed=(0.2, 0.0))],
                       level=3, **kw)
        s.initialize()
        return s

    va, pa = _run_steps(build(), 3)
    vb, pb = _run_steps(build(bc=FREE_SLIP), 3)
    np.testing.assert_array_equal(va, vb)
    np.testing.assert_array_equal(pa, pb)


@pytest.mark.slow
def test_default_table_bit_identical_sharded():
    # slow: ~37 s of 8-device sharded jit compiles (steps are ~free).
    # Same rationale as the shaped twin: the dispatch contract is
    # tier-1 on UniformSim, and the sharded step itself is pinned by
    # the tier-1 sharded==single equalities in test_mesh.py.
    from cup2d_tpu.parallel.mesh import ShardedUniformSim, make_mesh
    mesh = make_mesh(8)
    a = ShardedUniformSim(_cfg(), mesh, level=3)
    b = ShardedUniformSim(_cfg(), mesh, level=3, bc=FREE_SLIP)
    a.set_state(taylor_green_state(a.grid))
    b.set_state(taylor_green_state(b.grid))
    va, pa = _run_steps(a, 3)
    vb, pb = _run_steps(b, 3)
    np.testing.assert_array_equal(va, vb)
    np.testing.assert_array_equal(pa, pb)


# ---------------------------------------------------------------------------
# loud refusals: AMR forest, fleet admit (ISSUE 16 retired the Pallas
# tier's refusal — the megakernel now honors every table kind)
# ---------------------------------------------------------------------------

def test_pallas_tier_composes_with_bc_tables(monkeypatch):
    """ISSUE 16: the megakernel synthesizes EVERY bc.py ghost kind in
    VMEM (affine edge/inner-line combinations baked in at trace time),
    so the pre-16 non-free-slip construction refusal is gone — the
    grid latches the tier and the kernel_tier property names the
    table's token. Equivalence bounds live in test_megakernel.py."""
    monkeypatch.setenv("CUP2D_PALLAS", "1")
    monkeypatch.delenv("CUP2D_PREC", raising=False)
    cfg = _cfg(dtype="float32")
    g = UniformGrid(cfg, level=2, bc=cavity_table())
    assert g.kernel_tier == "pallas-fused+bc(ns,ns,ns,ns(1,0))"
    # the default table keeps the bare PR-9 tier string (and the
    # bit-identical executable, pinned in test_megakernel.py)
    assert UniformGrid(cfg, level=2, bc=FREE_SLIP).kernel_tier == \
        "pallas-fused"


def test_amr_refuses_non_free_slip_table():
    from cup2d_tpu.amr import AMRSim
    cfg = SimConfig(bpdx=1, bpdy=1, level_max=3, level_start=1,
                    extent=1.0, dtype="float64", nu=1e-3, lam=1e5,
                    rtol=0.5, ctol=0.05, max_poisson_iterations=40,
                    poisson_tol=1e-4, poisson_tol_rel=1e-3)
    with pytest.raises(ValueError, match="non-free-slip"):
        AMRSim(cfg, shapes=[], bc=cavity_table())


def test_fleet_server_refuses_bc_mismatched_admit():
    from cup2d_tpu.fleet import FleetRequest, FleetServer, FleetSim
    sim = FleetSim(_cfg(), level=2, members=2, bc=cavity_table())
    sim.step_count = 20            # production regime (as in fleet tests)
    server = FleetServer(sim)
    st = sim.grid.zero_state()

    # matching table admits; a session minted for the legacy box does not
    server.submit(FleetRequest(client_id="ok", state=st,
                               bc=cavity_table()))
    assert server.step() is not None
    server.submit(FleetRequest(client_id="bad", state=st, bc=FREE_SLIP))
    with pytest.raises(ValueError, match="does not match the pool"):
        server.step()


# ---------------------------------------------------------------------------
# physics sanity (tier-1 sized)
# ---------------------------------------------------------------------------

def test_cavity_coarse_develops_lid_shear():
    """32^2 cavity, a few dozen steps: state stays finite, the top row
    follows the lid, the bottom row barely moves, and the projection
    keeps the discrete divergence near zero — the cheap standing proxy
    for the @slow Ghia comparison."""
    sim = make_sim("cavity", level=2, dtype="float64")
    assert sim.case == "cavity" and sim.bc_table == "ns,ns,ns,ns(1,0)"
    for _ in range(40):
        sim.step_once()
    vel = np.asarray(sim.state.vel)
    assert np.all(np.isfinite(vel))
    top = float(vel[0, -1, :].mean())
    bottom = float(np.abs(vel[0, 0, :]).mean())
    assert top > 0.3                      # lid-following shear layer
    assert bottom < 0.1 * top
    d = np.asarray(sim.grid.laplacian(sim.state.pres))  # operator runs
    assert np.all(np.isfinite(d))


def test_plug_flow_is_exact_through_inflow_outflow():
    """Uniform u = u_in with inflow at x_lo and convective outflow at
    x_hi is an EXACT steady solution: zero divergence, zero advective
    residual, zero pressure. Any sign error in the Dirichlet pressure
    rows, the flipped divergence coefficient or the affine inflow
    source would break this immediately."""
    u_in = 0.2
    cfg = _cfg(bpdx=2, extent=2.0, nu=1e-3, cfl=0.3)
    bc = channel_table(u_in)
    sim = UniformSim(cfg, level=2, bc=bc)
    st = sim.grid.zero_state()
    sim.state = st._replace(
        vel=st.vel.at[0].set(jnp.asarray(u_in, sim.grid.dtype)))
    for _ in range(25):
        sim.step_once()
    vel = np.asarray(sim.state.vel)
    np.testing.assert_allclose(vel[0], u_in, atol=1e-10)
    np.testing.assert_allclose(vel[1], 0.0, atol=1e-10)


@pytest.mark.slow
def test_cavity_ghia_re100_within_2pct():
    """The full acceptance run: Re=100 at 128^2 to quasi-steady state,
    both centerline profiles within 2% of the lid speed vs Ghia et al.
    (1982). Standalone: python -m validation.cavity."""
    from validation.cavity import run
    err_u, err_v = run(level=4, dtype="float64", t_end=30.0, quiet=True)
    assert err_u <= 0.02
    assert err_v <= 0.02
