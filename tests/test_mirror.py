"""Host-redundant mirrored snapshot ring (PR 17): in-HBM recovery
from REAL host loss.

PR 7's elastic drill proves ring resume when the lost host's shards
survive (a simulated loss loses no process, so the DeviceSnapshot
still covers). A REAL loss takes its shard bytes with it — pre-PR-17
every real loss landed the slow disk rung. This file drills the new
mirrored-ring rung honestly on CPU: the ``shard_loss@N`` fault ZEROES
the dead host's shard slices (live state, every ring payload, and the
mirror slices it physically held — io.destroy_shards) before recovery
runs, so a resumed trajectory that matches the from-checkpoint
reference to <= 1e-12 provably came from the NEIGHBOR's mirror, not
the "lost" originals.

Coverage: the two new fault tokens (grammar + consumption +
suspension), the mirror exchange identity (one host-granular ppermute
== roll(+Nx/H) — parallel/mesh.host_ring_shift), checksum
verify/corrupt/destroy unit semantics, mirror-aware snapshot_covers
(owner OR surviving mirror holder; neighbor-also-dead uncovered), THE
destroyed-shard drill (mirror rung, restore_source attribution in
schema v9 metrics, trajectory pin), the corrupt-mirror degrade-to-disk
drill (checksum-reject event, never installs torn bytes), the
mirror-off bit-identity + zero-extra-host-sync contract, and the
durable-event fsync satellite.
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cup2d_tpu.config import SimConfig
from cup2d_tpu.faults import FaultPlan
from cup2d_tpu.io import (corrupt_mirror, destroy_shards, load_checkpoint,
                          mirror_nbytes, mirror_snapshot, save_checkpoint,
                          snapshot_covers, snapshot_state_device,
                          verify_mirror)
from cup2d_tpu.parallel.mesh import (ShardedUniformSim, host_ring_shift,
                                     make_mesh)
from cup2d_tpu.profiling import (HostCounters, MetricsRecorder,
                                 summarize_metrics)
from cup2d_tpu.resilience import (EventLog, PreemptionGuard, StepGuard,
                                  TopologyGuard)
from cup2d_tpu.uniform import taylor_green_state


def _cfg(**kw):
    base = dict(bpdx=2, bpdy=1, level_max=1, level_start=0, extent=2.0,
                nu=1e-3, cfl=0.4, dtype="float64",
                max_poisson_iterations=200)
    base.update(kw)
    return SimConfig(**base)


def _sharded(mesh, level=2):
    sim = ShardedUniformSim(_cfg(), mesh, level=level)
    sim.set_state(taylor_green_state(sim.grid))
    sim.step_count = 20     # production regime (test_elastic pattern)
    return sim


def _events(path, kind=None):
    with open(path) as f:
        evs = [json.loads(ln) for ln in f if ln.strip()]
    return [e for e in evs if kind is None or e.get("event") == kind]


# ---------------------------------------------------------------------------
# fault grammar: the real-loss and corruption tokens
# ---------------------------------------------------------------------------

def test_mirror_fault_grammar():
    plan = FaultPlan("shard_loss@5,mirror_corrupt@7")
    assert plan                       # the new tokens arm the plan
    assert plan.shard_loss == {5: 1}
    assert plan.mirror_corrupt == {7: 1}
    # consumed exactly once
    assert plan.shard_loss_at(4) is False
    assert plan.shard_loss_at(5) is True
    assert plan.shard_loss_at(5) is False
    # suspended during guard replay like every other injector
    with plan.suspend():
        assert plan.mirror_corrupt_at(7) is False
    assert plan.mirror_corrupt_at(7) is True
    assert plan.mirror_corrupt_at(7) is False
    # a typo'd directive raises instead of silently arming nothing
    with pytest.raises(ValueError):
        FaultPlan("shard_loss")           # needs @STEP
    with pytest.raises(ValueError):
        FaultPlan("mirror_corrupt")       # needs @STEP
    with pytest.raises(ValueError):
        FaultPlan("shard_lost@3")         # unknown token


# ---------------------------------------------------------------------------
# unit semantics: exchange identity, checksums, coverage, destruction
# ---------------------------------------------------------------------------

def test_host_ring_shift_is_roll():
    """The mirror exchange is exactly roll(+Nx/H) — the restore side
    (io.restore_snapshot_mirrored) relies on this identity to realign
    the neighbor-held blocks over the lost columns."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    mesh = make_mesh(devices=jax.devices()[:4])
    x = jnp.arange(4 * 16, dtype=jnp.float64).reshape(4, 16)
    xs = jax.device_put(x, NamedSharding(mesh, P(None, "x")))
    y = host_ring_shift(xs, mesh, 2)
    assert np.array_equal(np.asarray(y), np.roll(np.asarray(x), 8, -1))
    with pytest.raises(ValueError):
        host_ring_shift(xs, mesh, 1)      # no ring below 2 hosts
    with pytest.raises(ValueError):
        host_ring_shift(xs, mesh, 3)      # 3 does not divide 4 devices


def test_mirror_checksums_and_coverage():
    mesh = make_mesh(devices=jax.devices()[:4])
    sim = _sharded(mesh)
    snap = snapshot_state_device(sim)
    m = mirror_snapshot(snap, mesh, 2)
    assert m is not None and m.n_hosts == 2
    snap = snap._replace(mirror=m)
    assert mirror_nbytes(snap) > 0
    # the mirrored columns are the roll of the originals
    vel = np.asarray(snap.payload["vel"])
    assert np.array_equal(np.asarray(m.payload["vel"]),
                          np.roll(vel, vel.shape[-1] // 2, -1))
    # clean mirror verifies for either lost host
    assert verify_mirror(snap, (0,)) == []
    assert verify_mirror(snap, (1,)) == []
    # coverage: a simulated loss with DESTROYED shards voids the owner
    # rung but the surviving neighbor's mirror covers
    assert snapshot_covers(snap, lost_hosts=(1,), shards_destroyed=True)
    assert not snapshot_covers(snap, lost_hosts=(1,),
                               shards_destroyed=True, mirror=False)
    # neighbor-also-died: host 0's mirror lives on host 1 — both dead
    # means nothing holds the bytes, mirror coverage must refuse
    assert not snapshot_covers(snap, lost_hosts=(0, 1),
                               shards_destroyed=True)
    # no mirror captured -> destroyed shards are simply gone
    bare = snapshot_state_device(sim)
    assert not snapshot_covers(bare, lost_hosts=(1,),
                               shards_destroyed=True)

    # corruption: one flipped element per host block is DETECTED (the
    # injector flips exactly one so an even-count cancellation mod 2^32
    # can never mask it), and only then
    assert corrupt_mirror(snap) is True
    bad = verify_mirror(snap, (1,))
    assert bad and all(r["expected"] != r["actual"] for r in bad)
    fields = {r["field"] for r in bad}
    assert "vel" in fields and "pres" in fields
    assert corrupt_mirror(bare) is False      # nothing to corrupt

    # destruction: the dead host's slices are zeroed everywhere — the
    # live state, the snapshot payload, and the mirror slices the dead
    # host physically held (host 0's block mirrors onto host 1, so
    # killing host 1 wipes host 0's mirror copy too)
    snap2 = snapshot_state_device(sim)
    snap2 = snap2._replace(mirror=mirror_snapshot(snap2, mesh, 2))
    [wiped] = destroy_shards(sim, [snap2], (1,), 2)
    nx = vel.shape[-1]
    lost = np.s_[..., nx // 2:]
    surv = np.s_[..., :nx // 2]
    assert np.all(np.asarray(sim.state.vel)[lost] == 0)
    assert np.all(np.asarray(wiped.payload["vel"])[lost] == 0)
    assert np.any(np.asarray(wiped.payload["vel"])[surv] != 0)
    # the mirror array's PHYSICAL lost-host slice is zeroed — which
    # holds host 0's (rolled) copy; host 1's own copy lives on host 0
    # and survives
    assert np.all(np.asarray(wiped.mirror.payload["vel"])[lost] == 0)
    assert np.any(np.asarray(wiped.mirror.payload["vel"])[surv] != 0)
    # and the surviving blocks still checksum clean (per-block sums —
    # a whole-array sum would have been invalidated by the wipe)
    assert verify_mirror(wiped, (1,)) == []


# ---------------------------------------------------------------------------
# THE acceptance drill: destroyed shards, mirror-rung resume, restart pin
# ---------------------------------------------------------------------------

def test_elastic_drill_destroyed_shards_mirror_rung(tmp_path):
    """A 4-device / 2-simulated-host run REALLY loses host 1 at step
    27: host_exit@27 declares the loss and shard_loss@27 zeroes every
    byte the dead host held before recovery runs. The owner rung is
    provably void, so the guard resumes from the NEIGHBOR's mirror
    (remesh event source="mirror"), the continued trajectory matches a
    from-checkpoint restart on the shrunk mesh <= 1e-12, and the
    recovery is attributable from metrics.jsonl alone (schema v9:
    restore_source="mirror", mirror_bytes > 0)."""
    devs = jax.devices()[:4]
    mesh4 = make_mesh(devices=devs)
    events_path = str(tmp_path / "events.jsonl")
    metrics_path = str(tmp_path / "metrics.jsonl")
    log = EventLog(events_path)
    metrics_log = EventLog(metrics_path)
    ck = str(tmp_path / "ck")

    plan = FaultPlan("host_exit@27,shard_loss@27")
    topo = TopologyGuard(devices=devs, sim_hosts=2, miss_k=1,
                         faults=plan, event_log=log)
    sim = _sharded(mesh4)
    # construction-time solver-trigger state, restored for the
    # reference leg below so the restart is trigger-identical to a
    # fresh driver
    trig0 = {a: getattr(sim, a) for a in
             ("_coarse_on", "_last_iters", "_last_iters_dev")
             if hasattr(sim, a)}
    guard = StepGuard(sim, ckpt_dir=ck, event_log=log, faults=plan,
                      snap_every=1, mirror_hosts=2)
    recorder = MetricsRecorder(sink=metrics_log, guard=guard)
    recorder.prime(sim)
    stop = PreemptionGuard()

    def record(rec):
        if rec is not None:
            recorder.record_step(step=rec["step"], t=rec["t"],
                                 dt=rec["dt"], diag=rec, sim=sim)

    saved = False
    while sim.step_count < 32:
        if not saved and sim.step_count == 26:
            for rec in guard.drain():
                record(rec)
            save_checkpoint(ck, sim)
            saved = True
        beat = topo.step_boundary(stop, sim.step_count)
        assert not beat.hung and not beat.self_lost
        if beat.lost:
            guard.elastic_recover(topo)
            continue
        record(guard.step())
    for rec in guard.drain():
        record(rec)
    log.close()
    metrics_log.close()

    # the loss really happened in place, on the survivor mesh
    assert sim.mesh.devices.size == 2 and sim.step_count == 32
    assert guard.restore_source == "mirror"
    # mirror tier resized to the 1 surviving host -> disabled
    assert guard.mirror_hosts is None

    remesh_evs = _events(events_path, "remesh")
    assert len(remesh_evs) == 1
    assert remesh_evs[0]["source"] == "mirror"    # the new rung
    assert remesh_evs[0]["step"] == 26            # the checkpoint anchor
    assert _events(events_path, "mirror_reject") == []

    # schema v9 attribution from the metrics stream alone
    with open(metrics_path) as f:
        ms = [json.loads(ln) for ln in f if ln.strip()]
    pre = [m for m in ms if m["topology_epoch"] == 0]
    post = [m for m in ms if m["topology_epoch"] == 1]
    assert pre and post
    assert all(m["restore_source"] is None for m in pre)
    assert all(m["restore_source"] == "mirror" for m in post)
    assert any(m["mirror_bytes"] and m["mirror_bytes"] > 0 for m in pre)
    assert any(m["mirror_ms"] and m["mirror_ms"] > 0 for m in pre)
    # ... and post --metrics surfaces the rung (summarize_metrics is
    # exactly what the CLI report prints)
    summary = summarize_metrics(ms)
    assert summary["restore_source"] == "mirror"
    assert summary["mirror_bytes"] > 0

    # the reference: a from-checkpoint restart on the shrunk mesh —
    # the resumed trajectory must match to <= 1e-12. The restart
    # reuses the SAME (already remeshed + compiled) sim rather than a
    # fresh 2-device driver: load_checkpoint scrubs the dt chain and
    # trig0 resets the solver trigger, so the leg is state-identical
    # to a fresh restart without paying a second 2-device step compile
    # on the 1-core CI box.
    final_vel, final_pres = jax.device_get((sim.state.vel,
                                            sim.state.pres))
    final_t = float(sim.time)
    load_checkpoint(ck, sim)
    for a, v in trig0.items():
        setattr(sim, a, v)
    gref = StepGuard(sim, snap_every=1)
    while sim.step_count < 32:
        gref.step()
    gref.drain()
    assert sim.step_count == 32
    assert abs(sim.time - final_t) <= 1e-12
    for a, b in ((final_vel, sim.state.vel),
                 (final_pres, sim.state.pres)):
        assert np.max(np.abs(np.asarray(a) - np.asarray(b))) <= 1e-12


# ---------------------------------------------------------------------------
# degrade path: corrupt mirror -> checksum reject -> disk rung
# ---------------------------------------------------------------------------

@pytest.mark.slow   # ~60 s on the 1-core CI box (its own 4-device +
#                     2-device step compiles); the reject DETECTION is
#                     tier-1 via test_mirror_checksums_and_coverage
#                     (corrupt -> verify_mirror names the bad blocks),
#                     and the rung-choice plumbing this adds is pure
#                     host Python — same budget rule as the repo's
#                     other slow end-to-end drills
def test_corrupt_mirror_degrades_to_disk(tmp_path):
    """Same destroyed-shard loss, but the held mirrors are corrupted
    (mirror_corrupt@26 — fired at the dispatch right after the
    checkpoint, so the recovery's anchor carries flipped bytes). The
    rung must DETECT the corruption (one mirror_reject event naming
    the rejected blocks), refuse to install it, and degrade to the
    disk checkpoint — never silently resume from torn bytes."""
    devs = jax.devices()[:4]
    events_path = str(tmp_path / "events.jsonl")
    log = EventLog(events_path)
    ck = str(tmp_path / "ck")

    plan = FaultPlan("mirror_corrupt@26,host_exit@27,shard_loss@27")
    topo = TopologyGuard(devices=devs, sim_hosts=2, miss_k=1,
                         faults=plan, event_log=log)
    sim = _sharded(make_mesh(devices=devs))
    guard = StepGuard(sim, ckpt_dir=ck, event_log=log, faults=plan,
                      snap_every=1, mirror_hosts=2)
    stop = PreemptionGuard()

    saved = False
    while sim.step_count < 30:
        if not saved and sim.step_count == 26:
            guard.drain()
            save_checkpoint(ck, sim)
            saved = True
        beat = topo.step_boundary(stop, sim.step_count)
        if beat.lost:
            guard.elastic_recover(topo)
            continue
        guard.step()
    guard.drain()
    log.close()

    assert guard.restore_source == "disk"
    rejects = _events(events_path, "mirror_reject")
    assert len(rejects) == 1 and rejects[0]["n_rejects"] > 0
    assert rejects[0]["rejects"][0]["expected"] != \
        rejects[0]["rejects"][0]["actual"]
    remesh_evs = _events(events_path, "remesh")
    assert len(remesh_evs) == 1 and remesh_evs[0]["source"] == "disk"
    # the run continued past the degrade — recovery completed
    assert sim.step_count == 30 and sim.mesh.devices.size == 2
    assert np.all(np.isfinite(np.asarray(sim.state.vel)))


# ---------------------------------------------------------------------------
# the -noMirror contract: bit-identical, zero extra host syncs
# ---------------------------------------------------------------------------

def test_mirror_off_bit_identical_zero_extra_syncs():
    """The mirror tier must be invisible to the trajectory and to the
    host-sync discipline: a mirror-ON run produces bit-identical state
    to a mirror-OFF run with EQUAL device_get counts (capture-side
    mirroring is pure device collectives — ppermute + on-device
    checksums; the one checksum pull lives on the cold recovery path
    only). One sim object serves both runs so the comparison shares
    every compiled executable."""
    mesh = make_mesh(devices=jax.devices()[:4])
    sim = ShardedUniformSim(_cfg(), mesh, level=2)

    def run(mirror_hosts):
        sim.set_state(taylor_green_state(sim.grid))
        sim.step_count, sim.time = 20, 0.0
        sim._next_dt = None           # reset the cached dt chain
        guard = StepGuard(sim, mirror_hosts=mirror_hosts, snap_every=1)
        counters = HostCounters().install()
        try:
            while sim.step_count < 26:
                guard.step()
            guard.drain()
        finally:
            counters.uninstall()
        if mirror_hosts:
            assert guard.mirror_nbytes() > 0   # the tier really ran
        else:
            assert guard.mirror_nbytes() == 0
        return (jax.device_get(sim.state), counters.device_gets)

    state_off, gets_off = run(None)
    state_on, gets_on = run(2)
    for a, b in zip(state_off, state_on):
        assert np.asarray(a).tobytes() == np.asarray(b).tobytes()
    assert gets_off == gets_on


# ---------------------------------------------------------------------------
# satellite: recovery-critical events are fsynced at emit
# ---------------------------------------------------------------------------

def test_durable_events_fsynced_at_emit(tmp_path):
    """topology_lost / remesh / member_abort / mirror_reject must hit
    the disk AT EMIT — a crash right after a remesh must not lose the
    event trail post-mortem triage depends on. (Plain per-step metrics
    keep the cheap buffered-flush path; durability there costs an
    fsync per step for data that is reconstructible.)"""
    from cup2d_tpu.resilience import EventLog as EL
    assert {"topology_lost", "remesh", "member_abort",
            "mirror_reject"} <= set(EL._DURABLE_EVENTS)
    path = str(tmp_path / "events.jsonl")
    log = EL(path)
    log.emit(event="remesh", epoch=1, source="mirror")
    # WITHOUT closing: the line must already be durable on disk
    with open(path) as f:
        evs = [json.loads(ln) for ln in f if ln.strip()]
    assert len(evs) == 1 and evs[0]["event"] == "remesh"
    assert evs[0]["source"] == "mirror"
    log.close()
