"""Poisson subsystem tests: preconditioner correctness, BiCGSTAB
convergence on the discrete operator, and solver parity with the
reference's tolerance semantics."""

import jax.numpy as jnp
import numpy as np
import pytest

from cup2d_tpu.config import SimConfig
from cup2d_tpu.poisson import (
    apply_block_precond,
    bicgstab,
    block_precond_matrix,
)
from cup2d_tpu.uniform import UniformGrid, pad_scalar


def test_precond_matrix_matches_reference_formula():
    """P_inv must equal -inv(A_local) with A_local from getA_local
    (main.cpp:46-57): diag 4, -1 for |di|+|dj|==1 neighbors."""
    bs = 8
    p = block_precond_matrix(bs)
    n = bs * bs
    a = np.zeros((n, n))
    for i1 in range(n):
        for i2 in range(n):
            j1, x1 = divmod(i1, bs)
            j2, x2 = divmod(i2, bs)
            if i1 == i2:
                a[i1, i2] = 4.0
            elif abs(x1 - x2) + abs(j1 - j2) == 1:
                a[i1, i2] = -1.0
    np.testing.assert_allclose(p @ a, -np.eye(n), atol=1e-10)
    # symmetric (it's the inverse of a symmetric matrix)
    np.testing.assert_allclose(p, p.T, atol=1e-12)


def test_block_precond_apply_matches_dense():
    bs = 8
    p_inv = jnp.asarray(block_precond_matrix(bs))
    rng = np.random.default_rng(0)
    r = jnp.asarray(rng.standard_normal((16, 24)))
    z = apply_block_precond(r, p_inv, bs)
    # check one tile against the dense product
    tile = np.asarray(r[8:16, 8:16]).ravel()
    np.testing.assert_allclose(
        np.asarray(z[8:16, 8:16]).ravel(), np.asarray(p_inv) @ tile, rtol=1e-12
    )


def _grid(level=3, extent=1.0):
    cfg = SimConfig(bpdx=1, bpdy=1, level_max=level + 1, level_start=level,
                    extent=extent, dtype="float64")
    return UniformGrid(cfg)


def test_bicgstab_recovers_discrete_solution():
    """Apply the discrete Laplacian to a known zero-mean field, solve back:
    must recover it to solver tolerance (validates operator+solver pair)."""
    g = _grid(level=3)  # 64^2
    x, y = g.cell_centers()
    p_exact = jnp.asarray(np.cos(np.pi * x) * np.cos(np.pi * y))
    p_exact = p_exact - jnp.mean(p_exact)
    b = g.laplacian(p_exact)
    res = bicgstab(g.laplacian, b, M=g.precond, tol=1e-10, tol_rel=0.0,
                   max_iter=2000)
    assert bool(res.converged)
    p = res.x - jnp.mean(res.x)
    assert float(jnp.max(jnp.abs(p - p_exact))) < 1e-7


def test_precond_accelerates():
    g = _grid(level=3)
    # multi-mode RHS (a single cos mode is an eigenvector of the discrete
    # operator and converges in one Krylov step regardless of precond)
    rng = np.random.default_rng(42)
    raw = rng.standard_normal((g.ny, g.nx))
    b = jnp.asarray(raw - raw.mean())
    res_pc = bicgstab(g.laplacian, b, M=g.precond, tol=1e-8, tol_rel=0.0,
                      max_iter=1000)
    res_nopc = bicgstab(g.laplacian, b, M=None, tol=1e-8, tol_rel=0.0,
                        max_iter=1000)
    assert bool(res_pc.converged)
    assert int(res_pc.iters) < int(res_nopc.iters)


def test_poisson_physical_convergence():
    """Second-order convergence of the solved pressure vs the analytic
    solution of lap p = f with Neumann walls."""
    errs = []
    for level in (2, 3):
        g = _grid(level=level)
        x, y = g.cell_centers()
        k = np.pi
        p_exact = np.cos(k * x) * np.cos(k * y)
        f = -2 * k * k * p_exact  # continuous Laplacian
        b = jnp.asarray(f) * g.h * g.h  # undivided scaling
        res = bicgstab(g.laplacian, b, M=g.precond, tol=1e-12, tol_rel=0.0,
                       max_iter=2000)
        p = res.x - jnp.mean(res.x)
        errs.append(float(jnp.max(jnp.abs(p - (p_exact - p_exact.mean())))))
    order = np.log2(errs[0] / errs[1])
    assert order > 1.7, f"errors {errs}, order {order}"


def test_multigrid_preconditioner_reduces_error():
    """One V-cycle must reduce the error of lap(e)=r substantially (it
    is the production preconditioner at every uniform size)."""
    g = _grid(level=4)  # 128^2
    rng = np.random.default_rng(7)
    raw = rng.standard_normal((g.ny, g.nx))
    b = jnp.asarray(raw - raw.mean())
    e = g.mg(b)
    r1 = b - g.laplacian(e)
    # energy-norm style check on the l2 residual
    assert float(jnp.linalg.norm(r1)) < 0.3 * float(jnp.linalg.norm(b))


def test_multigrid_solver_iteration_count_flat_in_n():
    """The point of MG: Krylov iterations stay O(1) as N grows (block
    Jacobi degrades ~linearly in N_1d, measured 11 -> 174 from 1024^2
    to 4096^2 on TPU)."""
    iters = []
    for level in (3, 5):  # 64^2 -> 256^2
        g = _grid(level=level)
        x, y = g.cell_centers()
        rng = np.random.default_rng(3)
        raw = np.sin(3 * np.pi * x) * np.cos(2 * np.pi * y) \
            + 0.3 * rng.standard_normal(x.shape)
        b = jnp.asarray(raw - raw.mean())
        res = bicgstab(g.laplacian, b, M=g.mg, tol=0.0, tol_rel=1e-6,
                       max_iter=200)
        assert bool(res.converged)
        iters.append(int(res.iters))
    assert iters[1] <= iters[0] + 3, f"MG iters grew: {iters}"


def test_multigrid_f32_production_path():
    """f32 + bf16-cycle MG (the TPU production configuration) still
    converges to the reference production tolerance."""
    cfg = SimConfig(bpdx=1, bpdy=1, level_max=1, level_start=0,
                    extent=1.0, dtype="float32")
    g = UniformGrid(cfg, level=5)  # 256^2
    rng = np.random.default_rng(11)
    raw = rng.standard_normal((g.ny, g.nx)).astype(np.float32)
    b = jnp.asarray(raw - raw.mean(), jnp.float32)
    res = bicgstab(g.laplacian, b, M=g.mg, tol=1e-3,
                   tol_rel=1e-2, max_iter=200)
    assert bool(res.converged)
    true_r = float(jnp.max(jnp.abs(b - g.laplacian(res.x))))
    assert true_r <= 1.5 * max(1e-3, 1e-2 * float(jnp.max(jnp.abs(b))))


def test_coarse_dct_solve_matches_fft_solve():
    """coarse_neumann_solve_dct (the matmul form the two-level
    preconditioner runs, amr._pressure_project) must reproduce the
    mirror-extension FFT solve on non-square grids — the one round-5
    re-design without an equivalence pin (ADVICE r5): a regression in
    dct_neumann_operators (weights, eigenvalues, dtype) would otherwise
    only surface as silent preconditioner degradation."""
    from cup2d_tpu.poisson import (
        coarse_neumann_solve,
        coarse_neumann_solve_dct,
        dct_neumann_operators,
    )

    rng = np.random.default_rng(17)
    for (ncy, ncx) in ((32, 64), (48, 16)):
        raw = rng.standard_normal((ncy, ncx))
        rc = jnp.asarray(raw - raw.mean())
        h2 = 0.125 ** 2
        ops = dct_neumann_operators(ncy, ncx, dtype="float64")
        got = np.asarray(coarse_neumann_solve_dct(rc, ops, h2))
        want = np.asarray(coarse_neumann_solve(rc, h2))
        # identical diagonalization, different transform mechanics:
        # agreement to roundoff, and both mean-free (nullspace removed)
        np.testing.assert_allclose(got, want, rtol=0, atol=1e-12)
        assert abs(got.mean()) < 1e-12
