"""Poisson subsystem tests: preconditioner correctness, BiCGSTAB
convergence on the discrete operator, and solver parity with the
reference's tolerance semantics."""

import jax.numpy as jnp
import numpy as np
import pytest

from cup2d_tpu.config import SimConfig
from cup2d_tpu.poisson import (
    apply_block_precond,
    bicgstab,
    block_precond_matrix,
)
from cup2d_tpu.uniform import UniformGrid, pad_scalar


def test_precond_matrix_matches_reference_formula():
    """P_inv must equal -inv(A_local) with A_local from getA_local
    (main.cpp:46-57): diag 4, -1 for |di|+|dj|==1 neighbors."""
    bs = 8
    p = block_precond_matrix(bs)
    n = bs * bs
    a = np.zeros((n, n))
    for i1 in range(n):
        for i2 in range(n):
            j1, x1 = divmod(i1, bs)
            j2, x2 = divmod(i2, bs)
            if i1 == i2:
                a[i1, i2] = 4.0
            elif abs(x1 - x2) + abs(j1 - j2) == 1:
                a[i1, i2] = -1.0
    np.testing.assert_allclose(p @ a, -np.eye(n), atol=1e-10)
    # symmetric (it's the inverse of a symmetric matrix)
    np.testing.assert_allclose(p, p.T, atol=1e-12)


def test_block_precond_apply_matches_dense():
    bs = 8
    p_inv = jnp.asarray(block_precond_matrix(bs))
    rng = np.random.default_rng(0)
    r = jnp.asarray(rng.standard_normal((16, 24)))
    z = apply_block_precond(r, p_inv, bs)
    # check one tile against the dense product
    tile = np.asarray(r[8:16, 8:16]).ravel()
    np.testing.assert_allclose(
        np.asarray(z[8:16, 8:16]).ravel(), np.asarray(p_inv) @ tile, rtol=1e-12
    )


def _grid(level=3, extent=1.0):
    cfg = SimConfig(bpdx=1, bpdy=1, level_max=level + 1, level_start=level,
                    extent=extent, dtype="float64")
    return UniformGrid(cfg)


def test_bicgstab_recovers_discrete_solution():
    """Apply the discrete Laplacian to a known zero-mean field, solve back:
    must recover it to solver tolerance (validates operator+solver pair)."""
    g = _grid(level=3)  # 64^2
    x, y = g.cell_centers()
    p_exact = jnp.asarray(np.cos(np.pi * x) * np.cos(np.pi * y))
    p_exact = p_exact - jnp.mean(p_exact)
    b = g.laplacian(p_exact)
    res = bicgstab(g.laplacian, b, M=g.precond, tol=1e-10, tol_rel=0.0,
                   max_iter=2000)
    assert bool(res.converged)
    p = res.x - jnp.mean(res.x)
    assert float(jnp.max(jnp.abs(p - p_exact))) < 1e-7


def test_precond_accelerates():
    g = _grid(level=3)
    # multi-mode RHS (a single cos mode is an eigenvector of the discrete
    # operator and converges in one Krylov step regardless of precond)
    rng = np.random.default_rng(42)
    raw = rng.standard_normal((g.ny, g.nx))
    b = jnp.asarray(raw - raw.mean())
    res_pc = bicgstab(g.laplacian, b, M=g.precond, tol=1e-8, tol_rel=0.0,
                      max_iter=1000)
    res_nopc = bicgstab(g.laplacian, b, M=None, tol=1e-8, tol_rel=0.0,
                        max_iter=1000)
    assert bool(res_pc.converged)
    assert int(res_pc.iters) < int(res_nopc.iters)


def test_poisson_physical_convergence():
    """Second-order convergence of the solved pressure vs the analytic
    solution of lap p = f with Neumann walls."""
    errs = []
    for level in (2, 3):
        g = _grid(level=level)
        x, y = g.cell_centers()
        k = np.pi
        p_exact = np.cos(k * x) * np.cos(k * y)
        f = -2 * k * k * p_exact  # continuous Laplacian
        b = jnp.asarray(f) * g.h * g.h  # undivided scaling
        res = bicgstab(g.laplacian, b, M=g.precond, tol=1e-12, tol_rel=0.0,
                       max_iter=2000)
        p = res.x - jnp.mean(res.x)
        errs.append(float(jnp.max(jnp.abs(p - (p_exact - p_exact.mean())))))
    order = np.log2(errs[0] / errs[1])
    assert order > 1.7, f"errors {errs}, order {order}"


def test_multigrid_preconditioner_reduces_error():
    """One V-cycle must reduce the error of lap(e)=r substantially (it
    is the production preconditioner at every uniform size)."""
    g = _grid(level=3)  # 64^2 — the contraction factor is
    #                     size-independent (that's the point of MG)
    rng = np.random.default_rng(7)
    raw = rng.standard_normal((g.ny, g.nx))
    b = jnp.asarray(raw - raw.mean())
    e = g.mg(b)
    r1 = b - g.laplacian(e)
    # energy-norm style check on the l2 residual
    assert float(jnp.linalg.norm(r1)) < 0.3 * float(jnp.linalg.norm(b))


def test_multigrid_solver_iteration_count_flat_in_n():
    """The point of MG: Krylov iterations stay O(1) as N grows (block
    Jacobi degrades ~linearly in N_1d, measured 11 -> 174 from 1024^2
    to 4096^2 on TPU)."""
    iters = []
    for level in (3, 5):  # 64^2 -> 256^2
        g = _grid(level=level)
        x, y = g.cell_centers()
        rng = np.random.default_rng(3)
        raw = np.sin(3 * np.pi * x) * np.cos(2 * np.pi * y) \
            + 0.3 * rng.standard_normal(x.shape)
        b = jnp.asarray(raw - raw.mean())
        res = bicgstab(g.laplacian, b, M=g.mg, tol=0.0, tol_rel=1e-6,
                       max_iter=200)
        assert bool(res.converged)
        iters.append(int(res.iters))
    assert iters[1] <= iters[0] + 3, f"MG iters grew: {iters}"


def test_multigrid_f32_production_path():
    """f32 + bf16-cycle MG (the TPU production configuration) still
    converges to the reference production tolerance."""
    cfg = SimConfig(bpdx=1, bpdy=1, level_max=1, level_start=0,
                    extent=1.0, dtype="float32")
    g = UniformGrid(cfg, level=5)  # 256^2
    rng = np.random.default_rng(11)
    raw = rng.standard_normal((g.ny, g.nx)).astype(np.float32)
    b = jnp.asarray(raw - raw.mean(), jnp.float32)
    res = bicgstab(g.laplacian, b, M=g.mg, tol=1e-3,
                   tol_rel=1e-2, max_iter=200)
    assert bool(res.converged)
    true_r = float(jnp.max(jnp.abs(b - g.laplacian(res.x))))
    assert true_r <= 1.5 * max(1e-3, 1e-2 * float(jnp.max(jnp.abs(b))))


def test_mg_solve_full_solver_converges():
    """poisson.mg_solve (the CUP2D_POIS=fas path): pure MG cycles reach
    the same Linf criterion as the Krylov solver on a cold multi-mode
    RHS, with the true residual verifying the reported one, and the
    FMG opening (fas-f) never costs more cycles than plain V."""
    from cup2d_tpu.poisson import mg_solve

    g = _grid(level=3)  # 64^2 — the properties pinned here (true-
    #                     residual convergence, FMG <= V) are
    #                     size-independent; 64^2 keeps 3 MG levels and
    #                     halves the tier-1 cost
    rng = np.random.default_rng(7)
    raw = rng.standard_normal((g.ny, g.nx))
    b = jnp.asarray(raw - raw.mean())
    target = 1e-4 * float(jnp.max(jnp.abs(b)))
    rv = mg_solve(g.laplacian, b, g.mg, tol=0.0, tol_rel=1e-4,
                  max_cycles=100)
    rf = mg_solve(g.laplacian, b, g.mg, tol=0.0, tol_rel=1e-4,
                  max_cycles=100, fmg=True)
    assert bool(rv.converged) and bool(rf.converged)
    assert int(rf.iters) <= int(rv.iters)
    for r in (rv, rf):
        true_r = float(jnp.max(jnp.abs(b - g.laplacian(r.x))))
        assert true_r <= 1.001 * target    # reported == true residual
        assert true_r == pytest.approx(float(r.residual), rel=1e-10)


def test_mg_solve_stalls_below_precision_floor():
    """An unreachable target must exit ``stalled`` promptly (the health
    verdict treats that as benign), not burn max_cycles."""
    from cup2d_tpu.poisson import mg_solve

    cfg = SimConfig(bpdx=1, bpdy=1, level_max=1, level_start=0,
                    extent=1.0, dtype="float32")
    g = UniformGrid(cfg, level=3)   # 64^2, see size note above
    rng = np.random.default_rng(3)
    raw = rng.standard_normal((g.ny, g.nx)).astype(np.float32)
    b = jnp.asarray(raw - raw.mean(), jnp.float32)
    r = mg_solve(g.laplacian, b, g.mg, tol=0.0, tol_rel=0.0,
                 max_cycles=500)
    assert bool(r.stalled) and not bool(r.converged)
    assert int(r.iters) < 100


def test_mg_solve_member_freeze_is_exact():
    """The fleet contract at the solver level: a member's solution is
    BIT-identical across different co-member loads — once converged it
    freezes, and the extra cycles the fused loop runs for slower
    co-members are exact identity (poisson.mg_solve member_axis)."""
    from cup2d_tpu.poisson import mg_solve

    g = _grid(level=3)              # 64^2, see size note above
    rng = np.random.default_rng(11)
    raw = rng.standard_normal((g.ny, g.nx))
    b = jnp.asarray(raw - raw.mean())
    easy = jnp.asarray(
        np.cos(np.pi * np.linspace(0, 1, g.ny))[:, None]
        * np.ones((1, g.nx)))
    easy = easy - jnp.mean(easy)

    def solve(batch):
        return mg_solve(g.laplacian, jnp.stack(batch), g.mg,
                        tol=1e-8, tol_rel=1e-4, max_cycles=100,
                        member_axis=True)

    ra = solve([easy, b])          # member 0 converges cycles early
    rb = solve([easy, 0.1 * b])    # different co-member load
    assert bool(jnp.all(ra.x[0] == rb.x[0]))
    assert int(ra.iters[0]) == int(rb.iters[0])
    assert np.all(np.asarray(ra.converged))


def test_overlap_jacobi_sweeps_match_single_device():
    """The comm/compute-overlapped shard_map smoother
    (shard_halo.overlap_jacobi_sweeps) computes the SAME damped-Jacobi
    sweep as the single-device laplacian5_neumann form — the FAS
    sharded path's correctness hinge."""
    from cup2d_tpu.ops.stencil import _edge_ones, laplacian5_neumann
    from cup2d_tpu.parallel.mesh import make_mesh
    from cup2d_tpu.parallel.shard_halo import overlap_jacobi_sweeps

    mesh = make_mesh(8)
    rng = np.random.default_rng(0)
    ny, nx = 48, 64
    e = jnp.asarray(rng.standard_normal((ny, nx)))
    r = jnp.asarray(rng.standard_normal((ny, nx)))
    ex = _edge_ones(nx, e.dtype)
    ey = _edge_ones(ny, e.dtype)
    inv_d = 1.0 / (ey[:, None] + ex[None, :] - 4.0)
    ref = e
    for _ in range(3):
        ref = ref + 0.8 * (r - laplacian5_neumann(ref)) * inv_d
    got = overlap_jacobi_sweeps(e, r, inv_d, 0.8, 3, mesh)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=0, atol=1e-13)


def test_mg_solve_sharded_matches_single_device():
    """One FAS solve with the mesh-aware hierarchy (overlapped
    smoother at the finest level) against the meshless one: same
    cycles, solutions equal to reordering roundoff."""
    from cup2d_tpu.poisson import MultigridPreconditioner, mg_solve
    from cup2d_tpu.parallel.mesh import make_mesh

    g = _grid(level=3)  # 64^2 -> 8 columns per virtual device
    mesh = make_mesh(8)
    mgs = MultigridPreconditioner(g.ny, g.nx, g.dtype, mesh=mesh)
    rng = np.random.default_rng(5)
    raw = rng.standard_normal((g.ny, g.nx))
    b = jnp.asarray(raw - raw.mean())
    r1 = mg_solve(g.laplacian, b, g.mg, tol=0.0, tol_rel=1e-6,
                  max_cycles=100)
    r2 = mg_solve(g.laplacian, b, mgs, tol=0.0, tol_rel=1e-6,
                  max_cycles=100)
    assert bool(r1.converged) and bool(r2.converged)
    assert int(r1.iters) == int(r2.iters)
    np.testing.assert_allclose(np.asarray(r1.x), np.asarray(r2.x),
                               rtol=0, atol=1e-11)


def test_coarse_dct_solve_matches_fft_solve():
    """coarse_neumann_solve_dct (the matmul form the two-level
    preconditioner runs, amr._pressure_project) must reproduce the
    mirror-extension FFT solve on non-square grids — the one round-5
    re-design without an equivalence pin (ADVICE r5): a regression in
    dct_neumann_operators (weights, eigenvalues, dtype) would otherwise
    only surface as silent preconditioner degradation."""
    from cup2d_tpu.poisson import (
        coarse_neumann_solve,
        coarse_neumann_solve_dct,
        dct_neumann_operators,
    )

    rng = np.random.default_rng(17)
    for (ncy, ncx) in ((32, 64), (48, 16)):
        raw = rng.standard_normal((ncy, ncx))
        rc = jnp.asarray(raw - raw.mean())
        h2 = 0.125 ** 2
        ops = dct_neumann_operators(ncy, ncx, dtype="float64")
        got = np.asarray(coarse_neumann_solve_dct(rc, ops, h2))
        want = np.asarray(coarse_neumann_solve(rc, h2))
        # identical diagonalization, different transform mechanics:
        # agreement to roundoff, and both mean-free (nullspace removed)
        np.testing.assert_allclose(got, want, rtol=0, atol=1e-12)
        assert abs(got.mean()) < 1e-12
