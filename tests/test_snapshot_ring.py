"""Device-resident snapshot ring + snapshot-every-N replay + one-step-
lagged verdict (resilience.StepGuard / io.snapshot_state_device, PR 4):

- CI sync guard (the PR-3 equal-pull harness extended): a guarded
  lagged steady-state run makes ZERO full D2H state gathers (the
  io._gather_state counter) and no more device_get pulls than the
  unguarded driver — the verdict's one batched pull is merely moved
  off the critical path — while the trajectory stays bit-identical.
- Replay determinism: restore-from-device-snapshot + replay reproduces
  the uninterrupted trajectory bit-exactly on BOTH drivers (uniform
  and AMR), and a faults.py injection landing mid-cadence recovers
  through restore+replay with the replayed count in the event.
- Donation safety: ring entries survive the stepping jits' buffer
  donation — a restore can be issued twice and stepping continues.
- CLI: -snapEvery/-noLag plumbing, the final-step drain, and the new
  telemetry fields (snap_ring_bytes / replayed_steps / state_gathers).
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cup2d_tpu.config import SimConfig
from cup2d_tpu.faults import FaultPlan
from cup2d_tpu.models import DiskShape
from cup2d_tpu.profiling import HostCounters
from cup2d_tpu.resilience import EventLog, StepGuard
from cup2d_tpu.sim import Simulation
from cup2d_tpu.uniform import UniformSim, taylor_green_state


def _cfg(**kw):
    base = dict(bpdx=1, bpdy=1, level_max=1, level_start=0, extent=1.0,
                nu=1e-3, cfl=0.4, lam=1e6, dtype="float64",
                max_poisson_iterations=100)
    base.update(kw)
    return SimConfig(**base)


def _uniform_sim(kind="simulation"):
    cfg = _cfg()
    if kind == "uniformsim":
        sim = UniformSim(cfg, level=3)
    else:
        sim = Simulation(cfg, shapes=[], level=3)
    sim.state = taylor_green_state(sim.grid)
    # production regime from the start: the exact (tol-0) startup
    # solves would compile a second executable and grind to the
    # precision floor — none of the ring/lag/replay machinery under
    # test depends on the startup branch
    sim.step_count = 20
    return sim


def _amr_free_sim():
    from cup2d_tpu.amr import AMRSim
    cfg = SimConfig(bpdx=1, bpdy=1, level_max=2, level_start=1,
                    extent=1.0, dtype="float64", nu=1e-3,
                    max_poisson_iterations=40)
    rng = np.random.default_rng(0)
    sim = AMRSim(cfg, shapes=[])
    f = sim.forest
    f.fields["vel"] = f.fields["vel"] + jnp.asarray(
        0.1 * rng.standard_normal(f.fields["vel"].shape))
    return sim


def _recoveries(path):
    with open(path) as f:
        return [e for e in map(json.loads, filter(str.strip, f))
                if e.get("event") == "recovery"]


# ---------------------------------------------------------------------------
# CI sync guard: zero state gathers, equal pulls, bit-identical
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("kind", [
    "simulation",
    # ~6 s dup of the same mechanism on the thinner driver: UniformSim
    # shares the async_diag contract verbatim (uniform.step_once);
    # rewind-replay[uniform] keeps UniformSim guard coverage tier-1
    pytest.param("uniformsim", marks=pytest.mark.slow),
])
def test_lagged_guard_zero_gathers_equal_pulls_bit_identical(kind):
    n = 6

    def run(guarded):
        sim = _uniform_sim(kind)
        guard = StepGuard(sim) if guarded else None
        c = HostCounters().install()
        try:
            for _ in range(n):
                guard.step() if guarded else sim.step_once()
            if guarded:
                guard.drain()
        finally:
            c.uninstall()
        return (np.asarray(sim.state.vel), np.asarray(sim.state.pres),
                sim.time, c.snapshot(), guard)

    va, pa, ta, ca, _ = run(False)
    vb, pb, tb, cb, guard = run(True)
    # the lagged verdict mode actually engaged (device-diag driver)
    assert guard.sim.async_diag
    assert np.array_equal(va, vb)
    assert np.array_equal(pa, pb)
    assert ta == tb
    # the device ring + lagged verdict add NOTHING: zero full D2H
    # state gathers, and the same ONE batched device_get per step the
    # unguarded driver already paid — just issued after the next
    # dispatch instead of blocking before it
    assert cb["state_gathers"] == 0
    assert cb["device_gets"] == ca["device_gets"] == n


def test_amr_lagged_guard_zero_gathers_bit_identical():
    n = 4

    def run(guarded):
        sim = _amr_free_sim()
        guard = StepGuard(sim) if guarded else None
        c = HostCounters().install()
        try:
            for _ in range(n):
                guard.step() if guarded else sim.step_once()
            if guarded:
                guard.drain()
        finally:
            c.uninstall()
        vel = np.asarray(sim._ordered_state()["vel"])
        return vel, sim.time, c.snapshot()

    va, ta, ca = run(False)
    vb, tb, cb = run(True)
    assert np.array_equal(va, vb)
    assert ta == tb
    assert cb["state_gathers"] == 0
    # exactly the lagged pull per step and nothing else (the eager
    # driver's dt float() is not a device_get, so counts are asserted
    # absolutely rather than compared)
    assert cb["device_gets"] == n


# ---------------------------------------------------------------------------
# replay determinism: restore + replay == the uninterrupted trajectory
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("mk", [_uniform_sim, _amr_free_sim],
                         ids=["uniform", "amr"])
def test_rewind_replay_bit_exact(mk):
    sim = mk()
    guard = StepGuard(sim, snap_every=4)
    for _ in range(6):
        guard.step()
    guard.drain()
    # anchor = post-step-3 snapshot; steps 4,5 recorded for replay
    assert len(guard._replay) == 2

    def state_of():
        if hasattr(sim, "forest"):
            return np.asarray(sim._ordered_state()["vel"])
        return np.asarray(sim.state.vel)

    ref, t_ref, s_ref = state_of(), sim.time, sim.step_count
    c = HostCounters().install()
    try:
        n = guard._rewind_replay()
    finally:
        c.uninstall()
    assert n == 2 and guard.replayed_steps == 2
    # the replayed trajectory is the uninterrupted one, bit for bit —
    # and replay itself gathered nothing to host
    assert np.array_equal(state_of(), ref)
    assert sim.time == t_ref
    assert sim.step_count == s_ref
    assert c.snapshot()["state_gathers"] == 0

    # donation safety: the ring entry survived being restored (a
    # second rewind works) and stepping continues on restored buffers
    guard._rewind_replay()
    assert np.array_equal(state_of(), ref)
    guard.step()
    guard.drain()
    assert sim.step_count == s_ref + 1
    assert np.all(np.isfinite(state_of()))


@pytest.mark.slow   # ~13 s (shaped driver + unfaulted twin); the
#                     mid-cadence restore+replay drill stays tier-1 on
#                     the lagged AMR path (the next test), which also
#                     covers the discarded-successor-dispatch case
def test_mid_cadence_fault_restores_and_replays(tmp_path):
    """A NaN injection landing MID-cadence (snapEvery 3, fault between
    anchors) recovers through restore + 1-step replay + dt/2 retry; the
    recovered trajectory lands inside the same tolerances as the
    PR-2 rung-1 drill."""
    tend = 0.25

    def mk():
        return Simulation(_cfg(), shapes=[DiskShape(
            0.1, 0.4, 0.5, prescribed=(0.2, 0.0))], level=3)

    def drive_to(sim, stepper):
        # land EXACTLY on tend (last dt clipped) so faulted and
        # unfaulted runs compare at the same physical time — the dt/2
        # recovery step otherwise offsets the whole time grid
        while sim.time < tend:
            if sim._next_dt is not None:
                dt = min(sim._next_dt, sim._kinematic_dt_cap())
            else:
                dt = min(float(sim._dt(sim.state.vel)),
                         sim._kinematic_dt_cap())
            stepper(min(dt, tend - sim.time + 1e-15))

    ref = mk()
    drive_to(ref, lambda dt: ref.step_once(dt=dt))

    sim = mk()
    log = EventLog(str(tmp_path / "events.jsonl"))
    guard = StepGuard(sim, event_log=log, faults=FaultPlan("nan_vel@4"),
                      snap_every=3)
    drive_to(sim, lambda dt: guard.step(dt=dt))
    guard.drain()

    evs = _recoveries(tmp_path / "events.jsonl")
    assert [e["action"] for e in evs] == ["retry"]
    assert evs[0]["step"] == 4
    assert evs[0]["replayed"] == 1      # anchor post-2, replay step 3
    assert guard.replayed_steps == 1
    vel = np.asarray(sim.state.vel)
    ref_v = np.asarray(ref.state.vel)
    assert np.all(np.isfinite(vel))
    assert abs(np.abs(vel).max() - np.abs(ref_v).max()) \
        <= 2e-3 * np.abs(ref_v).max()


def test_discarded_dispatch_refunds_fault_counts(tmp_path):
    """Under the lagged verdict, step N+1 is dispatched before step N's
    bad verdict lands; that garbage dispatch consumes any fault armed
    for N+1 and is then discarded. The guard must REFUND the count so
    the injection fires at the real re-dispatch — here faults at two
    CONSECUTIVE steps must both be caught (without the refund only the
    first recovery happens)."""
    sim = _uniform_sim()
    log = EventLog(str(tmp_path / "events.jsonl"))
    guard = StepGuard(sim, event_log=log,
                      faults=FaultPlan("nan_vel@24,nan_vel@25"))
    assert sim.async_diag          # lagged device-diag path
    while sim.step_count < 28:
        guard.step()
    guard.drain()
    evs = _recoveries(tmp_path / "events.jsonl")
    assert [(e["step"], e["action"]) for e in evs] == \
        [(24, "retry"), (25, "retry")]
    assert np.all(np.isfinite(np.asarray(sim.state.vel)))


def test_amr_async_fault_mid_cadence_recovers(tmp_path):
    """Same drill on the lagged device-diag AMR path: the fault is
    detected one step late (step N+1 already dispatched), the garbage
    dispatch is discarded, and recovery restores the device ring and
    replays to the failed step."""
    sim = _amr_free_sim()
    log = EventLog(str(tmp_path / "events.jsonl"))
    guard = StepGuard(sim, event_log=log, faults=FaultPlan("nan_vel@4"),
                      snap_every=3)
    while sim.step_count < 6:
        guard.step()
    guard.drain()
    evs = _recoveries(tmp_path / "events.jsonl")
    assert [e["action"] for e in evs] == ["retry"]
    assert evs[0]["step"] == 4
    assert evs[0]["replayed"] == 1
    assert sim.step_count == 6
    assert np.all(np.isfinite(np.asarray(sim._ordered_state()["vel"])))
    assert np.isfinite(sim.time)


# ---------------------------------------------------------------------------
# snapshot cadence bookkeeping + ring telemetry
# ---------------------------------------------------------------------------

def test_snapshot_cadence_and_ring_bytes():
    sim = _uniform_sim()
    guard = StepGuard(sim, snap_every=4)
    per_snap = sum(np.asarray(v).nbytes
                   for v in sim.state._asdict().values())
    guard.step()                     # seed anchor + 1 pending
    assert len(guard.ring) == 1
    assert guard.ring_nbytes() == per_snap   # no cadence snap yet
    for _ in range(3):
        guard.step()                 # dispatch 4 takes the cadence snap
    # pending slot holds the optimistic post-step-3 copy: two full
    # snapshots coexist in HBM until the lagged verdict promotes it
    assert guard.ring_nbytes() == 2 * per_snap
    guard.step()                     # verdict of step 3 promotes it
    guard.drain()
    assert len(guard._replay) == 1   # step 4 rides the replay list
    assert guard.ring_nbytes() == per_snap


# ---------------------------------------------------------------------------
# CLI: -snapEvery + lagged verdict + final drain + telemetry fields
# ---------------------------------------------------------------------------

def test_cli_snap_every_lagged_drill(tmp_path, monkeypatch):
    from cup2d_tpu.__main__ import main
    from cup2d_tpu.profiling import load_metrics, summarize_metrics

    monkeypatch.setenv("CUP2D_FAULTS", "nan_vel@7")
    monkeypatch.delenv("CUP2D_TRACE", raising=False)
    out = tmp_path / "run"
    rc = main([
        "-bpdx", "1", "-bpdy", "1", "-levelMax", "1", "-levelStart", "0",
        "-Rtol", "2", "-Ctol", "1", "-extent", "1", "-CFL", "0.4",
        "-tend", "1", "-lambda", "1e6", "-nu", "0.001",
        "-poissonTol", "1e-3", "-poissonTolRel", "1e-2",
        "-maxPoissonRestarts", "0", "-maxPoissonIterations", "100",
        "-AdaptSteps", "20", "-tdump", "0", "-level", "3",
        "-dtype", "float64", "-output", str(out),
        "-maxSteps", "10", "-snapEvery", "3",
    ])
    assert rc == 0
    evs = _recoveries(out / "events.jsonl")
    assert [e["action"] for e in evs] == ["retry"]
    assert evs[0]["step"] == 7
    assert evs[0]["replayed"] == 1   # anchor post-5, replay step 6
    recs = load_metrics(str(out / "metrics.jsonl"))
    ms = [r for r in recs if r.get("event") == "metrics"]
    # the lagged records cover every step incl. the drained final one
    assert [r["step"] for r in ms] == list(range(1, 11))
    assert all(r["snap_ring_bytes"] > 0 for r in ms)
    assert all(r["state_gathers"] == 0 for r in ms)
    s = summarize_metrics(recs)
    assert s["replayed_steps_total"] == 1
    assert s["state_gathers_total"] == 0
    assert s["snap_ring_bytes"] > 0
