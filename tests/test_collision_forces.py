"""Collision impulse + surface-force diagnostics tests
(reference main.cpp:236-291, 6705-6943 collisions; 5573-5746 forces)."""

import io

import jax.numpy as jnp
import numpy as np

from cup2d_tpu.config import SimConfig
from cup2d_tpu.models import DiskShape
from cup2d_tpu.ops.collision import collision_response
from cup2d_tpu.sim import Simulation


def _head_on_colls():
    """Synthetic overlap structs: two unit-mass bodies at x = +-0.1
    moving toward each other at speed 1. The own-SDF gradient points
    INTO each body (sdf positive inside), so ivec points -x for the left
    body and +x for the right one."""
    # [m, posx, posy, momx, momy, vecx, vecy]; 10 overlap cells each
    coll_i = jnp.asarray([10.0, 10 * 0.45, 10 * 0.5, 10 * 1.0, 0.0,
                          -10 * 1.0, 0.0])
    coll_j = jnp.asarray([10.0, 10 * 0.55, 10 * 0.5, -10 * 1.0, 0.0,
                          10 * 1.0, 0.0])
    return coll_i, coll_j


def test_collision_head_on_elastic_exchange():
    coll_i, coll_j = _head_on_colls()
    uvw_i = jnp.asarray([1.0, 0.0, 0.0])
    uvw_j = jnp.asarray([-1.0, 0.0, 0.0])
    new_i, new_j, hit = collision_response(
        coll_i, coll_j, uvw_i, uvw_j,
        m1=1.0, m2=1.0, j1=1e-3, j2=1e-3,
        com_i=jnp.asarray([0.4, 0.5]), com_j=jnp.asarray([0.6, 0.5]),
        length_i=1.0)
    assert bool(hit)
    # e=1, equal masses, head-on: velocities exchange
    assert np.isclose(float(new_i[0]), -1.0, atol=1e-6)
    assert np.isclose(float(new_j[0]), 1.0, atol=1e-6)
    # momentum conserved
    assert np.isclose(float(new_i[0] + new_j[0]), 0.0, atol=1e-9)


def test_collision_receding_no_impulse():
    coll_i, coll_j = _head_on_colls()
    # bodies moving apart: projVel < 0 -> untouched
    uvw_i = jnp.asarray([-1.0, 0.0, 0.0])
    uvw_j = jnp.asarray([1.0, 0.0, 0.0])
    coll_i = coll_i.at[3].set(-10.0)
    coll_j = coll_j.at[3].set(10.0)
    new_i, new_j, hit = collision_response(
        coll_i, coll_j, uvw_i, uvw_j, 1.0, 1.0, 1e-3, 1e-3,
        jnp.asarray([0.4, 0.5]), jnp.asarray([0.6, 0.5]), 1.0)
    assert not bool(hit)
    assert np.allclose(np.asarray(new_i), [-1.0, 0.0, 0.0])


def test_collision_tiny_overlap_ignored():
    coll_i, coll_j = _head_on_colls()
    coll_i = coll_i.at[0].set(1.0)  # below the 2-cell gate
    new_i, new_j, hit = collision_response(
        coll_i, coll_j, jnp.asarray([1.0, 0.0, 0.0]),
        jnp.asarray([-1.0, 0.0, 0.0]), 1.0, 1.0, 1e-3, 1e-3,
        jnp.asarray([0.4, 0.5]), jnp.asarray([0.6, 0.5]), 1.0)
    assert not bool(hit)


def _cfg(**kw):
    base = dict(bpdx=1, bpdy=1, level_max=1, level_start=0, extent=1.0,
                nu=1e-3, cfl=0.4, lam=1e6, dtype="float64",
                max_poisson_iterations=200)
    base.update(kw)
    return SimConfig(**base)


def test_towed_disk_forces_and_log():
    disk = DiskShape(0.1, 0.35, 0.5, prescribed=(0.2, 0.0))
    sim = Simulation(_cfg(), shapes=[disk], level=4)
    log = io.StringIO()
    sim.force_log = log
    for _ in range(8):
        sim.step_once()
    f = disk.forces
    # discrete delta identity: sum |grad chi| ~ perimeter
    assert abs(f["perimeter"] - 2 * np.pi * 0.1) < 0.02
    # drag opposes +x motion; symmetry kills lateral force and torque
    assert f["forcex"] < 0
    assert abs(f["forcey"]) < 1e-8
    assert abs(f["torque"]) < 1e-8
    assert f["drag"] > 0 and f["thrust"] < 1e-3 * f["drag"]
    assert len(log.getvalue().splitlines()) == 8
    header = Simulation.force_log_header()
    assert header.startswith("time,shape,perimeter")


def test_overlapping_disks_collide_in_sim():
    """Towed disk driven into a free disk: the collision impulse must set
    the free disk moving away (positive u)."""
    d1 = DiskShape(0.08, 0.30, 0.5, prescribed=(0.5, 0.0))
    d2 = DiskShape(0.08, 0.47, 0.5)
    sim = Simulation(_cfg(), shapes=[d1, d2], level=4)
    hit_u = 0.0
    for _ in range(25):
        sim.step_once()
        hit_u = max(hit_u, d2.u)
        if d2.com[0] > 0.75:
            break
    assert hit_u > 0.1, f"free disk never kicked (max u={hit_u})"
    assert np.isfinite(d2.u) and np.isfinite(d2.omega)
