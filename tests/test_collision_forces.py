"""Collision impulse + surface-force diagnostics tests
(reference main.cpp:236-291, 6705-6943 collisions; 5573-5746 forces)."""

import io

import jax.numpy as jnp
import numpy as np
import pytest

from cup2d_tpu.config import SimConfig
from cup2d_tpu.models import DiskShape
from cup2d_tpu.ops.collision import collision_response
from cup2d_tpu.sim import Simulation


def _head_on_colls():
    """Synthetic overlap structs: two unit-mass bodies at x = +-0.1
    moving toward each other at speed 1. The own-SDF gradient points
    INTO each body (sdf positive inside), so ivec points -x for the left
    body and +x for the right one."""
    # [m, posx, posy, momx, momy, vecx, vecy]; 10 overlap cells each
    coll_i = jnp.asarray([10.0, 10 * 0.45, 10 * 0.5, 10 * 1.0, 0.0,
                          -10 * 1.0, 0.0])
    coll_j = jnp.asarray([10.0, 10 * 0.55, 10 * 0.5, -10 * 1.0, 0.0,
                          10 * 1.0, 0.0])
    return coll_i, coll_j


def test_collision_head_on_elastic_exchange():
    coll_i, coll_j = _head_on_colls()
    uvw_i = jnp.asarray([1.0, 0.0, 0.0])
    uvw_j = jnp.asarray([-1.0, 0.0, 0.0])
    new_i, new_j, hit = collision_response(
        coll_i, coll_j, uvw_i, uvw_j,
        m1=1.0, m2=1.0, j1=1e-3, j2=1e-3,
        com_i=jnp.asarray([0.4, 0.5]), com_j=jnp.asarray([0.6, 0.5]),
        length_i=1.0)
    assert bool(hit)
    # e=1, equal masses, head-on: velocities exchange
    assert np.isclose(float(new_i[0]), -1.0, atol=1e-6)
    assert np.isclose(float(new_j[0]), 1.0, atol=1e-6)
    # momentum conserved
    assert np.isclose(float(new_i[0] + new_j[0]), 0.0, atol=1e-9)


def test_collision_receding_no_impulse():
    coll_i, coll_j = _head_on_colls()
    # bodies moving apart: projVel < 0 -> untouched
    uvw_i = jnp.asarray([-1.0, 0.0, 0.0])
    uvw_j = jnp.asarray([1.0, 0.0, 0.0])
    coll_i = coll_i.at[3].set(-10.0)
    coll_j = coll_j.at[3].set(10.0)
    new_i, new_j, hit = collision_response(
        coll_i, coll_j, uvw_i, uvw_j, 1.0, 1.0, 1e-3, 1e-3,
        jnp.asarray([0.4, 0.5]), jnp.asarray([0.6, 0.5]), 1.0)
    assert not bool(hit)
    assert np.allclose(np.asarray(new_i), [-1.0, 0.0, 0.0])


def test_collision_tiny_overlap_ignored():
    coll_i, coll_j = _head_on_colls()
    coll_i = coll_i.at[0].set(1.0)  # below the 2-cell gate
    new_i, new_j, hit = collision_response(
        coll_i, coll_j, jnp.asarray([1.0, 0.0, 0.0]),
        jnp.asarray([-1.0, 0.0, 0.0]), 1.0, 1.0, 1e-3, 1e-3,
        jnp.asarray([0.4, 0.5]), jnp.asarray([0.6, 0.5]), 1.0)
    assert not bool(hit)


def _cfg(**kw):
    base = dict(bpdx=1, bpdy=1, level_max=1, level_start=0, extent=1.0,
                nu=1e-3, cfl=0.4, lam=1e6, dtype="float64",
                max_poisson_iterations=200)
    base.update(kw)
    return SimConfig(**base)


def test_towed_disk_forces_and_log():
    disk = DiskShape(0.1, 0.35, 0.5, prescribed=(0.2, 0.0))
    sim = Simulation(_cfg(), shapes=[disk], level=4)
    log = io.StringIO()
    sim.force_log = log
    for _ in range(8):
        sim.step_once()
    f = disk.forces
    # discrete delta identity: sum |grad chi| ~ perimeter
    assert abs(f["perimeter"] - 2 * np.pi * 0.1) < 0.02
    # drag opposes +x motion; symmetry kills lateral force and torque
    assert f["forcex"] < 0
    assert abs(f["forcey"]) < 1e-8
    assert abs(f["torque"]) < 1e-8
    assert f["drag"] > 0 and f["thrust"] < 1e-3 * f["drag"]
    assert len(log.getvalue().splitlines()) == 8
    header = Simulation.force_log_header()
    assert header.startswith("time,shape,perimeter")


@pytest.mark.slow   # ~18 s; duplicative tier-1 coverage: the same
#                     towed-into-free collision impulse is pinned
#                     BIT-LEVEL by test_golden_collision.py's golden
#                     trajectory (which fails on any physics change
#                     this behavioral assert would catch), and the
#                     multi-disk stepping path stays tier-1 via
#                     test_many_disk_simulation_steps — slow-marked to
#                     fund the PR-7 elastic drill within the 870 s cap
def test_overlapping_disks_collide_in_sim():
    """Towed disk driven into a free disk: the collision impulse must set
    the free disk moving away (positive u)."""
    d1 = DiskShape(0.08, 0.30, 0.5, prescribed=(0.5, 0.0))
    d2 = DiskShape(0.08, 0.47, 0.5)
    sim = Simulation(_cfg(), shapes=[d1, d2], level=4)
    hit_u = 0.0
    for _ in range(25):
        sim.step_once()
        hit_u = max(hit_u, d2.u)
        if d2.com[0] > 0.75:
            break
    assert hit_u > 0.1, f"free disk never kicked (max u={hit_u})"
    assert np.isfinite(d2.u) and np.isfinite(d2.omega)


def test_merged_overlap_matches_pair_sum():
    """merged_overlap_integrals must equal the per-opponent sum of
    overlap_integrals (the reference's collisions[i] accumulation) on a
    random 3-body configuration with genuine multi-overlap cells."""
    from cup2d_tpu.ops.collision import (
        merged_overlap_integrals, overlap_integrals)
    rng = np.random.default_rng(7)
    S, ny, nx = 3, 24, 24
    x = jnp.asarray(np.linspace(0, 1, nx)[None, :].repeat(ny, 0))
    y = jnp.asarray(np.linspace(0, 1, ny)[:, None].repeat(nx, 1))
    chi = jnp.asarray(
        np.clip(rng.random((S, ny, nx)) - 0.35, 0.0, 1.0))
    sdf = jnp.asarray(rng.standard_normal((S, ny, nx)))
    udef = jnp.asarray(0.1 * rng.standard_normal((S, 2, ny, nx)))
    uvw = jnp.asarray(rng.standard_normal((S, 3)))
    com = jnp.asarray(rng.random((S, 2)))

    got = merged_overlap_integrals(chi, sdf, udef, uvw, com, x, y)
    for i in range(S):
        want = sum(
            overlap_integrals(chi[i], chi[j], sdf[i], udef[i],
                              uvw[i], com[i], x, y)
            for j in range(S) if j != i)
        assert np.allclose(np.asarray(got[i]), np.asarray(want),
                           rtol=1e-12, atol=1e-12), i


def test_pairwise_update_matches_unrolled_order():
    """The fori_loop pair sweep must reproduce the Python (i<j) unroll
    bit-for-bit, including the sequential feed of earlier impulses into
    later pairs."""
    from cup2d_tpu.ops.collision import (
        collision_response, pairwise_collision_update)
    rng = np.random.default_rng(3)
    S = 4
    # overlapping momenta structs that actually trigger hits
    colls = np.zeros((S, 7))
    for k in range(S):
        colls[k] = [10.0, 10 * (0.4 + 0.05 * k), 10 * 0.5,
                    10.0 * (1 - k), 0.0, (-1.0) ** k * 10, 1.0]
    colls = jnp.asarray(colls)
    uvw = jnp.asarray(rng.standard_normal((S, 3)))
    mass = jnp.asarray(1.0 + rng.random(S))
    inertia = jnp.asarray(0.1 + rng.random(S))
    com = jnp.asarray(rng.random((S, 2)))
    lengths = jnp.asarray(0.2 + 0.1 * rng.random(S))

    got = pairwise_collision_update(colls, uvw, mass, inertia, com,
                                    lengths)
    want = uvw
    for i in range(S):
        for j in range(i + 1, S):
            ni, nj, _ = collision_response(
                colls[i], colls[j], want[i], want[j], mass[i], mass[j],
                inertia[i], inertia[j], com[i], com[j], lengths[i])
            want = want.at[i].set(ni).at[j].set(nj)
    assert np.allclose(np.asarray(got), np.asarray(want),
                       rtol=1e-12, atol=1e-12)


@pytest.mark.slow   # ~12 s; duplicative tier-1 coverage: the merged-
#                     integral + fori_loop impulse path is pinned
#                     bit-level by test_golden_collision.py and the
#                     in-sim force plumbing by
#                     test_towed_disk_forces_and_log — this is a
#                     9-body endurance composition of the same path
def test_many_disk_simulation_steps():
    """Nine free disks in a box: the many-body path (merged integrals +
    fori_loop impulses) compiles once and steps stably."""
    shapes = [DiskShape(0.035, 0.25 + 0.25 * (k % 3),
                        0.25 + 0.25 * (k // 3), n_surface=64)
              for k in range(9)]
    cfg = SimConfig(bpdx=1, bpdy=1, level_max=1, level_start=0,
                    extent=1.0, dtype="float64", nu=1e-3, lam=1e5,
                    cfl=0.4, max_poisson_iterations=60,
                    poisson_tol=1e-4, poisson_tol_rel=1e-3)
    sim = Simulation(cfg, level=4, shapes=shapes)   # 128x128
    sim.compute_forces_every = 0
    # give them motion so overlaps/collisions are reachable
    for k, s in enumerate(sim.shapes):
        s.u = 0.1 * ((k % 3) - 1)
        s.v = 0.1 * ((k // 3) - 1)
    sim.initialize()
    for _ in range(3):
        sim.step_once()
    vel = np.asarray(sim.state.vel)
    assert np.isfinite(vel).all()
