"""Elastic topology resilience (PR 7): detect lost hosts, re-mesh the
survivors, resume without relaunch.

The tier-1 proof runs on a SINGLE-PROCESS SIMULATED topology (the
2-process multihost harness is environment-broken in this container —
ROADMAP; tests/_multihost_worker.py now probes and SKIPs cleanly):
conftest's 8 virtual CPU devices are grouped into fake "hosts" by
resilience.TopologyGuard(sim_hosts=...), losses are injected through
the same env-latched fault plan as every other drill (faults.py
host_exit@N / host_hang@N), and the acceptance contract is pinned
end-to-end — an N-device run losing k devices mid-run detects the
loss, re-meshes the survivors, resumes from the device snapshot ring,
and the continued trajectory matches a from-checkpoint restart on the
shrunk mesh <= 1e-12, with the recovery visible as EventLog events and
an advancing schema-v5 topology_epoch in the metrics stream.

Unit coverage for the detection half (miss-count timeline, epoch
determinism, the bounded-collective hang watchdog) and the
PreemptionGuard.agree pre-init fast path (previously untested —
satellite of the version-safe-probe fix) lives here too.
"""

import json
import os

import jax
import numpy as np
import pytest

from cup2d_tpu.config import SimConfig
from cup2d_tpu.faults import FaultPlan
from cup2d_tpu.io import (load_checkpoint, restore_snapshot_resharded,
                          save_checkpoint, snapshot_covers,
                          snapshot_state_device)
from cup2d_tpu.parallel.mesh import ShardedUniformSim, make_mesh
from cup2d_tpu.profiling import MetricsRecorder
from cup2d_tpu.resilience import (EventLog, PreemptionGuard, StepGuard,
                                  TopologyGuard, bounded_call)
from cup2d_tpu.uniform import taylor_green_state


def _cfg(**kw):
    base = dict(bpdx=2, bpdy=1, level_max=1, level_start=0, extent=2.0,
                nu=1e-3, cfl=0.4, dtype="float64",
                max_poisson_iterations=200)
    base.update(kw)
    return SimConfig(**base)


def _sharded(mesh, level=2):
    sim = ShardedUniformSim(_cfg(), mesh, level=level)
    sim.set_state(taylor_green_state(sim.grid))
    # production regime from the start (the test_snapshot_ring
    # pattern): the exact tol-0 startup solves would compile a second
    # executable per mesh and grind at the precision floor — nothing
    # elastic depends on the startup branch
    sim.step_count = 20
    return sim


def _events(path, kind=None):
    with open(path) as f:
        evs = [json.loads(ln) for ln in f if ln.strip()]
    return [e for e in evs if kind is None or e.get("event") == kind]


# ---------------------------------------------------------------------------
# fault grammar: the host-loss tokens
# ---------------------------------------------------------------------------

def test_host_loss_fault_grammar():
    plan = FaultPlan("host_exit@5,host_hang@7,sigterm@3")
    assert plan  # host-loss tokens arm the plan
    assert plan.host_loss == {5: ["exit"], 7: ["hang"]}
    # consumed exactly once, per boundary
    assert plan.host_loss_at(4) == []
    assert plan.host_loss_at(5) == ["exit"]
    assert plan.host_loss_at(5) == []
    # suspended during guard replay like every other injector
    with plan.suspend():
        assert plan.host_loss_at(7) == []
    assert plan.host_loss_at(7) == ["hang"]
    # a typo'd directive raises instead of silently arming nothing
    with pytest.raises(ValueError):
        FaultPlan("host_exit")          # needs @STEP
    with pytest.raises(ValueError):
        FaultPlan("host_vanish@3")      # unknown token


# ---------------------------------------------------------------------------
# detection: miss-count timeline, epoch bump, survivor determinism
# ---------------------------------------------------------------------------

def test_topology_guard_detection_timeline(tmp_path):
    devs = jax.devices()[:8]
    log = EventLog(str(tmp_path / "events.jsonl"))
    plan = FaultPlan("host_exit@5")
    topo = TopologyGuard(devices=devs, sim_hosts=4, miss_k=2,
                         faults=plan, event_log=log)
    assert topo.n_hosts == 4 and topo.epoch == 0
    # before the fault: beats pass
    assert topo.poll(4) == ()
    # step 5: the fault marks the highest-index alive host dead — the
    # SAME beat counts miss 1 of K=2, so nothing is declared yet
    assert topo.poll(5) == ()
    assert topo.epoch == 0 and all(topo.alive)
    # the K-th consecutive missed beat declares the loss
    assert topo.poll(6) == (3,)
    assert topo.epoch == 1 and topo.alive == [True, True, True, False]
    # survivors: alive hosts' devices in original (contiguous) order —
    # the deterministic agreement rule
    assert topo.survivor_devices() == devs[:6]
    # simulated hosts lose no PROCESS — the snapshot ring still covers
    assert topo.lost_process_indices() == ()
    # nothing re-declares on later beats
    assert topo.poll(7) == () and topo.epoch == 1
    log.close()
    lost = _events(str(tmp_path / "events.jsonl"), "topology_lost")
    assert len(lost) == 1
    assert lost[0]["hosts"] == [3] and lost[0]["epoch"] == 1
    assert lost[0]["kinds"] == ["exit"] and lost[0]["miss_k"] == 2


def test_topology_guard_validates_host_grouping():
    devs = jax.devices()[:8]
    with pytest.raises(ValueError):
        TopologyGuard(devices=devs, sim_hosts=3)   # 3 does not divide 8
    with pytest.raises(ValueError):
        # a 1-host simulation can only lose its only host — nothing
        # left to re-mesh onto, refused at construction (the CLI
        # refuses the matching -elastic-without-simHosts single-process
        # case up front for the same reason)
        TopologyGuard(devices=devs, sim_hosts=1)


def test_bounded_call_hang_watchdog():
    """The hang case: a collective blocking past its deadline surfaces
    as (False, None) instead of an infinite wait; a prompt call returns
    its result; an exception propagates."""
    import time as _time
    done, r = bounded_call(lambda: 42, timeout=5.0)
    assert done and r == 42
    done, r = bounded_call(lambda: _time.sleep(30), timeout=0.2)
    assert not done and r is None

    def boom():
        raise RuntimeError("inside")

    with pytest.raises(RuntimeError, match="inside"):
        bounded_call(boom, timeout=5.0)


def test_preemption_agree_preinit_fast_path():
    """PreemptionGuard.agree before any distributed init is the LOCAL
    flag — no collective, no backend probe (the version-safe
    dist_initialized check; the former private-API fallback was
    untested here)."""
    from cup2d_tpu.resilience import dist_initialized
    assert dist_initialized() is False   # single-process test session
    stop = PreemptionGuard()
    assert stop.agree() is False
    stop.triggered = True
    assert stop.agree() is True          # local latch, nothing else


def test_step_boundary_piggybacks_single_process():
    """The combined step-boundary call: SIGTERM agreement and the
    simulated heartbeat in one call (single-process fast path)."""
    devs = jax.devices()[:4]
    plan = FaultPlan("host_exit@3")
    topo = TopologyGuard(devices=devs, sim_hosts=2, miss_k=1,
                         faults=plan)
    stop = PreemptionGuard()
    beat = topo.step_boundary(stop, 2)
    assert beat.stop is False and beat.lost == () and not beat.hung
    stop.triggered = True
    beat = topo.step_boundary(stop, 3)   # fault armed for this boundary
    assert beat.stop is True
    assert beat.lost == (1,) and not beat.self_lost


# ---------------------------------------------------------------------------
# re-mesh plumbing
# ---------------------------------------------------------------------------

def test_remesh_rejects_indivisible():
    mesh = make_mesh(devices=jax.devices()[:4])
    sim = ShardedUniformSim(_cfg(), mesh, level=2)   # nx = 64
    with pytest.raises(ValueError):
        sim.remesh(make_mesh(devices=jax.devices()[:3]))


# ---------------------------------------------------------------------------
# THE acceptance drill: simulated host loss, ring resume, restart pin
# ---------------------------------------------------------------------------

def test_elastic_drill_simulated_host_loss(tmp_path):
    """An N-device run losing k devices mid-run: the injected
    host_exit fault is detected at the step boundary, the survivors
    re-mesh, the run resumes from the device snapshot ring IN PLACE
    (same process, same sim object), and the continued trajectory
    matches a from-checkpoint restart on the shrunk mesh <= 1e-12 —
    with the recovery recorded as EventLog events and topology_epoch
    advancing in metrics.jsonl (the ISSUE 7 acceptance contract).

    Also the satellite re-shard pin: immediately after recovery the
    resumed state (DeviceSnapshot captured on the N-device mesh,
    restored onto the N-k-device mesh) is compared against
    _install_state from the equivalent disk checkpoint — the two
    install paths must agree on every field.
    """
    devs = jax.devices()[:4]
    mesh4 = make_mesh(devices=devs)
    events_path = str(tmp_path / "events.jsonl")
    metrics_path = str(tmp_path / "metrics.jsonl")
    log = EventLog(events_path)
    metrics_log = EventLog(metrics_path)
    ck = str(tmp_path / "ck")

    # host_exit@27 with miss_k=1: marked AND declared at boundary 27 —
    # the boundary right after the checkpoint below, so the recovery's
    # ring anchor and the disk checkpoint hold the SAME committed step
    # (the lagged pending dispatched on the lost topology is discarded)
    plan = FaultPlan("host_exit@27")
    topo = TopologyGuard(devices=devs, sim_hosts=2, miss_k=1,
                         faults=plan, event_log=log)
    sim = _sharded(mesh4)
    guard = StepGuard(sim, event_log=log, faults=plan, snap_every=1)
    recorder = MetricsRecorder(sink=metrics_log, guard=guard)
    recorder.prime(sim)
    stop = PreemptionGuard()

    def record(rec):
        if rec is not None:
            recorder.record_step(step=rec["step"], t=rec["t"],
                                 dt=rec["dt"], diag=rec, sim=sim)

    recovered_state = None
    saved = False
    while sim.step_count < 32:
        if not saved and sim.step_count == 26:
            # the comparison anchor: settle every verdict, persist the
            # committed state (the CLI's checkpointEvery pattern)
            for rec in guard.drain():
                record(rec)
            save_checkpoint(ck, sim)
            saved = True
        beat = topo.step_boundary(stop, sim.step_count)
        assert not beat.hung and not beat.self_lost
        if beat.lost:
            guard.elastic_recover(topo)
            recovered_state = jax.device_get(sim.state)
            continue
        record(guard.step())
    for rec in guard.drain():
        record(rec)
    log.close()
    metrics_log.close()

    # the loss really happened, in place: same process, same sim, now
    # on the 2-device survivor mesh, run completed to the target step
    assert recovered_state is not None
    assert sim.mesh.devices.size == 2
    assert set(sim.state.vel.sharding.device_set) == set(devs[:2])
    assert sim.step_count == 32
    assert guard.topology_epoch == 1 and guard.remesh_count == 1

    # EventLog: the detection and the recovery, in order
    lost_evs = _events(events_path, "topology_lost")
    remesh_evs = _events(events_path, "remesh")
    assert len(lost_evs) == 1 and lost_evs[0]["hosts"] == [1]
    assert len(remesh_evs) == 1
    assert remesh_evs[0]["source"] == "ring"      # snapshot ring resume
    assert remesh_evs[0]["epoch"] == 1
    assert remesh_evs[0]["devices"] == 2
    assert remesh_evs[0]["step"] == 26            # the checkpoint anchor

    # metrics.jsonl: topology_epoch advances 0 -> 1 across the loss;
    # the re-mesh itself is attributable (remesh_count, remesh_ms)
    with open(metrics_path) as f:
        ms = [json.loads(ln) for ln in f if ln.strip()]
    epochs = [m["topology_epoch"] for m in ms]
    assert epochs[0] == 0 and epochs[-1] == 1
    assert 0 in epochs and 1 in epochs
    post = [m for m in ms if m["topology_epoch"] == 1]
    assert post[0]["remesh_count"] == 1
    assert post[0]["remesh_ms"] is not None and post[0]["remesh_ms"] > 0

    # the reference: a from-checkpoint restart on the shrunk mesh
    mesh2 = make_mesh(devices=topo.survivor_devices())
    ref = ShardedUniformSim(_cfg(), mesh2, level=2)
    load_checkpoint(ck, ref)
    # satellite pin: ring-resume (DeviceSnapshot re-gathered +
    # re-sharded onto the survivor mesh) == _install_state from the
    # equivalent disk checkpoint — identical bits, bound per the issue
    ref0 = jax.device_get(ref.state)
    for a, b in zip(recovered_state, ref0):
        assert np.max(np.abs(np.asarray(a) - np.asarray(b))) <= 1e-12
    gref = StepGuard(ref, snap_every=1)
    while ref.step_count < 32:
        gref.step()
    gref.drain()
    assert ref.step_count == 32
    assert abs(ref.time - sim.time) <= 1e-12
    a = np.asarray(sim.state.vel)
    b = np.asarray(ref.state.vel)
    assert np.max(np.abs(a - b)) <= 1e-12
    a = np.asarray(sim.state.pres)
    b = np.asarray(ref.state.pres)
    assert np.max(np.abs(a - b)) <= 1e-12


# ---------------------------------------------------------------------------
# forest re-shard: DeviceSnapshot across mesh sizes == disk restore
# ---------------------------------------------------------------------------

def test_forest_snapshot_reshard_matches_checkpoint(tmp_path):
    """The forest half of the re-shard satellite: a DeviceSnapshot
    captured on an N-device sharded forest, restored onto an
    (N-k)-device mesh, matches _install_state from the equivalent disk
    checkpoint — through BOTH restore branches (the topology-mismatch
    reinstall into a fresh sim, and the same-forest fast path after an
    in-place remesh). No stepping: the table/placement rebuild is the
    contract under test, and it is compile-free."""
    import jax.numpy as jnp
    cfg = SimConfig(bpdx=1, bpdy=1, level_max=2, level_start=1,
                    extent=1.0, dtype="float64", nu=1e-3,
                    max_poisson_iterations=40)
    from cup2d_tpu.parallel.forest_mesh import ShardedAMRSim
    devs = jax.devices()
    mesh4 = make_mesh(devices=devs[:4])
    mesh2 = make_mesh(devices=devs[:2])
    rng = np.random.default_rng(0)
    sim = ShardedAMRSim(cfg, mesh4, shapes=[])
    f = sim.forest
    f.fields["vel"] = f.fields["vel"] + jnp.asarray(
        0.1 * rng.standard_normal(f.fields["vel"].shape))
    sim.time, sim.step_count = 0.125, 17

    snap = snapshot_state_device(sim)
    assert snapshot_covers(snap)   # single process: every shard local
    ck = str(tmp_path / "ck")
    save_checkpoint(ck, sim)

    # branch 1: fresh sim on the shrunk mesh (forest version differs ->
    # the _install_state re-shard path)
    over = ShardedAMRSim(cfg, mesh2, shapes=[])
    restore_snapshot_resharded(over, snap)
    ref = ShardedAMRSim(cfg, mesh2, shapes=[])
    load_checkpoint(ck, ref)
    over.sync_fields()
    ref.sync_fields()
    assert over.time == ref.time and over.step_count == ref.step_count
    for k in f.fields:
        a = np.asarray(over.forest.fields[k])
        b = np.asarray(ref.forest.fields[k])
        assert np.max(np.abs(a - b)) <= 1e-12, k

    # branch 2: IN-PLACE remesh of the donor (same forest version ->
    # the ordered-state fast path), then the ring restore re-shards
    sim.remesh(mesh2)
    restore_snapshot_resharded(sim, snap)
    ordv = sim._ordered_state()["vel"]
    assert set(ordv.sharding.device_set) == set(devs[:2])
    sim.sync_fields()
    # compare in SFC order: the donor's slot numbering is an allocator
    # detail that differs from a fresh sim's (checkpoints store fields
    # SFC-ordered for exactly this reason)
    oa = np.asarray(sim.forest.order())
    ob = np.asarray(ref.forest.order())
    for k in f.fields:
        a = np.asarray(sim.forest.fields[k])[oa]
        b = np.asarray(ref.forest.fields[k])[ob]
        assert np.max(np.abs(a - b)) <= 1e-12, k
    # the rebuilt table plans target the survivor mesh
    assert sim.mesh.devices.size == 2


# ---------------------------------------------------------------------------
# CLI drive (slow: subprocess pays two sharded-step compiles)
# ---------------------------------------------------------------------------

@pytest.mark.slow   # ~40 s subprocess (one 4-device + one 2-device
#                     sharded-step compile); the same elastic path is
#                     tier-1 via the library drill above — this adds
#                     only the -mesh/-elastic/-simHosts flag plumbing
def test_cli_elastic_simulated_drill(tmp_path):
    import subprocess
    import sys
    outdir = str(tmp_path)
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                        + " --xla_force_host_platform_device_count=4"
                        ).strip()
    env["CUP2D_FAULTS"] = "host_exit@6"
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))
    r = subprocess.run(
        [sys.executable, "-m", "cup2d_tpu",
         "-bpdx", "2", "-bpdy", "1", "-levelMax", "1", "-levelStart",
         "0", "-level", "2", "-extent", "2", "-CFL", "0.4", "-tend",
         "10", "-lambda", "1e6", "-nu", "1e-3", "-poissonTol", "1e-3",
         "-poissonTolRel", "1e-2", "-maxPoissonRestarts", "0",
         "-maxPoissonIterations", "200", "-AdaptSteps", "20",
         "-Rtol", "2", "-Ctol", "1", "-tdump", "0", "-dtype",
         "float64", "-maxSteps", "12", "-output", outdir,
         "-mesh", "4", "-elastic", "-simHosts", "2",
         "-heartbeatMissK", "1"],
        capture_output=True, text=True, env=env, timeout=600)
    assert r.returncode == 0, r.stderr[-4000:]
    remesh_evs = _events(os.path.join(outdir, "events.jsonl"), "remesh")
    assert len(remesh_evs) == 1 and remesh_evs[0]["devices"] == 2
    with open(os.path.join(outdir, "metrics.jsonl")) as f:
        ms = [json.loads(ln) for ln in f if ln.strip()]
    # the stream ends with the compile-ledger event record (schema v10),
    # so the epoch claim reads the last STEP record
    steps = [m for m in ms if "topology_epoch" in m]
    assert steps and steps[-1]["topology_epoch"] == 1
