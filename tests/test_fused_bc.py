"""Fused-BC operator forms vs the padded-lab originals.

The uniform path's linear operators (Laplacian, divergence, pressure
gradient) fold their physical BCs into zero-ghost shifts plus rank-1
edge corrections (ops/stencil.py) instead of edge-mode pads, whose
concatenate lowering dominated the round-3 halo-pad trace slice. The
algebra is identical; only the summation order differs in wall cells —
these tests pin the two forms against each other, and pin the strip-
flip pad_vector against the reference's two-pass BC sweep semantics.
"""

import jax.numpy as jnp
import numpy as np

from cup2d_tpu.ops.stencil import (
    divergence,
    divergence_freeslip,
    divergence_rhs,
    divergence_rhs_fused,
    laplacian5,
    laplacian5_neumann,
    pressure_gradient_update,
    pressure_gradient_update_fused,
)
from cup2d_tpu.uniform import pad_scalar, pad_vector


def _rand(shape, seed=0):
    return jnp.asarray(np.random.default_rng(seed).normal(size=shape))


def test_laplacian_fused_matches_padded():
    p = _rand((24, 40))
    a = laplacian5(pad_scalar(p, 1), 1)
    b = laplacian5_neumann(p)
    assert np.allclose(np.asarray(a), np.asarray(b), rtol=0, atol=1e-13)


def test_divergence_fused_matches_padded():
    v = _rand((2, 24, 40), seed=1)
    a = divergence(pad_vector(v, 1), 1)
    b = divergence_freeslip(v)
    assert np.allclose(np.asarray(a), np.asarray(b), rtol=0, atol=1e-13)


def test_divergence_rhs_fused_matches_padded():
    v = _rand((2, 16, 24), seed=2)
    u = _rand((2, 16, 24), seed=3)
    chi = jnp.abs(_rand((16, 24), seed=4))
    a = divergence_rhs(pad_vector(v, 1), pad_vector(u, 1), chi, 1,
                       0.01, 1e-3)
    b = divergence_rhs_fused(v, u, chi, 0.01, 1e-3)
    assert np.allclose(np.asarray(a), np.asarray(b), rtol=1e-12, atol=1e-12)


def test_gradient_fused_matches_padded():
    p = _rand((24, 40), seed=5)
    a = pressure_gradient_update(pad_scalar(p, 1), 1, 0.01, 1e-3)
    b = pressure_gradient_update_fused(p, 0.01, 1e-3)
    assert np.allclose(np.asarray(a), np.asarray(b), rtol=0, atol=1e-15)


def test_pad_vector_strip_flips_match_full_sweep():
    """pad_vector's strip-wise sign flips must reproduce the original
    whole-array two-pass sweep: u negated in ALL x-ghost columns, v in
    ALL y-ghost rows, corners composing both."""
    v = _rand((2, 10, 14), seed=6)
    g = 3
    out = np.asarray(pad_vector(v, g))
    ny, nx = 10, 14
    ref = np.array(pad_scalar(v, g))   # writable copy
    sx = np.ones(nx + 2 * g)
    sx[:g] = -1
    sx[nx + g:] = -1
    sy = np.ones(ny + 2 * g)
    sy[:g] = -1
    sy[ny + g:] = -1
    ref[0] *= sx[None, :]
    ref[1] *= sy[:, None]
    assert np.array_equal(out, ref)


def test_mg_lap_fused_neumann():
    """MultigridPreconditioner._lap (now the fused form) still applies
    the zero-Neumann operator its Jacobi diagonal assumes — in both
    _zshift variants."""
    from cup2d_tpu.poisson import MultigridPreconditioner

    p = _rand((16, 16), seed=7)
    pp = jnp.pad(p, 1, mode="edge")
    b = (pp[:-2, 1:-1] + pp[2:, 1:-1] + pp[1:-1, :-2]
         + pp[1:-1, 2:] - 4.0 * p)
    for safe in (False, True):
        mg = MultigridPreconditioner(16, 16, jnp.float64, spmd_safe=safe)
        a = mg._lap(p)
        assert np.allclose(np.asarray(a), np.asarray(b), rtol=0,
                           atol=1e-13), safe


def test_weno_mirror_identity_bit_exact():
    """weno_derivative's pre-selection form must be BIT-identical to
    the textbook both-branches-then-select form in both dtypes (the
    mirror identity weno5_minus(a..e) == weno5_plus(e..a) plus
    commutative adds only)."""
    from cup2d_tpu.ops.stencil import (
        weno5_minus,
        weno5_plus,
        weno_derivative,
    )

    rng = np.random.default_rng(3)
    for dtype in (jnp.float64, jnp.float32):
        args = [jnp.asarray(rng.normal(size=5000), dtype)
                for _ in range(7)]
        wind = jnp.asarray(rng.normal(size=5000), dtype)
        um3, um2, um1, u, up1, up2, up3 = args
        dplus = weno5_plus(um2, um1, u, up1, up2) \
            - weno5_plus(um3, um2, um1, u, up1)
        dminus = weno5_minus(um1, u, up1, up2, up3) \
            - weno5_minus(um2, um1, u, up1, up2)
        old = jnp.where(wind > 0, dplus, dminus)
        new = weno_derivative(wind, *args)
        assert bool(jnp.all(old == new)), dtype
        a, b, c, d, e = args[:5]
        assert bool(jnp.all(
            weno5_minus(a, b, c, d, e) == weno5_plus(e, d, c, b, a)))


def test_weno_fast_weights_match_ref_form_f32():
    """The f32 production branch of _weno5_weights (max-normalized
    cross products + the 0x7EF311C3 bit-trick scale reciprocal) must
    match the reference ratio form to f32 roundoff across 16 orders of
    magnitude of smoothness — the weights are exactly scale-invariant
    in the normalizer, so even a ~15%-error reciprocal cannot move
    them. The CPU suite otherwise only exercises the f64 exact-divide
    branch."""
    from cup2d_tpu.ops.stencil import _weno5_weights, _weno5_weights_ref

    rng = np.random.default_rng(0)
    b = [jnp.asarray(10.0 ** rng.uniform(-8, 8, 50000), jnp.float32)
         for _ in range(3)]
    for g in ((0.1, 0.6, 0.3), (0.3, 0.6, 0.1)):
        wf = np.stack([np.asarray(x) for x in _weno5_weights(*b, *g)])
        wr = np.stack([np.asarray(x)
                       for x in _weno5_weights_ref(*b, *g)])
        assert np.abs(wf - wr).max() < 5e-7, np.abs(wf - wr).max()
        assert np.abs(wf.sum(0) - 1.0).max() < 5e-7
    # overflow regime that killed the r2 single-divide form: stays
    # finite and convex
    bx = [jnp.asarray([2e9, 1e20, 1e38, 1e-6], jnp.float32),
          jnp.asarray([1e-3, 1e-6, 1e-6, 1e38], jnp.float32),
          jnp.asarray([5e8, 1e13, 1e-6, 1e20], jnp.float32)]
    w = np.stack([np.asarray(x)
                  for x in _weno5_weights(*bx, 0.1, 0.6, 0.3)])
    assert np.isfinite(w).all()
    assert np.abs(w.sum(0) - 1.0).max() < 1e-6


def test_zshift_spmd_safe_variant_matches():
    """Both _zshift forms agree exactly on every direction (the
    spmd_safe slice-then-pad form exists because the partitioner
    miscompiles the fast negative-pad form on sharded axes)."""
    from cup2d_tpu.ops.stencil import _zshift

    p = _rand((9, 13), seed=8)
    for dy in (-1, 0, 1):
        for dx in (-1, 0, 1):
            a = _zshift(p, dy, dx, spmd_safe=False)
            b = _zshift(p, dy, dx, spmd_safe=True)
            assert np.array_equal(np.asarray(a), np.asarray(b)), (dy, dx)
