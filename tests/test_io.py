"""Dump format + checkpoint/restore + CLI driver tests."""

import os
import subprocess
import sys

import jax.numpy as jnp
import numpy as np
import pytest

from cup2d_tpu.config import SimConfig
from cup2d_tpu.io import dump_uniform, load_checkpoint, read_dump, \
    save_checkpoint
from cup2d_tpu.models import DiskShape
from cup2d_tpu.sim import Simulation
from cup2d_tpu.uniform import UniformSim, taylor_green_state


def _cfg(**kw):
    base = dict(bpdx=1, bpdy=1, level_max=1, level_start=0, extent=1.0,
                nu=1e-3, cfl=0.4, lam=1e6, dtype="float64",
                max_poisson_iterations=100)
    base.update(kw)
    return SimConfig(**base)


def test_dump_roundtrip(tmp_path):
    cfg = _cfg()
    sim = UniformSim(cfg, level=2)
    sim.state = taylor_green_state(sim.grid)
    path = str(tmp_path / "vel.00000000")
    dump_uniform(path, 0.125, sim.state.vel, sim.grid.h)
    t, xyz, attr = read_dump(path)
    ncell = sim.grid.nx * sim.grid.ny
    assert t == 0.125
    assert xyz.shape == (ncell, 4, 2)
    assert attr.shape == (ncell, 3)
    # quad of cell 0: (0,0)-(h,h), corner order (x0,y0)(x0,y1)(x1,y1)(x1,y0)
    h = np.float32(sim.grid.h)
    assert np.allclose(xyz[0], [[0, 0], [0, h], [h, h], [h, 0]], atol=1e-7)
    # attr = (u, v, 0) in row-major y-outer order
    u = np.asarray(sim.state.vel[0], dtype=np.float32).ravel()
    assert np.allclose(attr[:, 0], u, atol=1e-6)
    assert np.all(attr[:, 2] == 0)


def test_dump_renders_with_reference_postpy(tmp_path):
    """The dump triplet must be consumable by the reference's own
    post-processor logic (memmap layout, ncell inference, xdmf time)."""
    cfg = _cfg()
    sim = UniformSim(cfg, level=2)
    sim.state = taylor_green_state(sim.grid)
    path = str(tmp_path / "vel.00000001")
    dump_uniform(path, 0.5, sim.state.vel, sim.grid.h)
    # replicate post.py's parsing exactly (minus matplotlib)
    dtype = np.dtype("float32")
    xyz = np.memmap(path + ".xyz.raw", dtype, "r")
    ncell = xyz.size // (2 * 4)
    assert ncell * 2 * 4 == xyz.size
    attr = np.memmap(path + ".attr.raw", dtype, "r").reshape((ncell, -1))
    assert attr.shape[1] == 3
    color = np.sum(attr**2, 1)
    assert np.all(np.isfinite(color))
    lx = xyz[4] - xyz[0]
    assert np.isclose(lx, sim.grid.h, atol=1e-7)


def test_checkpoint_resume_bitexact(tmp_path):
    """Run 6 steps; checkpoint at 3; resume; trajectories must match to
    fp roundoff — the restart capability the reference lacks."""
    def make():
        disk = DiskShape(0.1, 0.4, 0.5, prescribed=(0.2, 0.0))
        return Simulation(_cfg(), shapes=[disk], level=3)

    a = make()
    for _ in range(3):
        a.step_once()
    ck = str(tmp_path / "ck")
    save_checkpoint(ck, a)
    for _ in range(3):
        a.step_once()

    b = make()
    load_checkpoint(ck, b)
    assert b.step_count == 3
    for _ in range(3):
        b.step_once()

    assert np.allclose(np.asarray(a.state.vel), np.asarray(b.state.vel),
                       atol=1e-12)
    assert abs(a.time - b.time) < 1e-12
    assert abs(a.shapes[0].com[0] - b.shapes[0].com[0]) < 1e-12


def test_cli_driver_smoke(tmp_path):
    """python -m cup2d_tpu with reference flags runs and dumps."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PYTHONPATH", None)
    cmd = [
        sys.executable, "-m", "cup2d_tpu",
        "-bpdx", "1", "-bpdy", "1", "-levelMax", "1", "-levelStart", "0",
        "-Rtol", "2", "-Ctol", "1", "-extent", "1", "-CFL", "0.4",
        "-tend", "0.02", "-lambda", "1e6", "-nu", "0.001",
        "-poissonTol", "1e-3", "-poissonTolRel", "1e-2",
        "-maxPoissonRestarts", "0", "-maxPoissonIterations", "50",
        "-AdaptSteps", "20", "-tdump", "0.01", "-level", "3",
        "-dtype", "float64", "-maxSteps", "6",
        "-output", str(tmp_path),
        "-shapes", "angle=0 L=0.25 xpos=0.5 ypos=0.5",
    ]
    r = subprocess.run(cmd, cwd="/root/repo", env=env, timeout=400,
                       capture_output=True, text=True)
    assert r.returncode == 0, r.stderr[-2000:]
    dumps = [f for f in os.listdir(tmp_path) if f.endswith(".xdmf2")]
    assert dumps, "no dump written"
    assert os.path.exists(tmp_path / "forces.csv")
    lines = open(tmp_path / "forces.csv").read().splitlines()
    assert lines[0].startswith("time,shape,perimeter")
    assert len(lines) > 1


def test_post_renders_dump_png(tmp_path):
    """The offline renderer turns a dump pair into a PNG (the
    reference's post-processing step, post.py)."""
    import jax.numpy as jnp
    from cup2d_tpu.config import SimConfig
    from cup2d_tpu.io import dump_uniform
    from cup2d_tpu.post import render
    from cup2d_tpu.uniform import UniformGrid, taylor_green_state

    cfg = SimConfig(bpdx=1, bpdy=1, level_max=1, level_start=0,
                    extent=1.0, dtype="float64")
    grid = UniformGrid(cfg, level=1)
    state = taylor_green_state(grid)
    path = str(tmp_path / "vel.0000000001")
    dump_uniform(path, 0.25, state.vel, grid.h)
    png = render(path + ".xdmf2", dpi=80)
    import os
    assert os.path.exists(png) and os.path.getsize(png) > 1000


def test_restore_clears_cached_dt_state():
    """Restoring into a sim that already stepped must not reuse the
    abandoned trajectory's cached umax/dt (it would fork the restart
    from the uninterrupted run)."""
    from cup2d_tpu.amr import AMRSim
    from cup2d_tpu.config import SimConfig
    from cup2d_tpu.io import load_checkpoint, save_checkpoint
    from cup2d_tpu.models import DiskShape

    cfg = SimConfig(bpdx=1, bpdy=1, level_max=3, level_start=1,
                    extent=1.0, dtype="float64", nu=1e-3, lam=1e5,
                    rtol=0.5, ctol=0.05, max_poisson_iterations=40,
                    poisson_tol=1e-4, poisson_tol_rel=1e-3)
    import tempfile
    with tempfile.TemporaryDirectory() as d:
        sim = AMRSim(cfg, shapes=[DiskShape(0.08, 0.4, 0.5,
                                            prescribed=(0.2, 0.0))])
        sim.compute_forces_every = 0
        sim.initialize()
        save_checkpoint(d + "/ck", sim)
        sim.step_once()
        assert sim._next_umax is not None
        load_checkpoint(d + "/ck", sim)
        assert sim._next_umax is None
        assert sim._next_dt is None


def test_restore_resets_ordered_cache():
    """Restoring into a sim that stepped since its last sync_fields must
    discard the ordered-state cache: with _ord_dirty left set the next
    _ordered_state() raises, and following the error's advice
    (sync_fields) would clobber the restored fields with pre-restore
    data (ADVICE r3)."""
    from cup2d_tpu.amr import AMRSim
    from cup2d_tpu.config import SimConfig
    from cup2d_tpu.io import load_checkpoint, save_checkpoint
    from cup2d_tpu.models import DiskShape

    cfg = SimConfig(bpdx=1, bpdy=1, level_max=3, level_start=1,
                    extent=1.0, dtype="float64", nu=1e-3, lam=1e5,
                    rtol=0.5, ctol=0.05, max_poisson_iterations=40,
                    poisson_tol=1e-4, poisson_tol_rel=1e-3)
    import tempfile
    with tempfile.TemporaryDirectory() as d:
        sim = AMRSim(cfg, shapes=[DiskShape(0.08, 0.4, 0.5,
                                            prescribed=(0.2, 0.0))])
        sim.compute_forces_every = 0
        sim.initialize()
        sim.step_once()
        sim.sync_fields()
        ck = d + "/ck"
        save_checkpoint(ck, sim)
        # capture in SFC order: slot numbering does not survive restore
        saved_vel = np.array(
            sim.forest.fields["vel"][sim.forest.order()])
        sim.step_once()          # ordered state now newer than slots
        assert sim._ord_dirty
        load_checkpoint(ck, sim)
        # no RuntimeError, and the working state IS the checkpoint
        ordf = sim._ordered_state()
        n = len(sim.forest.order())
        got = np.asarray(ordf["vel"])[:n]
        assert np.array_equal(got, saved_vel)


@pytest.mark.slow   # ~11 s of the same AMR disk-case setup as its
#                     siblings — a NARROWER variant of the tier-1
#                     test_restore_clears_cached_dt_state (same
#                     dt-cache-drop contract, adds the field-write-in-
#                     the-restore-window timing); slow-marked for the
#                     PR-6 tier-1 budget per the PR-3/5 precedent.
def test_field_write_after_restore_drops_restored_dt_cache():
    """A forest.fields write in the restore->first-step window must
    still drop the restored dt cache: load_checkpoint re-anchors (not
    clears) the ordered-cache key precisely so the wver-moved
    invalidation stays armed (code-review r4)."""
    from cup2d_tpu.amr import AMRSim
    from cup2d_tpu.config import SimConfig
    from cup2d_tpu.io import load_checkpoint, save_checkpoint
    from cup2d_tpu.models import DiskShape

    cfg = SimConfig(bpdx=1, bpdy=1, level_max=3, level_start=1,
                    extent=1.0, dtype="float64", nu=1e-3, lam=1e5,
                    rtol=0.5, ctol=0.05, max_poisson_iterations=40,
                    poisson_tol=1e-4, poisson_tol_rel=1e-3)
    import tempfile
    with tempfile.TemporaryDirectory() as d:
        sim = AMRSim(cfg, shapes=[DiskShape(0.08, 0.4, 0.5,
                                            prescribed=(0.2, 0.0))])
        sim.compute_forces_every = 0
        sim.initialize()
        sim.step_once()
        sim.step_once()
        save_checkpoint(d + "/ck", sim)
        load_checkpoint(d + "/ck", sim)
        assert sim._next_dt is not None          # restored as current
        # no write: the first _ordered_state() must KEEP the restored
        # cache (the restart takes the same dt branch as the
        # uninterrupted run) — guards against the invalidation firing
        # on the unmoved key
        sim._ordered_state()
        assert sim._next_dt is not None and sim._next_umax is not None
        f = sim.forest
        order = f.order()
        vel = np.array(f.fields["vel"])
        vel[order] *= 10.0
        f.fields["vel"] = jnp.asarray(vel)       # wver moves
        sim._ordered_state()
        assert sim._next_dt is None and sim._next_umax is None


def test_fields_dict_noop_calls_do_not_bump_wver():
    """Non-mutating dict calls (setdefault on a present key, pop of a
    missing key with a default) are reads: a spurious wver bump either
    aborts the next _ordered_state() or silently drops the cached dt
    (code-review r4)."""
    from cup2d_tpu.forest import _FieldsDict

    fd = _FieldsDict()
    fd["a"] = 1
    w = fd.wver
    assert fd.setdefault("a", 2) == 1 and fd.wver == w
    assert fd.pop("missing", None) is None and fd.wver == w
    fd.update()
    fd.update({})
    fd.update([])
    assert fd.wver == w
    try:
        del fd["missing"]
    except KeyError:
        pass
    assert fd.wver == w
    # real mutations still count
    fd.setdefault("b", 3)
    assert fd.wver == w + 1
    fd.pop("b")
    assert fd.wver == w + 2
    fd.update({"c": 4})
    assert fd.wver == w + 3
    fd |= {"d": 5}                       # __ior__ bypasses update() in
    assert fd.wver == w + 4 and fd["d"] == 5   # plain dict subclasses


def test_restore_keeps_two_level_trigger_state():
    """The production two-level trigger must survive checkpoint/restore:
    a restore that re-arms it would run the first production solve with
    a DIFFERENT preconditioner (plain block-Jacobi, up to hundreds of
    iterations at 1e4 blocks) than the uninterrupted run, breaking the
    same-branch resume contract (ADVICE r4 medium)."""
    from cup2d_tpu.amr import AMRSim
    from cup2d_tpu.config import SimConfig
    from cup2d_tpu.io import load_checkpoint, save_checkpoint
    from cup2d_tpu.models import DiskShape

    cfg = SimConfig(bpdx=1, bpdy=1, level_max=3, level_start=1,
                    extent=1.0, dtype="float64", nu=1e-3, lam=1e5,
                    rtol=0.5, ctol=0.05, max_poisson_iterations=40,
                    poisson_tol=1e-4, poisson_tol_rel=1e-3)
    import tempfile
    with tempfile.TemporaryDirectory() as d:
        sim = AMRSim(cfg, shapes=[DiskShape(0.08, 0.4, 0.5,
                                            prescribed=(0.2, 0.0))])
        sim.compute_forces_every = 0
        sim.initialize()
        sim.step_once()
        # simulate an engaged trigger (organically needs a 1e4-block
        # near-uniform forest; the persistence contract is what's under
        # test, not the engagement policy)
        sim._coarse_on = True
        sim._last_iters = 23
        save_checkpoint(d + "/ck", sim)
        fresh = AMRSim(cfg, shapes=[DiskShape(0.08, 0.4, 0.5,
                                              prescribed=(0.2, 0.0))])
        fresh.compute_forces_every = 0
        load_checkpoint(d + "/ck", fresh)
        assert fresh._coarse_on is True
        assert fresh._last_iters == 23
        # old checkpoints without the key restore disarmed, not crashed
        import json, os
        mp = os.path.join(d, "ck", "meta.json")
        with open(mp) as fh:
            meta = json.load(fh)
        meta.pop("poisson_trigger")
        with open(mp, "w") as fh:
            json.dump(meta, fh)
        fresh2 = AMRSim(cfg, shapes=[DiskShape(0.08, 0.4, 0.5,
                                               prescribed=(0.2, 0.0))])
        fresh2.compute_forces_every = 0
        # pre-arm so the assertion discriminates: a load that ignored
        # the missing key entirely would leave this True
        fresh2._coarse_on = True
        fresh2._last_iters = 99
        load_checkpoint(d + "/ck", fresh2)
        assert fresh2._coarse_on is False
