"""AMR forest tests: halo gather tables, prolong/restrict, adaptive
stepping (reference main.cpp:2231-3000 BlockLab, 4657-5440 adapt)."""

import jax.numpy as jnp
import numpy as np

from cup2d_tpu.amr import AMRSim
from cup2d_tpu.config import SimConfig
from cup2d_tpu.forest import Forest
from cup2d_tpu.halo import assemble_labs, build_tables


def _two_level_forest():
    cfg = SimConfig(bpdx=2, bpdy=2, level_max=3, level_start=1,
                    extent=1.0, dtype="float64")
    f = Forest(cfg)
    f.release(1, 1, 1)
    for a in (0, 1):
        for b in (0, 1):
            f.allocate(2, 2 + a, 2 + b)
    return cfg, f


def _linear_fill(cfg, f, dim):
    bs = cfg.bs
    vals = np.zeros((f.capacity, dim, bs, bs))
    for (l, i, j), s in f.blocks.items():
        h = cfg.h_at(l)
        x = (i * bs + np.arange(bs) + 0.5) * h
        y = (j * bs + np.arange(bs) + 0.5) * h
        X, Y = np.meshgrid(x, y, indexing="xy")
        vals[s, 0] = 2.0 * X + 3.0 * Y + 1.0
        if dim == 2:
            vals[s, 1] = -1.0 * X + 0.5 * Y + 2.0
    return jnp.asarray(vals)


def _check_ghosts(cfg, f, labs, order, g, coeffs, comp, faces_only):
    bs = cfg.bs
    L = bs + 2 * g
    a, b, c = coeffs
    maxerr = 0.0
    for k, s in enumerate(order):
        l = int(f.level[s])
        i, j = int(f.bi[s]), int(f.bj[s])
        h = cfg.h_at(l)
        nbx, nby = f.nblocks_at(l)
        for ly in range(L):
            for lx in range(L):
                if faces_only:
                    in_x = g <= lx < g + bs
                    in_y = g <= ly < g + bs
                    if not (in_x or in_y):
                        continue
                gx = i * bs + lx - g
                gy = j * bs + ly - g
                if not (0 <= gx < nbx * bs and 0 <= gy < nby * bs):
                    continue  # wall ghosts are zeroth-order by design
                want = a * (gx + 0.5) * h + b * (gy + 0.5) * h + c
                maxerr = max(maxerr, abs(float(labs[k, comp, ly, lx]) - want))
    return maxerr


def test_halo_tables_linear_exact_tensorial():
    """g=3 tensorial labs (advection stencil) must reproduce a linear
    field exactly across the two-level interface — same-level copies,
    2x2 average-down, TestInterp + directional Taylor + LI/LE are all
    at least 2nd order."""
    cfg, f = _two_level_forest()
    order = f.order()
    field = _linear_fill(cfg, f, 1)
    t = build_tables(f, order, 3, True, 1)
    labs = np.asarray(assemble_labs(field, jnp.asarray(order), t))
    err = _check_ghosts(cfg, f, labs, order, 3, (2.0, 3.0, 1.0), 0, False)
    assert err < 1e-12, err


def test_halo_tables_linear_exact_g1():
    cfg, f = _two_level_forest()
    order = f.order()
    field = _linear_fill(cfg, f, 1)
    t = build_tables(f, order, 1, False, 1)
    labs = np.asarray(assemble_labs(field, jnp.asarray(order), t))
    # non-tensorial: corners legitimately unfilled, faces must be exact
    err = _check_ghosts(cfg, f, labs, order, 1, (2.0, 3.0, 1.0), 0, True)
    assert err < 1e-12, err


def test_halo_tables_vector_wall_flip():
    """Vector wall ghosts: normal component negated, tangential copied
    (free-slip mirror, main.cpp:3131-3155)."""
    cfg, f = _two_level_forest()
    order = f.order()
    field = _linear_fill(cfg, f, 2)
    t = build_tables(f, order, 1, False, 2)
    labs = np.asarray(assemble_labs(field, jnp.asarray(order), t))
    bs = cfg.bs
    # block (1, 0, 0) touches x=0 and y=0 walls
    k = next(k for k, s in enumerate(order)
             if (int(f.level[s]), int(f.bi[s]), int(f.bj[s])) == (1, 0, 0))
    g = 1
    # left ghost column: u flipped vs edge cell, v copied
    for iy in range(bs):
        u_ghost = labs[k, 0, iy + g, 0]
        u_edge = labs[k, 0, iy + g, g]
        v_ghost = labs[k, 1, iy + g, 0]
        v_edge = labs[k, 1, iy + g, g]
        assert np.isclose(u_ghost, -u_edge)
        assert np.isclose(v_ghost, v_edge)


def _fill_tg(sim):
    f = sim.forest
    cfg = sim.cfg
    bs = cfg.bs
    vals = np.zeros((f.capacity, 2, bs, bs))
    for (l, i, j), s in f.blocks.items():
        h = cfg.h_at(l)
        x = (i * bs + np.arange(bs) + 0.5) * h
        y = (j * bs + np.arange(bs) + 0.5) * h
        X, Y = np.meshgrid(x, y, indexing="xy")
        vals[s, 0] = np.sin(np.pi * X) * np.cos(np.pi * Y)
        vals[s, 1] = -np.cos(np.pi * X) * np.sin(np.pi * Y)
    f.fields["vel"] = jnp.asarray(vals)


def test_amr_two_level_taylor_green():
    """TG decay on a static two-level mesh matches the analytic rate —
    the level-interface coupling does not poison the solution."""
    cfg = SimConfig(bpdx=1, bpdy=1, level_max=4, level_start=2, extent=1.0,
                    nu=1e-3, cfl=0.4, dtype="float64",
                    max_poisson_iterations=150, poisson_tol=1e-6,
                    poisson_tol_rel=0, rtol=1e9, ctol=-1.0)
    sim = AMRSim(cfg)
    f = sim.forest
    for (i, j) in [(1, 1), (2, 1), (1, 2), (2, 2)]:
        f.release(2, i, j)
        for a in (0, 1):
            for b in (0, 1):
                f.allocate(3, 2 * i + a, 2 * j + b)
    _fill_tg(sim)

    def energy():
        sim.sync_fields()
        return sum(
            float(jnp.sum(f.fields["vel"][s] ** 2)) * cfg.h_at(l) ** 2
            for (l, i, j), s in f.blocks.items())

    e0 = energy()
    while sim.time < 0.1:
        sim.step_once()
    e1 = energy()
    expected = np.exp(-2 * 2 * np.pi ** 2 * cfg.nu * sim.time)
    assert abs(e1 / e0 - expected) < 0.02, (e1 / e0, expected)


def test_amr_dynamic_adapt_vortex():
    """A strong Gaussian vortex triggers refinement around its core; the
    run stays finite and the forest stays 2:1 balanced."""
    cfg = SimConfig(bpdx=1, bpdy=1, level_max=4, level_start=1, extent=1.0,
                    nu=1e-4, cfl=0.4, dtype="float64",
                    max_poisson_iterations=100,
                    poisson_tol=1e-4, poisson_tol_rel=1e-3,
                    rtol=2.0, ctol=0.5)
    sim = AMRSim(cfg)
    f = sim.forest
    bs = cfg.bs
    vals = np.zeros((f.capacity, 2, bs, bs))
    for (l, i, j), s in f.blocks.items():
        h = cfg.h_at(l)
        x = (i * bs + np.arange(bs) + 0.5) * h - 0.5
        y = (j * bs + np.arange(bs) + 0.5) * h - 0.5
        X, Y = np.meshgrid(x, y, indexing="xy")
        r2 = X ** 2 + Y ** 2
        gam, sig2 = 0.5, 0.0064
        ut = gam / (2 * np.pi * np.sqrt(r2 + 1e-12)) \
            * (1 - np.exp(-r2 / (2 * sig2)))
        th = np.arctan2(Y, X)
        vals[s, 0] = -ut * np.sin(th)
        vals[s, 1] = ut * np.cos(th)
    f.fields["vel"] = jnp.asarray(vals)

    n0 = len(f.blocks)
    assert sim.adapt()
    assert len(f.blocks) > n0
    levels = set(l for (l, i, j) in f.blocks)
    assert max(levels) > cfg.level_start

    for i in range(6):
        if i % 3 == 0:
            sim.adapt()
        d = sim.step_once()
    assert np.isfinite(float(d["umax"]))
    sim.sync_fields()
    vel = np.asarray(f.fields["vel"])
    assert np.isfinite(vel[f.active]).all()

    # 2:1 balance invariant: no active block has an active neighbor
    # differing by more than one level
    for (l, i, j) in f.blocks:
        nbx, nby = f.nblocks_at(l)
        for cx in (-1, 0, 1):
            for cy in (-1, 0, 1):
                ni, nj = i + cx, j + cy
                if not (0 <= ni < nbx and 0 <= nj < nby):
                    continue
                rel = f.owner_relation(l, ni, nj)
                if rel == -1:
                    # children active: they must be exactly l+1
                    assert (l + 1, 2 * ni, 2 * nj) in f.blocks or \
                        (l + 1, 2 * ni + 1, 2 * nj) in f.blocks
                assert rel != -3, (l, ni, nj)


def test_prolong_restrict_linear_roundtrip():
    """Taylor prolongation of a linear field is exact on an interior
    block (wall blocks degrade by design: the zeroth-order BC ghosts
    feed the Taylor derivatives, exactly like the reference); restricting
    the children recovers the parent exactly."""
    cfg = SimConfig(bpdx=1, bpdy=1, level_max=4, level_start=2,
                    extent=1.0, dtype="float64", rtol=1e9, ctol=-1.0)
    sim = AMRSim(cfg)
    f = sim.forest
    bs = cfg.bs
    vals = np.zeros((f.capacity, 2, bs, bs))
    for (l, i, j), s in f.blocks.items():
        h = cfg.h_at(l)
        x = (i * bs + np.arange(bs) + 0.5) * h
        y = (j * bs + np.arange(bs) + 0.5) * h
        X, Y = np.meshgrid(x, y, indexing="xy")
        vals[s, 0] = 3.0 * X - 2.0 * Y
        vals[s, 1] = X + Y
    f.fields["vel"] = jnp.asarray(vals)
    before = np.asarray(f.fields["vel"][f.blocks[(2, 1, 1)]]).copy()

    sim._refresh()
    sim._apply_regrid([(2, 1, 1)], [])  # interior block of the 4x4 grid
    s00 = f.blocks[(3, 2, 2)]
    h3 = cfg.h_at(3)
    x = (2 * bs + np.arange(bs) + 0.5) * h3
    X, Y = np.meshgrid(x, x, indexing="xy")
    got = np.asarray(f.fields["vel"][s00, 0])
    assert np.allclose(got, 3.0 * X - 2.0 * Y, atol=1e-12)

    # compress back: parent restored exactly (mean of exact linears)
    sim._tables_version = -1
    sim._refresh()
    sim._apply_regrid([], [[(3, 2, 2), (3, 3, 2), (3, 2, 3), (3, 3, 3)]])
    s = f.blocks[(2, 1, 1)]
    assert np.allclose(np.asarray(f.fields["vel"][s]), before, atol=1e-12)


def test_combined_refine_and_compress_one_dispatch():
    """Refine and compress in the SAME _apply_regrid call (the
    production adapt() shape): the restriction must read pre-regrid
    sibling data even though compress parent slots can reuse slots the
    same dispatch's refine scatters wrote. Linear field => both the
    prolonged children and the restored parent are exact."""
    cfg = SimConfig(bpdx=1, bpdy=1, level_max=4, level_start=2,
                    extent=1.0, dtype="float64", rtol=1e9, ctol=-1.0)
    sim = AMRSim(cfg)
    f = sim.forest
    bs = cfg.bs
    # refine (2,1,1) up, and pre-build a sibling quad at level 3 over
    # (2,2,2) to compress down, in one call
    f.release(2, 2, 2)
    for a in (0, 1):
        for b in (0, 1):
            f.allocate(3, 4 + a, 4 + b)
    vals = np.zeros((f.capacity, 2, bs, bs))
    for (l, i, j), s in f.blocks.items():
        h = cfg.h_at(l)
        x = (i * bs + np.arange(bs) + 0.5) * h
        y = (j * bs + np.arange(bs) + 0.5) * h
        X, Y = np.meshgrid(x, y, indexing="xy")
        vals[s, 0] = 3.0 * X - 2.0 * Y
        vals[s, 1] = X + Y
    f.fields["vel"] = jnp.asarray(vals)

    sim._tables_version = -1
    sim._refresh()
    sim._apply_regrid(
        [(2, 1, 1)],
        [[(3, 4, 4), (3, 5, 4), (3, 4, 5), (3, 5, 5)]])

    # prolonged child of the refined block: exact linear
    s00 = f.blocks[(3, 2, 2)]
    h3 = cfg.h_at(3)
    x = (2 * bs + np.arange(bs) + 0.5) * h3
    X, Y = np.meshgrid(x, x, indexing="xy")
    assert np.allclose(np.asarray(f.fields["vel"][s00, 0]),
                       3.0 * X - 2.0 * Y, atol=1e-12)
    # restored parent of the compressed quad: exact linear
    sp = f.blocks[(2, 2, 2)]
    h2 = cfg.h_at(2)
    x = (2 * bs + np.arange(bs) + 0.5) * h2
    X, Y = np.meshgrid(x, x, indexing="xy")
    assert np.allclose(np.asarray(f.fields["vel"][sp, 0]),
                       3.0 * X - 2.0 * Y, atol=1e-12)
    assert np.allclose(np.asarray(f.fields["vel"][sp, 1]),
                       X + Y, atol=1e-12)


def test_sticky_pad_decay_and_floor():
    """The padded block axis is a high-water mark with hysteresis: it
    holds through transient shrinkage, steps down one power of two only
    after 10 consecutive quarter-full rebuilds, and never decays below
    the reserve_blocks floor."""
    cfg = SimConfig(bpdx=1, bpdy=1, level_max=3, level_start=1,
                    extent=1.0, dtype="float64")
    sim = AMRSim(cfg)          # 4 active blocks -> n_bucket = 128 (min)
    sim._npad_hwm = 1024       # pretend a large peak happened
    for _ in range(9):
        sim._tables_version = -1
        sim._refresh()
        assert sim._npad_hwm == 1024
    sim._tables_version = -1
    sim._refresh()
    assert sim._npad_hwm == 512      # one step down after 10 quiet

    sim.reserve_blocks(400)          # floor 512: decay must stop here
    for _ in range(25):
        sim._tables_version = -1
        sim._refresh()
    assert sim._npad_hwm == 512


def test_initialize_reserves_blocks():
    """initialize() pre-sizes the bucket from the block estimate (the
    coarse-start climb makes the estimate small, so spy on the call
    instead of on a threshold) and the estimate covers the grid the
    climb actually produces."""
    from cup2d_tpu.models import DiskShape
    cfg = SimConfig(bpdx=4, bpdy=2, level_max=3, level_start=2,
                    extent=1.0, dtype="float64", rtol=0.5, ctol=0.05)
    sim = AMRSim(cfg, shapes=[DiskShape(0.06, 0.3, 0.25)])
    sim.compute_forces_every = 0
    seen = {}
    orig = sim.reserve_blocks
    sim.reserve_blocks = lambda n: seen.update(n=n) or orig(n)
    sim.initialize()
    assert "n" in seen, "initialize() no longer reserves blocks"
    assert seen["n"] >= len(sim.forest.blocks) // 2, \
        (seen["n"], len(sim.forest.blocks))
    sim._refresh()
    assert sim._npad_hwm >= sim._npad_floor


def test_initialize_coarse_start_matches_levelstart_grid():
    """The coarse-start climb (zero fields) and the reference-style
    from-levelStart climb converge to the same adapted grid: run the
    from-above variant by seeding a nonzero field so coarse start is
    disabled, settle both with chi-driven adapts, and compare."""
    from cup2d_tpu.models import DiskShape

    def build():
        cfg = SimConfig(bpdx=2, bpdy=1, level_max=3, level_start=2,
                        extent=1.0, dtype="float64", nu=1e-3, lam=1e5,
                        rtol=0.5, ctol=0.05)
        s = AMRSim(cfg, shapes=[DiskShape(0.08, 0.55, 0.25)])
        s.compute_forces_every = 0
        return s

    a = build()            # coarse start (all-zero fields)
    a.initialize()
    b = build()            # from-above: tiny nonzero pressure disables it
    b.forest.fields["pres"] = b.forest.fields["pres"].at[0, 0, 0, 0].set(
        1e-30)
    b.initialize()
    # settle both to the chi-tag fixed point
    for s in (a, b):
        for _ in range(4):
            if not s.adapt():
                break
    assert set(a.forest.blocks) == set(b.forest.blocks)


def test_external_field_write_invalidates_cached_dt():
    """Writing forest.fields mid-run (the established seeding pattern,
    applied between steps) must drop the cached end-state umax the next
    dt derives from, alongside the ordered-state cache — a stale umax
    would run the stronger new field at an overlarge dt (a silent CFL
    violation)."""
    from cup2d_tpu.ops.stencil import dt_from_umax

    cfg = SimConfig(bpdx=2, bpdy=2, level_max=3, level_start=1,
                    extent=1.0, dtype="float64", nu=1e-3,
                    rtol=1e9, ctol=-1.0)
    sim = AMRSim(cfg)
    f = sim.forest
    _fill_tg(sim)
    sim.step_once(dt=1e-3)
    sim.step_once()                      # populates the umax cache
    assert sim._next_umax is not None
    umax_old = float(jnp.asarray(sim._next_umax))

    # 10x stronger field written externally (slot layout, post-sync)
    sim.sync_fields()
    order = f.order()
    vel = np.array(f.fields["vel"])   # copy: device views are read-only
    vel[order] *= 10.0
    f.fields["vel"] = jnp.asarray(vel)

    t_before = sim.time
    sim.step_once()                      # dt must derive from NEW field
    dt_used = sim.time - t_before
    hmin = float(sim._hmin())
    dt_stale = float(dt_from_umax(
        jnp.asarray(umax_old), jnp.asarray(hmin), cfg.nu, cfg.cfl))
    dt_fresh = float(dt_from_umax(
        jnp.asarray(10.0 * umax_old), jnp.asarray(hmin),
        cfg.nu, cfg.cfl))
    # the used dt matches the fresh-field CFL, not the stale cache
    assert abs(dt_used - dt_fresh) < 1e-12 * dt_fresh, \
        (dt_used, dt_fresh, dt_stale)
    assert dt_used < 0.5 * dt_stale


def test_external_field_write_invalidates_cached_dt_shaped():
    """Same contract on the OBSTACLE path: its dt branch reads
    _next_dt/_next_umax, so the external-write invalidation must run
    BEFORE dt selection there too (ADVICE r3 medium — the megastep
    otherwise executes one step at the stale dt)."""
    from cup2d_tpu.models import DiskShape

    cfg = SimConfig(bpdx=2, bpdy=1, level_max=3, level_start=2,
                    extent=1.0, dtype="float64", nu=1e-3, lam=1e5,
                    rtol=1e9, ctol=-1.0)
    # prescribed tow speed so dt is CFL-(advection-)bound: a 10x field
    # write then moves dt materially (a still fluid is diffusion-bound
    # and dt barely notices umax)
    sim = AMRSim(cfg, shapes=[DiskShape(0.08, 0.55, 0.25,
                                        prescribed=(0.2, 0.0))])
    sim.compute_forces_every = 0
    sim.initialize()
    sim.step_once()
    sim.step_once()                      # populates _next_dt/_next_umax
    assert sim._next_dt is not None
    dt_stale = min(sim._next_dt, sim._kinematic_dt_cap())

    # 10x stronger field written externally (slot layout, post-sync)
    f = sim.forest
    sim.sync_fields()
    order = f.order()
    vel = np.array(f.fields["vel"])
    vel[order] *= 10.0
    f.fields["vel"] = jnp.asarray(vel)

    # expected dt from the new field WITHOUT calling sim.compute_dt()
    # (that would itself run _ordered_state()'s invalidation and mask a
    # missing fix in step_once)
    from cup2d_tpu.ops.stencil import dt_from_umax
    umax_new = float(np.abs(vel[order]).max())
    dt_fresh = min(
        float(dt_from_umax(jnp.asarray(umax_new), sim._hmin(),
                           cfg.nu, cfg.cfl)),
        sim._kinematic_dt_cap())
    t_before = sim.time
    sim.step_once()                      # dt must derive from NEW field
    dt_used = sim.time - t_before
    assert abs(dt_used - dt_fresh) < 1e-12 * dt_fresh, \
        (dt_used, dt_fresh, dt_stale)
    assert dt_used < 0.75 * dt_stale


def test_production_two_level_trigger():
    """VERDICT r3 #9: production solves engage the two-level coarse
    correction when the previous solve burned > 15 iterations (the
    block-Jacobi block-count scaling law on near-uniform forests), and
    the correction actually collapses the iteration count on the SAME
    inputs."""
    cfg = SimConfig(bpdx=2, bpdy=1, level_max=5, level_start=4,
                    extent=1.0, dtype="float64", nu=1e-4,
                    rtol=1e9, ctol=-1.0, cfl=0.4,
                    poisson_tol=1e-10, poisson_tol_rel=1e-8,
                    max_poisson_iterations=400)
    sim = AMRSim(cfg)
    _fill_tg(sim)
    sim.step_count = 20            # production regime from the start
    assert not sim._coarse_on

    sim.step_once(dt=1e-3)
    n1 = int(jnp.asarray(sim._last_iters_dev))
    assert n1 > 15, n1             # hard solve without the correction

    # direct same-inputs A/B: the two-level M on the identical solve
    sim._refresh()
    ordf = sim._ordered_state()
    f = sim.forest
    if sim._coarse_cw is None:
        sim._build_coarse_maps(sim._npad_hwm, sim._n_real)
    _, _, diag_c = sim._step_jit(
        ordf["vel"], ordf["pres"], jnp.asarray(1e-3, f.dtype),
        sim._h, sim._hsq_flat, sim._maskv,
        sim._tables["vec3"], sim._tables["vec1"],
        sim._tables["sca1"], sim._tables["pois"],
        sim._corr, sim._coarse_cw, exact_poisson=False)
    _, _, diag_p = sim._step_jit(
        ordf["vel"], ordf["pres"], jnp.asarray(1e-3, f.dtype),
        sim._h, sim._hsq_flat, sim._maskv,
        sim._tables["vec3"], sim._tables["vec1"],
        sim._tables["sca1"], sim._tables["pois"],
        sim._corr, None, exact_poisson=False)
    nc = int(diag_c["poisson_iters"])
    np_ = int(diag_p["poisson_iters"])
    assert nc < np_ / 2, (nc, np_)

    # driver-level: the next step drains the iters scalar, trips the
    # trigger, and runs with the coarse correction engaged
    sim.step_once(dt=1e-3)
    assert sim._coarse_on
    assert sim._last_iters == n1
    assert sim._coarse_cw is not None
    # topology change re-arms the trigger INCLUDING the stale count
    # (a pre-regrid 400-iteration count must not engage the correction
    # on the new topology)
    sim.forest.version += 1
    sim._refresh()
    assert not sim._coarse_on
    assert sim._last_iters == 0 and sim._last_iters_dev is None


def test_twolevel_env_gate_rejects_typos(monkeypatch):
    """CUP2D_TWOLEVEL typos must raise, not silently fall back — an
    A/B probe that measures the same form on both arms reports the
    additive speedup as gone (code-review r5). Since the gate latch
    moved to __init__ (ADVICE r5), the typo fails at CONSTRUCTION —
    before any step runs at the wrong form."""
    import pytest as _pytest

    from cup2d_tpu.amr import AMRSim
    from cup2d_tpu.config import SimConfig

    monkeypatch.setenv("CUP2D_TWOLEVEL", "add")
    cfg = SimConfig(bpdx=1, bpdy=1, level_max=2, level_start=1,
                    extent=1.0, dtype="float64")
    with _pytest.raises(ValueError, match="CUP2D_TWOLEVEL"):
        AMRSim(cfg, shapes=[])


def test_two_level_ladder_bounded_by_active_levels():
    """The two-level preconditioner's per-level image ladder must stop
    at the finest ACTIVE level (ADVICE r5 / PR 2): a levelMax-6 forest
    sitting entirely at level 1 must not carry level-5 full-domain
    image entries (O(4^level) cells) through _deposit/_interp."""
    cfg = SimConfig(bpdx=1, bpdy=1, level_max=6, level_start=1,
                    extent=1.0, dtype="float64")
    sim = AMRSim(cfg, shapes=[])
    sim._refresh()
    cw = sim._use_coarse(True)
    active = {int(v) for v in np.unique(sim.forest.level[sim._order])}
    assert set(cw["lev"].keys()) == active == {1}
    assert "levf" not in cw        # nothing finer than the coarse level


def _deep_corner_sim():
    """A levelMax-5 forest with one deep-refinement corner: level-2
    background, a level-3 patch, a level-4 spot (2:1 everywhere)."""
    cfg = SimConfig(bpdx=1, bpdy=1, level_max=5, level_start=2,
                    extent=1.0, dtype="float64")
    sim = AMRSim(cfg, shapes=[])
    f = sim.forest
    f.release(2, 0, 0)
    for a in (0, 1):
        for b in (0, 1):
            f.allocate(3, a, b)
    f.release(3, 0, 0)
    for a in (0, 1):
        for b in (0, 1):
            f.allocate(4, a, b)
    sim._refresh()
    return sim


def _full_domain_transfers(sim):
    """The PR-2/PR-3 FULL-DOMAIN two-level transfers, reimplemented as
    the test oracle for the cropped production form (one image per
    non-empty level at its own resolution — the O(4^level) cliff the
    crop closes)."""
    from cup2d_tpu.amr import _down2_mean, _up2_bilinear
    f = sim.forest
    c = sim._coarse_level
    bs = f.bs
    ncy, ncx = sim._coarse_shape
    lvo = f.level[sim._order].astype(np.int64)
    bio = f.bi[sim._order].astype(np.int64)
    bjo = f.bj[sim._order].astype(np.int64)
    n_real = sim._n_real
    n_pad = sim._npad_hwm
    per = {}
    for l in sorted(int(v) for v in np.unique(lvo)):
        ntx, nty = f.cfg.bpdx << l, f.cfg.bpdy << l
        sel = lvo == l
        tix = bjo[sel] * ntx + bio[sel]
        own = np.full(nty * ntx, n_real, np.int32)
        own[tix] = np.nonzero(sel)[0].astype(np.int32)
        ownm = np.zeros(nty * ntx)
        ownm[tix] = 1.0
        tid = np.zeros(n_pad, np.int32)
        tid[:n_real][sel] = tix.astype(np.int32)
        selp = np.zeros(n_pad)
        selp[:n_real][sel] = 1.0
        per[l] = (own.reshape(nty, ntx), ownm.reshape(nty, ntx),
                  jnp.asarray(tid), jnp.asarray(selp))

    def deposit(rp):
        rc = jnp.zeros((ncy, ncx), rp.dtype)
        for l in sorted(per):
            own, ownm, _, _ = per[l]
            nty, ntx = own.shape
            img = rp[own.reshape(-1)] \
                * jnp.asarray(ownm.reshape(-1))[:, None, None]
            img = img.reshape(nty, ntx, bs, bs).transpose(0, 2, 1, 3) \
                     .reshape(nty * bs, ntx * bs)
            if l > c:
                for _ in range(l - c):
                    img = _down2_mean(img)
            else:
                for _ in range(c - l):
                    img = jnp.repeat(jnp.repeat(img, 2, 0), 2, 1) * 0.25
            rc = rc + img
        return rc

    def interp(ec, like):
        imgs = {c: ec} if c in per else {}
        a = ec
        for l in range(c + 1, max(per) + 1):
            a = _up2_bilinear(a)
            if l in per:
                imgs[l] = a
        a = ec
        for l in range(c - 1, min(per) - 1, -1):
            a = _down2_mean(a)
            if l in per:
                imgs[l] = a
        e = jnp.zeros_like(like)
        for l in sorted(per):
            own, _, tid, selp = per[l]
            nty, ntx = own.shape
            tiles = imgs[l].reshape(nty, bs, ntx, bs) \
                           .transpose(0, 2, 1, 3) \
                           .reshape(nty * ntx, bs, bs)
            e = e + tiles[tid] * selp[:, None, None]
        return e

    return deposit, interp


def test_two_level_crop_matches_full_domain():
    """Cropping the fine-level (l > c) transfer images to the
    active-tile bounding box must be BIT-IDENTICAL to the full-domain
    form on every active cell — the 2-coarse-cell margin covers the
    bilinear up-ladder's dependence reach, so the crop is a pure cost
    optimization, not an approximation (the former ROADMAP
    O(4^level)-image cliff, amr._build_coarse_maps)."""
    sim = _deep_corner_sim()
    cw = sim._use_coarse(True)
    c = sim._coarse_level
    assert c == 3
    # the level-4 entry is cropped: window tiles strictly fewer than
    # the 16x16 full-domain tile grid
    assert set(cw["levf"].keys()) == {4}
    ntyw, ntxw = cw["levf"][4][0].shape
    assert ntyw < 16 and ntxw < 16
    assert set(cw["lev"].keys()) == {2, 3}

    rng = np.random.default_rng(7)
    n_pad = sim._npad_hwm
    bs = sim.forest.bs
    rp = jnp.asarray(rng.standard_normal((n_pad, bs, bs)))
    ncy, ncx = sim._coarse_shape
    ec = jnp.asarray(rng.standard_normal((ncy, ncx)))

    dep_c, itp_c = sim._coarse_transfers(cw)
    dep_f, itp_f = _full_domain_transfers(sim)
    assert np.array_equal(np.asarray(dep_c(rp)), np.asarray(dep_f(rp)))
    got = np.asarray(itp_c(ec, rp))
    want = np.asarray(itp_f(ec, rp))
    # pad rows are zero in both (selp masks them); active rows bitwise
    assert np.array_equal(got, want)
    # and the exact solve actually runs through the bounded ladder
    sim.step_once(dt=1e-3)
