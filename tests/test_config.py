"""Config/CLI tests — reference CommandlineParser semantics
(main.cpp:459-501) and the run.sh flag set."""

import pytest

from cup2d_tpu.config import CommandlineParser, LineParser, MissingKeyError, SimConfig

RUN_SH_ARGV = (
    "-AdaptSteps 20 -bpdx 2 -bpdy 1 -CFL 0.5 -Ctol 1 -extent 4 "
    "-lambda 1e7 -levelMax 8 -levelStart 5 -maxPoissonIterations 1000 "
    "-maxPoissonRestarts 0 -nu 0.00004 -poissonTol 1e-3 -poissonTolRel 1e-2 "
    "-Rtol 2 -tdump 0.5 -tend 10.0"
).split() + [
    "-shapes",
    "angle=0 L=0.2 xpos=1.8 ypos=0.8\nangle=180 L=0.2 xpos=1.6 ypos=0.8",
]


def test_basic_parsing():
    p = CommandlineParser(["-nu", "0.01", "-bpdx", "4", "-flag"])
    assert p("nu").asDouble() == 0.01
    assert p("bpdx").asInt() == 4
    assert p("flag").asString() == "true"


def test_negative_numbers_are_values():
    p = CommandlineParser(["-xvel", "-0.3", "-n", "-5"])
    assert p("xvel").asDouble() == -0.3
    assert p("n").asInt() == -5


def test_missing_key_aborts():
    p = CommandlineParser(["-nu", "0.01"])
    with pytest.raises(MissingKeyError):
        p("bpdx")


def test_plus_override():
    # first occurrence wins, unless +key forces override (main.cpp:484-490)
    p = CommandlineParser(["-nu", "1", "-nu", "2"])
    assert p("nu").asDouble() == 1
    p = CommandlineParser(["-nu", "1", "-+nu", "2"])
    assert p("nu").asDouble() == 2


def test_run_sh_case():
    cfg = SimConfig.from_argv(RUN_SH_ARGV)
    assert cfg.bpdx == 2 and cfg.bpdy == 1
    assert cfg.level_max == 8 and cfg.level_start == 5
    assert cfg.h0 == pytest.approx(4.0 / 2 / 8)
    assert cfg.extents[0] == pytest.approx(4.0)
    assert cfg.extents[1] == pytest.approx(2.0)
    assert cfg.min_h == pytest.approx(cfg.h0 / 128)
    shapes = cfg.parse_shapes()
    assert len(shapes) == 2
    assert shapes[0]["xpos"] == 1.8 and shapes[1]["angle"] == 180


def test_line_parser():
    p = LineParser("angle=0 L=0.2 xpos=1.8 ypos=0.8")
    assert p("L").asDouble() == 0.2
    assert not p.has("T")
