"""Worker for tests/test_multihost.py: one process of a 2-process
jax.distributed run of ShardedAMRSim on CPU (4 virtual devices per
process -> one 8-device global mesh). Prints one digest line per regrid
cycle; the parent asserts both processes print identical digests — the
reference's cross-rank state-agreement contract (update_boundary /
update_blocks, /root/reference/main.cpp:1410-1970) expressed as a test.

CAPABILITY PROBE: this container's CPU backend rejects multiprocess
computations (reproduction: a one-array cross-process reduction over
the global mesh fails inside XLA's CPU collectives at the first
dispatch — the same failure ShardedAMRSim init hits; pre-existing,
reproduced at HEAD~ in a clean worktree, see ROADMAP "Elastic pod
resilience"). The worker probes that FIRST and prints a
``SKIP_MULTIPROCESS`` line + exits 0 instead of erroring, so the
parent test SKIPs cleanly on broken boxes and still runs for real on
the first box with a working 2-process jax.distributed CPU runtime.

Phases: the default run is the determinism/IO/SIGTERM drill below;
``CUP2D_MH_PHASE=elastic`` runs the 2-process elastic host-loss drill
instead (host_exit on process 1 announced via the TopologyGuard beat,
survivor re-inits the runtime over the survivor world and resumes from
the disk checkpoint — per-shard snapshots die with their host, so a
real loss lands the disk rung by design).

Usage: python tests/_multihost_worker.py <process_id> <coordinator_port>
       [<reinit_port>]
"""

import hashlib
import os
import sys


def main():
    pid = int(sys.argv[1])
    port = sys.argv[2]
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=4").strip()
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)
    import numpy as np

    from cup2d_tpu.config import SimConfig
    from cup2d_tpu.models import DiskShape
    from cup2d_tpu.parallel.forest_mesh import ShardedAMRSim
    from cup2d_tpu.parallel.launch import global_mesh, init_distributed

    # the coordinator connect goes through init_distributed (NOT a
    # direct jax.distributed.initialize): that is the sanctioned
    # bring-up path, and it latches the version-safe
    # resilience.dist_initialized probe on jax builds without the
    # public is_initialized accessor
    assert init_distributed(
        coordinator_address=f"127.0.0.1:{port}",
        num_processes=2, process_id=pid,
        expected_processes=2) == pid
    mesh = global_mesh()
    assert mesh.devices.size == 8, mesh

    # ---- capability probe (see module docstring) ----
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P
    try:
        x = jax.device_put(
            np.arange(mesh.devices.size, dtype=np.float64),
            NamedSharding(mesh, P("x")))
        total = float(jax.jit(jnp.sum)(x))
        assert total == sum(range(mesh.devices.size))
    except Exception as e:
        print(f"SKIP_MULTIPROCESS {type(e).__name__}: "
              f"{str(e)[:200]}", flush=True)
        return 0

    if os.environ.get("CUP2D_MH_PHASE") == "elastic":
        return _elastic_phase(pid, mesh)

    # the HARD multi-process case (VERDICT r3 weak #7 said the r3 test
    # proved only the easy one): a DEFORMING fish (midline kinematics +
    # per-step rasterization) next to a disk, compression enabled
    # (ctol) so regrids run compression-group restriction, and rtol
    # low enough that the wake refines — the block count crosses the
    # 128-pad bucket mid-run, forcing a bucket re-bucket + full table
    # rebuild on every process in lockstep.
    from cup2d_tpu.models import FishShape

    cfg = SimConfig(bpdx=2, bpdy=1, level_max=4, level_start=1,
                    extent=1.0, dtype="float64", nu=4e-5, lam=1e6,
                    rtol=0.004, ctol=0.0008)
    sim = ShardedAMRSim(cfg, mesh, shapes=[
        FishShape(0.2, 0.62, 0.25, 0.0, cfg.min_h, period=1.0),
        DiskShape(0.05, 0.3, 0.3),
    ])
    sim.compute_forces_every = 0
    sim.initialize()
    npad0 = int(sim._npad_hwm)

    def digest():
        f = sim.forest
        h = hashlib.sha256()
        for key in sorted(f.blocks):
            h.update(repr((key, int(f.level[f.blocks[key]]))).encode())
        h.update(repr((sim._npad_hwm, sim._n_real)).encode())
        # table plans: per-device row arrays of every sharded set plus
        # the replicated prolongation tables
        for name in sorted(sim._tables):
            t = sim._tables[name]
            if hasattr(t, "nba") and hasattr(t, "pack"):
                # ShardPoissonOp (the sharded structured operator)
                for leaf in (*t.pack, t.nba, t.nbb):
                    h.update(np.asarray(
                        sim._pull_blockwise(leaf)).tobytes())
            elif hasattr(t, "pack"):    # ShardTables
                for leaf in (*t.pack, t.src_l, t.dest_sl, t.dest_l,
                             t.src_r, t.dest_sr, t.dest_r,
                             t.fc_nb, t.fc_mask):
                    h.update(np.asarray(
                        sim._pull_blockwise(leaf)).tobytes())
            else:                        # replicated HaloTables
                h.update(np.asarray(t.dest_s).tobytes())
                h.update(np.asarray(t.src).tobytes())
        return h.hexdigest()

    import jax.numpy as jnp

    def seed_vortices():
        """Mid-run external field write (the supported seeding
        pattern): strong vortex sheet whose tags refine a wide area on
        the next adapt — forces the pad bucket to CROSS 128 -> 256 with
        compression groups migrating, the regrid paths the r3 test
        never reached (VERDICT r3 weak #7). Identical numpy on every
        process -> deterministic."""
        sim.sync_fields()
        f = sim.forest
        order = f.order()
        bs = cfg.bs
        h = f.h_per_block(order)
        ar = np.arange(bs) + 0.5
        X = (f.bi[order].astype(np.float64) * bs * h)[:, None, None] \
            + ar[None, None, :] * h[:, None, None]
        Y = (f.bj[order].astype(np.float64) * bs * h)[:, None, None] \
            + ar[None, :, None] * h[:, None, None]
        # fields span both processes: gather the global value (every
        # process joins the collective, all hold identical numpy)
        from jax.experimental import multihost_utils
        vel = np.array(multihost_utils.process_allgather(
            f.fields["vel"], tiled=True))
        u = np.zeros((len(order), bs, bs))
        v = np.zeros((len(order), bs, bs))
        for k in range(6):
            cx, cy = 0.15 + 0.12 * k, 0.25 + 0.04 * (k % 3)
            dx, dy = X - cx, Y - cy
            r2 = dx * dx + dy * dy
            ut = 0.6 / (2 * np.pi * np.sqrt(r2 + 1e-8)) \
                * (1 - np.exp(-r2 / (2 * 0.02 ** 2)))
            th = np.arctan2(dy, dx)
            u += -ut * np.sin(th)
            v += ut * np.cos(th)
        vel[order, 0] = u
        vel[order, 1] = v
        f.fields["vel"] = jnp.asarray(vel)

    levels_mid = set()
    for cycle in range(3):
        if cycle == 2:
            # after two mixed-level cycles: record that the forest WAS
            # mixed (compression groups exercised), then seed and let
            # the tags climb (each adapt refines one level, 2:1)
            levels_mid = {l for (l, _, _) in sim.forest.blocks}
            seed_vortices()
            sim.adapt()
            sim.adapt()
        sim.adapt()
        for _ in range(2):
            sim.step_once(dt=1e-3)
        print(f"DIGEST {cycle} {digest()}", flush=True)
    # the hard-case ingredients actually occurred (deterministically so,
    # since both processes assert the same)
    assert len(levels_mid) >= 2, levels_mid   # mixed -> compression ran
    assert int(sim._npad_hwm) > npad0, (
        "pad bucket never crossed", npad0, int(sim._npad_hwm))
    print(f"BUCKET {npad0} {int(sim._npad_hwm)} "
          f"{len(sim.forest.blocks)}", flush=True)

    # ---- pod-safe I/O (VERDICT r3 #5): every process joins the gather
    # collectives; process 0 writes; the run restores and continues ----
    import glob

    from cup2d_tpu.io import dump_forest, load_checkpoint, \
        save_checkpoint

    outdir = os.environ["CUP2D_MH_OUTDIR"]     # shared (same machine)
    dump_forest(os.path.join(outdir, "vel.000"), sim.time, sim.forest,
                order=np.asarray(sim._order))
    ck = os.path.join(outdir, "ck")
    save_checkpoint(ck, sim)
    # the dump + checkpoint bytes exist and are complete on EVERY
    # process's view of the storage (barrier inside save/dump)
    for pat in ("vel.000.xyz.raw", "vel.000.attr.raw", "vel.000.xdmf2"):
        assert os.path.exists(os.path.join(outdir, pat)), pat
    assert os.path.exists(os.path.join(ck, "fields.npz"))
    import hashlib as hl
    ck_hash = hl.sha256(
        open(os.path.join(ck, "fields.npz"), "rb").read()).hexdigest()
    dump_hash = hl.sha256(
        open(os.path.join(outdir, "vel.000.attr.raw"), "rb").read()
    ).hexdigest()
    print(f"IOHASH {ck_hash} {dump_hash}", flush=True)

    # diverge the live sim, restore, and CONTINUE the trajectory —
    # the restored run must stay deterministic across processes
    sim.step_once(dt=1e-3)
    load_checkpoint(ck, sim)
    for _ in range(2):
        sim.step_once(dt=1e-3)
    print(f"DIGEST restore {digest()}", flush=True)
    assert not glob.glob(os.path.join(outdir, "ck.tmp*")), \
        "checkpoint temp dir left behind"

    # ---- SIGTERM latch agreement (the former ROADMAP pod gap (a)) ----
    # Skewed preemption delivery: the faults.py sigterm injector fires
    # on process 0 after step 3 and on process 1 after step 5 — exactly
    # the hosts-preempted-at-different-instants hazard. The per-process
    # latch alone would send process 0 into the collective checkpoint
    # at boundary 3 while process 1 keeps stepping (a mismatched-
    # collective hang); PreemptionGuard.agree() min-allreduces the flag
    # at every boundary, so BOTH processes agree to stop at boundary 5
    # (the first where every latch is set) and enter the collective
    # save together.
    from cup2d_tpu.faults import FaultPlan
    from cup2d_tpu.resilience import PreemptionGuard

    plan = FaultPlan(f"sigterm@{3 if pid == 0 else 5}")
    stop = PreemptionGuard().install()
    agreed_at = None
    local_at = None
    try:
        for k in range(1, 9):
            sim.step_once(dt=1e-3)
            plan.fire_post_step(k)
            if stop.triggered and local_at is None:
                local_at = k
            if stop.agree():          # collective: same call count on
                agreed_at = k         # every process, every boundary
                break
    finally:
        stop.uninstall()
    assert agreed_at is not None, "agreement never reached"
    # the locally-latched process saw its flag BEFORE the agreement
    # (process 0 latches at 3, agreement lands at 5 on both)
    assert local_at is not None and local_at <= agreed_at
    ck2 = os.path.join(outdir, "ck_sigterm")
    save_checkpoint(ck2, sim)         # the collective save, in lockstep
    assert os.path.exists(os.path.join(ck2, "meta.json"))
    print(f"SIGTERM_AGREE {agreed_at}", flush=True)

    print("DONE", flush=True)


def _elastic_phase(pid: int, mesh) -> int:
    """2-process elastic host-loss drill (slow-marked; validated on the
    first box with a working multiprocess CPU runtime — ROADMAP).

    Process 1 arms ``host_exit@3`` (its own CUP2D_FAULTS env, the
    process-scoped real-mode consumer): at boundary 3 it announces the
    exit in its final heartbeat and hard-exits. Process 0's SAME beat
    sees the announcement — deterministic evidence, no timeout needed
    for the graceful flavor — declares the loss, re-initializes the
    runtime as a 1-process world on the fresh ``reinit_port`` (the old
    world's collectives died with the peer), re-meshes onto its own
    4 devices and resumes from the disk checkpoint (per-shard
    snapshots died with the host: snapshot_covers says so, the disk
    rung is the designed real-loss path)."""
    from cup2d_tpu.config import SimConfig
    from cup2d_tpu.faults import FaultPlan
    from cup2d_tpu.io import save_checkpoint
    from cup2d_tpu.parallel.launch import reinit_distributed
    from cup2d_tpu.parallel.mesh import ShardedUniformSim, make_mesh
    from cup2d_tpu.resilience import (EventLog, PreemptionGuard,
                                      StepGuard, TopologyGuard,
                                      set_event_log)
    from cup2d_tpu.uniform import taylor_green_state

    outdir = os.environ["CUP2D_MH_OUTDIR"]
    reinit_port = sys.argv[3]
    log = EventLog(os.path.join(outdir, f"elastic_events.{pid}.jsonl"))
    set_event_log(log)
    plan = FaultPlan.from_env()          # host_exit@3 on pid 1 only
    cfg = SimConfig(bpdx=2, bpdy=1, level_max=1, level_start=0,
                    extent=2.0, nu=1e-3, cfl=0.4, dtype="float64",
                    max_poisson_iterations=200)
    sim = ShardedUniformSim(cfg, mesh, level=2)   # nx=64 over 8 devs
    sim.set_state(taylor_green_state(sim.grid))
    sim.step_count = 20
    ck = os.path.join(outdir, "elastic_ck")
    guard = StepGuard(sim, ckpt_dir=ck, event_log=log, faults=plan,
                      snap_every=1)
    topo = TopologyGuard(devices=list(mesh.devices.flat),
                         timeout=30.0, faults=plan, event_log=log)
    stop = PreemptionGuard()
    while sim.step_count < 28:
        if sim.step_count == 23:
            guard.drain()
            save_checkpoint(ck, sim)     # collective, pre-loss
        beat = topo.step_boundary(stop, sim.step_count)
        if beat.self_lost:
            os._exit(17)                 # the dying host: no cleanup
        if beat.hung or beat.lost:
            # survivors: new 1-process world FIRST (old collectives
            # are dead), then re-mesh + disk resume
            reinit_distributed(f"127.0.0.1:{reinit_port}",
                               num_processes=1, process_id=0)
            guard.elastic_recover(topo)
            continue
        guard.step()
    guard.drain()
    assert sim.mesh.devices.size == 4    # this host's own devices
    assert guard.remesh_count == 1 and guard.topology_epoch == 1
    print(f"ELASTIC_RESUMED step={sim.step_count} "
          f"t={sim.time:.6f}", flush=True)
    log.close()
    return 0


if __name__ == "__main__":
    main()
