"""Worker for tests/test_multihost.py: one process of a 2-process
jax.distributed run of ShardedAMRSim on CPU (4 virtual devices per
process -> one 8-device global mesh). Prints one digest line per regrid
cycle; the parent asserts both processes print identical digests — the
reference's cross-rank state-agreement contract (update_boundary /
update_blocks, /root/reference/main.cpp:1410-1970) expressed as a test.

Usage: python tests/_multihost_worker.py <process_id> <coordinator_port>
"""

import hashlib
import os
import sys


def main():
    pid = int(sys.argv[1])
    port = sys.argv[2]
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=4").strip()
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)
    jax.distributed.initialize(
        coordinator_address=f"127.0.0.1:{port}",
        num_processes=2, process_id=pid)
    import numpy as np

    from cup2d_tpu.config import SimConfig
    from cup2d_tpu.models import DiskShape
    from cup2d_tpu.parallel.forest_mesh import ShardedAMRSim
    from cup2d_tpu.parallel.launch import global_mesh, init_distributed

    assert init_distributed(expected_processes=2) == pid
    mesh = global_mesh()
    assert mesh.devices.size == 8, mesh

    cfg = SimConfig(bpdx=2, bpdy=1, level_max=3, level_start=1,
                    extent=1.0, dtype="float64", nu=4e-5, lam=1e6,
                    rtol=2.0, ctol=1.0)
    sim = ShardedAMRSim(cfg, mesh, shapes=[DiskShape(0.08, 0.55, 0.25)])
    sim.compute_forces_every = 0
    sim.initialize()

    def digest():
        f = sim.forest
        h = hashlib.sha256()
        for key in sorted(f.blocks):
            h.update(repr((key, int(f.level[f.blocks[key]]))).encode())
        h.update(repr((sim._npad_hwm, sim._n_real)).encode())
        # table plans: per-device row arrays of every sharded set plus
        # the replicated prolongation tables
        for name in sorted(sim._tables):
            t = sim._tables[name]
            if hasattr(t, "pack"):      # ShardTables
                for leaf in (t.pack, t.src_l, t.dest_sl, t.dest_l,
                             t.src_r, t.dest_sr, t.dest_r):
                    h.update(np.asarray(
                        sim._pull_blockwise(leaf)).tobytes())
            else:                        # replicated HaloTables
                h.update(np.asarray(t.dest_s).tobytes())
                h.update(np.asarray(t.src).tobytes())
        return h.hexdigest()

    for cycle in range(3):
        sim.adapt()
        for _ in range(2):
            sim.step_once(dt=1e-3)
        print(f"DIGEST {cycle} {digest()}", flush=True)

    # ---- pod-safe I/O (VERDICT r3 #5): every process joins the gather
    # collectives; process 0 writes; the run restores and continues ----
    import glob

    from cup2d_tpu.io import dump_forest, load_checkpoint, \
        save_checkpoint

    outdir = os.environ["CUP2D_MH_OUTDIR"]     # shared (same machine)
    dump_forest(os.path.join(outdir, "vel.000"), sim.time, sim.forest,
                order=np.asarray(sim._order))
    ck = os.path.join(outdir, "ck")
    save_checkpoint(ck, sim)
    # the dump + checkpoint bytes exist and are complete on EVERY
    # process's view of the storage (barrier inside save/dump)
    for pat in ("vel.000.xyz.raw", "vel.000.attr.raw", "vel.000.xdmf2"):
        assert os.path.exists(os.path.join(outdir, pat)), pat
    assert os.path.exists(os.path.join(ck, "fields.npz"))
    import hashlib as hl
    ck_hash = hl.sha256(
        open(os.path.join(ck, "fields.npz"), "rb").read()).hexdigest()
    dump_hash = hl.sha256(
        open(os.path.join(outdir, "vel.000.attr.raw"), "rb").read()
    ).hexdigest()
    print(f"IOHASH {ck_hash} {dump_hash}", flush=True)

    # diverge the live sim, restore, and CONTINUE the trajectory —
    # the restored run must stay deterministic across processes
    sim.step_once(dt=1e-3)
    load_checkpoint(ck, sim)
    for _ in range(2):
        sim.step_once(dt=1e-3)
    print(f"DIGEST restore {digest()}", flush=True)
    assert not glob.glob(os.path.join(outdir, "ck.tmp*")), \
        "checkpoint temp dir left behind"
    print("DONE", flush=True)


if __name__ == "__main__":
    main()
