"""Worker for tests/test_multihost.py: one process of a 2-process
jax.distributed run of ShardedAMRSim on CPU (4 virtual devices per
process -> one 8-device global mesh). Prints one digest line per regrid
cycle; the parent asserts both processes print identical digests — the
reference's cross-rank state-agreement contract (update_boundary /
update_blocks, /root/reference/main.cpp:1410-1970) expressed as a test.

Usage: python tests/_multihost_worker.py <process_id> <coordinator_port>
"""

import hashlib
import os
import sys


def main():
    pid = int(sys.argv[1])
    port = sys.argv[2]
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=4").strip()
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)
    jax.distributed.initialize(
        coordinator_address=f"127.0.0.1:{port}",
        num_processes=2, process_id=pid)
    import numpy as np

    from cup2d_tpu.config import SimConfig
    from cup2d_tpu.models import DiskShape
    from cup2d_tpu.parallel.forest_mesh import ShardedAMRSim
    from cup2d_tpu.parallel.launch import global_mesh, init_distributed

    assert init_distributed(expected_processes=2) == pid
    mesh = global_mesh()
    assert mesh.devices.size == 8, mesh

    cfg = SimConfig(bpdx=2, bpdy=1, level_max=3, level_start=1,
                    extent=1.0, dtype="float64", nu=4e-5, lam=1e6,
                    rtol=2.0, ctol=1.0)
    sim = ShardedAMRSim(cfg, mesh, shapes=[DiskShape(0.08, 0.55, 0.25)])
    sim.compute_forces_every = 0
    sim.initialize()

    def digest():
        f = sim.forest
        h = hashlib.sha256()
        for key in sorted(f.blocks):
            h.update(repr((key, int(f.level[f.blocks[key]]))).encode())
        h.update(repr((sim._npad_hwm, sim._n_real)).encode())
        # table plans: per-device row arrays of every sharded set plus
        # the replicated prolongation tables
        for name in sorted(sim._tables):
            t = sim._tables[name]
            if hasattr(t, "pack"):      # ShardTables
                for leaf in (t.pack, t.src, t.dest_s, t.dest):
                    h.update(np.asarray(
                        sim._pull_blockwise(leaf)).tobytes())
            else:                        # replicated HaloTables
                h.update(np.asarray(t.dest_s).tobytes())
                h.update(np.asarray(t.src).tobytes())
        return h.hexdigest()

    for cycle in range(3):
        sim.adapt()
        for _ in range(2):
            sim.step_once(dt=1e-3)
        print(f"DIGEST {cycle} {digest()}", flush=True)
    print("DONE", flush=True)


if __name__ == "__main__":
    main()
