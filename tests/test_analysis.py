"""graftlint (cup2d_tpu.analysis) — framework, rules, CLI.

Every rule is demonstrated LIVE on a seeded-violation snippet compiled
from strings (never from repo files, so the fixtures can't rot with
the tree) next to a clean twin that must pass; the suppression syntax
is pinned including its failure mode (an allow without a reason is a
config error, rc 2); and the CLI is smoke-pinned the way
test_bench_smoke.py pins bench — a real subprocess, rc semantics and
one JSON line, with the ``--only env-latch`` run agreeing with the
pytest wrapper in test_env_latch.py.
"""

import json
import os
import subprocess
import sys

import pytest

from cup2d_tpu.analysis import (LintConfigError, lint_package,
                                lint_sources)

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _findings(sources, only=None):
    return lint_sources(sources, only=only).findings


def _rules_hit(sources, only=None):
    return {f.rule for f in _findings(sources, only=only)}


# ---------------------------------------------------------------------------
# env-latch
# ---------------------------------------------------------------------------

ENV_BAD = """\
import os

def refresh(self):
    mode = os.environ.get("CUP2D_POIS", "structured")
    return mode
"""

ENV_CLEAN = """\
import os

def refresh(self):
    return self._pois_mode       # reads the latched value, not the env
"""


def test_env_latch_flags_unsanctioned_read():
    fs = _findings({"somefile.py": ENV_BAD}, only=["env-latch"])
    assert len(fs) == 1
    assert fs[0].rule == "env-latch"
    assert fs[0].scope == "refresh"
    assert "CUP2D_POIS" in fs[0].message


def test_env_latch_clean_twin_passes():
    assert not _findings({"somefile.py": ENV_CLEAN}, only=["env-latch"])


def test_env_latch_sanctioned_site_passes():
    # the same read of a policy-listed var at its (file, scope) latch
    src = ENV_BAD.replace("def refresh(self):",
                          "def enable_compilation_cache():") \
        .replace("CUP2D_POIS", "CUP2D_CACHE")
    # note: finalize will flag the OTHER policy vars as stale for
    # cache.py; restrict to the read check by asserting no finding on
    # the read's line
    fs = _findings({"cache.py": src}, only=["env-latch"])
    assert not [f for f in fs if "outside the sanctioned" in f.message]


def test_env_latch_config_file_fully_sanctioned():
    assert not [f for f in _findings({"config.py": ENV_BAD},
                                     only=["env-latch"])
                if "outside the sanctioned" in f.message]


# ---------------------------------------------------------------------------
# host-sync
# ---------------------------------------------------------------------------

SYNC_BAD = """\
import jax
import jax.numpy as jnp
import numpy as np

def step_diag(self, vel):
    umax = float(jnp.max(jnp.abs(vel)))      # per-scalar pull
    return umax
"""

SYNC_BAD_TAINT = """\
import jax.numpy as jnp
import numpy as np

def step_diag(self, vel):
    nrm = jnp.linalg.norm(vel)
    return np.asarray(nrm)                   # pull via tainted name
"""

SYNC_BAD_ITEM = """\
import jax.numpy as jnp

def step_diag(self, vel):
    return jnp.max(vel).item()
"""

SYNC_CLEAN = """\
import jax
import jax.numpy as jnp

def step_diag(self, vel):
    # stays on device; the driver's ONE batched pull fetches it
    return jnp.max(jnp.abs(vel))

def cold_restore(path, host_buf):
    # host math on host values is not a sync
    return float(sum(host_buf))
"""


def test_host_sync_flags_scalar_pull():
    assert _rules_hit({"driver.py": SYNC_BAD}) == {"host-sync"}
    assert _rules_hit({"driver.py": SYNC_BAD_TAINT}) == {"host-sync"}
    assert _rules_hit({"driver.py": SYNC_BAD_ITEM}) == {"host-sync"}


def test_host_sync_clean_twin_passes():
    assert not _findings({"driver.py": SYNC_CLEAN}, only=["host-sync"])


def test_host_sync_sanctioned_scope_passes():
    # fleet.py's FleetSim.step_once is a sanctioned pull site
    src = """\
import jax
import jax.numpy as jnp

class FleetSim:
    def step_once(self, vel):
        umax = float(jnp.max(jnp.abs(vel)))
        return umax
"""
    # (the finalize pass rightly flags the OTHER sanctioned fleet.py
    # scopes as missing from this one-class fixture — not under test)
    fs = _findings({"fleet.py": src}, only=["host-sync"])
    assert not [f for f in fs if "stale policy row" not in f.message]


def test_host_sync_device_get_of_pulled_value_not_double_flagged():
    src = """\
import jax

def cold(self, diag):
    host = jax.device_get(diag)
    return float(host)
"""
    fs = _findings({"driver.py": src}, only=["host-sync"])
    # exactly the device_get itself — float() of an already-pulled
    # host value is not a second sync
    assert len(fs) == 1 and "device_get" in fs[0].message


# ---------------------------------------------------------------------------
# donation-safety
# ---------------------------------------------------------------------------

DON_BAD = """\
import jax
import numpy as np

_step = jax.jit(lambda st, dt: st, donate_argnums=(0,))

def restore(path, dt):
    npz = np.load(path)
    st = npz["vel"]
    return _step(st, dt)
"""

DON_BAD_WRAPPED = """\
import jax
import numpy as np

_step = jax.jit(lambda st, dt: st, donate_argnums=(0,))

def restore(path, dt):
    npz = np.load(path)
    st = FlowState(npz["vel"], npz["p"])     # constructor wraps buffers
    return _step(st, dt)
"""

DON_CLEAN = """\
import jax
import jax.numpy as jnp
import numpy as np

_step = jax.jit(lambda st, dt: st, donate_argnums=(0,))

def restore(path, dt):
    npz = np.load(path)
    st = jnp.array(npz["vel"])               # owning device copy
    return _step(st, dt)
"""


def test_donation_flags_numpy_into_donated_arg():
    assert _rules_hit({"io2.py": DON_BAD},
                      only=["donation-safety"]) == {"donation-safety"}


def test_donation_flags_constructor_wrapped_buffers():
    assert _rules_hit({"io2.py": DON_BAD_WRAPPED},
                      only=["donation-safety"]) == {"donation-safety"}


def test_donation_clean_twin_passes():
    assert not _findings({"io2.py": DON_CLEAN}, only=["donation-safety"])


def test_donation_non_donated_arg_passes():
    # dt position is not donated — numpy there is legal
    src = DON_CLEAN.replace("return _step(st, dt)",
                            "return _step(st, np.float64(dt))")
    assert not _findings({"io2.py": src}, only=["donation-safety"])


# ---------------------------------------------------------------------------
# retrace-hazard
# ---------------------------------------------------------------------------

RET_BAD_FSTRING = """\
import jax

_run = jax.jit(lambda v: v, static_argnames=("mode",))

def serve(v, i):
    return _run(v, mode=f"case-{i}")
"""

RET_BAD_LIST = """\
import functools
import jax

@functools.partial(jax.jit, static_argnums=(1,))
def _run(v, shape):
    return v

def serve(v, ny, nx):
    return _run(v, [ny, nx])
"""

RET_CLEAN = """\
import jax

_run = jax.jit(lambda v: v, static_argnames=("mode",))

def serve(v, mode):
    return _run(v, mode=mode)        # hashable, caller-stable

def serve2(v, ny, nx):
    return _run(v, mode=(ny, nx))    # tuple is hashable
"""


def test_retrace_flags_fstring_static_operand():
    assert _rules_hit({"srv.py": RET_BAD_FSTRING},
                      only=["retrace-hazard"]) == {"retrace-hazard"}


def test_retrace_flags_unhashable_static_operand():
    assert _rules_hit({"srv.py": RET_BAD_LIST},
                      only=["retrace-hazard"]) == {"retrace-hazard"}


def test_retrace_clean_twin_passes():
    assert not _findings({"srv.py": RET_CLEAN}, only=["retrace-hazard"])


# ---------------------------------------------------------------------------
# leading-dim
# ---------------------------------------------------------------------------

LEAD_BAD = """\
import jax.numpy as jnp

def laplacian(u, h):
    ny = u.shape[0]                          # front-counted rank
    c = u[1, 2]                              # hard positional index
    return jnp.sum(u, axis=0) / h            # positional axis
"""

LEAD_CLEAN = """\
import jax.numpy as jnp

def laplacian(u, h):
    ny = u.shape[-2]
    c = u[..., 1, 2]
    ex = u[:, None]                          # newaxis shaping is legal
    return jnp.sum(u, axis=-2) / h
"""


def test_leading_dim_flags_front_indexing():
    # only fires in policy-listed contract files
    fs = _findings({"ops/stencil.py": LEAD_BAD}, only=["leading-dim"])
    assert len(fs) == 3
    assert {f.rule for f in fs} == {"leading-dim"}


def test_leading_dim_clean_twin_passes():
    assert not _findings({"ops/stencil.py": LEAD_CLEAN},
                         only=["leading-dim"])


def test_leading_dim_ignores_files_outside_contract():
    assert not _findings({"somewhere_else.py": LEAD_BAD},
                         only=["leading-dim"])


def test_leading_dim_ignores_type_annotations():
    src = """\
from typing import Callable
import jax.numpy as jnp

def solve(A: Callable[[jnp.ndarray], jnp.ndarray], b):
    return A(b)
"""
    assert not _findings({"ops/stencil.py": src}, only=["leading-dim"])


# ---------------------------------------------------------------------------
# suppressions
# ---------------------------------------------------------------------------

def test_suppression_with_reason_silences_finding():
    src = SYNC_BAD.replace(
        "    umax = float(jnp.max(jnp.abs(vel)))      # per-scalar pull",
        "    # lint: allow[host-sync] -- cold path, once per restore\n"
        "    umax = float(jnp.max(jnp.abs(vel)))")
    rep = lint_sources({"driver.py": src}, only=["host-sync"])
    assert rep.clean
    assert rep.suppressed.get("host-sync") == 1


def test_suppression_without_reason_is_config_error():
    src = SYNC_BAD.replace(
        "# per-scalar pull", "# lint: allow[host-sync]")
    with pytest.raises(LintConfigError, match="without a reason"):
        lint_sources({"driver.py": src})


def test_suppression_unknown_rule_is_config_error():
    src = SYNC_BAD.replace(
        "# per-scalar pull", "# lint: allow[no-such-rule] -- because")
    with pytest.raises(LintConfigError, match="unknown"):
        lint_sources({"driver.py": src})


def test_unknown_rule_selection_is_config_error():
    with pytest.raises(LintConfigError, match="unknown rule"):
        lint_sources({"x.py": "pass\n"}, only=["no-such-rule"])


# ---------------------------------------------------------------------------
# package runs clean + stays import-light
# ---------------------------------------------------------------------------

def test_package_lints_clean_in_process():
    report = lint_package()
    assert report.clean, "\n".join(str(f) for f in report.findings)
    assert report.files_scanned > 30
    assert set(report.rules_run) == {
        "env-latch", "host-sync", "donation-safety", "retrace-hazard",
        "leading-dim"}


def test_analysis_package_never_imports_jax():
    # the jax-import-free contract, proven in a pristine interpreter
    # (the lazy parent package pulls numpy via curve.py; jax is the
    # heavy dependency the lint must run without)
    code = ("import sys; import cup2d_tpu.analysis as a; "
            "a.lint_package(); "
            "bad = [m for m in sys.modules if m.split('.')[0] in "
            "('jax', 'jaxlib')]; "
            "sys.exit(2 if bad else 0)")
    proc = subprocess.run(
        [sys.executable, "-c", code], cwd=ROOT,
        env={**os.environ, "PYTHONPATH": ROOT}, capture_output=True)
    assert proc.returncode == 0, proc.stderr.decode()


# ---------------------------------------------------------------------------
# CLI smoke (subprocess, like test_bench_smoke.py)
# ---------------------------------------------------------------------------

def _run_cli(*args, inputs=None):
    return subprocess.run(
        [sys.executable, "-m", "cup2d_tpu.analysis", *args],
        cwd=ROOT, env={**os.environ, "PYTHONPATH": ROOT},
        capture_output=True, text=True)


def test_cli_json_clean_on_head():
    proc = _run_cli("--json")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    lines = [ln for ln in proc.stdout.splitlines() if ln.strip()]
    assert len(lines) == 1, "ONE machine-readable JSON line"
    payload = json.loads(lines[0])
    assert payload["graftlint"] == 1
    assert payload["clean"] is True
    assert payload["findings"] == []
    assert set(payload["counts"]) == {
        "env-latch", "host-sync", "donation-safety", "retrace-hazard",
        "leading-dim"}
    assert all(v == 0 for v in payload["counts"].values())
    assert payload["files_scanned"] > 30


def test_cli_only_env_latch_agrees_with_pytest_wrapper():
    proc = _run_cli("--json", "--only", "env-latch")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    payload = json.loads(proc.stdout.strip())
    assert payload["rules"] == ["env-latch"]
    # the pytest wrapper (test_env_latch.py) asserts the same thing
    # in-process; both must agree
    report = lint_package(only=["env-latch"])
    assert payload["clean"] == report.clean
    assert payload["counts"]["env-latch"] == len(report.findings)


def test_cli_rc1_on_findings(tmp_path):
    bad = tmp_path / "dirty.py"
    bad.write_text("import os\nV = os.environ['CUP2D_POIS']\n")
    proc = _run_cli(str(bad))
    assert proc.returncode == 1
    assert "env-latch" in proc.stdout


def test_cli_rc2_on_config_error(tmp_path):
    proc = _run_cli("--only", "no-such-rule")
    assert proc.returncode == 2
    bad = tmp_path / "noreason.py"
    bad.write_text("x = 1  # lint: allow[host-sync]\n")
    proc = _run_cli(str(bad))
    assert proc.returncode == 2


def test_cli_list_rules():
    proc = _run_cli("--list-rules")
    assert proc.returncode == 0
    for rule in ("env-latch", "host-sync", "donation-safety",
                 "retrace-hazard", "leading-dim"):
        assert rule in proc.stdout


def test_fftd_rides_the_sanctioned_pois_latch():
    # ISSUE 20: "fftd" is a VALUE of the CUP2D_POIS latch, not a new
    # read site — the policy table must still sanction exactly the two
    # historical constructor latches, and the package walk must stay
    # clean (an fftd-motivated os.environ read anywhere else would
    # surface here as an unsanctioned-site finding).
    from cup2d_tpu.analysis.policy import ENV_LATCH_SITES
    sites = sorted(site for site, vars_ in ENV_LATCH_SITES.items()
                   if "CUP2D_POIS" in vars_)
    assert sites == [("amr.py", "AMRSim.__init__"),
                     ("uniform.py", "UniformGrid.__init__")]
    report = lint_package(only=["env-latch"])
    assert report.clean, [str(f) for f in report.findings]
