"""Tier-1 bench smoke: a bench regression must never land silently.

BENCH_r05.json ended in an rc=1 stack trace because `_init_platform`
probed only `jax.devices()` — the axon backend registers devices
eagerly and defers the real failure to the first op, so the probe
passed and the bench died at its first jnp call. Nothing in CI ran
bench.py at all, so the breakage shipped. This smoke runs the REAL
bench.py entry point as a subprocess on CPU with a tiny configuration
and pins the driver contract: rc 0, ONE JSON line, the platform
recorded, the telemetry block in the metrics schema, and the fleet
curve present.
"""

import json
import os
import subprocess
import sys

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_bench_cpu_smoke():
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)           # no virtual-device forcing
    env.update(
        JAX_PLATFORMS="cpu",
        PYTHONPATH=ROOT,
        BENCH_SIZE="32",                 # level-2 grid: seconds, not minutes
        BENCH_WARMUP="1",
        BENCH_STEPS="2",
        BENCH_ADAPTIVE="0",              # the AMR bench is its own path
        BENCH_FLEET="1,2",
        BENCH_FLEET_SIZE="16",
        BENCH_FLEET_STEPS="5",
        BENCH_SERVE="1",                 # continuous-batching churn curve
        BENCH_SERVE_SIZE="16",
        BENCH_SERVE_MEMBERS="4",
        BENCH_SERVE_STEPS="8",
        BENCH_MIRROR_SIZE="32",          # mirror-overhead point, tiny
        BENCH_MIRROR_ITERS="5",
        BENCH_POISSON_SIZE="32",         # tiny solver micro-curve
        BENCH_KERNEL_SIZE="32",          # kernel-tier curve, interpret mode
        BENCH_KERNEL_REPS="1",
    )
    proc = subprocess.run(
        [sys.executable, os.path.join(ROOT, "bench.py")],
        capture_output=True, text=True, env=env, cwd=ROOT, timeout=300)
    assert proc.returncode == 0, proc.stderr[-4000:]
    # driver contract: ONE JSON object on stdout
    lines = [ln for ln in proc.stdout.splitlines() if ln.strip()]
    assert len(lines) == 1, proc.stdout
    out = json.loads(lines[0])
    # the r05 failure class: the platform must be probed with a real op
    # and RECORDED (an honest 'platform: cpu', never a crash)
    assert out["platform"] == "cpu"
    assert out["backend"] == "cpu"
    assert out["metric"] and out["value"] > 0
    # telemetry block rides the run-metrics schema (profiling.py)
    from cup2d_tpu.profiling import METRICS_KEYS
    summary = out["telemetry"]["summary"]
    assert summary["steps"] == 2
    last = out["telemetry"]["last_records"][-1]
    assert set(last) == set(METRICS_KEYS)
    # fleet curve (the -fleet bench mode): every requested B measured
    fleet = out["fleet"]
    assert "error" not in fleet, fleet
    assert [p["members"] for p in fleet["points"]] == [1, 2]
    assert all(p["member_steps_per_s"] > 0 for p in fleet["points"])
    assert fleet["speedup_vs_b1"] > 0
    # continuous-batching serving curve (PR 11): the churn window ran
    # real admit/retire traffic and the zero-recompile contract held —
    # every serving executable (masked step, slot scatter, fresh-dt
    # admit) compiled in warmup, NONE after. The throughput ratio is
    # timing-noise-prone on a shared CI box, so the smoke pins it
    # present-and-positive; the >= 0.9x acceptance is the bench box's
    # claim (BENCH JSON), not the smoke's.
    srv = out["fleet_serving"]
    assert "error" not in srv, srv
    assert srv["members"] == 4 and srv["steps"] == 8
    assert srv["recompiles_after_warmup"] == 0, srv
    assert srv["throughput_ratio"] > 0, srv
    assert 0 < srv["occupancy_mean"] <= 1, srv
    assert srv["admitted"] > srv["retired"] >= 4, srv
    assert srv["evicted"] == 0, srv
    # serving latency histograms (PR 18): the pool-wide block must be
    # present with all three distributions populated by the churn —
    # every fused step observed, percentiles ordered and positive
    slat = srv["serving_latency"]
    for kind in ("queue_wait", "admit_to_first_step", "step"):
        assert slat[kind]["count"] > 0, slat
    assert slat["step"]["p50_ms"] > 0, slat
    assert slat["step"]["p99_ms"] >= slat["step"]["p50_ms"], slat
    # mirror-overhead point (PR 17): the host-redundant snapshot tier
    # measured on the bench's 2 forced virtual devices grouped into 2
    # hosts — present, no error, sane values (non-negative overhead,
    # positive redundancy bytes)
    mr = out["mirror"]
    assert "error" not in mr, mr
    assert mr["devices"] == 2 and mr["hosts"] == 2
    assert mr["snap_ms"] > 0 and mr["snap_mirror_ms"] > 0, mr
    assert mr["mirror_overhead_ms"] >= 0, mr
    assert mr["mirror_bytes"] > 0 and mr["snapshot_bytes"] > 0, mr
    # Poisson solve-path micro-curve (PR 6): every path present with a
    # real converged solve, so the solver trajectory is tracked in the
    # BENCH JSON across rounds
    pc = out["poisson_curve"]
    assert "error" not in pc, pc
    assert set(pc["paths"]) == {"bicgstab_jacobi", "bicgstab_mg",
                                "fas_v", "fas_f",
                                "fas_v+strip", "fas_v+bf16leg",
                                "fftd_periodic", "fftd_channel"}
    for name, p in pc["paths"].items():
        assert p["converged"], (name, p)
        assert p["iters"] >= 1 and p["ms_per_solve"] > 0, (name, p)
        # roofline fields (ISSUE 19, kernel_curve methodology): every
        # arm carries the modeled passes/bytes + derived util/MFU
        assert set(p) >= {"hbm_passes", "hbm_bytes", "hbm_util_pct",
                          "mfu_pct"}, (name, p)
    # memory-tiered FAS acceptance (ISSUE 19): the bf16-leg strip arm
    # models >= ~2x fewer bytes/cycle than the XLA f32 chain while
    # converging by the SAME f32 true-residual criterion with iters
    # within +1 of the f32-leg arm; the strip tiers report themselves
    assert (pc["paths"]["fas_v"]["hbm_bytes"]
            >= 2.0 * pc["paths"]["fas_v+bf16leg"]["hbm_bytes"]), pc
    assert (pc["paths"]["fas_v+bf16leg"]["iters"]
            <= pc["paths"]["fas_v"]["iters"] + 1), pc
    assert (pc["paths"]["fas_v+strip"]["iters"]
            <= pc["paths"]["fas_v"]["iters"] + 1), pc
    assert pc["paths"]["fas_v+strip"]["smoother_tier"] == "strip", pc
    assert (pc["paths"]["fas_v+bf16leg"]["smoother_tier"]
            == "strip+bf16"), pc
    # FFT-diagonalized direct arms (ISSUE 20): one application reaches
    # the shared relative criterion on both periodic operators —
    # iters == 1 is the CONTRACT, not a measurement. The
    # beats-best-fas ms/solve claim is the bench box's (BENCH JSON +
    # BASELINE round 14), not the smoke's — ms on a shared CI box is
    # noise.
    for name, tok in (("fftd_periodic", "pd,pd,pd,pd"),
                      ("fftd_channel", "pd,pd,ns,ns")):
        p = pc["paths"][name]
        assert p["iters"] == 1, (name, p)
        assert p["converged"], (name, p)
        assert p["bc_table"] == tok, (name, p)
    # composite-forest solve-path block (PR 13): the three forest arms
    # each ran a real converged production solve on the multi-level
    # topology. ms/solve ordering is timing-noise-prone on a shared CI
    # box, so the smoke pins presence + convergence + the CYCLE-count
    # claim (FAS needs no more outer iterations than mg2-Krylov); the
    # ms/solve win is the bench box's claim (BENCH JSON), not the
    # smoke's.
    fc = pc["forest"]
    assert "error" not in fc, fc
    assert set(fc["paths"]) == {"krylov_jacobi", "krylov_fft",
                                "forest_fas"}
    for name, p in fc["paths"].items():
        assert p["converged"], (name, p)
        assert p["iters"] >= 1 and p["ms_per_solve"] > 0, (name, p)
    assert (fc["paths"]["forest_fas"]["iters"]
            <= fc["paths"]["krylov_fft"]["iters"]), fc
    # advection kernel-tier curve (PR 9 + ISSUE 16): every tier
    # present — the three PR-9 arms plus the BC'd cavity/channel arms
    # and the 2-device sharded point (bench.py forces 2 virtual host
    # devices before jax initializes, so the sharded arm runs even
    # though this smoke pops XLA_FLAGS). The fused tiers run the REAL
    # kernels in Pallas interpret mode on the CPU box, so this pins
    # the plumbing, schema, and bytes model.
    kc = out["kernel_curve"]
    assert "error" not in kc, kc
    assert kc["interpret_mode"] is True          # CPU box
    assert set(kc["tiers"]) == {"xla", "pallas_fused",
                                "pallas_fused_bf16",
                                "pallas_fused_cavity",
                                "pallas_fused_channel",
                                "pallas_fused_sharded"}
    for name, tr in kc["tiers"].items():
        assert tr["ms_per_substage"] > 0, (name, tr)
        assert set(tr) >= {"adv_field_reads", "adv_field_writes",
                           "hbm_bytes", "hbm_passes", "hbm_util_pct",
                           "mfu_pct", "storage_dtype"}, (name, tr)
    # the ISSUE-9 acceptance, asserted from the bytes model: the XLA
    # chain re-reads the advected field >= 3x per substage where the
    # megakernel reads it ONCE, and the modeled HBM bytes drop
    assert kc["tiers"]["xla"]["adv_field_reads"] >= 3
    assert kc["tiers"]["pallas_fused"]["adv_field_reads"] == 1
    assert (kc["tiers"]["pallas_fused"]["hbm_bytes"]
            < kc["tiers"]["xla"]["hbm_bytes"])
    assert (kc["tiers"]["pallas_fused_bf16"]["hbm_bytes"]
            < kc["tiers"]["pallas_fused"]["hbm_bytes"])
    # the ISSUE-16 acceptance: ghost synthesis is in-VMEM affine
    # arithmetic, so every BC'd/sharded arm keeps the single-read
    # single-write bytes model with <= 2.25 modeled f32-equiv passes
    # and names its boundary table
    for name in ("pallas_fused_cavity", "pallas_fused_channel",
                 "pallas_fused_sharded"):
        tr = kc["tiers"][name]
        assert tr["adv_field_reads"] == 1, (name, tr)
        assert tr["hbm_passes"] <= 2.25, (name, tr)
        assert tr["bc_token"], (name, tr)
    assert kc["tiers"]["pallas_fused_cavity"]["bc_token"] == \
        "ns,ns,ns,ns(1,0)"
    assert kc["tiers"]["pallas_fused_sharded"]["mesh"] == "x:2"


@pytest.mark.slow   # ~5 s subprocess; the satellite's tier-1 ask is
#                     the smoke above — this drills the broken-box
#                     fallback branch specifically
def test_platform_fallback_on_deferred_backend_failure():
    """The r05 failure class itself: a backend whose devices register
    fine but whose FIRST OP raises (the axon behavior). The fallback
    must clear the poisoned backend cache, flip to CPU and succeed —
    run in a clean subprocess with the first probe stubbed to fail
    (clear_backends in the live test process would invalidate every
    array the suite holds)."""
    script = (
        "import bench\n"
        "orig = bench._probe_platform\n"
        "state = {'n': 0}\n"
        "def flaky():\n"
        "    if state['n'] == 0:\n"
        "        state['n'] += 1\n"
        "        raise RuntimeError('deferred backend failure (sim)')\n"
        "    return orig()\n"
        "bench._probe_platform = flaky\n"
        "print(bench._init_platform())\n"
    )
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env.update(JAX_PLATFORMS="cpu", PYTHONPATH=ROOT)
    proc = subprocess.run([sys.executable, "-c", script],
                          capture_output=True, text=True, env=env,
                          cwd=ROOT, timeout=120)
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert proc.stdout.strip().splitlines()[-1] == "cpu"
    assert "falling back to cpu" in proc.stderr
