"""Coarse-fine conservation tests: the makeFlux Poisson closure and the
kernel flux correction (reference main.cpp:5916-5997, 1392-1849).

The reference treats these as correctness invariants (SURVEY.md §4.6):
fluxes crossing a level interface must cancel exactly between the fine
pair and the coarse cell, and the variable-resolution Poisson operator
must stay 2nd-order consistent across interfaces.
"""

import jax
import jax.numpy as jnp
import numpy as np

from cup2d_tpu.amr import AMRSim
from cup2d_tpu.config import SimConfig
from cup2d_tpu.flux import (
    apply_flux_corr,
    build_flux_corr,
    build_poisson_tables,
    diffusive_deposits,
    divergence_deposits,
    gradient_deposits,
    laplacian_deposits,
)
from cup2d_tpu.forest import Forest
from cup2d_tpu.halo import assemble_labs, assemble_labs_ordered, build_tables
from cup2d_tpu.ops.stencil import divergence, laplacian5
from cup2d_tpu.poisson import apply_block_precond_blocks, bicgstab, \
    block_precond_matrix


def _two_level_forest():
    cfg = SimConfig(bpdx=2, bpdy=2, level_max=3, level_start=1,
                    extent=1.0, dtype="float64")
    f = Forest(cfg)
    f.release(1, 1, 1)
    for a in (0, 1):
        for b in (0, 1):
            f.allocate(2, 2 + a, 2 + b)
    return cfg, f


def _cell_coords(cfg, f, order):
    """x, y, h arrays [N, BS, BS] for the active blocks in order."""
    bs = cfg.bs
    xs, ys, hs = [], [], []
    for s in order:
        l = int(f.level[s])
        h = cfg.h_at(l)
        i, j = int(f.bi[s]), int(f.bj[s])
        x = (i * bs + np.arange(bs) + 0.5) * h
        y = (j * bs + np.arange(bs) + 0.5) * h
        X, Y = np.meshgrid(x, y, indexing="xy")
        xs.append(X)
        ys.append(Y)
        hs.append(np.full((bs, bs), h))
    return np.stack(xs), np.stack(ys), np.stack(hs)


def _apply_A(forest, order, x_blocks):
    t = build_poisson_tables(forest, order)
    lab = assemble_labs_ordered(jnp.asarray(x_blocks)[:, None], t)
    return np.asarray(laplacian5(lab, 1)[:, 0])


def test_poisson_tables_uniform_matches_plain_lap():
    """Single-level forest: A must be the plain Neumann 5-point stencil."""
    cfg = SimConfig(bpdx=2, bpdy=1, level_max=2, level_start=1,
                    extent=1.0, dtype="float64")
    f = Forest(cfg)
    order = f.order()
    rng = np.random.default_rng(0)
    x = rng.standard_normal((len(order), cfg.bs, cfg.bs))
    got = _apply_A(f, order, x)

    # reconstruct the global grid and compare
    bs = cfg.bs
    nbx, nby = f.nblocks_at(1)
    glob = np.zeros((nby * bs, nbx * bs))
    for k, s in enumerate(order):
        i, j = int(f.bi[s]), int(f.bj[s])
        glob[j * bs:(j + 1) * bs, i * bs:(i + 1) * bs] = x[k]
    pad = np.pad(glob, 1, mode="edge")
    lap = (pad[:-2, 1:-1] + pad[2:, 1:-1] + pad[1:-1, :-2]
           + pad[1:-1, 2:] - 4.0 * glob)
    for k, s in enumerate(order):
        i, j = int(f.bi[s]), int(f.bj[s])
        want = lap[j * bs:(j + 1) * bs, i * bs:(i + 1) * bs]
        assert np.abs(got[k] - want).max() < 1e-12


def test_poisson_tables_quadratic_exact():
    """The makeFlux interface ghosts reproduce quadratics exactly
    (verified analytically: normal^2, tangential^2 via D2, cross via
    D1), so A(q)/h^2 = const for any quadratic q — including interface
    cells. Wall-adjacent cells excluded (zero-flux walls by design)."""
    cfg, f = _two_level_forest()
    order = f.order()
    X, Y, H = _cell_coords(cfg, f, order)
    q = 1.3 * X * X - 0.7 * Y * Y + 0.9 * X * Y + 0.4 * X - Y + 2.0
    got = _apply_A(f, order, q) / (H * H)
    want = 2 * 1.3 - 2 * 0.7
    mask = np.ones_like(got, bool)
    for k, s in enumerate(order):
        l = int(f.level[s])
        i, j = int(f.bi[s]), int(f.bj[s])
        nbx, nby = f.nblocks_at(l)
        if i == 0:
            mask[k, :, 0] = False
        if i == nbx - 1:
            mask[k, :, -1] = False
        if j == 0:
            mask[k, 0, :] = False
        if j == nby - 1:
            mask[k, -1, :] = False
    assert np.abs(got - want)[mask].max() < 1e-10


def test_poisson_closure_second_order_across_interfaces():
    """Mixed-level SOLUTION convergence: solving A p = b on a two-level
    forest converges to the analytic p at 2nd order (VERDICT r1 'done'
    criterion). The closure's pointwise truncation at interface cells is
    O(h) — same as the reference's identical weights — but conservation
    plus quadratic-exact ghosts give the classic supra-convergent
    2nd-order solution error on locally refined grids."""
    p_inv = None
    errs = []
    for ls in (1, 2):
        cfg = SimConfig(bpdx=2, bpdy=2, level_max=ls + 2, level_start=ls,
                        extent=1.0, dtype="float64")
        f = Forest(cfg)
        # refine the same physical quadrant at both resolutions
        nbx, nby = f.nblocks_at(ls)
        for i in range(nbx // 2, nbx):
            for j in range(nby // 2, nby):
                f.release(ls, i, j)
                for a in (0, 1):
                    for b in (0, 1):
                        f.allocate(ls + 1, 2 * i + a, 2 * j + b)
        order = f.order()
        X, Y, H = _cell_coords(cfg, f, order)
        p_exact = np.cos(np.pi * X) * np.cos(2 * np.pi * Y)  # Neumann-ok
        lap = -(np.pi ** 2 + 4 * np.pi ** 2) * p_exact
        b = lap * H * H
        b -= b.sum() / b.size            # discrete solvability
        t = build_poisson_tables(f, order)

        def A(x, t=t):
            lab = assemble_labs_ordered(x[:, None], t)
            return laplacian5(lab, 1)[:, 0]

        if p_inv is None:
            p_inv = jnp.asarray(block_precond_matrix(cfg.bs))
        res = bicgstab(A, jnp.asarray(b),
                       M=lambda r: apply_block_precond_blocks(r, p_inv),
                       tol=1e-12, tol_rel=0.0, max_iter=2000,
                       max_restarts=50)
        got = np.asarray(res.x)
        # compare mean-free solutions, hsq-weighted means
        w = H * H
        got = got - (got * w).sum() / w.sum()
        pe = p_exact - (p_exact * w).sum() / w.sum()
        errs.append(np.abs(got - pe).max())
    ratio = errs[0] / errs[1]
    assert ratio > 3.0, (errs, ratio)   # 2nd order => ratio ~ 4


def test_poisson_operator_conservative():
    """Interface fluxes cancel exactly: sum_cells A(x) == 0 for any x
    (each interior face's flux enters its two cells with opposite signs;
    wall faces carry zero flux)."""
    cfg, f = _two_level_forest()
    order = f.order()
    rng = np.random.default_rng(1)
    x = rng.standard_normal((len(order), cfg.bs, cfg.bs))
    got = _apply_A(f, order, x)
    assert abs(got.sum()) < 1e-10 * np.abs(got).sum()


def test_poisson_solve_mixed_forest():
    """BiCGSTAB with the closure operator converges on a 2-level forest
    (SURVEY.md §7 hard part #2: get the closure wrong and it stalls)."""
    cfg, f = _two_level_forest()
    order = f.order()
    X, Y, H = _cell_coords(cfg, f, order)
    # mean-free rhs in the solvability sense: sum of undivided rhs = 0
    b = np.sin(2 * np.pi * X) * np.cos(np.pi * Y) * H * H
    b -= b.sum() / (H * H).sum() * H * H
    t = build_poisson_tables(f, order)

    def A(x):
        lab = assemble_labs_ordered(x[:, None], t)
        return laplacian5(lab, 1)[:, 0]

    p_inv = jnp.asarray(block_precond_matrix(cfg.bs))
    res = bicgstab(A, jnp.asarray(b),
                   M=lambda r: apply_block_precond_blocks(r, p_inv),
                   tol=1e-10, tol_rel=0.0, max_iter=400, max_restarts=10)
    assert bool(res.converged), float(res.residual)
    # solution actually satisfies the system
    r = b - np.asarray(A(res.x))
    r -= r.sum() / r.size
    assert np.abs(r).max() < 1e-8


def _compact_bump(X, Y, x0=0.55, y0=0.55, r=0.2):
    d2 = (X - x0) ** 2 + (Y - y0) ** 2
    return np.where(d2 < r * r, (1 - d2 / (r * r)) ** 3, 0.0)


def test_divergence_rhs_conservation():
    """Flux-corrected divergence RHS sums to zero on a mixed forest —
    the Poisson solvability condition the reference maintains via
    fillcases (main.cpp:7007-7027). The bump straddles the level
    interface but vanishes at the walls."""
    cfg, f = _two_level_forest()
    order = f.order()
    X, Y, H = _cell_coords(cfg, f, order)
    vel = np.stack([_compact_bump(X, Y), -0.7 * _compact_bump(X, Y)],
                   axis=1)
    t1v = build_tables(f, order, 1, False, 2)
    corr = build_flux_corr(f, order)
    field = jnp.zeros((f.capacity, 2, cfg.bs, cfg.bs))
    field = field.at[order].set(jnp.asarray(vel))
    vlab = assemble_labs(field, jnp.asarray(order), t1v)
    fac = jnp.asarray(0.5 * H[:, 0, 0] / 1e-2)
    b = fac[:, None, None] * divergence(vlab, 1)
    assert abs(float(jnp.sum(b))) > 1e-6  # uncorrected does NOT conserve
    b = apply_flux_corr(b, divergence_deposits(vlab, None, None, fac), corr)
    assert abs(float(jnp.sum(b))) < 1e-10


def test_diffusive_flux_conservation():
    """Corrected diffusive fluxes conserve momentum: for a field with
    compact support away from the walls, sum_cells dfac*lap(u) with
    correction = 0 on a mixed forest (main.cpp:1392-1849)."""
    cfg, f = _two_level_forest()
    order = f.order()
    X, Y, H = _cell_coords(cfg, f, order)
    vel = np.stack([_compact_bump(X, Y), _compact_bump(X, Y, 0.45, 0.6)],
                   axis=1)
    t3 = build_tables(f, order, 3, True, 2)
    corr = build_flux_corr(f, order)
    field = jnp.zeros((f.capacity, 2, cfg.bs, cfg.bs))
    field = field.at[order].set(jnp.asarray(vel))
    lab = assemble_labs(field, jnp.asarray(order), t3)
    dfac = 1e-3
    rhs = dfac * laplacian5(lab, 3)
    raw = float(jnp.abs(jnp.sum(rhs, axis=(0, 2, 3))).max())
    assert raw > 1e-9  # uncorrected leaks momentum at interfaces
    rhs = apply_flux_corr(rhs, diffusive_deposits(lab, 3, dfac), corr)
    tot = np.asarray(jnp.sum(rhs, axis=(0, 2, 3)))
    assert np.abs(tot).max() < 1e-12


def test_gradient_and_laplacian_deposit_conservation():
    """Projection-gradient and lap deposits: corrected sums vanish for
    compactly supported pressure (pressureCorrectionKernel /
    pressure_rhs1 + fillcases)."""
    cfg, f = _two_level_forest()
    order = f.order()
    X, Y, H = _cell_coords(cfg, f, order)
    p = _compact_bump(X, Y, 0.5, 0.55)
    t1s = build_tables(f, order, 1, False, 1)
    corr = build_flux_corr(f, order)
    plab = assemble_labs_ordered(jnp.asarray(p)[:, None], t1s)[:, 0]

    pfac = jnp.asarray(-0.5 * 1e-2 * H[:, 0, 0])
    dpx = plab[:, 1:-1, 2:] - plab[:, 1:-1, :-2]
    dpy = plab[:, 2:, 1:-1] - plab[:, :-2, 1:-1]
    dv = pfac[:, None, None, None] * jnp.stack([dpx, dpy], axis=1)
    dv = apply_flux_corr(dv, gradient_deposits(plab, pfac), corr)
    tot = np.asarray(jnp.sum(dv, axis=(0, 2, 3)))
    assert np.abs(tot).max() < 1e-12

    # written value is -lap (pressure_rhs1 does TMP -= lap), and the
    # deposit is defined against the WRITTEN value, so no extra sign
    lap = -laplacian5(plab, 1)
    lap = apply_flux_corr(lap, laplacian_deposits(plab), corr)
    assert abs(float(jnp.sum(lap))) < 1e-12


def test_amr_taylor_green_two_level():
    """End-to-end: AMRSim with a frozen two-level topology advances a
    Taylor-Green-like field stably and keeps the velocity finite with
    the conservative operators in the loop."""
    cfg, f = _two_level_forest()
    sim = AMRSim(cfg)
    # rebuild the sim's forest as the mixed one
    sim.forest = f
    f.add_field("vel", 2)
    f.add_field("pres", 1)
    sim._tables_version = -1
    order = f.order()
    X, Y, _ = _cell_coords(cfg, f, order)
    u = np.sin(np.pi * X) * np.cos(np.pi * Y)
    v = -np.cos(np.pi * X) * np.sin(np.pi * Y)
    vel = jnp.zeros((f.capacity, 2, cfg.bs, cfg.bs))
    vel = vel.at[order].set(jnp.asarray(np.stack([u, v], axis=1)))
    f.fields["vel"] = vel
    e0 = float(jnp.sum(vel[order] ** 2))
    for _ in range(5):
        diag = sim.step_once(dt=1e-3)
    sim.sync_fields()
    e1 = float(jnp.sum(f.fields["vel"][order] ** 2))
    assert np.isfinite(e1) and 0 < e1 < e0  # viscous decay, no blowup


def test_poisson_structured_matches_tables():
    """The structured per-face operator (build_poisson_structured) must
    agree with the lab-table form on a mixed three-level forest with
    walls, same-level, coarse and fine faces (both parities) present —
    the two implementations share the _D1/_D2 constants, and this pins
    the index/orientation algebra (round 5)."""
    from cup2d_tpu.flux import build_poisson_structured, \
        poisson_apply_structured
    from cup2d_tpu.halo import pad_tables

    cfg = SimConfig(bpdx=2, bpdy=3, level_max=4, level_start=1,
                    extent=1.0, dtype="float64")
    f = Forest(cfg)
    # refine corner (1,0,0) -> level 2; then its corner child -> level 3
    # (2:1-balanced: the level-3 quad touches only level-2 or walls);
    # plus the opposite corner -> level 2 for more coarse/fine faces
    f.release(1, 0, 0)
    for a in (0, 1):
        for b in (0, 1):
            f.allocate(2, a, b)
    f.release(2, 0, 0)
    for a in (0, 1):
        for b in (0, 1):
            f.allocate(3, a, b)
    f.release(1, 3, 5)
    for a in (0, 1):
        for b in (0, 1):
            f.allocate(2, 6 + a, 10 + b)
    order = f.order()
    n = len(order)
    n_pad = n + 5
    rng = np.random.default_rng(7)
    x = rng.standard_normal((n_pad, cfg.bs, cfg.bs))
    x[n:] = 0.0
    xj = jnp.asarray(x)

    t = pad_tables(build_poisson_tables(f, order), n_pad)
    lab = assemble_labs_ordered(xj[:, None],
                                jax.tree_util.tree_map(jnp.asarray, t))
    want = np.asarray(laplacian5(lab, 1)[:, 0])

    op = build_poisson_structured(f, order, n_pad)
    got = np.asarray(poisson_apply_structured(xj, op))
    np.testing.assert_allclose(got[:n], want[:n],
                               rtol=1e-12, atol=1e-12)


def test_fast_face_copy_assembly_matches_tables():
    """assemble_labs_ordered through the FastHalo face-copy path must
    reproduce the plain-table assembly bit-for-bit on a mixed
    three-level forest (walls, same-level faces/corners, coarse and
    fine interfaces), for a tensorial g=3 vector set and a face-only
    g=1 set (round 5)."""
    from cup2d_tpu.halo import assemble_labs_ordered, build_face_copy, \
        make_fast_tables, pad_tables

    cfg = SimConfig(bpdx=2, bpdy=3, level_max=4, level_start=1,
                    extent=1.0, dtype="float64")
    f = Forest(cfg)
    f.release(1, 0, 0)
    for a in (0, 1):
        for b in (0, 1):
            f.allocate(2, a, b)
    f.release(2, 0, 0)
    for a in (0, 1):
        for b in (0, 1):
            f.allocate(3, a, b)
    f.release(1, 3, 5)
    for a in (0, 1):
        for b in (0, 1):
            f.allocate(2, 6 + a, 10 + b)
    order = f.order()
    n = len(order)
    n_pad = n + 5
    rng = np.random.default_rng(3)
    nb, mask = build_face_copy(f, order, n_pad)
    assert mask.sum() > 0          # the fast path actually engages
    for (g, tensorial, dim, corners) in ((3, True, 2, True),
                                         (1, False, 2, False),
                                         (1, True, 1, True)):
        x = rng.standard_normal((n_pad, dim, cfg.bs, cfg.bs))
        x[n:] = 0.0
        xj = jnp.asarray(x)
        t = build_tables(f, order, g, tensorial, dim)
        want = np.asarray(assemble_labs_ordered(
            xj, jax.device_put(pad_tables(t, n_pad))))
        fh = jax.device_put(make_fast_tables(t, nb, mask, n_pad,
                                             corners=corners))
        # the filter must actually drop rows (paint takes them over)
        assert fh.t.dest_s.shape[0] < pad_tables(t, n_pad).dest_s.shape[0] \
            or fh.t.dest.shape[0] < pad_tables(t, n_pad).dest.shape[0]
        got = np.asarray(assemble_labs_ordered(xj, fh))
        np.testing.assert_array_equal(
            got[:n], want[:n],
            err_msg=f"g={g} tensorial={tensorial} dim={dim}")


def test_pois_build_selects_structured_with_env_fallback(monkeypatch):
    """Single-device AMRSim must actually WIRE the structured operator
    into its hot-loop tables (a silent fallback to the lab-table form
    would erase the round-5 speedup without failing anything), and
    CUP2D_POIS=tables must restore the table form for A/B runs."""
    from cup2d_tpu.amr import AMRSim
    from cup2d_tpu.flux import PoissonOp
    from cup2d_tpu.halo import HaloTables

    cfg = SimConfig(bpdx=1, bpdy=1, level_max=2, level_start=1,
                    extent=1.0, dtype="float64")
    # an ambient CUP2D_POIS from the documented A/B workflow must not
    # fail the default-wiring assertion
    monkeypatch.delenv("CUP2D_POIS", raising=False)
    sim = AMRSim(cfg, shapes=[])
    sim._refresh()
    assert isinstance(sim._tables["pois"], PoissonOp)

    monkeypatch.setenv("CUP2D_POIS", "tables")
    sim2 = AMRSim(cfg, shapes=[])
    sim2._refresh()
    assert isinstance(sim2._tables["pois"], HaloTables)


def test_fast_paint_collapses_rows_to_interfaces():
    """The face-copy filter must remove ALL interior same-level rows on
    a uniform forest — leaving only wall/BC rows — or the paint
    silently stops paying for itself (the scatter it replaces is the
    serialized TPU lowering the round-5 speedup removed)."""
    from cup2d_tpu.halo import build_face_copy, build_tables, \
        filter_face_rows

    cfg = SimConfig(bpdx=2, bpdy=2, level_max=2, level_start=1,
                    extent=1.0, dtype="float64")
    f = Forest(cfg)           # uniform 4x4 level-1 grid
    order = f.order()
    n = len(order)
    nb, mask = build_face_copy(f, order, n + 3)
    t = build_tables(f, order, 3, True, 2)
    ft = filter_face_rows(t, mask, corners=True)
    # interior blocks (no wall side) contribute ZERO remaining rows;
    # the survivors must all belong to wall-touching blocks
    L2 = t.L * t.L
    import numpy as _np
    lv = f.level[order]
    bi = f.bi[order]
    bj = f.bj[order]
    nbx = cfg.bpdx << 1
    nby = cfg.bpdy << 1
    wallb = (bi == 0) | (bi == nbx - 1) | (bj == 0) | (bj == nby - 1)
    surv_blocks = _np.asarray(ft.dest_s) // L2
    assert len(ft.dest_s) < len(t.dest_s)          # filter engaged
    assert wallb[surv_blocks].all(), \
        "interior same-level rows survived the paint filter"
