"""Multi-process AMR determinism (VERDICT r2 missing #2 / next #7).

The host-side regrid bookkeeping (tag pull, 2:1 state fixing, slot
allocation, table builds) runs independently on every process of a pod;
if any process reaches a different decision the SPMD program diverges
and hangs or corrupts. Two real jax.distributed processes on localhost
(4 virtual CPU devices each -> one 8-device global mesh) run the
sharded sim through 3 regrid+step cycles and must print identical
topology+table digests. Reference contract: update_boundary /
update_blocks (/root/reference/main.cpp:1410-1970)."""

import os
import socket
import subprocess
import sys

import pytest


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _run_workers(outdir, extra_args=(), extra_env=None, per_pid_env=None,
                 allow_rc=None):
    """Spawn the 2-process worker pair; returns their stdouts. SKIPs
    the calling test when the worker's capability probe reports the
    broken multiprocess CPU backend (SKIP_MULTIPROCESS — the
    documented, pre-existing container regression, ROADMAP) instead of
    erroring."""
    port = _free_port()
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    worker = os.path.join(root, "tests", "_multihost_worker.py")
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)           # worker sets its own count
    env["PYTHONPATH"] = root
    env["CUP2D_MH_OUTDIR"] = outdir
    if extra_env:
        env.update(extra_env)
    procs = []
    for pid in (0, 1):
        penv = dict(env)
        if per_pid_env and pid in per_pid_env:
            penv.update(per_pid_env[pid])
        procs.append(subprocess.Popen(
            [sys.executable, worker, str(pid), str(port),
             *map(str, extra_args)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True, env=penv, cwd=root))
    outs = []
    for pid, p in enumerate(procs):
        try:
            out, err = p.communicate(timeout=900)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        ok_rcs = {0} | set(allow_rc.get(pid, ()) if allow_rc else ())
        assert p.returncode in ok_rcs, f"worker failed:\n{err[-4000:]}"
        outs.append(out)
    if any("SKIP_MULTIPROCESS" in out for out in outs):
        line = next(ln for out in outs for ln in out.splitlines()
                    if ln.startswith("SKIP_MULTIPROCESS"))
        pytest.skip(
            "CPU backend rejects multiprocess computations on this box "
            f"(pre-existing, ROADMAP): {line}")
    return outs


@pytest.mark.slow
def test_two_process_amr_determinism(tmp_path):
    outs = _run_workers(str(tmp_path))
    digests = []
    iohashes = []
    buckets = []
    sigterms = []
    for out in outs:
        lines = [ln for ln in out.splitlines() if ln.startswith("DIGEST")]
        assert len(lines) == 4, out       # 3 cycles + post-restore
        digests.append(lines)
        iohashes.append(
            [ln for ln in out.splitlines() if ln.startswith("IOHASH")])
        buckets.append([ln for ln in out.splitlines()
                        if ln.startswith("BUCKET")])
        sigterms.append([ln for ln in out.splitlines()
                         if ln.startswith("SIGTERM_AGREE")])
        assert buckets[-1], out
        assert "DONE" in out
    # the hard case's bucket line must also agree across processes
    assert buckets[0] == buckets[1], buckets
    assert digests[0] == digests[1], (
        "processes diverged:\n" + "\n".join(
            f"{a}   vs   {b}" for a, b in zip(*digests)))
    # pod-safe I/O (VERDICT r3 #5): both processes observed the SAME
    # complete checkpoint/dump bytes (gather + process-0 write +
    # barrier), and the restored run continued identically (the 4th
    # digest above)
    assert iohashes[0] and iohashes[0] == iohashes[1], iohashes
    # SIGTERM latch agreement (ROADMAP pod gap (a)): skewed sigterm@N
    # delivery (step 3 on pid 0, step 5 on pid 1) must make BOTH
    # processes stop at the SAME step boundary — the later latch —
    # and enter the collective checkpoint together
    assert sigterms[0] == sigterms[1] == ["SIGTERM_AGREE 5"], sigterms


@pytest.mark.slow   # 2-process runtime drill — environment-broken in
#                     this container (the capability probe SKIPs);
#                     validates on the first box with a working
#                     multiprocess jax.distributed CPU runtime (ROADMAP)
def test_two_process_elastic_host_loss(tmp_path):
    """Real-mode elastic drill: process 1 host_exits mid-run (announced
    in its final heartbeat, then a hard os._exit(17)); process 0's same
    beat sees the announcement, declares the loss, re-inits the runtime
    as a 1-process world on a fresh port, re-meshes onto its surviving
    devices and resumes from the disk checkpoint (per-shard snapshots
    died with the host — the designed real-loss rung)."""
    reinit_port = _free_port()
    outs = _run_workers(
        str(tmp_path), extra_args=(reinit_port,),
        extra_env={"CUP2D_MH_PHASE": "elastic"},
        per_pid_env={1: {"CUP2D_FAULTS": "host_exit@23"}},
        allow_rc={1: (17,)})             # pid 1 dies by design
    assert any(ln.startswith("ELASTIC_RESUMED")
               for ln in outs[0].splitlines()), outs[0]
