"""Multi-process AMR determinism (VERDICT r2 missing #2 / next #7).

The host-side regrid bookkeeping (tag pull, 2:1 state fixing, slot
allocation, table builds) runs independently on every process of a pod;
if any process reaches a different decision the SPMD program diverges
and hangs or corrupts. Two real jax.distributed processes on localhost
(4 virtual CPU devices each -> one 8-device global mesh) run the
sharded sim through 3 regrid+step cycles and must print identical
topology+table digests. Reference contract: update_boundary /
update_blocks (/root/reference/main.cpp:1410-1970)."""

import os
import socket
import subprocess
import sys

import pytest


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.mark.slow
def test_two_process_amr_determinism(tmp_path):
    port = _free_port()
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    worker = os.path.join(root, "tests", "_multihost_worker.py")
    outdir = str(tmp_path)     # pytest-managed: auto-cleaned
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)           # worker sets its own count
    env["PYTHONPATH"] = root
    env["CUP2D_MH_OUTDIR"] = outdir
    procs = [
        subprocess.Popen(
            [sys.executable, worker, str(pid), str(port)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True, env=env, cwd=root)
        for pid in (0, 1)
    ]
    outs = []
    for p in procs:
        try:
            out, err = p.communicate(timeout=900)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        assert p.returncode == 0, f"worker failed:\n{err[-4000:]}"
        outs.append(out)
    digests = []
    iohashes = []
    buckets = []
    sigterms = []
    for out in outs:
        lines = [ln for ln in out.splitlines() if ln.startswith("DIGEST")]
        assert len(lines) == 4, out       # 3 cycles + post-restore
        digests.append(lines)
        iohashes.append(
            [ln for ln in out.splitlines() if ln.startswith("IOHASH")])
        buckets.append([ln for ln in out.splitlines()
                        if ln.startswith("BUCKET")])
        sigterms.append([ln for ln in out.splitlines()
                         if ln.startswith("SIGTERM_AGREE")])
        assert buckets[-1], out
        assert "DONE" in out
    # the hard case's bucket line must also agree across processes
    assert buckets[0] == buckets[1], buckets
    assert digests[0] == digests[1], (
        "processes diverged:\n" + "\n".join(
            f"{a}   vs   {b}" for a, b in zip(*digests)))
    # pod-safe I/O (VERDICT r3 #5): both processes observed the SAME
    # complete checkpoint/dump bytes (gather + process-0 write +
    # barrier), and the restored run continued identically (the 4th
    # digest above)
    assert iohashes[0] and iohashes[0] == iohashes[1], iohashes
    # SIGTERM latch agreement (ROADMAP pod gap (a)): skewed sigterm@N
    # delivery (step 3 on pid 0, step 5 on pid 1) must make BOTH
    # processes stop at the SAME step boundary — the later latch —
    # and enter the collective checkpoint together
    assert sigterms[0] == sigterms[1] == ["SIGTERM_AGREE 5"], sigterms
