"""The sharded megastep's collective traffic must be boundary-, not
volume-proportional — the scaling law of the reference's halo exchange
(/root/reference/main.cpp:909-2142, which ships only halo slabs between
neighbor ranks). GSPMD legally lowers a data-dependent gather from a
sharded operand to an all-gather of the whole field; this test compiles
the actual megastep executable on the 8-virtual-device mesh and fails
if any such whole-field collective reappears (the exact regression
round 2 shipped: 28 full-field all-gathers per step, re-run per Krylov
iteration — validation/comm_audit.py measured it)."""

import re

import jax
import numpy as np
import pytest

from cup2d_tpu.config import SimConfig
from cup2d_tpu.models import DiskShape
from cup2d_tpu.parallel.forest_mesh import ShardedAMRSim
from cup2d_tpu.parallel.mesh import make_mesh
from validation.comm_audit import _COLL_RE


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 devices")
def test_megastep_comm_is_boundary_proportional():
    cfg = SimConfig(bpdx=2, bpdy=1, level_max=3, level_start=1,
                    extent=1.0, dtype="float32", nu=4e-5, lam=1e6,
                    rtol=2.0, ctol=1.0)
    mesh = make_mesh(8)
    sim = ShardedAMRSim(cfg, mesh, shapes=[DiskShape(0.08, 0.55, 0.25)])
    sim.compute_forces_every = 0
    sim.initialize()

    captured = {}
    orig = sim._mega_jit

    def wrapper(*a, **k):
        captured["a"], captured["k"] = a, k
        return orig(*a, **k)

    sim._mega_jit = wrapper
    sim.step_once(dt=1e-3)
    assert captured, "megastep never ran"
    txt = orig.lower(*captured["a"], **captured["k"]).compile().as_text()

    # the only legitimate large exchange is an all-gathered surface
    # buffer [D, S, dim, BS, BS] (shard_halo) — leading dim D. Anything
    # whose element count reaches even a SCALAR field's volume without
    # that structure is the GSPMD whole-field fallback (the round-2
    # regression re-issued it per Krylov iteration).
    n_pad = sim._npad_hwm
    bs = cfg.bs
    n_dev = 8
    smax = max(t.S for t in sim._tables.values() if hasattr(t, "S"))
    scalar_field_elems = n_pad * bs * bs
    surface_elems_cap = n_dev * 4 * smax * 2 * bs * bs  # 4x slack

    offenders = []
    n_coll = 0
    for line in txt.splitlines():
        m = _COLL_RE.search(line)
        if not m:
            continue
        dt_, dims, op = m.groups()
        dim_list = [int(x) for x in dims.split(",") if x]
        elems = int(np.prod(dim_list)) if dim_list else 1
        n_coll += 1
        surface_like = (op == "all-gather" and dim_list
                        and dim_list[0] == n_dev
                        and elems <= surface_elems_cap)
        if elems >= scalar_field_elems and not surface_like:
            offenders.append((op, f"{dt_}[{dims}]", elems))
    assert n_coll > 0, "no collectives at all — not actually sharded?"
    assert not offenders, (
        f"volume-sized collectives in the megastep "
        f"(scalar field = {scalar_field_elems} elems): {offenders}")
