"""The sharded megastep's collective traffic must be boundary-, not
volume-proportional — the scaling law of the reference's halo exchange
(/root/reference/main.cpp:909-2142, which ships only halo slabs between
neighbor ranks). GSPMD legally lowers a data-dependent gather from a
sharded operand to an all-gather of the whole field; this test compiles
the actual megastep executable on the 8-virtual-device mesh and fails
if any such whole-field collective reappears (the exact regression
round 2 shipped: 28 full-field all-gathers per step, re-run per Krylov
iteration — validation/comm_audit.py measured it)."""

import re

import jax
import numpy as np
import pytest

from cup2d_tpu.config import SimConfig
from cup2d_tpu.models import DiskShape
from cup2d_tpu.parallel.forest_mesh import ShardedAMRSim
from cup2d_tpu.parallel.mesh import make_mesh
from validation.comm_audit import _COLL_RE


def _build_sim(initialize=True):
    cfg = SimConfig(bpdx=2, bpdy=1, level_max=3, level_start=1,
                    extent=1.0, dtype="float32", nu=4e-5, lam=1e6,
                    rtol=2.0, ctol=1.0)
    mesh = make_mesh(8)
    sim = ShardedAMRSim(cfg, mesh, shapes=[DiskShape(0.08, 0.55, 0.25)])
    sim.compute_forces_every = 0
    if initialize:
        sim.initialize()
    return cfg, sim


def _capture(sim, attr, trigger):
    """Swap the jitted callable at ``attr`` for a capturing wrapper,
    run ``trigger``, return the compiled HLO text of the real call."""
    captured = {}
    orig = getattr(sim, attr)

    def wrapper(*a, **k):
        captured["a"], captured["k"] = a, k
        return orig(*a, **k)

    setattr(sim, attr, wrapper)
    try:
        trigger()
    finally:
        setattr(sim, attr, orig)
    assert captured, f"{attr} never ran"
    return orig.lower(*captured["a"], **captured["k"]).compile().as_text()


def _assert_boundary_proportional(txt, sim, cfg, what):
    """No collective in ``txt`` may reach a scalar field's volume; the
    only large exchanges allowed are the shard_halo surface forms
    (per-offset collective-permutes, or the [D, S, ...] surface
    all-gather in audit mode)."""
    n_pad = sim._npad_hwm
    bs = cfg.bs
    n_dev = sim.mesh.devices.size
    smax = max(t.S for t in sim._tables.values() if hasattr(t, "S"))
    scalar_field_elems = n_pad * bs * bs
    surface_elems_cap = n_dev * 4 * smax * 2 * bs * bs  # 4x slack

    offenders = []
    n_coll = 0
    for line in txt.splitlines():
        m = _COLL_RE.search(line)
        if not m:
            continue
        dt_, dims, op = m.groups()
        dim_list = [int(x) for x in dims.split(",") if x]
        elems = int(np.prod(dim_list)) if dim_list else 1
        n_coll += 1
        surface_like = (op == "all-gather" and dim_list
                        and dim_list[0] == n_dev
                        and elems <= surface_elems_cap)
        permute_like = (op == "collective-permute"
                        and elems <= surface_elems_cap)
        if elems >= scalar_field_elems and not (
                surface_like or permute_like):
            offenders.append((op, f"{dt_}[{dims}]", elems))
    assert n_coll > 0, f"no collectives in {what} — not actually sharded?"
    assert not offenders, (
        f"volume-sized collectives in {what} "
        f"(scalar field = {scalar_field_elems} elems): {offenders}")


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 devices")
def test_per_step_comm_is_boundary_proportional():
    """Megastep + rasterize + tags (every per-STEP / per-tag
    executable); the regrid APPLY is exempt — volume-sized by design,
    like the reference's migration (main.cpp:5205-5424)."""
    cfg, sim = _build_sim(initialize=False)

    # the standalone rasterize executable runs during initialize()
    # (per-STEP rasterization is fused into the megastep, guarded below)
    txt_raster = _capture(sim, "_raster_jit", sim.initialize)
    _assert_boundary_proportional(txt_raster, sim, cfg, "rasterize")

    txt = _capture(sim, "_mega_jit", lambda: sim.step_once(dt=1e-3))
    _assert_boundary_proportional(txt, sim, cfg, "megastep")

    txt = _capture(sim, "_tags_jit", sim.adapt)
    _assert_boundary_proportional(txt, sim, cfg, "tags")


@pytest.mark.slow   # ~26 s; duplicative tier-1 coverage: the comm
#                     VOLUME bound (the regression class that actually
#                     moves) stays tier-1 via
#                     test_per_step_comm_is_boundary_proportional, and
#                     the local/remote row-split STRUCTURE this asserts
#                     is fixed at table-build time (halo.py round 4) and
#                     re-evidenced by the standing
#                     validation/overlap_check.py probe — slow-marked to
#                     fund the PR-7 elastic drill within the 870 s cap
@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 devices")
def test_exchange_has_overlappable_local_work():
    """Comm/compute overlap as STRUCTURE (VERDICT r3 #6): in the
    compiled megastep, every surface collective must leave substantial
    dependence-independent work (the local-only ghost rows + lab init)
    that a latency-hiding scheduler can run while the exchange is in
    flight — and the majority of ghost rows must be local-only."""
    from validation.overlap_check import analyze, row_split

    cfg, sim = _build_sim()
    txt = _capture(sim, "_mega_jit", lambda: sim.step_once(dt=1e-3))
    pairs = analyze(txt)
    assert pairs, "no collectives found in the megastep"
    # every exchange has at least 3x its own volume of independent
    # work available to hide behind. (3x, not the old 10x: the
    # structured Poisson operator's Krylov body carries far less
    # arithmetic than the lab-table scatter it replaced, and on this
    # toy forest — 16 blocks/device — the whole per-device operand is
    # only 4x a surface buffer; production shards grow the window as
    # B/boundary while the exchange stays boundary-sized.)
    for p in pairs:
        assert (p["independent_elems_total"]
                >= 3 * p["elems_exchanged"]), p
    # and the split itself: most ghost rows never touch the exchange
    split = row_split(sim._tables)
    assert split
    for name, s in split.items():
        assert s["local_rows"] > s["remote_rows"], (name, s)


def test_ppermute_padding_ratio_bounded():
    """The power-of-two surface bucket S is shared by every (owner,
    offset) ppermute buffer, so padded bytes grow faster than real
    payload with device count (VERDICT r5 weak #5: 2.64 -> 4.05
    MB/device over 8 -> 64 devices on the 1e4-block probe). This guard
    bounds padded/real at pod-scale SIMULATED device counts — plan
    construction is pure host numpy, so 64 'devices' need no mesh — and
    fails CI if a plan change inflates the buckets toward shard volume
    (ratio there would be ~B/boundary, an order of magnitude above the
    bound)."""
    from cup2d_tpu.forest import Forest
    from cup2d_tpu.halo import build_tables
    from cup2d_tpu.parallel.shard_halo import exchange_padding_stats

    cfg = SimConfig(bpdx=4, bpdy=4, level_max=4, level_start=2,
                    extent=1.0, dtype="float32")
    f = Forest(cfg)              # 16x16 level-2 grid
    # refine two quads for a realistic mixed-level boundary
    for (i0, j0) in ((4, 4), (10, 8)):
        f.release(2, i0, j0)
        for a in (0, 1):
            for b in (0, 1):
                f.allocate(3, 2 * i0 + a, 2 * j0 + b)
    order = f.order()
    t = build_tables(f, order, 3, True, 2)   # the vec3 hot set
    n_pad = 512                              # divides 8 and 64
    for D in (8, 64):
        st = exchange_padding_stats(t, n_pad, D, mode="ppermute")
        assert st["real_blocks"] > 0, st
        # measured with the per-offset sparse-pair plan: ratio 1.6 at
        # D=8, 1.9 at D=64 (the old shared-bucket plan sat at 8.1 and
        # 36.6); a volume-scale regression (surface set ~ B per
        # device) would blow far past this even before bucket rounding
        assert st["ratio"] <= 4.0, st


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 devices")
def test_surface_bucket_tracks_shard_boundary():
    """The exchanged surface bucket S must be bounded by the GEOMETRIC
    shard boundary (blocks whose 3x3 spatial neighborhood, at same /
    coarser / finer level, crosses a shard range) — a builder change
    that silently inflates the exchanged set to shard volume would pass
    the HLO-shape test above but fail this one."""
    from cup2d_tpu.halo import _bucket

    cfg, sim = _build_sim()
    sim._refresh()
    f = sim.forest
    order = f.order()
    n_pad = sim._npad_hwm
    D = sim.mesh.devices.size
    B = n_pad // D
    pos = {tuple(k): i for i, k in enumerate(
        np.stack([f.level[order], f.bi[order], f.bj[order]], axis=1))}

    def owner(i):
        return i // B

    # geometric boundary: for each block, every same/coarser/finer
    # neighbor key that exists; count blocks with any cross-shard edge
    boundary = np.zeros(D, np.int64)
    for (lvl, bi, bj), i in pos.items():
        cross = False
        for di in (-1, 0, 1):
            for dj in (-1, 0, 1):
                if di == dj == 0:
                    continue
                ni, nj = bi + di, bj + dj
                cands = [(lvl, ni, nj), (lvl - 1, ni // 2, nj // 2)]
                cands += [(lvl + 1, 2 * ni + a, 2 * nj + b)
                          for a in (0, 1) for b in (0, 1)]
                for key in cands:
                    j = pos.get(key)
                    if j is not None and owner(j) != owner(i):
                        cross = True
        if cross:
            boundary[owner(i)] += 1
    bmax = int(boundary.max())
    assert bmax > 0, "test forest has no shard boundary?"

    for name, t in sim._tables.items():
        if not hasattr(t, "S"):
            continue
        # S is a per-(pair|owner) bucket: bounded by the bucket of the
        # worst geometric boundary (2x slack for the K-padding bucket
        # rounding and edge-interface double counting)
        assert t.S <= 2 * _bucket(bmax, lo=4), (
            name, t.S, bmax, _bucket(bmax, lo=4))
