"""Obstacles on the adaptive forest: rasterization parity with the
uniform path, chi-driven refinement (GradChiOnTmp, main.cpp:4631-4656),
forest checkpoint round-trip, and mixed-level dumps."""

import os

import jax.numpy as jnp
import numpy as np
import pytest

from cup2d_tpu.amr import AMRSim
from cup2d_tpu.config import SimConfig
from cup2d_tpu.io import dump_forest, load_checkpoint, read_dump, \
    save_checkpoint
from cup2d_tpu.models import DiskShape
from cup2d_tpu.sim import Simulation


def _fill_tg(sim):
    """Taylor-Green velocity on every active block."""
    f = sim.forest
    cfg = sim.cfg
    order = f.order()
    bs = cfg.bs
    vals = np.zeros((f.capacity, 2, bs, bs))
    for s in order:
        l = int(f.level[s])
        h = cfg.h_at(l)
        i, j = int(f.bi[s]), int(f.bj[s])
        x = (i * bs + np.arange(bs) + 0.5) * h
        y = (j * bs + np.arange(bs) + 0.5) * h
        X, Y = np.meshgrid(x, y, indexing="xy")
        vals[s, 0] = np.sin(np.pi * X) * np.cos(np.pi * Y)
        vals[s, 1] = -np.cos(np.pi * X) * np.sin(np.pi * Y)
    f.fields["vel"] = jnp.asarray(vals, f.dtype)


def test_disk_forest_matches_uniform():
    """Single-level forest with a disk must reproduce the uniform-grid
    Simulation trajectory to rounding (same algorithms, same
    resolution)."""
    cfg = SimConfig(bpdx=2, bpdy=2, level_max=2, level_start=1,
                    extent=1.0, dtype="float64", nu=1e-3, lam=1e6,
                    rtol=1e9, ctol=-1.0)   # topology frozen
    mk = lambda: DiskShape(0.08, 0.5, 0.55, prescribed=(0.0, 0.0))
    asim = AMRSim(cfg, shapes=[mk()])
    usim = Simulation(cfg, shapes=[mk()], level=1)
    asim.compute_forces_every = 0
    usim.compute_forces_every = 0

    X, Y = usim.grid.cell_centers()
    u = np.sin(np.pi * X) * np.cos(np.pi * Y)
    v = -np.cos(np.pi * X) * np.sin(np.pi * Y)
    usim.state = usim.state._replace(vel=jnp.asarray(np.stack([u, v])))
    _fill_tg(asim)

    for _ in range(3):
        asim.step_once(dt=2e-3)
        usim.step_once(dt=2e-3)

    asim.sync_fields()
    f = asim.forest
    bs = cfg.bs
    gv = np.asarray(usim.state.vel)
    err = 0.0
    for s in f.order():
        i, j = int(f.bi[s]), int(f.bj[s])
        blk = np.asarray(f.fields["vel"][s])
        err = max(err, np.abs(
            blk - gv[:, j * bs:(j + 1) * bs, i * bs:(i + 1) * bs]).max())
    assert err < 1e-10, err


@pytest.mark.slow   # ~23 s; duplicative tier-1 coverage: the canonical
#                     golden (test_golden.py) pins the post-climb block
#                     topology EXACTLY (n_blocks at every CHECK_STEP of
#                     the 2-fish levelStart -> levelMax case), so a chi
#                     tagging regression cannot pass tier-1 — this
#                     drills the same climb in isolation on a disk
def test_chi_tagging_refines_to_finest():
    """Initialization must refine every chi-support block to the finest
    level (the canonical case's levelStart -> levelMax climb,
    main.cpp:6542-6545)."""
    cfg = SimConfig(bpdx=2, bpdy=1, level_max=3, level_start=1,
                    extent=1.0, dtype="float64", nu=4e-5, lam=1e6,
                    rtol=2.0, ctol=1.0)
    sim = AMRSim(cfg, shapes=[DiskShape(0.08, 0.55, 0.25)])
    sim.compute_forces_every = 0
    sim.initialize()
    f = sim.forest
    levels = {int(f.level[s]) for s in f.blocks.values()}
    assert cfg.level_max - 1 in levels
    order = f.order()
    chi = np.asarray(f.fields["chi"][order])
    for k, s in enumerate(order):
        if chi[k].max() > 0.2:
            assert int(f.level[s]) == cfg.level_max - 1

    # and the adaptive run is stable with a disk + quiescent flow
    for _ in range(3):
        diag = sim.step_once()
    assert np.isfinite(float(diag["umax"]))
    # quiescent flow, free disk: nothing should move
    assert abs(sim.shapes[0].u) < 1e-12
    # surface-delta perimeter approximates 2 pi r
    sim.compute_forces_every = 1
    sim.step_once()
    per = sim.shapes[0].forces["perimeter"]
    assert abs(per - 2 * np.pi * 0.08) < 0.15 * 2 * np.pi * 0.08, per


@pytest.mark.slow   # ~32 s; checkpoint bit-exactness stays tier-1 via
#                     test_io (uniform roundtrip + the AMR restore-cache
#                     trio) and test_resilience rung 3
def test_amr_checkpoint_roundtrip(tmp_path):
    """Forest checkpoint restores topology + fields bit-exactly and the
    resumed trajectory matches an uninterrupted run."""
    cfg = SimConfig(bpdx=2, bpdy=1, level_max=3, level_start=1,
                    extent=1.0, dtype="float64", nu=4e-5, lam=1e6,
                    rtol=2.0, ctol=1.0)
    sim = AMRSim(cfg, shapes=[DiskShape(0.08, 0.55, 0.25)])
    sim.compute_forces_every = 0
    sim.initialize()
    _fill_tg(sim)
    sim.step_once(dt=1e-3)
    path = str(tmp_path / "ckpt")
    save_checkpoint(path, sim)

    sim2 = AMRSim(cfg, shapes=[DiskShape(0.08, 0.55, 0.25)])
    sim2.compute_forces_every = 0
    load_checkpoint(path, sim2)
    assert sim2.forest.blocks.keys() == sim.forest.blocks.keys() or \
        set(sim2.forest.blocks) == set(sim.forest.blocks)
    o1, o2 = sim.forest.order(), sim2.forest.order()
    for name in sim.forest.fields:
        a = np.asarray(sim.forest.fields[name][o1])
        b = np.asarray(sim2.forest.fields[name][o2])
        assert np.array_equal(a, b), name

    sim.step_once(dt=1e-3)
    sim2.step_once(dt=1e-3)
    sim.sync_fields()
    sim2.sync_fields()
    a = np.asarray(sim.forest.fields["vel"][sim.forest.order()])
    b = np.asarray(sim2.forest.fields["vel"][sim2.forest.order()])
    assert np.abs(a - b).max() < 1e-12

    # and WITHOUT an explicit dt: the checkpoint persists the cached
    # next-dt state (a restart must take the SAME dt branch as the
    # uninterrupted run — a post-regrid restart would otherwise fork),
    # and a cache-cleared restart exercises the compute_dt fallback,
    # whose shared dt_from_umax arithmetic must keep times in lockstep
    path2 = str(tmp_path / "ckpt2")
    save_checkpoint(path2, sim)
    sim3 = AMRSim(cfg, shapes=[DiskShape(0.08, 0.55, 0.25)])
    sim3.compute_forces_every = 0
    load_checkpoint(path2, sim3)
    assert sim3._next_dt == sim._next_dt      # cache restored
    sim4 = AMRSim(cfg, shapes=[DiskShape(0.08, 0.55, 0.25)])
    sim4.compute_forces_every = 0
    load_checkpoint(path2, sim4)
    sim4._next_dt = None                       # force the fallback
    sim4._next_umax = None
    sim.step_once()                    # cached-dt path
    sim3.step_once()                   # restored-cache path
    sim4.step_once()                   # compute_dt fallback path
    assert sim.time == sim3.time == sim4.time, (
        sim.time, sim3.time, sim4.time)


@pytest.mark.slow   # ~206 s, the tier-1 dominator (PR-3 satellite):
#                     the fast end-to-end CLI smoke retained in tier-1
#                     is tests/test_io.py::test_cli_driver_smoke (+ the
#                     in-process telemetry CLI test)
def test_cli_amr_smoke(tmp_path):
    """`python -m cup2d_tpu` with run.sh-style flags (no -level) runs the
    ADAPTIVE path end-to-end: dumps, forces.csv, checkpoint, restart."""
    from cup2d_tpu.__main__ import main
    out = str(tmp_path / "out")
    argv = ("-bpdx 2 -bpdy 1 -levelMax 3 -levelStart 1 -Rtol 2 -Ctol 1 "
            "-extent 1 -CFL 0.5 -tend 10 -lambda 1e6 -nu 0.00004 "
            "-poissonTol 1e-3 -poissonTolRel 0.01 -maxPoissonRestarts 0 "
            "-maxPoissonIterations 200 -AdaptSteps 5 -tdump 1e-9 "
            "-maxSteps 3 -checkpointEvery 2").split()
    argv += ["-shapes", "angle=0 L=0.16 xpos=0.5 ypos=0.25 kind=disk "
                        "radius=0.08", "-output", out]
    assert main(argv) == 0
    assert os.path.exists(os.path.join(out, "forces.csv"))
    dumps = [p for p in os.listdir(out) if p.endswith(".xdmf2")]
    assert dumps, os.listdir(out)
    assert os.path.exists(os.path.join(out, "checkpoint", "meta.json"))
    # restart continues from the checkpoint without re-blending
    argv2 = argv + ["+maxSteps", "4",
                    "-restart", os.path.join(out, "checkpoint")]
    assert main(argv2) == 0


@pytest.mark.slow   # ~102 s CLI smoke (see test_cli_amr_smoke note)
def test_cli_uniform_smoke(tmp_path):
    """`-level N` forces the single-resolution uniform path through the
    same CLI (dump + forces + exit 0)."""
    from cup2d_tpu.__main__ import main
    out = str(tmp_path / "uout")
    argv = ("-bpdx 2 -bpdy 1 -levelMax 3 -levelStart 1 -Rtol 2 -Ctol 1 "
            "-extent 1 -CFL 0.5 -tend 10 -lambda 1e6 -nu 0.00004 "
            "-poissonTol 1e-3 -poissonTolRel 0.01 -maxPoissonRestarts 0 "
            "-maxPoissonIterations 100 -AdaptSteps 5 -tdump 1e-9 "
            "-maxSteps 2 -level 2").split()
    argv += ["-shapes", "angle=0 L=0.16 xpos=0.5 ypos=0.25 kind=disk "
                        "radius=0.08", "-output", out]
    assert main(argv) == 0
    assert os.path.exists(os.path.join(out, "forces.csv"))
    assert [p for p in os.listdir(out) if p.endswith(".xdmf2")]


def test_dump_forest_mixed_level(tmp_path):
    """Mixed-level dump: one quad per cell, quad areas sum to the domain
    area, and attrs round-trip the velocity."""
    cfg = SimConfig(bpdx=2, bpdy=1, level_max=3, level_start=1,
                    extent=1.0, dtype="float64", nu=4e-5, lam=1e6,
                    rtol=2.0, ctol=1.0)
    sim = AMRSim(cfg, shapes=[DiskShape(0.08, 0.55, 0.25)])
    sim.compute_forces_every = 0
    sim.initialize()
    _fill_tg(sim)
    path = str(tmp_path / "vel.0")
    dump_forest(path, 0.25, sim.forest)
    t, xyz, attr = read_dump(path)
    assert t == 0.25
    f = sim.forest
    bs = cfg.bs
    assert xyz.shape[0] == len(f.blocks) * bs * bs
    # shoelace quad areas sum to extent_x * extent_y
    x = xyz[:, :, 0]
    y = xyz[:, :, 1]
    area = 0.5 * np.abs(
        np.sum(x * np.roll(y, -1, axis=1) - np.roll(x, -1, axis=1) * y,
               axis=1))
    assert abs(area.sum() - cfg.extents[0] * cfg.extents[1]) < 1e-3
    # attr values match the stored field (first block, first cells)
    order = f.order()
    vel = np.asarray(f.fields["vel"][order], np.float32)
    assert np.allclose(attr[:, 0], vel[:, 0].ravel(), atol=1e-6)
    assert np.allclose(attr[:, 1], vel[:, 1].ravel(), atol=1e-6)
