"""Solve-path latch tests (PR 6): the CUP2D_POIS=fas FAS-multigrid
full solver on the uniform/fleet drivers, the CUP2D_POIS=fft forest-FFT
two-grid production preconditioner, latch validation, and the
FAS-vs-Krylov pressure agreement the acceptance pins.

Expensive developed-regime A/B probes live in the slow tier
(per-test justifications below); this module's tier-1 half runs small
grids only.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cup2d_tpu.config import SimConfig


def _cfg(**kw):
    base = dict(bpdx=1, bpdy=1, level_max=1, level_start=0, extent=1.0,
                nu=1e-3, cfl=0.4, dtype="float64",
                max_poisson_iterations=200)
    base.update(kw)
    return SimConfig(**base)


# ---------------------------------------------------------------------------
# latch validation
# ---------------------------------------------------------------------------

def test_uniform_latch_rejects_typo(monkeypatch):
    from cup2d_tpu.uniform import UniformGrid
    monkeypatch.setenv("CUP2D_POIS", "fass")
    with pytest.raises(ValueError, match="CUP2D_POIS"):
        UniformGrid(_cfg(), level=3)


def test_forest_latch_accepts_fas_rejects_unknown(monkeypatch):
    """PR 13 grew the forest latch: 'fas'/'fas-f' now select the
    forest-native FAS full solver (they were uniform-only refusals
    before), while a genuinely unknown token must still fail loudly at
    construction — never silently run the default on one A/B arm."""
    from cup2d_tpu.amr import AMRSim
    cfg = SimConfig(bpdx=1, bpdy=1, level_max=2, level_start=1,
                    extent=1.0, dtype="float64")
    for tok, mode in (("fas", "fas+forest"), ("fas-f", "fas-f+forest")):
        monkeypatch.setenv("CUP2D_POIS", tok)
        sim = AMRSim(cfg, shapes=[])
        assert sim._pois_mode == tok
        assert sim.poisson_mode == mode
    monkeypatch.setenv("CUP2D_POIS", "fasx")
    with pytest.raises(ValueError, match="CUP2D_POIS"):
        AMRSim(cfg, shapes=[])


def test_twolevel_latch_accepts_mg2(monkeypatch):
    from cup2d_tpu.amr import AMRSim
    monkeypatch.setenv("CUP2D_TWOLEVEL", "mg2")
    cfg = SimConfig(bpdx=1, bpdy=1, level_max=2, level_start=1,
                    extent=1.0, dtype="float64")
    sim = AMRSim(cfg, shapes=[])
    assert sim._twolevel_form == "mg2"


# ---------------------------------------------------------------------------
# FAS on the uniform driver: converged pressure matches Krylov
# ---------------------------------------------------------------------------

def _tg_sim(monkeypatch, mode):
    from cup2d_tpu.uniform import UniformSim, taylor_green_state
    if mode:
        monkeypatch.setenv("CUP2D_POIS", mode)
    else:
        monkeypatch.delenv("CUP2D_POIS", raising=False)
    sim = UniformSim(_cfg(), level=3)   # 64^2
    sim.state = taylor_green_state(sim.grid)
    sim.step_count = 20                 # production regime
    return sim


def test_fas_matches_krylov_pressure(monkeypatch):
    """Acceptance pin: the FAS path's converged pressure/velocity
    match the Krylov path's on the Taylor-Green case to the documented
    tolerance — both solve to the same Linf criterion, so trajectories
    agree to the solver-tolerance band (the two paths' error lives in
    modes whose residual is below target; measured headroom ~10x)."""
    a = _tg_sim(monkeypatch, None)
    b = _tg_sim(monkeypatch, "fas")
    assert a.poisson_mode == "bicgstab+mg"
    assert b.poisson_mode == "fas"
    for _ in range(4):
        da = a.step_once()
        db = b.step_once()
    assert bool(db["poisson_converged"])
    # cycle-count accounting: FAS iters ARE preconditioner cycles
    assert int(db["precond_cycles"]) == int(db["poisson_iters"])
    # documented tolerance: production poisson_tol=1e-3 (undivided
    # Linf); pressure agreement to ~tol, velocity tighter (the
    # correction applies grad dp scaled by dt/h)
    dp = float(jnp.max(jnp.abs(a.state.pres - b.state.pres)))
    dv = float(jnp.max(jnp.abs(a.state.vel - b.state.vel)))
    assert dp < 1e-3, dp
    assert dv < 1e-4, dv


def test_fleet_fas_latch_wiring(monkeypatch):
    """Cheap tier-1 wiring assert: FleetSim under CUP2D_POIS=fas
    reads the GRID's latch (fleet.py stays env-read-free) and routes
    production solves to the member-batched mg_solve branch. The
    member-vs-solo trajectory drill runs in the slow tier below; the
    freeze contract itself is tier-1 at the solver level
    (test_poisson.py::test_mg_solve_member_freeze_is_exact)."""
    from cup2d_tpu.fleet import FleetSim
    monkeypatch.setenv("CUP2D_POIS", "fas")
    fleet = FleetSim(_cfg(), level=3, members=2)
    assert fleet.poisson_mode == "fas"
    assert fleet.grid.solver_mode == "fas"


@pytest.mark.slow   # ~8 s — duplicative composition: the converged-
#                     member freeze is tier-1 at the solver level
#                     (test_mg_solve_member_freeze_is_exact), the
#                     member-vs-solo ≤1e-12 contract is tier-1 for the
#                     Krylov path (test_fleet.py), and the fas branch
#                     wiring is tier-1 via the latch assert above;
#                     this drills the composition end-to-end.
def test_fleet_fas_members_match_solo(monkeypatch):
    """The fleet fas path (member-batched mg_solve): B=2 members match
    their solo fas runs to the documented fleet deviation bound, with
    identical per-member cycle counts."""
    from cup2d_tpu.fleet import FleetSim, taylor_green_fleet
    monkeypatch.setenv("CUP2D_POIS", "fas")
    cfg = _cfg()
    fleet = FleetSim(cfg, level=3, members=2)   # 64^2
    fleet.state = taylor_green_fleet(fleet.grid, 2)
    fleet.step_count = 20
    solos = []
    for m in range(2):
        from cup2d_tpu.uniform import UniformSim, taylor_green_state
        s = UniformSim(cfg, level=3)
        st = taylor_green_state(s.grid)
        s.state = st._replace(vel=st.vel * (0.8 ** m))
        s.step_count = 20
        solos.append(s)
    for _ in range(3):
        df = fleet.step_once()
        ds = [s.step_once() for s in solos]
    assert fleet.poisson_mode == "fas"
    for m in range(2):
        dv = float(jnp.max(jnp.abs(
            fleet.state.vel[m] - solos[m].state.vel)))
        assert dv <= 1e-12, (m, dv)
        assert int(df["poisson_iters"][m]) == int(ds[m]["poisson_iters"])
        assert int(df["precond_cycles"][m]) == \
            int(ds[m]["precond_cycles"])


def test_sharded_fas_attach_mesh_wiring(monkeypatch):
    """ShardedUniformSim under CUP2D_POIS=fas rebuilds the MG
    hierarchy mesh-aware in __init__ (UniformGrid.attach_mesh): the
    compiled step then captures the overlapped smoother. Cheap wiring
    assert — the overlapped solve's NUMERICS are tier-1-pinned at the
    solver level (test_poisson: overlap sweeps == laplacian5_neumann,
    sharded mg_solve == meshless); the full sharded trajectory runs in
    the slow tier below."""
    from cup2d_tpu.parallel.mesh import ShardedUniformSim, make_mesh

    monkeypatch.setenv("CUP2D_POIS", "fas")
    cfg = _cfg(bpdx=2, bpdy=1, extent=2.0)
    mesh = make_mesh(8)
    sh = ShardedUniformSim(cfg, mesh, level=3)
    assert sh.grid.solver_mode == "fas"
    assert sh.grid.mg.overlap_levels > 0    # the overlapped smoother
    assert sh.grid.mg.mesh is mesh
    # the Krylov default must NOT swap hierarchies (its GSPMD
    # sharded==single equality is pinned elsewhere)
    monkeypatch.delenv("CUP2D_POIS")
    sh2 = ShardedUniformSim(cfg, mesh, level=3)
    assert sh2.grid.mg.overlap_levels == 0


@pytest.mark.slow   # ~50 s (sharded jit compiles dominate) —
#                     end-to-end confirmation of the wiring test
#                     above; the overlapped smoother's numerics are
#                     tier-1 via the solver-level equivalences in
#                     test_poisson.py
def test_sharded_fas_matches_single_device(monkeypatch):
    """End-to-end sharded FAS driver: ShardedUniformSim under
    CUP2D_POIS=fas rebuilds the MG hierarchy mesh-aware
    (UniformGrid.attach_mesh -> overlap_jacobi_sweeps at the finest
    level) and its trajectory matches the single-device FAS run to the
    sharded-equality bound — the attach_mesh wiring itself, not just
    the solver-level pieces test_poisson pins."""
    from cup2d_tpu.parallel.mesh import ShardedUniformSim, make_mesh
    from cup2d_tpu.uniform import UniformSim, taylor_green_state

    monkeypatch.setenv("CUP2D_POIS", "fas")
    cfg = _cfg(bpdx=2, bpdy=1, extent=2.0)
    ref = UniformSim(cfg, level=3)          # 128x64; Nx=128 / 8 devs
    ref.state = taylor_green_state(ref.grid)
    ref.step_count = 20
    mesh = make_mesh(8)
    sh = ShardedUniformSim(cfg, mesh, level=3)
    sh.set_state(taylor_green_state(sh.grid))
    sh.step_count = 20
    assert sh.grid.solver_mode == "fas"
    assert sh.grid.mg.overlap_levels > 0    # the overlapped smoother
    for _ in range(3):
        ref.advance(1)
        sh.advance(1)
    assert len(sh.state.vel.sharding.device_set) == 8
    dv = np.max(np.abs(np.asarray(ref.state.vel)
                       - np.asarray(sh.state.vel)))
    assert dv < 1e-12, dv


# ---------------------------------------------------------------------------
# forest-FFT production preconditioner (CUP2D_POIS=fft)
# ---------------------------------------------------------------------------

def test_fft_mode_cuts_cold_production_iters(monkeypatch):
    """The tentpole's acceptance shape at tier-1 scale: on a 256-block
    uniform-level forest with a cold multi-scale RHS, the always-on
    fft two-grid path converges the first production solve in <= half
    the block-Jacobi default's iterations at the same tolerance
    criterion. (The developed-regime 1e4-block record lives in
    BASELINE.md round 6; iteration counts are platform-independent.)"""
    from validation.poisson_ab import build_forest_sim

    monkeypatch.delenv("CUP2D_POIS", raising=False)
    a = build_forest_sim(bpd=4, level_start=2)
    a._refresh()
    monkeypatch.setenv("CUP2D_POIS", "fft")
    b = build_forest_sim(bpd=4, level_start=2)
    b._refresh()
    assert b.poisson_mode == "bicgstab+fft"
    da = a.step_once()
    db = b.step_once()
    assert bool(da["poisson_converged"]) and bool(db["poisson_converged"])
    ia, ib = int(da["poisson_iters"]), int(db["poisson_iters"])
    assert ia > 2, f"default arm trivially easy (iters={ia})"
    assert ib <= max(1, ia // 2), (ia, ib)
    # cycle accounting: 2 two-grid cycles per Krylov iteration
    assert int(db["precond_cycles"]) == 2 * ib
    # the default arm never engaged the correction (sub-trigger)
    assert int(da["precond_cycles"]) == 0
    assert a.poisson_mode == "bicgstab+jacobi"


@pytest.mark.slow   # ~2-4 min: the BASELINE round-6 1e4-block probe
#                     itself (10.5k blocks over levels 6-8 — the
#                     synthetic builder STARTS at 8,192 level-6
#                     blocks, so the target must exceed that for the
#                     forest to actually refine into the multi-level
#                     regime where the base-level correction is
#                     genuinely approximate) — duplicative coverage
#                     of the tier-1 256-block A/B above, pinning the
#                     acceptance numbers recorded in BASELINE.md r6
#                     (additive 10/9/8 -> mg2 4/4/4 iters/step).
def test_fft_mode_multilevel_regime_iters(monkeypatch):
    from validation.poisson_ab import run_path

    monkeypatch.delenv("CUP2D_POIS", raising=False)
    monkeypatch.delenv("CUP2D_TWOLEVEL", raising=False)
    add = run_path("additive", bpd=0, steps=2, synthetic=10000,
                   levelmax=8)
    mg2 = run_path("mg2", bpd=0, steps=2, synthetic=10000, levelmax=8)
    assert mg2["n_blocks"] > 8192          # really multi-level
    assert all(add["converged"]) and all(mg2["converged"])
    assert sum(mg2["iters"]) <= sum(add["iters"]), (add, mg2)
    assert max(mg2["iters"]) <= 4, mg2


# ---------------------------------------------------------------------------
# forest-native FAS full solver (CUP2D_POIS=fas|fas-f, PR 13)
# ---------------------------------------------------------------------------

def test_forest_fas_matches_krylov_pressure():
    """Acceptance pin at tier-1 scale: on a genuinely MULTI-LEVEL
    forest (vortex-tagged, levels straddling the coarse base level c),
    the forest-FAS full solve converges in no more cycles than the
    mg2-Krylov arm takes iterations, and its pressure/velocity match
    that arm's to the solve criterion — both paths solve the identical
    composite operator to the same Linf target (pinned TIGHT here so
    the sub-tolerance mode band is small against the O(10) pressure
    scale). Cycle accounting rides along: FAS iters ARE the cycles."""
    from validation.poisson_ab import build_multilevel_sim

    sa = build_multilevel_sim(tol=1e-7, tol_rel=1e-7)
    sa._refresh()
    sa._pois_mode = "fft"            # the mg2-Krylov reference arm
    sa._coarse_on = True
    sb = build_multilevel_sim(tol=1e-7, tol_rel=1e-7)
    sb._refresh()
    sb._pois_mode = "fas"
    sb._coarse_on = True
    assert sa.poisson_mode == "bicgstab+fft"
    assert sb.poisson_mode == "fas+forest"
    for s in (sa, sb):
        s._last_iters = 0
        s._last_iters_dev = None
    da = sa.step_once(1e-3)
    db = sb.step_once(1e-3)
    assert bool(da["poisson_converged"]) and bool(db["poisson_converged"])
    # the full-solver cycle train beats the Krylov iteration count at
    # the same (deep) target — the ISSUE-13 acceptance shape; the
    # 1e4-block record is the slow drill below + BASELINE round 10
    assert int(db["poisson_iters"]) <= int(da["poisson_iters"]), (da, db)
    assert int(db["precond_cycles"]) == int(db["poisson_iters"])
    va = sa._ordered_state()
    vb = sb._ordered_state()
    dp = float(jnp.max(jnp.abs(va["pres"] - vb["pres"])))
    dv = float(jnp.max(jnp.abs(va["vel"] - vb["vel"])))
    pscale = float(jnp.max(jnp.abs(va["pres"])))
    # both solved to 1e-7 undivided Linf; the pressure gap is the
    # sub-tolerance band amplified by A^-1 (O(N^2) in undivided
    # units), so the honest bound is RELATIVE to the O(100) field
    # scale — measured 2.7e-4 relative, ~7x headroom here; velocity
    # is tighter by dt/h (measured 2.5e-8 absolute)
    assert dp < 2e-3 * pscale, (dp, pscale)
    assert dv < 1e-6, dv


@pytest.mark.slow   # ~4-6 min: the ISSUE-13 acceptance drill at the
#                     BASELINE 1e4-block probe itself (10.5k blocks,
#                     levels 6-8 — a multi-RUNG window ladder, the
#                     regime that exposed the Dirichlet-ghost
#                     instability) — duplicative of the tier-1
#                     multi-level A/B above except for the recorded
#                     acceptance numbers (fas <= mg2's 4 iters/step,
#                     validation/poisson_ab_r10.json)
def test_forest_fas_multilevel_regime_iters(monkeypatch):
    from validation.poisson_ab import run_path

    monkeypatch.delenv("CUP2D_POIS", raising=False)
    monkeypatch.delenv("CUP2D_TWOLEVEL", raising=False)
    mg2 = run_path("mg2", bpd=0, steps=2, synthetic=10000, levelmax=8)
    fas = run_path("fas", bpd=0, steps=2, synthetic=10000, levelmax=8)
    assert fas["n_blocks"] > 8192          # really multi-level
    assert all(mg2["converged"]) and all(fas["converged"])
    # acceptance: FAS cycles per step <= the mg2-Krylov iteration
    # count per step (each cycle costs ~half an mg2-preconditioned
    # Krylov iteration: 3 A-applies + 2 GEMMs vs 6 A + 6 GEMM + 2 DCT)
    assert max(fas["iters"]) <= max(mg2["iters"]), (mg2, fas)
    assert max(mg2["iters"]) <= 4, mg2


# ---------------------------------------------------------------------------
# lagged-verdict trigger freshness (the hysteresis fix)
# ---------------------------------------------------------------------------

def test_lagged_trigger_engages_without_extra_step(monkeypatch):
    """Regression for the r4-documented one-step-late trigger under
    the lagged verdict: with the freshness window
    (resilience.StepGuard.step), the iters>15 evidence of production
    step 1 is pulled BEFORE step 2's dispatch, so the coarse
    correction engages at step 2 — the same step the eager driver
    engages at (pinned against an eager twin)."""
    from cup2d_tpu.resilience import StepGuard
    from validation.poisson_ab import build_forest_sim

    monkeypatch.delenv("CUP2D_POIS", raising=False)
    sim = build_forest_sim(bpd=2, level_start=2,
                           tol=1e-9, tol_rel=1e-8)
    guard = StepGuard(sim, lag=True, recover=False)
    engaged_at = None
    recs = []
    for call in range(1, 4):
        recs.append(guard.step())
        if engaged_at is None and sim._coarse_on:
            engaged_at = call
    guard.drain()
    # step 1 (verdicted during call 2's freshness window) supplied the
    # >15-iteration evidence...
    assert recs[0] is None                      # lag-1: still in flight
    assert recs[1]["poisson_iters"] > 15
    # ...and call 2 = step-1 evidence consumed at step-2's dispatch —
    # the eager driver's engagement step (drained via the dt pull
    # there); the pre-fix lagged pipeline engaged at call 3
    assert engaged_at == 2, engaged_at
    # schema-v4 attribution under lag: each record labels the path its
    # step actually TOOK (captured at dispatch, _Pending.mode) — a
    # live read at commit time would stamp step 1 with the trigger
    # state AFTER step 2's dispatch flipped it
    assert recs[1]["poisson_mode"] == "bicgstab+jacobi"
    assert recs[2]["poisson_mode"] == "bicgstab+twolevel"
