"""Quantitative physics validation: channel flow past a fixed cylinder.

The true inflow-outflow configuration (cases.py ``channel``: Dirichlet
inflow at x_lo, convective outflow at x_hi, free-slip side walls) that
the towed-cylinder case (validation/cylinder.py) only reaches by
Galilean transformation. The body is FIXED and the stream flows past
it — the stream is sustained by the boundary table, which the closed
free-slip box cannot do.

    python -m validation.channel drag      # Re=40 steady drag
    python -m validation.channel strouhal  # Re=200 shedding, ~30+ min

Published references, same as the towed twin: Cd(Re=40) ~ 1.5-1.6
unbounded (Tritton 1959); St(Re=200) ~ 0.19-0.20 (Williamson 1989).
The acceptance bar (ISSUE 12) is St within 5% of the literature band.
Measured numbers live in BASELINE.md.
"""

from __future__ import annotations

import io
import sys
import time

import numpy as np


def _build(re, level, u_in=0.2, diameter=0.1, xpos=1.0,
           forces_every=4):
    from cup2d_tpu.cache import enable_compilation_cache
    from cup2d_tpu.cases import make_sim

    enable_compilation_cache()
    sim = make_sim("channel", level=level, re=re, u_in=u_in,
                   diameter=diameter, xpos=xpos)
    sim.compute_forces_every = forces_every
    sim.force_log = io.StringIO()
    sim.initialize()
    return sim


def _force_table(sim):
    rows = sim.force_log.getvalue().strip().splitlines()
    return np.array([[float(c) for c in row.split(",")] for row in rows])


def drag(level: int = 5, t_end: float = 30.0):
    """Re = 40: steady drag on the fixed cylinder from the
    surface-traction diagnostics, averaged after the impulsive-start
    transient washes out (one flow-through is extent/u_in = 20)."""
    D, U = 0.1, 0.2
    sim = _build(re=40.0, level=level, u_in=U, diameter=D,
                 forces_every=5)
    t0 = time.perf_counter()
    while sim.time < t_end:
        sim.step_once()
    data = _force_table(sim)
    t, fx = data[:, 0], data[:, 4]
    m = t > 0.7 * t_end
    cd = float(np.mean(fx[m]) / (0.5 * U * U * D))
    print(f"steps={sim.step_count} wall={time.perf_counter()-t0:.0f}s "
          f"Cd={cd:.3f}  (lit unbounded 1.5-1.6; ~10% blockage here)")
    return cd


def strouhal(level: int = 5, t_end: float = 45.0):
    """Re = 200: vortex-shedding frequency from the lift oscillation
    on the fixed cylinder. A small transverse kick just downstream
    breaks symmetry so shedding saturates early; the FFT window skips
    the impulsive-start transient."""
    import jax.numpy as jnp

    D, U, xpos = 0.1, 0.2, 1.0
    sim = _build(re=200.0, level=level, u_in=U, diameter=D, xpos=xpos)
    x, y = sim.grid.cell_centers()
    r2 = ((x - (xpos + 1.2 * D)) ** 2
          + (y - (0.5 + 0.3 * D)) ** 2) / (0.5 * D) ** 2
    vel = np.array(sim.state.vel)   # copy: device views are read-only
    vel[1] += (0.04 * np.exp(-r2)).astype(vel.dtype)
    sim.state = sim.state._replace(
        vel=jnp.asarray(vel, sim.grid.dtype))
    t0 = time.perf_counter()
    while sim.time < t_end:
        sim.step_once()
    data = _force_table(sim)
    t, fy = data[:, 0], data[:, 5]
    m = t > 0.45 * t_end
    fy_w = fy[m] - fy[m].mean()
    dtm = float(np.median(np.diff(t[m])))
    freqs = np.fft.rfftfreq(len(fy_w), dtm)
    amp = np.abs(np.fft.rfft(fy_w * np.hanning(len(fy_w))))
    fpk = float(freqs[1 + np.argmax(amp[1:])])
    st = fpk * D / U
    print(f"steps={sim.step_count} wall={time.perf_counter()-t0:.0f}s "
          f"lift_rms={float(fy_w.std()):.2e} f={fpk:.4f} "
          f"St={st:.4f}  (lit 0.19-0.20, bar: within 5%)")
    return st


def main(argv=None) -> int:
    args = sys.argv[1:] if argv is None else argv
    which = args[0] if args else "drag"
    if which == "drag":
        drag()
    elif which == "strouhal":
        strouhal()
    else:
        print("usage: python -m validation.channel [drag|strouhal]",
              file=sys.stderr)
        return 2
    return 0


if __name__ == "__main__":
    sys.exit(main())
