"""Comm/compute overlap evidence from the compiled schedule (VERDICT r3
missing #3 / next #6).

The sharded lab assembly issues its surface exchange first and scatters
every local-only ghost row before touching the received buffer
(parallel/shard_halo.py). This tool compiles the real megastep on the
8-virtual-device mesh and inspects the optimized module's instruction
stream: for every async collective start/done pair it counts the
non-trivial compute ops (fusions/gathers/scatters and their element
totals) that the dependence structure places BETWEEN start and done —
work the scheduler is free to (and on TPU's latency-hiding scheduler,
does) run while the exchange is in flight. It also reports the
local/remote row split, i.e. what fraction of the ghost assembly is
exchange-independent.

    python -m validation.overlap_check [--devices 8]
"""

from __future__ import annotations

import argparse
import json
import re
import sys


def build_and_lower(n_dev: int):
    import os
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n_dev}"
        ).strip()
    import jax
    jax.config.update("jax_platforms", "cpu")

    from cup2d_tpu.config import SimConfig
    from cup2d_tpu.models import DiskShape
    from cup2d_tpu.parallel.forest_mesh import ShardedAMRSim
    from cup2d_tpu.parallel.mesh import make_mesh

    cfg = SimConfig(bpdx=2, bpdy=1, level_max=3, level_start=1,
                    extent=1.0, dtype="float32", nu=4e-5, lam=1e6,
                    rtol=2.0, ctol=1.0)
    mesh = make_mesh(n_dev)
    sim = ShardedAMRSim(cfg, mesh, shapes=[DiskShape(0.08, 0.55, 0.25)])
    sim.compute_forces_every = 0
    sim.initialize()

    captured = {}
    orig = sim._mega_jit

    def wrapper(*a, **k):
        captured["a"], captured["k"] = a, k
        return orig(*a, **k)

    sim._mega_jit = wrapper
    sim.step_once(dt=1e-3)
    txt = orig.lower(*captured["a"], **captured["k"]).compile().as_text()

    return txt, row_split(sim._tables)


def row_split(tables) -> dict:
    """Real (non-padded) local/remote ghost-row counts per sharded
    table set — the ONE definition of the B*L*L scratch-slot
    convention, shared with tests/test_comm_volume.py."""
    import numpy as np
    split = {}
    for name, t in tables.items():
        if hasattr(t, "src_l"):
            scr = t.B * t.L * t.L
            n_l = int((np.asarray(t.dest_sl) < scr).sum()
                      + (np.asarray(t.dest_l) < scr).sum())
            n_r = int((np.asarray(t.dest_sr) < scr).sum()
                      + (np.asarray(t.dest_r) < scr).sum())
            split[name] = {"local_rows": n_l, "remote_rows": n_r}
    return split


_INSTR = re.compile(r"^\s+(?:ROOT )?%([\w.\-]+) = ([a-z0-9]+)\[([0-9,]*)\][^ ]* (\S+)\((.*)$")
# tuple-shaped instructions (async collective starts on TPU lower as
# '(f32[256], u32[], ...) collective-permute-start(...)'): capture the
# FIRST element's dtype/dims as the payload shape
_INSTR_TUPLE = re.compile(
    r"^\s+(?:ROOT )?%([\w.\-]+) = \(([a-z0-9]+)\[([0-9,]*)\][^)]*\) "
    r"(\S+)\((.*)$")
_OPND = re.compile(r"%([\w.\-]+)")
_WORK_OPS = ("fusion", "gather", "scatter", "dynamic-update-slice",
             "concatenate", "copy", "transpose", "reduce")


def analyze(txt: str) -> list[dict]:
    """Dependence-graph overlap evidence per collective.

    The CPU backend (the only multi-device backend available here)
    lowers collectives SYNCHRONOUSLY — no start/done pairs exist to
    inspect. The schedulable-overlap property is still decidable from
    the dependence graph: for each collective-permute/all-gather, every
    op that sits between its issue point and its FIRST consumer in
    program order and is neither an ancestor nor a descendant of the
    collective is work a latency-hiding scheduler (TPU's) may run while
    the exchange is in flight. Reported per collective with element
    volumes."""
    out = []
    for comp in txt.split("\n\n"):
        lines = comp.splitlines()
        instrs = []          # (name, op, dims, operands, line_idx)
        by_name = {}
        for i, ln in enumerate(lines):
            m = _INSTR.match(ln) or _INSTR_TUPLE.match(ln)
            if not m:
                continue
            name, dt_, dims, op = m.group(1), m.group(2), m.group(3), \
                m.group(4)
            opnds = _OPND.findall(m.group(5))
            dims_l = [int(x) for x in dims.split(",") if x]
            n = 1
            for d_ in dims_l:
                n *= d_
            by_name[name] = len(instrs)
            instrs.append((name, op, n, opnds))
        colls = [k for k, (nm, op, _, _) in enumerate(instrs)
                 if op in ("collective-permute", "all-gather",
                           "collective-permute-start",
                           "all-gather-start")]
        if not colls:
            continue
        # descendants per collective (transitive users)
        users: list[list[int]] = [[] for _ in instrs]
        for k, (_, _, _, opnds) in enumerate(instrs):
            for o in opnds:
                j = by_name.get(o)
                if j is not None:
                    users[j].append(k)
        for c in colls:
            desc = set()
            stack = [c]
            while stack:
                k = stack.pop()
                for u in users[k]:
                    if u not in desc:
                        desc.add(u)
                        stack.append(u)
            anc = set()
            stack = [c]
            while stack:
                k = stack.pop()
                for o in instrs[k][3]:
                    j = by_name.get(o)
                    if j is not None and j not in anc:
                        anc.add(j)
                        stack.append(j)
            first_use = min((d for d in desc), default=len(instrs))
            free_ops = 0
            free_elems = 0
            indep_ops = 0
            indep_elems = 0
            for k in range(len(instrs)):
                if k == c or k in desc or k in anc:
                    continue
                nm, op, n, _ = instrs[k]
                if op not in _WORK_OPS:
                    continue
                indep_ops += 1
                indep_elems += n
                if c < k < first_use:
                    free_ops += 1
                    free_elems += n
            out.append({
                "collective": instrs[c][1],
                "elems_exchanged": instrs[c][2],
                # textual window (what the CPU emitter already placed
                # between issue and first consumer)
                "independent_ops_before_first_consumer": free_ops,
                "independent_elems_before_first_consumer": free_elems,
                # dependence-graph bound (what a latency-hiding
                # scheduler — TPU's — may move into the window)
                "independent_ops_total": indep_ops,
                "independent_elems_total": indep_elems,
            })
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--devices", type=int, default=8)
    args = ap.parse_args()
    txt, split = build_and_lower(args.devices)
    pairs = analyze(txt)
    overlapped = [
        p for p in pairs
        if p["independent_ops_before_first_consumer"] > 0]
    print(json.dumps({
        "n_collectives": len(pairs),
        "n_with_overlappable_work": len(overlapped),
        "pairs": pairs[:24],
        "row_split": split,
    }, indent=1))
    return 0 if overlapped else 1


if __name__ == "__main__":
    sys.exit(main())
