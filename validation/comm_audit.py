"""Collective-traffic audit of the sharded forest step.

The reference's comm layer (/root/reference/main.cpp:909-2142) exists to
move ONLY halo slabs between neighbor ranks; its per-step traffic is
proportional to the shard *surface*. Our sharded path delegates comm to
GSPMD, which for a data-dependent gather from a sharded operand may
legally lower to an all-gather of the whole field — traffic proportional
to *volume*. This tool measures which one we actually got: it runs one
adaptive step of ShardedAMRSim on an 8-virtual-device CPU mesh with XLA
HLO dumping enabled, then parses every optimized module for collective
ops (all-gather / all-reduce / collective-permute / all-to-all) and sums
their bytes.

Run:  python validation/comm_audit.py [--devices 8]
Prints one line per executable and a JSON summary; exits 0 always (it is
a measurement, not a test — tests/test_comm_volume.py asserts the
bound).
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
import tempfile

_DTYPE_BYTES = {
    "f64": 8, "f32": 4, "f16": 2, "bf16": 2,
    "s64": 8, "u64": 8, "s32": 4, "u32": 4, "s16": 2, "u16": 2,
    "s8": 1, "u8": 1, "pred": 1, "c64": 8, "c128": 16,
}

# e.g.  %ag = f64[8,512,2,8,8]{4,3,2,1,0} all-gather(%p), ...
_COLL_RE = re.compile(
    r"=\s*(?:\(\s*)?([a-z0-9]+)\[([0-9,]*)\][^=]*?"
    r"\b(all-gather|all-reduce|collective-permute|all-to-all|"
    r"reduce-scatter|collective-broadcast)\b")


def shape_bytes(dtype: str, dims: str) -> int:
    n = 1
    for d in dims.split(","):
        if d:
            n *= int(d)
    return n * _DTYPE_BYTES.get(dtype, 4)


def audit_dump_dir(dump_dir: str) -> dict:
    """Parse every optimized HLO module in dump_dir; return per-module
    and total collective byte counts."""
    mods = {}
    for path in sorted(glob.glob(
            os.path.join(dump_dir, "*after_optimizations.txt"))):
        name = os.path.basename(path)
        # module name: module_NNNN.jit_foo.sm_8... -> jit_foo
        m = re.search(r"module_\d+\.([^.]+)", name)
        label = m.group(1) if m else name
        per_op: dict[str, list] = {}
        with open(path) as f:
            for line in f:
                cm = _COLL_RE.search(line)
                if not cm:
                    continue
                dt, dims, op = cm.groups()
                per_op.setdefault(op, []).append(
                    (shape_bytes(dt, dims), f"{dt}[{dims}]"))
        if per_op:
            entry = mods.setdefault(label, {})
            for op, items in per_op.items():
                e = entry.setdefault(op, {"count": 0, "bytes": 0,
                                          "largest": "", "_max": 0})
                e.setdefault("_max", 0)
                for b, shp in items:
                    e["count"] += 1
                    e["bytes"] += b
                    if b > e["_max"]:
                        e["largest"], e["_max"] = shp, b
            for e in entry.values():
                e.pop("_max", None)
    return mods


def run_step_with_dump(n_dev: int, dump_dir: str) -> dict:
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + f" --xla_force_host_platform_device_count={n_dev}"
        + f" --xla_dump_to={dump_dir}"
        + " --xla_dump_hlo_pass_re=").strip()
    # the image's sitecustomize pins JAX_PLATFORMS to the TPU plugin;
    # config.update before first backend use wins (tests/conftest.py)
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np  # noqa: F401

    from cup2d_tpu.config import SimConfig
    from cup2d_tpu.models import DiskShape
    from cup2d_tpu.parallel.forest_mesh import ShardedAMRSim
    from cup2d_tpu.parallel.mesh import make_mesh

    cfg = SimConfig(bpdx=2, bpdy=1, level_max=3, level_start=1,
                    extent=1.0, dtype="float32", nu=4e-5, lam=1e6,
                    rtol=2.0, ctol=1.0)
    mesh = make_mesh(n_dev)
    sim = ShardedAMRSim(cfg, mesh, shapes=[DiskShape(0.08, 0.55, 0.25)])
    sim.compute_forces_every = 0
    sim.initialize()
    for _ in range(2):
        sim.step_once(dt=1e-3)
    # field stats for the proportionality check
    f = sim.forest
    n_act = len(f.order())
    return {
        "n_devices": n_dev,
        "n_active_blocks": int(n_act),
        "n_pad": int(sim._npad_hwm),
        "bs": int(cfg.bs),
        "field_bytes_vel": int(
            sim._npad_hwm * 2 * cfg.bs * cfg.bs
            * np.dtype(f.dtype).itemsize),
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--devices", type=int, default=8)
    ap.add_argument("--dump-dir", default=None)
    args = ap.parse_args()
    dump_dir = args.dump_dir or tempfile.mkdtemp(prefix="hlo_comm_")

    meta = run_step_with_dump(args.devices, dump_dir)
    mods = audit_dump_dir(dump_dir)

    grand = {}
    for label, entry in sorted(mods.items()):
        for op, e in sorted(entry.items()):
            g = grand.setdefault(op, {"count": 0, "bytes": 0})
            g["count"] += e["count"]
            g["bytes"] += e["bytes"]
            print(f"{label:50s} {op:20s} x{e['count']:<4d} "
                  f"{e['bytes']/1e6:10.3f} MB   largest {e['largest']}",
                  file=sys.stderr)
    print(json.dumps({"meta": meta, "dump_dir": dump_dir,
                      "modules": mods, "total": grand}))


if __name__ == "__main__":
    main()
