"""Golden-trajectory capture for the CI regression test (VERDICT r2 #5).

Runs a small version of the reference's canonical two-fish case
(run.sh flags, levelMax reduced so CPU f64 finishes in CI time) and
records fish CoM / velocity, umax, block count and Poisson iterations
at fixed steps. `--write` stores them in tests/golden_canonical.json;
tests/test_golden.py replays the same run and asserts agreement to
tight tolerances — the silent-physics-regression tripwire the round-2
verdict called for (a suite of invariant tests passes even if the
actual trajectory drifts).

    JAX_PLATFORMS=cpu python -m validation.golden --write
"""

from __future__ import annotations

import argparse
import json
import os


GOLDEN_PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "tests", "golden_canonical.json")

# 25 is the mid-trajectory checkpoint (pre-chaotic, just after the
# impulse): it carries INTERMEDIATE tolerances in test_golden.py,
# restoring late-window discriminating power the wide final-step
# windows gave up (ADVICE r5)
CHECK_STEPS = (5, 10, 20, 25, 30)
MID_STEP = 25


def _force_cpu_x64():
    """Match tests/conftest.py exactly: CPU backend, x64 on. The golden
    numbers are only meaningful under the same precision/backend the CI
    test replays them with."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)


def build_sim():
    _force_cpu_x64()
    from validation.canonical import build_canonical_sim

    # reduced depth so CPU f64 finishes in CI time; same case otherwise
    return build_canonical_sim(levelmax=6, levelstart=3,
                               adapt_steps=10, dtype="float64")


def run_trajectory():
    sim = build_sim()
    sim.initialize()
    rec = {}
    for _ in range(max(CHECK_STEPS)):
        if sim.step_count <= 10 or sim.step_count % sim.cfg.adapt_steps == 0:
            sim.adapt()
        diag = sim.step_once()
        if sim.step_count in CHECK_STEPS:
            rec[str(sim.step_count)] = {
                "time": float(sim.time),
                "umax": float(diag["umax"]),
                "poisson_iters": int(diag["poisson_iters"]),
                "n_blocks": len(sim.forest.blocks),
                "fish": [
                    {"com": [float(s.com[0]), float(s.com[1])],
                     "u": float(s.u), "v": float(s.v),
                     "omega": float(s.omega)}
                    for s in sim.shapes
                ],
            }
    return rec


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--write", action="store_true")
    args = ap.parse_args()
    rec = run_trajectory()
    print(json.dumps(rec, indent=1))
    if args.write:
        with open(GOLDEN_PATH, "w") as f:
            json.dump(rec, f, indent=1, sort_keys=True)
        print(f"wrote {GOLDEN_PATH}")


if __name__ == "__main__":
    main()
