"""Collision golden trajectory (VERDICT r3 #8).

The collision model (reference main.cpp:236-291 impulse math,
6705-6943 detection/response) has invariant tests (momentum exchange,
receding pairs untouched) — but a sign error that happens to be
symmetric would pass them. This pins the ACTUAL trajectory of two free
disks driven onto a collision course through contact: per-step rigid
states (com, u, v, omega) of both bodies on CPU f64, recorded to
tests/golden_collision.json by `--write` and replayed by
tests/test_golden_collision.py.

The disks are set moving by seeding the FLUID with rigid-motion blobs
(the penalization momentum solve derives body velocity from the flow,
so seeding the bodies alone would not move them); the generator asserts
a genuine approach->contact->rebound happened, so the golden can never
silently pin a miss.

The window is 6 steps: approach at full speed (step 0), the e=1
impulse exchange (step 1: closing du = -0.82 flips to receding +0.21),
and four post-impulse steps. It deliberately ENDS while the bodies are
still distinct (min gap ~0.012): past that the converging seeded flow
pushes the pair into quasi-static deep interpenetration, a regime the
reference's approach-only impulse model leaves undefined (its
chi-integral CoM recentring, main.cpp:4472-4630, then drags both
measured centers to the midpoint — measured here, single-disk control
shows <= 5e-4 drift, so it is overlap-specific and inherited from the
model, not a raster bug).

    JAX_PLATFORMS=cpu python -m validation.golden_collision --write
"""

from __future__ import annotations

import argparse
import json
import os

GOLDEN_PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "tests", "golden_collision.json")

N_STEPS = 6


def _force_cpu_x64():
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)


def build_sim():
    _force_cpu_x64()
    import jax.numpy as jnp
    import numpy as np

    from cup2d_tpu.amr import AMRSim
    from cup2d_tpu.config import SimConfig
    from cup2d_tpu.models import DiskShape

    cfg = SimConfig(bpdx=1, bpdy=1, level_max=3, level_start=2,
                    extent=1.0, dtype="float64", nu=2e-4, lam=1e6,
                    cfl=0.4, rtol=1e9, ctol=-1.0,
                    max_poisson_iterations=60, poisson_tol=1e-6,
                    poisson_tol_rel=1e-4)
    r = 0.06
    sim = AMRSim(cfg, shapes=[DiskShape(r, 0.42, 0.5),
                              DiskShape(r, 0.58, 0.5)])
    sim.compute_forces_every = 0
    sim.initialize()

    # rigid-motion velocity blobs around each disk (established
    # seeding pattern: sync then rewrite the slot fields)
    sim.sync_fields()
    f = sim.forest
    order = f.order()
    bs = cfg.bs
    h = f.h_per_block(order)
    ar = np.arange(bs) + 0.5
    xc = (f.bi[order].astype(np.float64) * bs * h)[:, None, None] \
        + ar[None, None, :] * h[:, None, None]
    yc = (f.bj[order].astype(np.float64) * bs * h)[:, None, None] \
        + ar[None, :, None] * h[:, None, None]
    vel = np.array(f.fields["vel"])
    u0 = 0.6
    blob = np.zeros((len(order), bs, bs))
    for (cx, cy, uu) in ((0.42, 0.5, u0), (0.58, 0.5, -u0)):
        rr2 = (xc - cx) ** 2 + (yc - cy) ** 2
        blob += uu * np.exp(-rr2 / (2.0 * (1.0 * r) ** 2))
    vel[order, 0] = blob
    vel[order, 1] = 0.0
    f.fields["vel"] = jnp.asarray(vel)
    return sim


def run_trajectory():
    sim = build_sim()
    rec = {"steps": []}
    for _ in range(N_STEPS):
        # fixed dt: the CFL dt balloons as the blobs decay, and a
        # pinned trajectory should not owe its step times to umax noise
        sim.step_once(dt=0.008)
        rec["steps"].append({
            "time": float(sim.time),
            "bodies": [
                {"com": [float(s.com[0]), float(s.com[1])],
                 "u": float(s.u), "v": float(s.v),
                 "omega": float(s.omega)}
                for s in sim.shapes
            ],
        })
    # the run must contain a real collision: the pair approaches
    # (du = u1 - u0 < 0 while closing) and then rebounds (du > 0)
    du = [st["bodies"][1]["u"] - st["bodies"][0]["u"]
          for st in rec["steps"]]
    gap = [st["bodies"][1]["com"][0] - st["bodies"][0]["com"][0]
           for st in rec["steps"]]
    assert min(du) < -0.05, f"bodies never approached: {du}"
    assert max(du[du.index(min(du)):]) > 0.0, \
        f"no rebound after closest approach: {du}"
    assert min(gap) < gap[0], "gap never closed"
    rec["min_gap"] = min(gap)
    return rec


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--write", action="store_true")
    args = ap.parse_args()
    rec = run_trajectory()
    print(json.dumps(rec, indent=1))
    if args.write:
        with open(GOLDEN_PATH, "w") as f:
            json.dump(rec, f, indent=1, sort_keys=True)
        print(f"wrote {GOLDEN_PATH}")


if __name__ == "__main__":
    main()
