"""Comm-scaling audit at REALISTIC occupancy (VERDICT r4 #6).

The r4 ppermute-vs-allgather table (BASELINE.md) was measured on the
~20-block disk case — under one block per shard at 32 devices, so the
"near-flat per-device bytes" row was dominated by fragmentation, not a
real boundary-to-volume ratio. This audit re-measures on the 1e4-block
synthetic vortex forest (hundreds of blocks per shard), adding 64
devices:

  phase A (TPU or CPU, once):  grow the synthetic forest to >= 1e4
      blocks exactly like validation/device_time.py, then checkpoint it
      (topology + fields) to --state DIR.
  phase B (CPU, per device count / exchange mode): restore the
      checkpoint into a ShardedAMRSim on an N-virtual-device mesh and
      STATICALLY compile the production step with XLA HLO dumping on
      (jit .lower().compile() — no execution, so 64-device audits don't
      need to run a 64-way step on one core), then sum the collective
      bytes per optimized module exactly like validation/comm_audit.py.
      SPMD-lowered HLO shapes are per-device, so the reported MB are
      per-device directly.

  python -m validation.comm_audit_scale --grow            # phase A
  python -m validation.comm_audit_scale --devices 8 16 32 64  # phase B

Prints one JSON line (phase B) with per-device collective MB per mode.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile

STATE_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         "_comm_scale_state")


def grow(target: int, levelmax: int):
    from types import SimpleNamespace

    from cup2d_tpu.cache import enable_compilation_cache
    enable_compilation_cache()
    from cup2d_tpu.io import save_checkpoint
    from validation.scale_proof import _synthetic_sim

    sim = _synthetic_sim(SimpleNamespace(levelmax=levelmax, rtol=0.1))
    steps = 0
    while len(sim.forest.blocks) < target and steps < 40:
        sim.adapt()
        sim.step_once()
        steps += 1
    save_checkpoint(STATE_DIR, sim)
    print(json.dumps({"grown_blocks": len(sim.forest.blocks),
                      "steps": steps, "state": STATE_DIR}))


def audit_one(n_dev: int, mode: str, levelmax: int,
              two_level: bool) -> dict:
    """Run in a SUBPROCESS (backend flags must be set pre-init)."""
    code = f"""
import os, json
os.environ["CUP2D_SHARD_EXCHANGE"] = {mode!r}
dump = os.environ["AUDIT_DUMP"]
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count={n_dev}"
    + " --xla_dump_to=" + dump
    + " --xla_dump_hlo_pass_re=").strip()
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
from types import SimpleNamespace
from cup2d_tpu.io import load_checkpoint
from cup2d_tpu.parallel.forest_mesh import ShardedAMRSim
from cup2d_tpu.parallel.mesh import make_mesh
from validation.scale_proof import _synthetic_sim
from validation.comm_audit_scale import STATE_DIR

base = _synthetic_sim(SimpleNamespace(levelmax={levelmax}, rtol=0.1))
sim = ShardedAMRSim(base.cfg, make_mesh({n_dev}), shapes=[])
load_checkpoint(STATE_DIR, sim)
sim._refresh()
ordf = sim._ordered_state()
f = sim.forest
dt = jnp.asarray(1e-4, f.dtype)
tc = None
if {two_level!r}:
    sim._build_coarse_maps(sim._npad_hwm, sim._n_real)
    tc = sim._coarse_cw
lowered = sim._step_jit.lower(
    ordf["vel"], ordf["pres"], dt, sim._h, sim._hsq_flat,
    sim._maskv, sim._tables["vec3"], sim._tables["vec1"],
    sim._tables["sca1"], sim._tables["pois"], sim._corr, tc,
    exact_poisson=False)
lowered.compile()
print(json.dumps({{"n_blocks": len(f.blocks),
                   "n_pad": int(sim._npad_hwm)}}))
"""
    with tempfile.TemporaryDirectory(prefix="hlo_scale_") as dump:
        env = dict(os.environ)
        env["AUDIT_DUMP"] = dump
        env.pop("JAX_PLATFORMS", None)
        r = subprocess.run([sys.executable, "-c", code], env=env,
                           capture_output=True, text=True,
                           cwd="/root/repo", timeout=3600)
        if r.returncode != 0:
            return {"error": r.stderr[-2000:]}
        meta = json.loads(r.stdout.strip().splitlines()[-1])
        from validation.comm_audit import audit_dump_dir
        mods = audit_dump_dir(dump)
    # only the STEP module matters (the audit compiles exactly one)
    step_mod = {}
    for label, entry in mods.items():
        if "_step_impl" in label:
            step_mod = entry
    total = {"bytes": 0, "count": 0}
    per_op = {}
    for op, e in step_mod.items():
        per_op[op] = {"count": e["count"],
                      "mb": round(e["bytes"] / 1e6, 4)}
        total["bytes"] += e["bytes"]
        total["count"] += e["count"]
    return {**meta, "per_device_mb": round(total["bytes"] / 1e6, 4),
            "collectives": per_op}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--grow", action="store_true")
    ap.add_argument("--target", type=int, default=10000)
    ap.add_argument("--levelmax", type=int, default=8)
    ap.add_argument("--devices", type=int, nargs="+",
                    default=[8, 16, 32, 64])
    ap.add_argument("--two-level", action="store_true",
                    help="audit with the coarse correction engaged")
    args = ap.parse_args()
    if args.grow:
        grow(args.target, args.levelmax)
        return
    out = {}
    for n in args.devices:
        for mode in ("ppermute", "allgather"):
            key = f"{n}dev_{mode}"
            out[key] = audit_one(n, mode, args.levelmax, args.two_level)
            print(f"{key}: {out[key].get('per_device_mb', 'ERR')} "
                  f"MB/device", file=sys.stderr)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
