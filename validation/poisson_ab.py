"""Forest Poisson solve-path A/B: production iters/step per path.

Builds a near-uniform obstacle-free forest at a chosen block count,
seeds a multi-scale velocity field (the bench_state recipe on the
forest), and measures ONE production solve (cold deltap — the
worst-case production RHS) plus a short warm train under each solve
path:

  jacobi    block-Jacobi only (trigger off — the sub-15-iters default)
  additive  two-level additive (the round-5 production form, forced on)
  mult      two-level multiplicative (coarse first, BJ post)
  mg2       two-grid cycle: BJ pre-smooth + spectral base-level
            correction + BJ post-smooth (the CUP2D_POIS=fft form)
  fas       forest-native FAS multigrid as the FULL solver over the
            forest's own refinement levels (the CUP2D_POIS=fas form —
            iters are mg_solve CYCLES, ~half the per-unit cost of a
            preconditioned Krylov iteration)
  fas-f     same hierarchy, every solve opened base-level-first
            (CUP2D_POIS=fas-f)
  fas-bf16leg
            the memory-tiered cycle (ISSUE 19): same fas hierarchy
            with the window-image ladder legs stored bf16
            (CUP2D_PREC=bf16 + CUP2D_POIS=fas in production; pinned
            directly here like the other arms). mg_solve's outer loop
            keeps the solver-precision true residual, so the
            acceptance claim is iters within +1 of the fas arm at the
            SAME convergence criterion

Iteration counts are platform-independent (the loop is the same XLA
program everywhere), so this probe runs anywhere; ms/step numbers are
only meaningful on the production rig. Usage:

    python -m validation.poisson_ab [--bpd 8] [--steps 4] [--out F]

Prints one JSON line per path: {path, n_blocks, iters (per step),
residual, converged}; ``--out`` additionally records the arms + probe
metadata as one provenance JSON (the BASELINE round-10 record at the
1e4-block probe is validation/poisson_ab_r10.json).
"""

from __future__ import annotations

import argparse
import json

import numpy as np


def _seed_multiscale(sim):
    """Seed the bench's multi-scale divergence-bearing field, each
    active block sampled analytically at its OWN resolution."""
    import jax.numpy as jnp

    f = sim.forest
    cfg = sim.cfg
    bs = cfg.bs
    vals = np.zeros((f.capacity, 2, bs, bs))
    n1d = cfg.bpdx * bs << cfg.level_start
    m = max(n1d // 64, 8)
    for (l, i, j), s in f.blocks.items():
        h = cfg.h_at(l)
        x = (i * bs + np.arange(bs) + 0.5) * h
        y = (j * bs + np.arange(bs) + 0.5) * h
        X, Y = np.meshgrid(x, y, indexing="xy")
        xs, ys = np.pi * X, np.pi * Y
        vals[s, 0] = (np.sin(xs) * np.cos(ys)
                      + 0.25 * np.sin(8 * xs) * np.cos(8 * ys)
                      + 0.3 * np.sin(m * xs) * np.sin(m * ys))
        vals[s, 1] = (-np.cos(xs) * np.sin(ys)
                      + 0.25 * np.sin(16 * ys) * np.sin(16 * xs)
                      + 0.3 * np.sin(m * ys) * np.sin(m * xs))
    f.fields["vel"] = jnp.asarray(vals, f.dtype)


def build_forest_sim(bpd: int = 8, level_start: int = 2,
                     dtype: str = "float64", tol: float = 1e-3,
                     tol_rel: float = 1e-2):
    """Obstacle-free AMRSim on the uniform level_start grid
    (bpd*2^level_start squared blocks), regridding disabled, seeded
    with the bench's multi-scale divergence-bearing field."""
    from cup2d_tpu.amr import AMRSim
    from cup2d_tpu.config import SimConfig

    cfg = SimConfig(bpdx=bpd, bpdy=bpd, level_max=level_start + 1,
                    level_start=level_start, extent=1.0, nu=4e-5,
                    cfl=0.5, dtype=dtype, rtol=1e9, ctol=-1.0,
                    poisson_tol=tol, poisson_tol_rel=tol_rel,
                    max_poisson_iterations=2000)
    sim = AMRSim(cfg)
    _seed_multiscale(sim)
    sim.step_count = 20          # production regime (no exact override)
    return sim


def _seed_vortex_field(sim):
    """Weak smooth background + two strong localized Gaussian vortices
    (the scale_proof synthetic-vortex recipe at small scale), each
    active block sampled analytically at its OWN resolution — the
    vorticity tagging then refines ONLY the vortex neighborhoods, so
    the resulting forest is genuinely multi-level."""
    import jax.numpy as jnp

    f = sim.forest
    cfg = sim.cfg
    bs = cfg.bs
    vals = np.zeros((f.capacity, 2, bs, bs))
    centers = [(0.31, 0.62, 0.030, 0.8), (0.68, 0.37, 0.045, -0.6)]
    for (l, i, j), s in f.blocks.items():
        h = cfg.h_at(l)
        x = (i * bs + np.arange(bs) + 0.5) * h
        y = (j * bs + np.arange(bs) + 0.5) * h
        X, Y = np.meshgrid(x, y, indexing="xy")
        xs, ys = np.pi * X, np.pi * Y
        u = 0.2 * np.sin(xs) * np.cos(ys)
        v = -0.2 * np.cos(xs) * np.sin(ys)
        for cx, cy, sg, g in centers:
            dx, dy = X - cx, Y - cy
            r2 = dx * dx + dy * dy
            ut = g / (2 * np.pi * np.sqrt(r2 + 1e-8)) \
                * (1 - np.exp(-r2 / (2 * sg ** 2)))
            th = np.arctan2(dy, dx)
            u += -ut * np.sin(th)
            v += ut * np.cos(th)
        vals[s, 0] = u
        vals[s, 1] = v
    f.fields["vel"] = jnp.asarray(vals, f.dtype)


def build_multilevel_sim(bpd: int = 4, level_start: int = 1,
                         level_max: int = 5, dtype: str = "float64",
                         tol: float = 1e-3, tol_rel: float = 1e-2,
                         rtol: float = 30.0, rounds: int = 4,
                         sim_cls=None):
    """Small MULTI-LEVEL forest for the forest-FAS arms and tier-1
    agreement tests: seed the vortex field, let the production
    vorticity tagging refine (re-seeding analytically after each
    round so fine blocks carry their own-resolution content), and
    leave the topology wherever the tagging converged — deterministic
    (same seed field + thresholds => same forest), spanning levels on
    BOTH sides of the coarse base level c (= min(3, level_max-1)).
    The A/B drivers never call adapt(), so all arms solve the
    identical forest."""
    from cup2d_tpu.amr import AMRSim
    from cup2d_tpu.config import SimConfig

    cfg = SimConfig(bpdx=bpd, bpdy=bpd, level_max=level_max,
                    level_start=level_start, extent=1.0, nu=4e-5,
                    cfl=0.5, dtype=dtype, rtol=rtol, ctol=-1.0,
                    poisson_tol=tol, poisson_tol_rel=tol_rel,
                    max_poisson_iterations=2000)
    sim = (sim_cls or AMRSim)(cfg)
    _seed_vortex_field(sim)
    for _ in range(rounds):
        if not sim.adapt():
            break
        _seed_vortex_field(sim)
    sim.step_count = 20
    return sim


def build_synthetic_sim(target: int, levelmax: int = 8):
    """The BASELINE.md 1e4-block-regime forest (scale_proof's synthetic
    vortices on the canonical domain, levelStart 6), adapted until
    ``target`` blocks are active — the same topology class the r4/r5
    production-iteration numbers were measured on."""
    from types import SimpleNamespace

    from validation.scale_proof import _synthetic_sim

    sim = _synthetic_sim(SimpleNamespace(levelmax=levelmax, rtol=0.05))
    while len(sim.forest.blocks) < target and sim.adapt():
        pass
    sim.step_count = 20
    return sim


def run_path(path: str, bpd: int, steps: int, synthetic: int = 0,
             levelmax: int = 8, multilevel: bool = False) -> dict:
    """Fresh sim per path so no state leaks between arms."""
    if synthetic:
        sim = build_synthetic_sim(synthetic, levelmax)
    elif multilevel:
        sim = build_multilevel_sim(bpd=bpd)
    else:
        sim = build_forest_sim(bpd=bpd)
    # build tables/maps BEFORE pinning the path: _refresh_impl re-arms
    # the trigger (coarse_on = False), which would silently turn the
    # first measured solve into the jacobi arm on every path
    sim._refresh()
    if path == "jacobi":
        sim._coarse_on = False       # the trigger-off default
        use = False
    elif path in ("fas", "fas-f", "fas-bf16leg"):
        # the forest-FAS full-solve arms: pin the CUP2D_POIS latch
        # slot directly (fresh sim, first trace sees it — the same
        # post-construction pinning discipline as _twolevel_form) and
        # force-engage the hierarchy maps like _use_coarse would.
        # fas-bf16leg additionally pins the ISSUE-19 leg-dtype latch
        # (production: CUP2D_PREC=bf16 at construction)
        sim._pois_mode = "fas" if path == "fas-bf16leg" else path
        if path == "fas-bf16leg":
            import jax.numpy as jnp
            sim._fas_leg_dtype = jnp.bfloat16
        sim._coarse_on = True
        use = True
    else:
        sim._twolevel_form = path    # the latched A/B slot
        sim._coarse_on = True        # force-engage the correction
        use = True
    iters, res, conv = [], [], []
    dt = None
    for _ in range(steps):
        # keep the trigger state pinned: this is an A/B arm, the
        # sticky iters>15 trigger must not flip it mid-train. Pinning
        # _coarse_on alone is NOT enough — _use_coarse re-engages off
        # sim._last_iters (>15 after any rough step), which would
        # silently turn the jacobi arm's steps 2..N into two-level
        # measurements — so the trigger EVIDENCE is zeroed too.
        sim._coarse_on = use
        sim._last_iters = 0
        sim._last_iters_dev = None
        d = sim.step_once(dt)
        iters.append(int(d["poisson_iters"]))
        res.append(float(d["poisson_residual"]))
        conv.append(bool(d["poisson_converged"]))
    return {
        "path": path,
        "n_blocks": int(sim._n_real),
        "smoother_tier": sim.smoother_tier,
        "iters": iters,
        "residual": res,
        "converged": conv,
    }


def main():
    import jax
    jax.config.update("jax_enable_x64", True)
    ap = argparse.ArgumentParser()
    ap.add_argument("--bpd", type=int, default=8)
    ap.add_argument("--steps", type=int, default=4)
    ap.add_argument("--paths",
                    default="jacobi,additive,mult,mg2,fas,fas-f,"
                            "fas-bf16leg")
    ap.add_argument("--synthetic", type=int, default=0,
                    help="use the BASELINE 1e4-regime synthetic forest "
                         "adapted to >= this many blocks")
    ap.add_argument("--levelmax", type=int, default=8)
    ap.add_argument("--multilevel", action="store_true",
                    help="use the small multi-level forest "
                         "(build_multilevel_sim) instead of the "
                         "near-uniform one")
    ap.add_argument("--out", default="",
                    help="also record the arms + probe metadata as one "
                         "provenance JSON file")
    args = ap.parse_args()
    arms = []
    for path in args.paths.split(","):
        rec = run_path(path, args.bpd, args.steps,
                       synthetic=args.synthetic,
                       levelmax=args.levelmax,
                       multilevel=args.multilevel)
        arms.append(rec)
        print(json.dumps(rec), flush=True)
    if args.out:
        import platform
        with open(args.out, "w") as fh:
            json.dump({
                "probe": {"bpd": args.bpd, "steps": args.steps,
                          "synthetic": args.synthetic,
                          "levelmax": args.levelmax,
                          "multilevel": args.multilevel,
                          "machine": platform.machine(),
                          "backend": jax.default_backend()},
                "arms": arms,
            }, fh, indent=1)
            fh.write("\n")


if __name__ == "__main__":
    main()
