"""The ONE definition of the canonical two-fish case for validation
tooling (run.sh, /root/reference/run.sh:1-22). device_time, trace_ops
and golden all measure/pin THIS case — a flag drifting in one copy
would silently make them describe different physics (ADVICE r3)."""

from __future__ import annotations


def canonical_flags(levelmax: int = 8, levelstart: int = 5,
                    adapt_steps: int = 20, dtype: str = "float32",
                    rtol: float = 2.0, ctol: float = 1.0):
    flags = (
        "-AdaptSteps {a} -bpdx 2 -bpdy 1 -CFL 0.5 -Ctol {ct} -extent 4 "
        "-lambda 1e7 -levelMax {lm} -levelStart {ls} "
        "-maxPoissonIterations 1000 -maxPoissonRestarts 0 -nu 0.00004 "
        "-poissonTol 1e-3 -poissonTolRel 1e-2 -Rtol {rt} -tdump 0 "
        "-tend 10.0 -dtype {dt}"
    ).format(a=adapt_steps, lm=levelmax, ls=levelstart, dt=dtype,
             rt=rtol, ct=ctol).split()
    return flags + [
        "-shapes",
        "angle=0 L=0.2 xpos=1.8 ypos=0.8\n"
        "angle=180 L=0.2 xpos=1.6 ypos=0.8",
    ]


def build_canonical_sim(levelmax: int = 8, levelstart: int = 5,
                        adapt_steps: int = 20, dtype: str = "float32",
                        rtol: float = 2.0, ctol: float = 1.0):
    from cup2d_tpu.amr import AMRSim
    from cup2d_tpu.config import SimConfig
    from cup2d_tpu.sim import make_shapes

    cfg = SimConfig.from_argv(
        canonical_flags(levelmax, levelstart, adapt_steps, dtype,
                        rtol, ctol))
    sim = AMRSim(cfg, shapes=make_shapes(cfg))
    sim.compute_forces_every = 0
    return sim
