"""Op-level device-time breakdown via the JAX profiler (VERDICT r2 #2/#3).

Captures a real profiler trace of either the canonical adaptive
megastep (--mode mega) or the uniform 8192^2 projection step
(--mode uniform) on the attached chip, then parses the xplane protobuf
with tensorboard_plugin_profile into per-op device totals — the
trace-backed evidence the round-2 verdict demanded in place of the
analytic flop/byte model.

    python -m validation.trace_ops --mode uniform --size 8192
    python -m validation.trace_ops --mode mega --levelmax 8
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import tempfile
import time


def _fence(x) -> float:
    return float(x.reshape(-1)[0])


def capture_uniform(size: int, trace_dir: str, reps: int):
    import jax
    import jax.numpy as jnp

    from cup2d_tpu.cache import enable_compilation_cache
    enable_compilation_cache()
    from bench import bench_state  # repo-root bench helpers
    from cup2d_tpu.config import SimConfig
    from cup2d_tpu.uniform import UniformGrid

    level = 0
    cfg = SimConfig(bpdx=size // 8, bpdy=size // 8, level_max=1,
                    level_start=0, extent=1.0, nu=1e-5, cfl=0.45,
                    dtype="float32", poisson_tol=1e-3,
                    poisson_tol_rel=1e-2, max_poisson_iterations=1000)
    grid = UniformGrid(cfg, level=level)
    state = bench_state(grid)
    dt = jnp.asarray(1e-4, grid.dtype)
    step = jax.jit(lambda s: grid.step(s, dt, obstacle_terms=False)[0],
                   donate_argnums=(0,))
    # warm until the deltap initial guess coasts (bench.py's production
    # regime: ~0.5 Poisson iterations/step) so the trace shows the
    # steady-state composition, not a cold pressure solve
    for _ in range(8):
        state = step(state)
    _fence(state.vel)
    with jax.profiler.trace(trace_dir):
        s = state
        for _ in range(reps):
            s = step(s)
        _fence(s.vel)


def capture_mega(levelmax: int, trace_dir: str, reps: int):
    import jax
    import jax.numpy as jnp

    from cup2d_tpu.cache import enable_compilation_cache
    enable_compilation_cache()
    from validation.canonical import build_canonical_sim

    sim = build_canonical_sim(levelmax=levelmax)
    cfg = sim.cfg
    sim.initialize()
    for _ in range(30):
        if sim.step_count <= 10 or sim.step_count % cfg.adapt_steps == 0:
            sim.adapt()
        sim.step_once()
    sim._refresh()
    ordf = sim._ordered_state()
    inputs = sim._shape_inputs()
    f = sim.forest
    prescribed = jnp.asarray(
        [[s.u, s.v, s.omega] for s in sim.shapes], dtype=f.dtype)
    dt = jnp.asarray(sim._next_dt or sim.compute_dt(), f.dtype)
    hmin = jnp.asarray(cfg.h_at(int(f.level[sim._order].max())), f.dtype)

    def mega(vel, pres):
        return sim._mega_jit(
            vel, pres, inputs, prescribed, dt, hmin,
            sim._h, sim._hsq_flat, sim._maskv, sim._xc, sim._yc,
            sim._tables["vec3"], sim._tables["vec1"],
            sim._tables["sca1"], sim._tables["pois"],
            sim._tables.get("vec4t"), sim._tables.get("sca4t"),
            sim._corr, None, exact_poisson=False, with_forces=False)

    v, p = ordf["vel"], ordf["pres"]
    out = mega(v, p)
    _fence(out[0])
    with jax.profiler.trace(trace_dir):
        for _ in range(reps):
            v, p, _, scal, _ = mega(v, p)
        _fence(v)
    print(json.dumps({"n_blocks": len(sim.forest.blocks),
                      "n_pad": int(sim._npad_hwm)}))


def parse_trace(trace_dir: str, reps: int, top: int = 40):
    """Per-op device totals straight from the xplane protobuf (the
    tensorboard_plugin_profile converter in this image predates its TF
    pywrap API, so walk planes/lines/events directly)."""
    from tensorflow.tsl.profiler.protobuf import xplane_pb2

    paths = glob.glob(os.path.join(
        trace_dir, "plugins", "profile", "*", "*.xplane.pb"))
    assert paths, f"no xplane under {trace_dir}"
    xs = xplane_pb2.XSpace()
    xs.ParseFromString(open(paths[0], "rb").read())
    plane = next(p for p in xs.planes if p.name.startswith("/device:"))
    em = plane.event_metadata
    mod_ps = 0
    agg: dict = {}
    for line in plane.lines:
        for ev in line.events:
            name = em[ev.metadata_id].name
            if line.name == "XLA Modules":
                mod_ps += ev.duration_ps
                continue
            if line.name not in ("XLA Ops", "Async XLA Ops"):
                continue
            # strip the %op.NN id so occurrences aggregate by kind+shape
            label = name.split(" = ", 1)[-1][:100]
            d = agg.setdefault(label, [0, 0])
            d[0] += ev.duration_ps
            d[1] += 1
    print(f"device module time: {mod_ps/1e9:.2f} ms over {reps} reps "
          f"=> {mod_ps/1e9/reps:.3f} ms/rep")
    for label, (ps, occ) in sorted(
            agg.items(), key=lambda kv: -kv[1][0])[:top]:
        print(f"{ps/1e9/reps:9.3f} ms/rep  x{occ:<6d} {label}")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--mode", choices=("uniform", "mega"), required=True)
    ap.add_argument("--size", type=int, default=8192)
    ap.add_argument("--levelmax", type=int, default=8)
    ap.add_argument("--reps", type=int, default=5)
    ap.add_argument("--trace-dir", default=None)
    ap.add_argument("--parse-only", default=None)
    args = ap.parse_args()
    if args.parse_only:
        parse_trace(args.parse_only, args.reps)
        return
    trace_dir = args.trace_dir or tempfile.mkdtemp(prefix="cup2d_trace_")
    t0 = time.perf_counter()
    if args.mode == "uniform":
        capture_uniform(args.size, trace_dir, args.reps)
    else:
        capture_mega(args.levelmax, trace_dir, args.reps)
    print(f"captured in {time.perf_counter()-t0:.1f} s -> {trace_dir}")
    parse_trace(trace_dir, args.reps)


if __name__ == "__main__":
    main()
