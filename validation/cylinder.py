"""Quantitative physics validation: towed cylinder drag + Strouhal.

The reference validates by eye (SURVEY.md §4: smoke runs + rendered
dumps); these runnable cases pin the solver to published numbers
instead. Both tow a rigid disk through still fluid — the closed
free-slip box (the reference's only BC, main.cpp:3126-3256) cannot
sustain a stream, so towing is the Galilean twin of flow past a fixed
body, exactly like the reference's self-propelled fish.

    python -m validation.cylinder drag      # Re=40 steady drag, ~10 min
    python -m validation.cylinder dragwide  # same at half blockage
    python -m validation.cylinder strouhal  # Re=200 shedding, ~30 min

Published references: Cd(Re=40) ~ 1.5-1.6 unbounded (Tritton 1959);
St(Re=200) ~ 0.19-0.20 (Williamson 1989). Blockage inflates both a few
percent. Measured on a v5e chip: see BASELINE.md.
"""

from __future__ import annotations

import io
import sys
import time

import numpy as np


def _build(D, U, nu, level, xpos, forces_every, bpdy=1):
    # the case registry (cases.py) owns the config/shape recipe now;
    # this probe just adds the force-log plumbing it measures with
    from cup2d_tpu.cache import enable_compilation_cache
    from cup2d_tpu.cases import make_sim

    enable_compilation_cache()
    sim = make_sim("cylinder", D=D, U=U, nu=nu, level=level, xpos=xpos,
                   bpdy=bpdy)
    sim.compute_forces_every = forces_every
    sim.force_log = io.StringIO()
    sim.initialize()
    return sim


def _force_table(sim):
    rows = sim.force_log.getvalue().strip().splitlines()
    return np.array([[float(c) for c in row.split(",")] for row in rows])


def drag(bpdy=1):
    """Re = 40: steady drag coefficient from the surface-traction
    diagnostics, averaged over the quasi-steady window. ``bpdy=2``
    doubles the transverse extent (blockage 10% -> 5%) — the domain-size
    study that pins the blockage correction the round-2 Cd leaned on
    (VERDICT r2 weak #7)."""
    D, U, nu = 0.1, 0.2, 5e-4
    sim = _build(D, U, nu, level=5, xpos=3.2, forces_every=5,
                 bpdy=bpdy)  # 1024 x 256*bpdy
    t0 = time.perf_counter()
    while sim.time < 6.0 and sim.shapes[0].com[0] > 0.5:
        sim.step_once()
    data = _force_table(sim)
    t, fx = data[:, 0], data[:, 4]
    m = (t > 4.5)
    cd = float(np.mean(fx[m]) / (0.5 * U * U * D))
    print(f"steps={sim.step_count} wall={time.perf_counter()-t0:.0f}s "
          f"Cd={cd:.3f}  (lit unbounded 1.5-1.6; ~10% blockage here)")
    return cd


def strouhal():
    """Re = 200: vortex-shedding frequency from the lift oscillation.
    A small transverse vortical kick behind the body breaks symmetry so
    shedding saturates within the tow distance."""
    import jax.numpy as jnp

    D, U, nu = 0.05, 0.2, 5e-5
    sim = _build(D, U, nu, level=6, xpos=3.5, forces_every=4)  # 2048x512
    x, y = sim.grid.cell_centers()
    r2 = ((x - 3.56) ** 2 + (y - 0.515) ** 2) / (0.5 * D) ** 2
    vel = np.array(sim.state.vel)   # copy: device views are read-only
    vel[1] += (0.04 * np.exp(-r2)).astype(vel.dtype)
    sim.state = sim.state._replace(
        vel=jnp.asarray(vel, sim.grid.dtype))
    t0 = time.perf_counter()
    while sim.time < 15.0 and sim.shapes[0].com[0] > 0.4:
        sim.step_once()
    data = _force_table(sim)
    t, fy = data[:, 0], data[:, 5]
    m = t > 5.0
    fy_w = fy[m] - fy[m].mean()
    dtm = float(np.median(np.diff(t[m])))
    freqs = np.fft.rfftfreq(len(fy_w), dtm)
    amp = np.abs(np.fft.rfft(fy_w * np.hanning(len(fy_w))))
    fpk = float(freqs[1 + np.argmax(amp[1:])])
    st = fpk * D / U
    print(f"steps={sim.step_count} wall={time.perf_counter()-t0:.0f}s "
          f"lift_rms={float(fy_w.std()):.2e} f={fpk:.4f} "
          f"St={st:.4f}  (lit 0.19-0.20)")
    return st


def main(argv=None) -> int:
    args = sys.argv[1:] if argv is None else argv
    which = args[0] if args else "drag"
    if which == "drag":
        drag()
    elif which == "dragwide":
        drag(bpdy=2)
    elif which == "strouhal":
        strouhal()
    else:
        print("usage: python -m validation.cylinder [drag|strouhal]",
              file=sys.stderr)
        return 2
    return 0


if __name__ == "__main__":
    sys.exit(main())
