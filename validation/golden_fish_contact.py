"""Deforming-body (fish-fish) contact golden (VERDICT r4 #5).

The two-disk golden (validation/golden_collision.py) pins the impulse
math through a rigid contact, but the canonical case's actual event is
a FISH-fish head-on encounter — deforming bodies, where the
chi-overlap integrals and skin normals are most stressed
(reference main.cpp:6705-6943 detection/response on the swimmers of
run.sh). This pins that event: two fish driven nose-to-nose by seeded
rigid-motion flow blobs on a coarse AMR forest (CPU f64, levelMax 4 —
the smallest resolution whose finest cells resolve the fish width),
recording per-step rigid states AND per-shape surface forces across
the impulse to tests/golden_fish_contact.json.

The generator asserts the window contains a genuine approach ->
impulse -> recede sequence (closing du ~ -0.24 flips to ~ +0.24 in one
step, the e=1 signature), so the golden can never silently pin a miss.

    JAX_PLATFORMS=cpu python -m validation.golden_fish_contact --write
"""

from __future__ import annotations

import argparse
import io
import json
import os

GOLDEN_PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "tests", "golden_fish_contact.json")

N_STEPS = 12
DT = 0.008


def _force_cpu_x64():
    os.environ["JAX_PLATFORMS"] = "cpu"
    # this case's tight tolerances engage the production two-level
    # trigger, so an ambient CUP2D_TWOLEVEL from the A/B workflow
    # would silently record/replay the wrong preconditioner form
    os.environ.pop("CUP2D_TWOLEVEL", None)
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)


def build_sim():
    _force_cpu_x64()
    import jax.numpy as jnp
    import numpy as np

    from cup2d_tpu.amr import AMRSim
    from cup2d_tpu.config import SimConfig
    from cup2d_tpu.models.fish import FishShape

    cfg = SimConfig(bpdx=2, bpdy=1, level_max=4, level_start=3,
                    extent=1.0, dtype="float64", nu=2e-4, lam=1e6,
                    cfl=0.4, rtol=1e9, ctol=-1.0,
                    max_poisson_iterations=60, poisson_tol=1e-6,
                    poisson_tol_rel=1e-4)
    L = 0.3
    fa = FishShape(L, 0.66, 0.25, 180.0, cfg.min_h)
    fb = FishShape(L, 0.34, 0.25, 0.0, cfg.min_h)
    sim = AMRSim(cfg, shapes=[fa, fb])
    sim.compute_forces_every = 1
    sim.force_log = io.StringIO()
    sim.initialize()

    # rigid-motion flow blobs drive the pair together (the momentum
    # solve derives body velocity from the flow — same seeding pattern
    # as the disk golden)
    sim.sync_fields()
    f = sim.forest
    order = f.order()
    bs = cfg.bs
    h = f.h_per_block(order)
    ar = np.arange(bs) + 0.5
    xc = (f.bi[order].astype(np.float64) * bs * h)[:, None, None] \
        + ar[None, None, :] * h[:, None, None]
    yc = (f.bj[order].astype(np.float64) * bs * h)[:, None, None] \
        + ar[None, :, None] * h[:, None, None]
    vel = np.array(f.fields["vel"])
    u0 = 0.6
    blob = np.zeros((len(order), bs, bs))
    for (cx, cy, uu) in ((0.66, 0.25, -u0), (0.34, 0.25, u0)):
        rr2 = (xc - cx) ** 2 + (yc - cy) ** 2
        blob += uu * np.exp(-rr2 / (2.0 * (0.5 * L) ** 2))
    vel[order, 0] = blob
    vel[order, 1] = 0.0
    f.fields["vel"] = jnp.asarray(vel)
    return sim


def run_trajectory():
    sim = build_sim()
    rec = {"steps": []}
    for _ in range(N_STEPS):
        mark = sim.force_log.tell()
        sim.step_once(dt=DT)
        sim.force_log.seek(mark)
        rows = [r.split(",") for r in
                sim.force_log.read().strip().splitlines() if r]
        sim.force_log.seek(0, io.SEEK_END)
        forces = {}
        for r in rows:
            # header: time,shape,perimeter,circulation,forcex,forcey,...
            forces[int(r[1])] = {
                "fx": float(r[4]), "fy": float(r[5]),
                "torque": float(r[10]),
            }
        rec["steps"].append({
            "time": float(sim.time),
            "bodies": [
                {"com": [float(s.com[0]), float(s.com[1])],
                 "u": float(s.u), "v": float(s.v),
                 "omega": float(s.omega),
                 **forces.get(k, {})}
                for k, s in enumerate(sim.shapes)
            ],
        })
    # the window must contain the impulse: the pair closes hard, then
    # the closing velocity REVERSES in one step (e=1 pair impulse)
    du = [st["bodies"][0]["u"] - st["bodies"][1]["u"]
          for st in rec["steps"]]         # negative while closing
    imin = du.index(min(du))
    assert min(du) < -0.15, f"fish never closed hard: {du}"
    assert max(du[imin:]) > 0.05, \
        f"no impulse reversal after closest approach: {du}"
    rec["impulse_step"] = next(
        i for i in range(imin, N_STEPS) if du[i] > 0.05)
    # forces must be live through the event (the surface kernel sees
    # deforming skins in proximity)
    assert any(abs(st["bodies"][0].get("fx", 0.0)) > 0.0
               for st in rec["steps"]), "forces all zero"
    return rec


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--write", action="store_true")
    args = ap.parse_args()
    rec = run_trajectory()
    print(json.dumps(rec, indent=1))
    if args.write:
        with open(GOLDEN_PATH, "w") as f:
            json.dump(rec, f, indent=1, sort_keys=True)
        print(f"wrote {GOLDEN_PATH}")


if __name__ == "__main__":
    main()
