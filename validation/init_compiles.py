"""Count XLA executables compiled during the canonical init climb
(VERDICT r4 #7).

Warm init on the canonical case is ~85 s through the TPU tunnel, and
the cost is per-EXECUTABLE transport (loading a cached executable
through the remote-compile helper costs nearly as much as compiling —
BASELINE.md). The number of distinct executables the levelMax climb
creates is therefore a code property worth measuring and shrinking.

Uses jax_log_compiles: every cache-miss compile (in-process; a
persistent-cache load still pays the tunnel) logs one line. Reports
counts per jitted-function name for (a) the climb (initialize()), and
(b) 3 production steps + 1 regrid afterwards, so climb-only
executables are visible.

    python -m validation.init_compiles [--levelmax 8]
"""

from __future__ import annotations

import argparse
import json
import logging
import re
import time


class _CompileCounter(logging.Handler):
    def __init__(self):
        super().__init__()
        self.events: list[str] = []

    def emit(self, record):
        msg = record.getMessage()
        m = re.search(r"Finished XLA compilation of (?:jit\()?"
                      r"([\w.<>\[\]_-]+)", msg)
        if m:
            self.events.append(m.group(1))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--levelmax", type=int, default=8)
    args = ap.parse_args()

    import jax

    from cup2d_tpu.cache import enable_compilation_cache
    enable_compilation_cache()
    from validation.canonical import build_canonical_sim

    jax.config.update("jax_log_compiles", True)
    counter = _CompileCounter()
    logging.getLogger("jax._src.interpreters.pxla").addHandler(counter)
    logging.getLogger("jax._src.interpreters.pxla").setLevel(logging.DEBUG)
    logging.getLogger("jax._src.dispatch").addHandler(counter)
    logging.getLogger("jax._src.dispatch").setLevel(logging.DEBUG)

    sim = build_canonical_sim(levelmax=args.levelmax)
    t0 = time.perf_counter()
    sim.initialize()
    init_s = time.perf_counter() - t0
    init_events = list(counter.events)
    counter.events.clear()

    t1 = time.perf_counter()
    for _ in range(3):
        sim.step_once()
    sim.adapt()
    sim.step_once()
    post_s = time.perf_counter() - t1
    post_events = list(counter.events)

    def by_name(evs):
        out: dict[str, int] = {}
        for e in evs:
            out[e] = out.get(e, 0) + 1
        return dict(sorted(out.items(), key=lambda kv: -kv[1]))

    print(json.dumps({
        "levelmax": args.levelmax,
        "init_s": round(init_s, 1),
        "init_compiles": len(init_events),
        "init_by_name": by_name(init_events),
        "post_s": round(post_s, 1),
        "post_compiles": len(post_events),
        "post_by_name": by_name(post_events),
        "n_blocks": len(sim.forest.blocks),
        "n_pad": int(sim._npad_hwm),
    }))


if __name__ == "__main__":
    main()
