"""Quantitative physics validation: lid-driven cavity vs Ghia et al.

The canonical wall-bounded benchmark the free-slip-only box could not
express before the BC engine (cup2d_tpu/bc.py): unit box, four no-slip
walls, the top lid translating at U=1, Re = U L / nu = 100. At steady
state the centerline velocity profiles are tabulated to three decimals
in Ghia, Ghia & Shin (J. Comput. Phys. 48, 1982, Table I/II, 129x129
multigrid) — the standard quantitative anchor for incompressible
solvers.

    python -m validation.cavity          # Re=100 at 128^2, ~minutes

Passes when both centerline profiles match Ghia to within 2% of the
lid speed (the acceptance bar in ISSUE 12). Measured numbers live in
BASELINE.md.
"""

from __future__ import annotations

import sys
import time

import numpy as np

# Ghia, Ghia & Shin (1982), Re=100: u along the vertical centerline
# x = 0.5 (Table I) and v along the horizontal centerline y = 0.5
# (Table II), both on the 129x129 grid, endpoints included.
GHIA_Y = np.array([
    0.0000, 0.0547, 0.0625, 0.0703, 0.1016, 0.1719, 0.2813, 0.4531,
    0.5000, 0.6172, 0.7344, 0.8516, 0.9531, 0.9609, 0.9688, 0.9766,
    1.0000])
GHIA_U = np.array([
    0.00000, -0.03717, -0.04192, -0.04775, -0.06434, -0.10150,
    -0.15662, -0.21090, -0.20581, -0.13641, 0.00332, 0.23151,
    0.68717, 0.73722, 0.78871, 0.84123, 1.00000])
GHIA_X = np.array([
    0.0000, 0.0625, 0.0703, 0.0781, 0.0938, 0.1563, 0.2266, 0.2344,
    0.5000, 0.8047, 0.8594, 0.9063, 0.9453, 0.9531, 0.9609, 0.9688,
    1.0000])
GHIA_V = np.array([
    0.00000, 0.09233, 0.10091, 0.10890, 0.12317, 0.16077, 0.17507,
    0.17527, 0.05454, -0.24533, -0.22445, -0.16914, -0.10313,
    -0.08864, -0.07391, -0.05906, 0.00000])


def centerline_profiles(sim):
    """(y, u(x=0.5)) and (x, v(y=0.5)) with the wall/lid boundary
    values appended, from the cell-centered state. The centerlines sit
    on cell faces, so each profile averages the two adjacent center
    columns/rows."""
    grid = sim.grid
    vel = np.asarray(sim.state.vel)
    ny, nx = grid.ny, grid.nx
    h = grid.h
    bc = grid.bc

    yc = (np.arange(ny) + 0.5) * h
    xc = (np.arange(nx) + 0.5) * h
    u_mid = 0.5 * (vel[0][:, nx // 2 - 1] + vel[0][:, nx // 2])
    v_mid = 0.5 * (vel[1][ny // 2 - 1, :] + vel[1][ny // 2, :])

    lid_u = bc.y_hi.u_wall[0]
    y = np.concatenate([[0.0], yc, [ny * h]])
    u = np.concatenate([[0.0], u_mid, [lid_u]])
    x = np.concatenate([[0.0], xc, [nx * h]])
    v = np.concatenate([[0.0], v_mid, [0.0]])
    return (y, u), (x, v)


def run(level: int = 4, re: float = 100.0, t_end: float = 30.0,
        dtype: str = "float32", quiet: bool = False):
    """Run the cavity case to quasi-steady state and compare both
    centerline profiles against Ghia. Returns (err_u, err_v), each the
    max deviation normalized by the lid speed."""
    from cup2d_tpu.cache import enable_compilation_cache
    from cup2d_tpu.cases import make_sim

    enable_compilation_cache()
    sim = make_sim("cavity", level=level, re=re, dtype=dtype)
    t0 = time.perf_counter()
    while sim.time < t_end:
        sim.step_once()
    (y, u), (x, v) = centerline_profiles(sim)
    err_u = float(np.max(np.abs(np.interp(GHIA_Y, y, u) - GHIA_U)))
    err_v = float(np.max(np.abs(np.interp(GHIA_X, x, v) - GHIA_V)))
    if not quiet:
        n = sim.grid.nx
        print(f"cavity Re={re:g} {n}x{n} steps={sim.step_count} "
              f"wall={time.perf_counter() - t0:.0f}s  "
              f"max|u-Ghia|={err_u:.4f} max|v-Ghia|={err_v:.4f} "
              f"(bar: 0.02 of lid speed)")
    return err_u, err_v


def main(argv=None) -> int:
    args = sys.argv[1:] if argv is None else argv
    level = int(args[0]) if args else 4
    err_u, err_v = run(level=level)
    ok = err_u <= 0.02 and err_v <= 0.02
    print("PASS" if ok else "FAIL")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
