"""Scale-proof: drive the forest into the >=1e4-active-block regime and
record per-phase costs (VERDICT r2 #4).

The fully developed run.sh case lives at 1e4-1e5 blocks (SURVEY §6);
round 2 only ever measured ~500. Two modes:

* default: the organic two-fish levelMax-8 case with an aggressive
  refinement threshold (--rtol/--ctol override), stopping at --target
  blocks. Measured round 3: block growth is smooth but slow (~1k blocks
  after 300 steps) — wakes need thousands of steps to demand 1e4.
* --synthetic: dense start — uniform levelStart-6 grid (8,192 blocks)
  + strong seeded vortices refining past 1e4 immediately. This is the
  mode that produced the BASELINE.md 1e4-regime table; the machinery
  whose scaling is in question (halo-table rebuild, regrid commit,
  pad-bucket growth, step at 16k-pad) doesn't care where blocks came
  from. Compression is disabled there: --ctol is rejected, --target
  is ignored (the run holds the regime for --max-steps).

Prints one JSON line per sampled step plus a final summary.

    python -m validation.scale_proof [--target 10000] [--rtol 0.05]
    python -m validation.scale_proof --synthetic [--rtol 0.1] \
        [--max-steps 30]
"""

from __future__ import annotations

import argparse
import json
import time

import numpy as np


def _synthetic_sim(args):
    """Obstacle-free canonical-domain forest that STARTS in the 1e4
    regime: uniform levelStart-6 grid (8,192 blocks) seeded with strong
    vortices whose tags refine past the target. The organic two-fish
    wake needs thousands of steps to demand this many blocks; the
    machinery whose scaling VERDICT r2 #4 questions (table rebuild,
    regrid commit, megastep at 16k-pad, bucket crossings) doesn't care
    where the blocks came from. Compression is disabled (ctol < 0) so
    the measured topology stays in-regime."""
    import jax.numpy as jnp

    from cup2d_tpu.amr import AMRSim
    from cup2d_tpu.config import SimConfig

    cfg = SimConfig(bpdx=2, bpdy=1, level_max=args.levelmax,
                    level_start=6, extent=4.0, dtype="float32",
                    nu=4e-5, cfl=0.5, rtol=args.rtol, ctol=-1.0,
                    poisson_tol=1e-3, poisson_tol_rel=1e-2,
                    max_poisson_iterations=1000, adapt_steps=5)
    sim = AMRSim(cfg, shapes=[])
    f = sim.forest
    order = f.order()
    bs = cfg.bs
    rng = np.random.default_rng(7)
    centers = rng.uniform([0.5, 0.3], [3.5, 1.7], size=(8, 2))
    h = cfg.h0 / (1 << f.level[order]).astype(np.float64)
    x0 = f.bi[order].astype(np.float64) * bs * h
    y0 = f.bj[order].astype(np.float64) * bs * h
    ar = np.arange(bs) + 0.5
    X = np.broadcast_to(
        x0[:, None, None] + ar[None, None, :] * h[:, None, None],
        (len(order), bs, bs))
    Y = np.broadcast_to(
        y0[:, None, None] + ar[None, :, None] * h[:, None, None],
        (len(order), bs, bs))
    u = np.zeros(X.shape)
    v = np.zeros(X.shape)
    for cx, cy in centers:
        dx, dy = X - cx, Y - cy
        r2 = dx * dx + dy * dy
        ut = 0.8 / (2 * np.pi * np.sqrt(r2 + 1e-8)) \
            * (1 - np.exp(-r2 / (2 * 0.03 ** 2)))
        th = np.arctan2(dy, dx)
        u += -ut * np.sin(th)
        v += ut * np.cos(th)
    vals = np.zeros((f.capacity, 2, bs, bs), np.float32)
    vals[order, 0] = u
    vals[order, 1] = v
    f.fields["vel"] = jnp.asarray(vals, f.dtype)
    return sim


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--target", type=int, default=10000)
    ap.add_argument("--rtol", type=float, default=0.05)
    ap.add_argument("--ctol", type=float, default=None)
    ap.add_argument("--max-steps", type=int, default=400)
    ap.add_argument("--levelmax", type=int, default=8)
    ap.add_argument("--synthetic", action="store_true")
    args = ap.parse_args()

    from cup2d_tpu.cache import enable_compilation_cache
    enable_compilation_cache()
    from cup2d_tpu.profiling import PhaseTimers

    from validation.canonical import build_canonical_sim

    ctol = args.ctol if args.ctol is not None else args.rtol / 5.0
    if args.synthetic:
        if args.ctol is not None:
            ap.error("--ctol has no effect with --synthetic "
                     "(compression is disabled there)")
        sim = _synthetic_sim(args)
    else:
        sim = build_canonical_sim(levelmax=args.levelmax, rtol=args.rtol,
                                  ctol=ctol)
    sim.timers = PhaseTimers()
    t0 = time.perf_counter()
    sim.initialize()
    print(json.dumps({"phase": "init", "wall_s": round(
        time.perf_counter() - t0, 1),
        "n_blocks": len(sim.forest.blocks)}), flush=True)

    step_walls, regrid_walls, table_walls = [], [], []
    nb_hist = []
    while sim.step_count < args.max_steps and (
            args.synthetic or len(sim.forest.blocks) < args.target):
        if sim.step_count <= 10 or \
                sim.step_count % sim.cfg.adapt_steps == 0:
            t1 = time.perf_counter()
            sim.adapt()
            t2 = time.perf_counter()
            # table rebuild happens inside the NEXT _refresh; time it
            sim._refresh()
            t3 = time.perf_counter()
            regrid_walls.append(t2 - t1)
            table_walls.append(t3 - t2)
        t1 = time.perf_counter()
        sim.step_once()
        step_walls.append(time.perf_counter() - t1)
        nb_hist.append(len(sim.forest.blocks))
        if sim.step_count % 20 == 0:
            print(json.dumps({
                "step": sim.step_count, "t": round(sim.time, 4),
                "n_blocks": nb_hist[-1], "n_pad": int(sim._npad_hwm),
                "step_ms_median_last20": round(
                    float(np.median(step_walls[-20:]) * 1e3), 1),
            }), flush=True)

    w = np.asarray(step_walls[5:] or step_walls or [0.0])
    print(json.dumps({
        "phase": "summary",
        "final_blocks": len(sim.forest.blocks),
        "final_pad": int(sim._npad_hwm),
        "steps": sim.step_count,
        "step_ms_median": round(float(np.median(w) * 1e3), 1),
        "step_ms_p90": round(float(np.percentile(w, 90) * 1e3), 1),
        "regrid_s_median": round(
            float(np.median(regrid_walls)), 2) if regrid_walls else None,
        "regrid_s_max": round(
            float(np.max(regrid_walls)), 2) if regrid_walls else None,
        "tables_s_median": round(
            float(np.median(table_walls)), 2) if table_walls else None,
        "tables_s_max": round(
            float(np.max(table_walls)), 2) if table_walls else None,
        "timers": sim.timers.summary() if sim.timers else None,
    }), flush=True)


if __name__ == "__main__":
    main()
