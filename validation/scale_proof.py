"""Scale-proof: drive the canonical case into the >=1e4-active-block
regime and record per-phase costs (VERDICT r2 #4).

The fully developed run.sh case lives at 1e4-1e5 blocks (SURVEY §6);
round 2 only ever measured ~500. Wakes take hours of simulated time to
develop that much resolution demand, so this probe reaches the regime
the honest-but-fast way: the same two-fish levelMax-8 case with an
aggressive refinement threshold (-Rtol override), which exercises the
exact machinery that scales with block count — halo-table rebuild,
regrid commit, pad-bucket growth, megastep at large n_pad — on the real
chip. Prints one JSON line per sampled step plus a final summary.

    python -m validation.scale_proof [--target 10000] [--rtol 0.05]
"""

from __future__ import annotations

import argparse
import json
import time

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--target", type=int, default=10000)
    ap.add_argument("--rtol", type=float, default=0.05)
    ap.add_argument("--ctol", type=float, default=None)
    ap.add_argument("--max-steps", type=int, default=400)
    ap.add_argument("--levelmax", type=int, default=8)
    args = ap.parse_args()

    from cup2d_tpu.cache import enable_compilation_cache
    enable_compilation_cache()
    from cup2d_tpu.profiling import PhaseTimers

    from validation.canonical import build_canonical_sim

    ctol = args.ctol if args.ctol is not None else args.rtol / 5.0
    sim = build_canonical_sim(levelmax=args.levelmax, rtol=args.rtol,
                              ctol=ctol)
    sim.timers = PhaseTimers()
    t0 = time.perf_counter()
    sim.initialize()
    print(json.dumps({"phase": "init", "wall_s": round(
        time.perf_counter() - t0, 1),
        "n_blocks": len(sim.forest.blocks)}), flush=True)

    step_walls, regrid_walls, table_walls = [], [], []
    nb_hist = []
    while (sim.step_count < args.max_steps
           and len(sim.forest.blocks) < args.target):
        if sim.step_count <= 10 or \
                sim.step_count % sim.cfg.adapt_steps == 0:
            t1 = time.perf_counter()
            sim.adapt()
            t2 = time.perf_counter()
            # table rebuild happens inside the NEXT _refresh; time it
            sim._refresh()
            t3 = time.perf_counter()
            regrid_walls.append(t2 - t1)
            table_walls.append(t3 - t2)
        t1 = time.perf_counter()
        sim.step_once()
        step_walls.append(time.perf_counter() - t1)
        nb_hist.append(len(sim.forest.blocks))
        if sim.step_count % 20 == 0:
            print(json.dumps({
                "step": sim.step_count, "t": round(sim.time, 4),
                "n_blocks": nb_hist[-1], "n_pad": int(sim._npad_hwm),
                "step_ms_median_last20": round(
                    float(np.median(step_walls[-20:]) * 1e3), 1),
            }), flush=True)

    w = np.asarray(step_walls[5:] or step_walls or [0.0])
    print(json.dumps({
        "phase": "summary",
        "final_blocks": len(sim.forest.blocks),
        "final_pad": int(sim._npad_hwm),
        "steps": sim.step_count,
        "step_ms_median": round(float(np.median(w) * 1e3), 1),
        "step_ms_p90": round(float(np.percentile(w, 90) * 1e3), 1),
        "regrid_s_median": round(
            float(np.median(regrid_walls)), 2) if regrid_walls else None,
        "regrid_s_max": round(
            float(np.max(regrid_walls)), 2) if regrid_walls else None,
        "tables_s_median": round(
            float(np.median(table_walls)), 2) if table_walls else None,
        "tables_s_max": round(
            float(np.max(table_walls)), 2) if table_walls else None,
        "timers": sim.timers.summary() if sim.timers else None,
    }), flush=True)


if __name__ == "__main__":
    main()
