"""Device-time probe of the canonical adaptive case (VERDICT r2 #2).

Every round-2 adaptive measurement was tunnel-wall time: one megastep
dispatch + one scalar pull per step costs ~2 tunnel round trips
(~100 ms each), swamping device compute. This probe separates the two:
after warming the canonical two-fish levelMax-8 case, it re-dispatches
the megastep N times back-to-back with the velocity/pressure outputs
chained into the next call's inputs (raster windows, dt and shape
kinematics frozen — legal: all block-level work including the Poisson
while_loop still runs), fencing ONCE at the end. Wall/N then bounds the
true device time per step; the same chain fenced per-call reproduces
the tunnel-bound number for contrast.

    python -m validation.device_time [--steps 60] [--chain 20]

Prints one JSON line.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np


def _fence(x) -> float:
    return float(x.reshape(-1)[0])


def _probe_scale_step(sim, args):
    """Chained OBSTACLE-FREE step probe for the synthetic >=1e4-block
    forest (VERDICT r3 #3: the adaptive device time at the reference's
    own scale was never measured — the r3 scale proof recorded only
    tunnel wall). Freezes dt and chains _step_jit with outputs fed
    back, fencing once; optional profiler trace parsed at op level."""
    import jax.numpy as jnp

    cfg = sim.cfg
    f = sim.forest
    sim._refresh()
    ordf = sim._ordered_state()
    dt = jnp.asarray(1e-4, f.dtype)

    def make_step(tcoarse):
        def step(vel, pres):
            return sim._step_jit(
                vel, pres, dt, sim._h, sim._hsq_flat, sim._maskv,
                sim._tables["vec3"], sim._tables["vec1"],
                sim._tables["sca1"], sim._tables["pois"],
                sim._corr, tcoarse, exact_poisson=False)
        return step

    def chain_time(step, vel, pres):
        out = step(vel, pres)
        _fence(out[0])
        lat = []
        for _ in range(3):
            t0 = time.perf_counter()
            _fence(out[0])
            lat.append(time.perf_counter() - t0)
        lat_floor = min(lat)
        best = None
        for _ in range(3):
            v, p = vel, pres
            t0 = time.perf_counter()
            for _ in range(args.chain):
                v, p, _ = step(v, p)
            _fence(v)
            w = time.perf_counter() - t0 - lat_floor
            best = w if best is None else min(best, w)
        it = int(jax.device_get(step(vel, pres)[2]["poisson_iters"]))
        return best / args.chain * 1e3, lat_floor, it

    vel, pres = ordf["vel"], ordf["pres"]
    # A: plain block-Jacobi (what the r3 builds ran in production)
    dev_ms, lat_floor, iters_plain = chain_time(
        make_step(None), vel, pres)
    # B: the production two-level trigger engaged (iters>15 policy)
    if sim._coarse_cw is None:
        sim._build_coarse_maps(sim._npad_hwm, sim._n_real)
    dev_ms_coarse, _, iters_coarse = chain_time(
        make_step(sim._coarse_cw), vel, pres)

    if args.trace_dir:
        step = make_step(sim._coarse_cw)
        with jax.profiler.trace(args.trace_dir):
            v, p = vel, pres
            for _ in range(args.chain):
                v, p, _ = step(v, p)
            _fence(v)
    return (dev_ms, iters_plain, dev_ms_coarse, iters_coarse,
            lat_floor)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=60,
                    help="normal warm-up steps before probing")
    ap.add_argument("--chain", type=int, default=20)
    ap.add_argument("--levelmax", type=int, default=8)
    ap.add_argument("--synthetic-scale", type=int, default=0,
                    help="probe the obstacle-free synthetic forest "
                         "grown to >= this many blocks instead of the "
                         "canonical two-fish case")
    ap.add_argument("--trace-dir", default=None,
                    help="also capture a profiler trace of the chain "
                         "(parse with validation.trace_ops "
                         "--parse-only)")
    args = ap.parse_args()

    from cup2d_tpu.cache import enable_compilation_cache
    enable_compilation_cache()

    if args.synthetic_scale:
        from types import SimpleNamespace

        from validation.scale_proof import _synthetic_sim

        sim = _synthetic_sim(SimpleNamespace(
            levelmax=args.levelmax, rtol=0.1))
        cfg = sim.cfg
        t0 = time.perf_counter()
        grow_steps = 0
        while len(sim.forest.blocks) < args.synthetic_scale \
                and grow_steps < 40:
            sim.adapt()
            sim.step_once()
            grow_steps += 1
        t_init = time.perf_counter() - t0
        n_blocks = len(sim.forest.blocks)
        (dev_ms, iters_plain, dev_ms_coarse, iters_coarse,
         lat_floor) = _probe_scale_step(sim, args)
        cells = n_blocks * cfg.bs * cfg.bs
        print(json.dumps({
            "case": f"synthetic vortices levelMax={args.levelmax}, "
                    f">= {args.synthetic_scale} blocks",
            "backend": jax.default_backend(),
            "n_blocks": n_blocks,
            "n_pad": int(sim._npad_hwm),
            "grow_s": round(t_init, 1),
            "device_ms_per_step_blockjacobi": round(dev_ms, 2),
            "poisson_iters_blockjacobi": iters_plain,
            "device_ms_per_step_twolevel": round(dev_ms_coarse, 2),
            "poisson_iters_twolevel": iters_coarse,
            "latency_floor_ms": round(lat_floor * 1e3, 1),
            "cells_steps_per_sec_device": round(
                cells / (min(dev_ms, dev_ms_coarse) / 1e3)),
            "trace_dir": args.trace_dir,
        }))
        sys.stdout.flush()
        return

    from validation.canonical import build_canonical_sim

    sim = build_canonical_sim(levelmax=args.levelmax)
    cfg = sim.cfg

    t0 = time.perf_counter()
    sim.initialize()
    t_init = time.perf_counter() - t0

    # warm run: real driver loop (regrids + megasteps), median wall/step
    walls = []
    for k in range(args.steps):
        if sim.step_count <= 10 or sim.step_count % cfg.adapt_steps == 0:
            sim.adapt()
        t0 = time.perf_counter()
        sim.step_once()
        walls.append(time.perf_counter() - t0)
    n_blocks = len(sim.forest.blocks)
    warm_ms = float(np.median(walls[min(10, len(walls) // 2):]) * 1e3)

    # frozen-input chained dispatches: device time per megastep
    sim._refresh()
    ordf = sim._ordered_state()
    inputs = sim._shape_inputs()
    f = sim.forest
    prescribed = jnp.asarray(
        [[s.u, s.v, s.omega] for s in sim.shapes], dtype=f.dtype)
    dt = jnp.asarray(sim._next_dt or sim.compute_dt(), f.dtype)
    hmin = jnp.asarray(
        cfg.h_at(int(f.level[sim._order].max())), f.dtype)

    def mega(vel, pres):
        return sim._mega_jit(
            vel, pres, inputs, prescribed, dt, hmin,
            sim._h, sim._hsq_flat, sim._maskv, sim._xc, sim._yc,
            sim._tables["vec3"], sim._tables["vec1"],
            sim._tables["sca1"], sim._tables["pois"],
            sim._tables.get("vec4t"), sim._tables.get("sca4t"),
            sim._corr, None, exact_poisson=False, with_forces=False)

    vel, pres = ordf["vel"], ordf["pres"]
    out = mega(vel, pres)          # compile/warm this exact signature
    _fence(out[0])
    # latency floor of one fenced readback
    lat = []
    for _ in range(3):
        t0 = time.perf_counter()
        _fence(out[0])
        lat.append(time.perf_counter() - t0)
    lat_floor = min(lat)

    best = None
    for _ in range(3):
        v, p = vel, pres
        t0 = time.perf_counter()
        for _ in range(args.chain):
            v, p, _, scal, _ = mega(v, p)
        _fence(v)
        w = time.perf_counter() - t0 - lat_floor
        best = w if best is None else min(best, w)
    dev_ms = best / args.chain * 1e3

    # contrast: same chain, fenced every call (the per-step tunnel cost)
    v, p = vel, pres
    t0 = time.perf_counter()
    for _ in range(args.chain):
        v, p, _, scal, _ = mega(v, p)
        _fence(v)
    per_call_ms = (time.perf_counter() - t0) / args.chain * 1e3

    cells = n_blocks * cfg.bs * cfg.bs
    print(json.dumps({
        "case": f"two-fish levelMax={args.levelmax} (run.sh)",
        "backend": jax.default_backend(),
        "n_blocks": n_blocks,
        "n_pad": int(sim._npad_hwm),
        "init_s": round(t_init, 1),
        "warm_step_wall_ms": round(warm_ms, 1),
        "device_ms_per_megastep": round(dev_ms, 2),
        "fenced_ms_per_megastep": round(per_call_ms, 1),
        "latency_floor_ms": round(lat_floor * 1e3, 1),
        "cells_steps_per_sec_device": round(cells / (dev_ms / 1e3)),
    }))
    sys.stdout.flush()


if __name__ == "__main__":
    main()
