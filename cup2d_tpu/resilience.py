"""Supervised stepping: health verdicts, rewind-and-retry, clean preemption.

The reference's ``main()`` dies on the first NaN and loses the run; our
CLI inherited that (`__main__.py` pre-PR2 aborted with exit 1, missed
Inf, and left the force log unclosed). Production AMR frameworks treat
solver-failure handling and checkpoint/restart as first-class
subsystems (AMReX, arXiv:2009.12009); the atomic-checkpoint half lives
in ``io.py`` — this module is the supervision half on top of it:

- :func:`health_verdict`: a per-step health check that rides the
  diagnostics the step ALREADY pulls (the fused isfinite reduction over
  vel/pres plus the Poisson ``converged``/``stalled`` flags, which the
  solver has always computed and nothing consumed). On the CLI driver
  paths the scalars arrive host-side in the step's existing batched
  pull, so the verdict adds NO device round trips and NO retraces —
  asserted by ``tests/test_resilience.py``.
- :class:`StepGuard`: keeps a DEVICE-RESIDENT ring of good-state
  snapshots (HBM copies via ``io.snapshot_state_device`` — no D2H
  gather; the host ring of PR 2/3 taxed every good step with a full
  state transfer, the former ROADMAP pod gap (b)) and on a bad verdict
  walks a bounded recovery ladder:

      1. rewind to the last device snapshot, replay the recorded good
         steps since it bit-exactly (``snap_every`` cadence), retry the
         failed step at dt/2
      2. rewind/replay again, retry with the exact Poisson solve
      3. restore from the on-disk checkpoint and resume
      4. abort — post-mortem checkpoint + closed force log

  Every rung emits one JSONL event (step, verdict, action, replayed)
  through :class:`EventLog`.

  The verdict is ONE-STEP-LAGGED on the device-diag drivers (the
  obstacle-free uniform/AMR paths, ``sim.async_diag``): step N's diag
  stays on device, step N+1 is dispatched first, and only then is N's
  scalar set pulled — still exactly one batched ``device_get`` per
  step, now overlapped with N+1's compute instead of idling the
  device. Detection latency is 1 step; the pending post-N snapshot is
  simply discarded when N turns out bad, so the rewind target is still
  the pre-N state. Drivers whose diag arrives host-side at dispatch
  (the shaped paths must pull uvw/CoM for the host kinematics anyway)
  verdict eagerly — the lag would buy nothing there and the host
  kinematics must never consume unverdicted scalars. Callers finish a
  run with :meth:`StepGuard.drain` (the final step's verdict is still
  pending at loop exit).
- :class:`PhysicsWatchdog`: windowed drift bounds on the fused physics
  invariants (kinetic energy, max |∇·u|) the diag pull carries since
  PR 3 — catches wrong-but-FINITE corruption the isfinite verdict
  cannot (the former ROADMAP open item), feeding the same ladder.
- :class:`PreemptionGuard`: SIGTERM latches a flag; the driver loop
  checkpoints at the next step boundary and exits 0 (preemptible-pod
  semantics: the grace window is spent writing the restart point, not
  dying mid-collective).

- :class:`FleetStepGuard`: the per-member generalization for the
  fleet-batched driver (fleet.py) — vectorized verdicts over the [B]
  diag vectors of one fused dispatch; a bad member restores ONLY its
  slice of the device snapshot ring and replays solo, healthy members
  never rewind.

Multi-host note: the verdict scalars are outputs of global reductions
(replicated by SPMD semantics) and the device snapshots are per-shard
local copies (no collective at all — strictly safer than the host
gather they replace), so every process reaches the same ladder
decision in the same order — the determinism contract of
``parallel/launch.py`` extends to recovery. The SIGTERM latch is
per-process but the DECISION is not: :meth:`PreemptionGuard.agree`
min-allreduces the flag at every step boundary, so all hosts enter the
collective checkpoint at the same step (the former ROADMAP pod gap
(a); drilled by the skewed-delivery phase of the multihost harness).

Topology-changing loss (the one failure class the ladder above cannot
touch — a host or process dropping OUT of the SPMD program) is handled
by the elastic subsystem (PR 7):

- :class:`TopologyGuard`: detection + agreement. The heartbeat
  piggybacks on the step-boundary collective the run already pays
  (:meth:`PreemptionGuard.agree`'s one-int allgather grows to a
  three-int payload: SIGTERM latch, topology epoch, exiting flag) and
  is BOUNDED — the collective runs under a deadline, so a peer that
  died mid-step surfaces as a timeout instead of an infinite hang. A
  host that misses ``miss_k`` consecutive beats (or announces a
  graceful exit in its last beat) is DECLARED lost; every survivor
  computes the same new device set from the same allgathered evidence
  and bumps the same epoch counter — the deterministic agreement that
  keeps the re-mesh collective-safe. Single-process runs can stand up
  a SIMULATED topology (``sim_hosts=H`` groups the virtual devices
  into H hosts) whose losses are injected by ``faults.py``
  ``host_exit@N`` / ``host_hang@N`` directives — the tier-1 drill.
- :meth:`StepGuard.elastic_recover`: re-mesh + resume. Survivor
  devices become a fresh mesh (``parallel.mesh.make_mesh``), the sim
  rebuilds its placement/tables/step executable over it
  (``sim.remesh``), and the state comes from the device snapshot ring
  where the surviving shards still cover it (``io.snapshot_covers`` —
  re-sharded onto the new mesh by ``io.restore_snapshot_resharded``),
  falling back to the last disk checkpoint otherwise. No process
  relaunch. Every stage emits one JSONL event (``topology_lost``,
  ``remesh``) and the telemetry stream carries the schema-v5
  ``topology_epoch`` / ``remesh_*`` field group.

Real-pod coverage note: per-shard-local snapshots die with their host
(an x-split state loses the lost host's columns). The host-redundant
MIRRORED ring (PR 17) closes that gap: every capture additionally
ships each host's shard block to its ring neighbor (io.MirroredSnapshot
via parallel.mesh.host_ring_shift, checksummed on device), and
``elastic_recover`` gains a mirrored-ring rung between the plain ring
and disk — reconstruct the lost hosts' blocks from the survivors'
mirrors (io.restore_snapshot_mirrored), re-shard, replay. The ladder
is ring -> mirror -> disk -> abort; the mirror rung degrades to disk
when the anchor carries no mirror (cadence staleness), the checksum
rejects (``mirror_reject`` event), or a lost host's ring neighbor died
with it. Drilled end-to-end on CPU with the destroyed-shard semantics
(``shard_loss@N`` zeroes the dead host's slices first, so the resumed
bytes provably came from the mirror); the 2-process real-runtime
drills remain slow-marked (`tests/_multihost_worker.py`; the harness
is environment-broken in this container, see ROADMAP).
"""

from __future__ import annotations

import json
import os
import sys
import time
from collections import deque
from typing import NamedTuple, Optional

import numpy as np

from . import tracing


# ---------------------------------------------------------------------------
# version-safe distributed-runtime probe (no backend touch, no private API)
# ---------------------------------------------------------------------------

# latch set by parallel.launch.init_distributed after a successful
# bring-up — the fallback evidence on jax builds whose public
# `jax.distributed.is_initialized` accessor does not exist yet (the
# image's 0.4.x line). The former fallback read
# `jax._src.distributed.global_state.client`, a private attribute that
# moves between versions; this latch is version-proof and still never
# touches the XLA backend (a backend probe would make a later
# initialize() impossible). Library users on old jax who bypass
# `launch.init_distributed` and call `jax.distributed.initialize`
# directly should call :func:`note_distributed_initialized` too.
_DIST_NOTED = False


def note_distributed_initialized() -> None:
    """Record that the jax distributed runtime is up (called by
    ``parallel.launch.init_distributed``; see :func:`dist_initialized`)."""
    global _DIST_NOTED
    _DIST_NOTED = True


def dist_initialized() -> bool:
    """True when the jax distributed runtime is initialized — the
    public ``jax.distributed.is_initialized`` accessor where the build
    has it, else the ``init_distributed`` latch above. Never probes the
    backend (safe to call before a later ``initialize()``)."""
    import jax
    probe = getattr(jax.distributed, "is_initialized", None)
    if probe is not None:
        return bool(probe())
    return _DIST_NOTED


# ---------------------------------------------------------------------------
# JSONL event log
# ---------------------------------------------------------------------------

class EventLog:
    """Append-only JSONL log of resilience events (one object per line,
    flushed per event so a dying process keeps its tail).

    Multi-host: once the distributed runtime is up, only process 0
    writes — the recovery decisions are replicated by construction
    (see the module docstring), so N processes appending the same
    lines to one shared-FS file would only duplicate and interleave
    them. Events BEFORE the runtime joins (coordinator connect
    retries) are written by every process: they are genuinely
    per-process and the world membership is unknown at that point.
    ``all_writers=True`` (the span-timeline sink) opts OUT of the
    process-0 gate: spans are genuinely per-process, so every process
    writes — to its own ``<path>.p<idx>`` file past process 0, never
    interleaving on a shared FS (the Perfetto export merges them).

    ``rotate_mb`` caps the file (``-logRotateMB``, default off): on
    crossing the cap the live file is renamed to the next numbered
    segment ``<path>.N`` and reopened fresh; ``profiling.load_metrics``
    reads the segments back in write order. Off by default — rotation
    exists for long serving runs, and a rotated-away segment is no
    longer fsync-reachable for the durable-event tail guarantee."""

    def __init__(self, path: str, rotate_mb=None, all_writers=False):
        self._all_writers = bool(all_writers)
        if self._all_writers:
            try:
                import jax
                if dist_initialized() and jax.process_index() > 0:
                    path = f"{path}.p{jax.process_index()}"
            except Exception:
                pass
        self.path = path
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        self.rotate_bytes = (int(rotate_mb * 2 ** 20) if rotate_mb
                             else None)
        self._seq = None
        self._f = open(path, "a")

    def _is_writer(self) -> bool:
        # version-safe no-probe check (dist_initialized above): must
        # not touch the XLA backend — EventLog exists before
        # init_distributed runs, and a backend probe would make a
        # later initialize() impossible
        if self._all_writers:
            return True
        import jax
        return (not dist_initialized()) or jax.process_index() == 0

    # recovery-critical events are fsynced at emit: a process that dies
    # right after a remesh (exactly the failure class the elastic path
    # exists for) must not take the event trail post-mortem triage
    # depends on into the page cache with it. Per-step metrics and
    # routine events keep the cheap buffered write+flush path — fsync
    # per step would serialize the dispatch pipeline on disk latency.
    _DURABLE_EVENTS = frozenset({
        "topology_lost", "remesh", "member_abort", "member_aborted",
        "mirror_reject",
    })

    def emit(self, **fields) -> None:
        if not self._is_writer():
            return
        fields.setdefault("wall", time.time())
        self._f.write(json.dumps(fields, sort_keys=True,
                                 default=float) + "\n")
        self._f.flush()
        if fields.get("event") in self._DURABLE_EVENTS:
            try:
                os.fsync(self._f.fileno())
            except OSError:
                pass    # non-seekable sink (pipe/pty): flush is all it has
        if self.rotate_bytes and self._f.tell() >= self.rotate_bytes:
            self._rotate()

    def _rotate(self) -> None:
        self._f.close()
        if self._seq is None:
            from .profiling import _next_segment_seq
            self._seq = _next_segment_seq(self.path)
        os.replace(self.path, f"{self.path}.{self._seq}")
        self._seq += 1
        self._f = open(self.path, "a")

    def close(self) -> None:
        if not self._f.closed:
            self._f.close()


_EVENT_LOG: Optional[EventLog] = None


def set_event_log(log: Optional[EventLog]) -> None:
    """Register the process-wide event sink (io.py's checkpoint-fallback
    warning and launch.py's connect-retry report through it)."""
    global _EVENT_LOG
    _EVENT_LOG = log


def record_event(**fields) -> None:
    """Emit into the registered event log; silently dropped when no run
    log is active (library users without a supervised loop)."""
    if _EVENT_LOG is not None:
        _EVENT_LOG.emit(**fields)


# ---------------------------------------------------------------------------
# per-step health verdict
# ---------------------------------------------------------------------------

class StepVerdict(NamedTuple):
    ok: bool
    reason: str           # "ok" | "nonfinite" | "poisson_nonfinite"
    #                     | "poisson_exhausted" | "poisson_giveup(injected)"
    #                     | "invariant_umax" | "invariant_energy"
    #                     | "invariant_divergence"


_HEALTH_KEYS = ("finite", "umax", "poisson_converged", "poisson_stalled",
                "poisson_residual")

# the fused on-device physics invariants (uniform.step_diag /
# amr._step_impl): watchdog inputs, riding the same batched diag pull
_INVARIANT_KEYS = ("energy", "div_linf")

# everything the guard's ONE batched pull fetches per step: health +
# invariants + the trigger/telemetry scalars + the dt actually used
# (the async drivers put it in the diag — the lagged clock and the
# replay dts come from this same pull)
_PULL_KEYS = _HEALTH_KEYS + _INVARIANT_KEYS + (
    "poisson_iters", "precond_cycles", "dt_next", "dt")


def _host_scalars(diag: dict, keys) -> dict:
    """The named diag entries as host scalars. On the CLI driver paths
    every value is already host-side (batched into the step's one
    existing pull); library paths that keep scalars on device pay ONE
    ``device_get`` for the whole set."""
    import jax

    vals = {k: diag[k] for k in keys if k in diag}
    if any(isinstance(v, jax.Array) for v in vals.values()):
        vals = jax.device_get(vals)
    return vals


def health_verdict(diag: dict,
                   residual_ok: Optional[float] = None) -> StepVerdict:
    """Classify a step's diagnostics dict.

    Policy: a step is BAD when (a) the fused isfinite reduction over
    vel/pres failed (covers the Inf the old ``umax != umax`` check
    missed), (b) the Poisson residual itself is nonfinite, or (c) the
    solve exited neither converged nor stalled — a breakdown give-up
    past the restart budget, or max_iter exhaustion — with a residual
    above ``residual_ok``. A ``stalled`` exit is NOT bad: it is the
    solver's precision floor (exact-mode solves end there by design,
    see poisson.bicgstab). ``residual_ok`` (the StepGuard passes 100x
    the case's poisson_tol) keeps a merely budget-capped solve that
    still sits near its target out of the recovery ladder — the
    reference ran its whole life with unchecked budget exhaustion;
    exhaustion with a residual FAR above target is what recovery is
    for. ``residual_ok=None`` flags every non-converged non-stalled
    exit (strict mode).

    On the CLI driver paths every value here is already host-side
    (batched into the step's one existing pull); if any is still a
    device array (library paths that keep scalars on device, e.g. the
    obstacle-free AMR step), they are fetched in ONE device_get.
    """
    vals = _host_scalars(diag, _HEALTH_KEYS)
    finite = vals.get("finite")
    if finite is None:
        u = float(vals.get("umax", 0.0))
        finite = np.isfinite(u)
    if not bool(finite):
        return StepVerdict(False, "nonfinite")
    resid = vals.get("poisson_residual")
    if resid is not None and not np.isfinite(float(resid)):
        return StepVerdict(False, "poisson_nonfinite")
    conv = vals.get("poisson_converged")
    stall = vals.get("poisson_stalled")
    if conv is not None and not bool(conv) \
            and stall is not None and not bool(stall):
        rf = float(resid) if resid is not None else float("inf")
        if residual_ok is None or not (rf <= residual_ok):
            return StepVerdict(False, "poisson_exhausted")
    return StepVerdict(True, "ok")


# ---------------------------------------------------------------------------
# physics-invariant watchdog (the silent-corruption gap, ROADMAP)
# ---------------------------------------------------------------------------

class PhysicsWatchdog:
    """Windowed drift bounds on the fused physics invariants (umax,
    kinetic energy, max |∇·u|) that every step's diag already carries.

    The health verdict's isfinite reduction catches NaN/Inf, but
    wrong-but-FINITE fields (a bit-flipped exponent, a corrupted halo
    exchange, a stale buffer reinstalled by a bad restore) sail through
    it — the ROADMAP open item this closes. Physics pins them down: a
    viscous box flow cannot multiply its velocity scale or kinetic
    energy inside one step, and advection bounds the divergence
    production, so a step whose invariants jump far outside the recent
    window is corrupt even though every number in it is finite.

    Policy (deliberately loose — a FALSE positive costs a rewind-retry
    and forks the trajectory, so the bounds are orders of magnitude
    above legitimate step-to-step variation):

    - each invariant ARMS itself independently, and only once its
      window is both full and SETTLED (window max/min <= its settle
      ratio). Relative drift bounds are meaningless on an unsettled
      signal: during spin-up from rest the kinetic energy legitimately
      multiplies per step (measured on the deforming-fish case: a dt/2
      retry lands 8x the window max while E is still ~1e-10), so an
      unsettled invariant stays dormant rather than false-positive.
      umax is the invariant that arms FIRST in practice — it is
      body-velocity-dominated and near-constant from the first steps
      even while the energy still ramps — so corruption is caught long
      before the energy bound wakes up;
    - umax: BAD when outside [window min / factor, factor x window max]
      (``umax_factor``, settle ``umax_settle``);
    - energy: same two-sided bound (``energy_factor``/``energy_settle``
      — corruption can deflate as well as inflate; legitimate viscous
      decay is a few % per step, never a 4x cliff inside an 8-step
      window);
    - divergence: BAD when max |∇·u| > ``div_factor`` x the window max
      (one-sided — a too-CLEAN divergence is what the projection aims
      for; settle ``div_settle``).

    Drive it through :class:`StepGuard` (``watchdog=``): a flagged step
    walks the same recovery ladder as a nonfinite one, and only steps
    with an OK final verdict enter the window — a corrupted step can
    never poison its own baseline. ``tests/test_telemetry.py`` injects
    a wrong-but-finite field (``faults.py scale_vel``) and asserts the
    flag + recovery; an unfaulted guarded run stays bit-identical."""

    def __init__(self, window: int = 8,
                 umax_factor: float = 4.0, umax_settle: float = 2.0,
                 energy_factor: float = 4.0, energy_settle: float = 2.0,
                 div_factor: float = 50.0, div_settle: float = 4.0):
        self.window = int(window)
        self.umax_factor = float(umax_factor)
        self.umax_settle = float(umax_settle)
        self.energy_factor = float(energy_factor)
        self.energy_settle = float(energy_settle)
        self.div_factor = float(div_factor)
        self.div_settle = float(div_settle)
        self.umax: deque = deque(maxlen=self.window)
        self.energy: deque = deque(maxlen=self.window)
        self.div: deque = deque(maxlen=self.window)

    @classmethod
    def for_prec(cls, prec_mode: str, **kw) -> "PhysicsWatchdog":
        """Tolerance band matched to the driver's storage-precision
        contract (``sim.prec_mode``, PR 9). The bf16 tier's legitimate
        step-to-step invariant jitter is ~2^-8 relative (bf16 mantissa)
        instead of f32's ~2^-23, so its windows settle later and sit
        wider: the settle ratios and the one-sided divergence factor
        loosen. The CORRUPTION factors stay put where they bound
        corruption scale, not rounding (a 4x energy cliff inside an
        8-step window is corrupt in any precision); div_factor doubles
        because the projection's reachable divergence floor — the
        window baseline the factor multiplies — is itself noisier at
        bf16 storage. Explicit ``**kw`` overrides win."""
        if prec_mode == "bf16":
            kw.setdefault("umax_settle", 2.5)
            kw.setdefault("energy_settle", 2.5)
            kw.setdefault("div_settle", 8.0)
            kw.setdefault("div_factor", 100.0)
        return cls(**kw)

    def _armed(self, hist: deque, settle: float):
        """(hi, lo) when the invariant's window is full and settled,
        else None — drift bounds only mean something against a stable
        baseline."""
        if len(hist) < self.window:
            return None
        hi, lo = max(hist), min(hist)
        if lo <= 0.0 or hi > settle * lo:
            return None
        return hi, lo

    def check(self, vals: dict) -> Optional[str]:
        """Verdict reason for a drifted invariant, or None. ``vals``
        holds host scalars (the guard pre-pulls them with the health
        keys in one batch)."""
        u = vals.get("umax")
        band = self._armed(self.umax, self.umax_settle)
        if u is not None and band is not None:
            hi, lo = band
            if not (lo / self.umax_factor <= float(u)
                    <= self.umax_factor * hi):
                return "invariant_umax"
        e = vals.get("energy")
        band = self._armed(self.energy, self.energy_settle)
        if e is not None and band is not None:
            hi, lo = band
            if not (lo / self.energy_factor <= float(e)
                    <= self.energy_factor * hi):
                return "invariant_energy"
        d = vals.get("div_linf")
        band = self._armed(self.div, self.div_settle)
        if d is not None and band is not None:
            hi, _ = band
            if float(d) > self.div_factor * hi:
                return "invariant_divergence"
        return None

    def observe(self, vals: dict) -> None:
        """Fold a GOOD step's invariants into the window."""
        if vals.get("umax") is not None:
            self.umax.append(float(vals["umax"]))
        if vals.get("energy") is not None:
            self.energy.append(float(vals["energy"]))
        if vals.get("div_linf") is not None:
            self.div.append(float(vals["div_linf"]))

    def reset(self) -> None:
        """Drop the window (after a disk restore the history describes
        steps FORWARD of the restored point)."""
        self.umax.clear()
        self.energy.clear()
        self.div.clear()


# ---------------------------------------------------------------------------
# the supervised stepper
# ---------------------------------------------------------------------------

class ResilienceAbort(RuntimeError):
    """The recovery ladder is exhausted; the run cannot continue. A
    post-mortem checkpoint (if configured) was written before raising."""


class _Pending:
    """One dispatched-but-unverdicted step (the lagged slot)."""

    __slots__ = ("step0", "t0", "diag", "exact", "dt_host", "advanced",
                 "snap", "trig", "fired", "mode", "tier")

    def __init__(self, step0, t0, diag, exact, dt_host, advanced,
                 snap=None, trig=None, fired=(), mode=None, tier=None):
        self.step0 = step0
        self.t0 = t0
        self.diag = diag
        self.exact = exact
        self.dt_host = dt_host       # None on the async (device-dt) paths
        self.advanced = advanced     # driver advanced sim.time at dispatch
        self.snap = snap             # optimistic post-step device snapshot
        self.trig = trig             # (coarse_on, last_iters) at dispatch
        self.fired = fired           # fault entries this dispatch consumed
        self.mode = mode             # sim.poisson_mode at dispatch (v4):
        #                              a lagged commit must label step N
        #                              with the path N actually TOOK, not
        #                              the live mode after N+1's dispatch
        #                              may have flipped the trigger
        self.tier = tier             # sim.kernel_tier at dispatch (v6/
        #                              ISSUE 16): BC-token-suffixed tier
        #                              string, captured under the same
        #                              lagged-commit rule as mode


class StepGuard:
    """Wraps ``sim.step_once`` with verdict + bounded recovery ladder.

    Parameters
    ----------
    sim : Simulation | AMRSim | UniformSim (step_once/time/step_count)
    ring : confirmed device snapshots to keep in HBM (>= 1). The ladder
        consumes only the LATEST anchor; an unconfirmed post-step
        snapshot additionally lives in the pending slot under the
        lagged verdict, so >= 2 snapshots coexist in HBM whenever a
        cadence step is in flight — that pairing is what lets a
        late-detected bad step N still rewind to the pre-N state.
    ckpt_dir : the run's on-disk checkpoint (the disk-restore rung;
        None or missing disables that rung)
    postmortem_dir : where the abort rung writes its final checkpoint
    event_log : EventLog for the JSONL recovery events
    faults : FaultPlan whose pre/post-step hooks this guard drives
        (suspended during replay — replay reproduces verdicted-good
        steps, it is not a fresh attempt)
    recover : False = verdict-only mode (first bad verdict aborts, with
        the same post-mortem/event path)
    watchdog : PhysicsWatchdog consulted after the health verdict (a
        drifted invariant walks the same recovery ladder; None skips
        the invariant check)
    snap_every : device-snapshot cadence in good steps (``-snapEvery``).
        N > 1 amortizes even the HBM copy: the dt/exact sequence since
        the last snapshot is recorded, and a bad verdict restores the
        snapshot and REPLAYS forward bit-exactly (same dts, same solver
        branches, faults suspended) to the failed step before entering
        the ladder.
    lag : one-step-lagged verdict (default on). Device-diag drivers
        (``sim.async_diag``) keep their scalars on device; the guard
        dispatches step N+1, then pulls step N's set — the one batched
        ``device_get`` per step moves off the critical path. Host-diag
        drivers verdict eagerly either way.
    mirror_hosts : host-ring size for the host-redundant mirrored
        snapshot tier (None/<2 disables it — the default, bit-identical
        to the pre-mirror guard). When set, every captured snapshot
        additionally ships each host's shard block to its ring neighbor
        (io.mirror_snapshot: one shard_map ppermute + on-device
        checksums, enqueued before the next dispatch donates its
        buffers — zero host transfers), and ``elastic_recover`` gains
        the mirrored-ring rung between ring and disk.
    mirror_every : mirror cadence in snapshots (``-mirrorEvery``): N > 1
        mirrors every Nth capture — anchors between carry no mirror, so
        a loss there finds the mirror rung stale and degrades to disk.
    """

    def __init__(self, sim, *, ring: int = 1, ckpt_dir: Optional[str] = None,
                 postmortem_dir: Optional[str] = None,
                 event_log: Optional[EventLog] = None,
                 faults=None, recover: bool = True, watchdog=None,
                 snap_every: int = 1, lag: bool = True,
                 mirror_hosts: Optional[int] = None,
                 mirror_every: int = 1):
        self.sim = sim
        self.ring: deque = deque(maxlen=max(1, int(ring)))
        self.ckpt_dir = ckpt_dir
        self.postmortem_dir = postmortem_dir
        self.event_log = event_log
        self.faults = faults
        self.recover = recover
        self.watchdog = watchdog
        self.snap_every = max(1, int(snap_every))
        self.lag = bool(lag)
        self.recoveries = 0       # completed recovery actions (telemetry)
        self.replayed_steps = 0   # cumulative replayed steps (telemetry)
        # elastic-topology state (schema v5 field group; advanced only
        # by elastic_recover — a run that never loses a host reports
        # epoch 0 / count 0 forever)
        self.topology_epoch = 0
        self.remesh_count = 0
        self.remesh_ms_total = 0.0
        # host-redundant mirrored snapshot tier (PR 17, schema v9
        # field group). mirror_hosts None/<2 keeps every mirror code
        # path dormant — bit-identical dispatch stream to the
        # pre-mirror guard, zero extra host syncs.
        self.mirror_hosts = (int(mirror_hosts)
                             if mirror_hosts and int(mirror_hosts) >= 2
                             else None)
        self.mirror_every = max(1, int(mirror_every))
        self.mirror_ms_total = 0.0   # enqueue-side cost (telemetry)
        self.restore_source = None   # last recovery rung: ring|mirror|disk
        self._mirror_tick = 0
        self._pendings: list = []
        self._replay: list = []   # (dt, exact, trig) good steps since anchor
        self._since_snap = 0
        self._last_fired = ()     # fault entries the last _attempt consumed
        # two-level-trigger freshness (PR 6): True from each re-anchor
        # until the first PRODUCTION verdict delivers the new
        # topology's iteration count — the window where the lagged
        # pipeline would otherwise consult stale trigger evidence (see
        # step())
        self._trigger_fresh = False
        if self.lag and hasattr(sim, "async_diag"):
            # device-diag mode: the obstacle-free branches keep their
            # diag (incl. the dt used) on device and leave the clock
            # settlement to the lagged verdict below
            sim.async_diag = True

    # -- snapshot machinery (device-resident, io.py) ------------------
    def _snapshot(self):
        from .io import snapshot_state_device, mirror_snapshot
        with tracing.span("snapshot", step=int(self.sim.step_count)):
            snap = snapshot_state_device(self.sim)
            mh = self.mirror_hosts
            mesh = getattr(self.sim, "mesh", None)
            if mh is not None and mesh is not None:
                self._mirror_tick += 1
                if self._mirror_tick >= self.mirror_every:
                    t0 = time.perf_counter()
                    with tracing.span("mirror",
                                      step=int(self.sim.step_count)):
                        m = mirror_snapshot(snap, mesh, mh)
                    if m is None:
                        # unmirrorable family (forest payloads, odd
                        # divisibility): latch the tier off rather than
                        # re-probing every capture
                        self.mirror_hosts = None
                    else:
                        snap = snap._replace(mirror=m)
                        self._mirror_tick = 0
                    # enqueue-side only — the collective itself overlaps
                    # with the next dispatch (async device execution)
                    self.mirror_ms_total += \
                        (time.perf_counter() - t0) * 1e3
        return snap

    def ring_nbytes(self) -> int:
        """HBM footprint of every live snapshot (anchors + pending)."""
        from .io import snapshot_nbytes
        n = sum(snapshot_nbytes(s) for s in self.ring)
        return n + sum(snapshot_nbytes(p.snap) for p in self._pendings
                       if p.snap is not None)

    def mirror_nbytes(self) -> int:
        """HBM footprint of the held mirror payloads (anchors +
        pending) — the redundancy the host-redundant tier buys."""
        from .io import mirror_nbytes
        n = sum(mirror_nbytes(s) for s in self.ring)
        return n + sum(mirror_nbytes(p.snap) for p in self._pendings
                       if p.snap is not None)

    def _held_mirror_snaps(self) -> list:
        """Every held snapshot carrying a mirror, newest first (the
        mirror_corrupt fault injector targets the newest)."""
        out = [p.snap for p in reversed(self._pendings)
               if p.snap is not None and p.snap.mirror is not None]
        out += [s for s in reversed(self.ring) if s.mirror is not None]
        return out

    @property
    def pending(self) -> bool:
        """True while a dispatched step awaits its lagged verdict."""
        return bool(self._pendings)

    def _disk_available(self) -> bool:
        return bool(self.ckpt_dir) and (
            os.path.exists(os.path.join(self.ckpt_dir, "meta.json"))
            or os.path.exists(os.path.join(
                self.ckpt_dir.rstrip("/") + ".old", "meta.json")))

    # -- one supervised step ------------------------------------------
    def step(self, dt: Optional[float] = None) -> Optional[dict]:
        """Dispatch one step; return the most recently VERDICTED step's
        record (host scalars + ``step``/``t``/``dt``), or None when the
        first lagged dispatch is still in flight."""
        with tracing.span("step", step=int(self.sim.step_count)):
            return self._step_guarded(dt)

    def _step_guarded(self, dt: Optional[float]) -> Optional[dict]:
        self._seed()
        out = None
        # Two-level-trigger freshness window (PR 6): while the trigger
        # is re-armed-but-off after a re-anchor (a regrid, or the run
        # start), resolve the in-flight verdict BEFORE dispatching so
        # the pulled step-N iteration count anchors the trigger that
        # THIS dispatch consults — the preconditioner upgrade then
        # lands at step N+1, same as the eager drivers, instead of the
        # documented one-step-late N+2. The cost is one exposed pull
        # round trip per re-anchor window (the window closes at the
        # first production verdict, _commit); outside it the pull
        # stays overlapped behind the next dispatch as before.
        # Guards on the drain: the upcoming dispatch must be a
        # PRODUCTION solve (exact dispatches neither consult the
        # trigger nor, at run start, exist past step 9 — draining the
        # steps-0..9 exact-startup pipeline would serialize ~10
        # pointless exposed pulls for zero trigger evidence), at
        # least one pending verdict must be production (exact verdicts
        # cannot deliver the count that closes the window, _commit),
        # and the sim must actually CONSULT the trigger — under
        # CUP2D_POIS=fft (and the forest-FAS modes fas/fas-f, whose
        # hierarchy IS the solver) the correction is forced on
        # unconditionally (amr._use_coarse), so the pulled count
        # decides nothing and the drain would just re-tax every
        # post-regrid step.
        if self.lag and self._trigger_fresh \
                and hasattr(self.sim, "_coarse_on") \
                and not self.sim._coarse_on \
                and getattr(self.sim, "_pois_mode", None) not in (
                    "fft", "fas", "fas-f") \
                and not (self.sim.step_count < 10
                         or getattr(self.sim, "_force_exact", False)) \
                and any(not p.exact for p in self._pendings):
            while self._pendings:
                out = self._resolve_oldest()
        self._dispatch(dt)
        while self._pendings:
            if self.lag and len(self._pendings) == 1 \
                    and _on_device(self._pendings[-1].diag):
                break   # leave the newest device-diag step in flight
            out = self._resolve_oldest()
        return out

    def drain(self) -> list:
        """Resolve every pending verdict (call at loop exit and before
        dumps/checkpoints/regrids). Recovery runs as usual; returns the
        resolved records in step order."""
        out = []
        while self._pendings:
            out.append(self._resolve_oldest())
        return out

    def _seed(self) -> None:
        sim = self.sim
        if self.ring:
            if hasattr(sim, "forest") and \
                    self.ring[-1].meta.get("forest_version") \
                    != sim.forest.version:
                # topology moved (a regrid between guarded steps): the
                # ring must never span it — replay cannot reproduce a
                # regrid. Settle any in-flight verdicts against the old
                # anchor, then re-anchor on the new topology.
                self.drain()
                self._reanchor()
            return
        # run the lazy chi-blend initialization BEFORE seeding: a
        # snapshot of the pre-initialize state marks the sim
        # initialized on restore, so a rewind after a FIRST-step
        # failure would silently skip the blend and fork the
        # trajectory from t=0
        if getattr(sim, "shapes", None) \
                and not getattr(sim, "_initialized", False):
            sim.initialize()
        # seed: the pre-first-step state is by definition good
        self._reanchor()

    def _reanchor(self) -> None:
        self.ring.append(self._snapshot())
        self._replay.clear()
        self._since_snap = 0
        self._trigger_fresh = True

    def _trigger_state(self):
        """The two-level-trigger inputs the next dispatch consults —
        recorded per step so replay reproduces the SAME preconditioner
        branch the original trajectory took (replay steps never commit,
        so the trigger would otherwise stay frozen at the anchor's
        value)."""
        sim = self.sim
        if hasattr(sim, "_coarse_on"):
            return (bool(sim._coarse_on), int(sim._last_iters))
        return None

    def _dispatch(self, dt) -> None:
        sim = self.sim
        step0, t0 = sim.step_count, sim.time
        if tracing.recorder() is not None:
            # compile-ledger context (host strings, recorder-on only):
            # the trigger step and the dispatch-time latch token any
            # compile fired by this dispatch gets blamed on
            tracing.note_step(step0)
            mode = getattr(sim, "poisson_mode", None)
            tier = getattr(sim, "kernel_tier", None)
            if mode is not None or tier is not None:
                tracing.note_token("/".join(
                    str(x) for x in (mode, tier) if x is not None))
        trig = self._trigger_state()
        diag = self._attempt(dt, exact=False)
        pend = _Pending(
            step0=step0, t0=t0, diag=diag,
            exact=bool(step0 < 10 or getattr(sim, "_force_exact", False)),
            dt_host=(sim.time - t0 if sim.time != t0 else None),
            advanced=(sim.time != t0), trig=trig,
            fired=self._last_fired,
            mode=getattr(sim, "poisson_mode", None),
            tier=getattr(sim, "kernel_tier", None))
        # optimistic cadence snapshot: the post-step state must be
        # copied BEFORE the next dispatch donates its buffers; if this
        # step's lagged verdict comes back bad, the copy is discarded
        # and the rewind target is the previous (confirmed) anchor
        self._since_snap += 1
        if self._since_snap >= self.snap_every:
            pend.snap = self._snapshot()
            self._since_snap = 0
        self._pendings.append(pend)
        # fault injection: mirror_corrupt@N flips bytes in EVERY held
        # mirror so the recovery-time checksum-reject path is drillable
        # regardless of which anchor the next loss lands on (suspended
        # during replay like every other token; keyed on the pre-step
        # count like apply_pre_step)
        if self.faults is not None \
                and getattr(self.faults, "mirror_corrupt", None) \
                and self.faults.mirror_corrupt_at(step0):
            from .io import corrupt_mirror
            for s in self._held_mirror_snaps():
                corrupt_mirror(s)

    def _resolve_oldest(self) -> dict:
        pend = self._pendings.pop(0)
        with tracing.span("verdict", step=int(pend.step0)):
            # the ONE batched pull (host-side already on the eager
            # paths) — where the diag is on device this span fences,
            # so its interval is fence-accurate by construction
            vals = _host_scalars(pend.diag, _PULL_KEYS)
            v = self._verdict_from(vals, pend.step0)
        if v.ok:
            return self._commit(pend, vals)
        return self._recover(pend, vals, v)

    @staticmethod
    def _dt_of(pend: _Pending, vals: dict) -> float:
        # prefer the dt the driver actually used (stamped into the
        # diag on every path): reconstructing it from the time
        # difference rounds differently by an ulp, and the replay
        # record must be EXACT
        dtv = vals.get("dt")
        if dtv is not None:
            return float(dtv)
        return pend.dt_host if pend.dt_host is not None else float("nan")

    def _commit(self, pend: _Pending, vals: dict) -> dict:
        sim = self.sim
        dt_used = self._dt_of(pend, vals)
        if not pend.advanced:
            # async path: the driver left the clock to the verdict;
            # commits run in step order, so sim.time is settled through
            # the previous step here
            sim.time = sim.time + dt_used
            if hasattr(sim, "_last_iters") and not pend.exact \
                    and vals.get("poisson_iters") is not None:
                # the pulled count IS the drained trigger scalar. The
                # r4-documented one-step hysteresis lag is closed by
                # the freshness window in step(): while the trigger is
                # re-armed, the verdict resolves BEFORE the next
                # dispatch, so the upgrade lands one step earlier.
                # The first production count closes the window — the
                # trigger is sticky, later counts only re-confirm.
                sim._last_iters = int(vals["poisson_iters"])
                sim._last_iters_dev = None
                self._trigger_fresh = False
        if self.watchdog is not None:
            self.watchdog.observe(vals)
        if pend.snap is not None:
            # promote to confirmed anchor; its capture-time clock (and
            # on the async paths the trigger count) was lagged —
            # settle both now
            pend.snap.meta["time"] = sim.time
            if hasattr(sim, "_coarse_on"):
                pend.snap.meta["coarse_on"] = bool(sim._coarse_on)
                pend.snap.meta["last_iters"] = int(sim._last_iters)
            self.ring.append(pend.snap)
            self._replay.clear()
        else:
            self._replay.append((dt_used, pend.exact, pend.trig))
        if self.faults is not None:
            self.faults.fire_post_step(pend.step0 + 1)
        # host scalars replace any device originals: a downstream
        # metrics consumer must never pay a SECOND device_get
        rec = {**pend.diag, **vals, "step": pend.step0 + 1,
               "t": sim.time, "dt": dt_used}
        if pend.mode is not None:
            # dispatch-time solve-path label (see _Pending.mode): the
            # recorder prefers this over the live sim property, which
            # may already reflect a later dispatch's trigger flip
            rec["poisson_mode"] = pend.mode
        if pend.tier is not None:
            # dispatch-time kernel-tier label (BC-token-suffixed,
            # ISSUE 16), same lagged-commit rule
            rec["kernel_tier"] = pend.tier
        return rec

    def _verdict_from(self, vals: dict, step: int) -> StepVerdict:
        tol = float(getattr(self.sim.cfg, "poisson_tol", 0.0))
        v = health_verdict(vals,
                           residual_ok=(100.0 * tol if tol > 0 else None))
        if v.ok and self.watchdog is not None:
            reason = self.watchdog.check(vals)
            if reason is not None:
                v = StepVerdict(False, reason)
        if v.ok and self.faults is not None \
                and self.faults.poisson_giveup_at(step):
            v = StepVerdict(False, "poisson_giveup(injected)")
        return v

    def _discard_pendings(self) -> None:
        """Drop every in-flight dispatch (and its optimistic snapshot)
        and REFUND the fault counts each one consumed, so an injection
        armed for a discarded step still fires at its real re-dispatch.
        Shared by the ladder (garbage dispatched on top of a bad step)
        and the elastic path (dispatches issued against a lost
        topology) — one refund rule, one place."""
        for p in self._pendings:
            for ent in p.fired:
                ent[1] += 1
        self._pendings.clear()

    # -- the recovery ladder ------------------------------------------
    def _recover(self, pend: _Pending, vals: dict,
                 v: StepVerdict) -> dict:
        sim = self.sim
        # any step dispatched on top of the bad one is garbage (the bad
        # step's own fault genuinely fired and is not refunded)
        self._discard_pendings()
        step0 = pend.step0
        dt_used = self._dt_of(pend, vals)
        rung = 0
        retry_dt: Optional[float] = None
        with tracing.span("recover", step=int(step0), verdict=v.reason):
            while True:
                action = self._next_action(rung)
                # one span per ladder rung, named by its action — an
                # aborting rung keeps its interval (error-marked), so
                # the timeline shows where the ladder died
                with tracing.span(action, step=int(step0), rung=rung):
                    if action == "abort":
                        self._abort(step0, v, vals, dt_used)
                    replayed = 0
                    if action in ("retry", "escalate"):
                        replayed = self._rewind_replay()
                        if pend.trig is not None:
                            # the retry consults the trigger with the
                            # same inputs the failed step's dispatch saw
                            self.sim._coarse_on, self.sim._last_iters \
                                = pend.trig
                            self.sim._last_iters_dev = None
                        if action == "retry":
                            # half the failed dt; a nonfinite dt (fault
                            # at a cold-cache step) falls back to a
                            # fresh CFL dt from the restored clean state
                            retry_dt = (0.5 * dt_used
                                        if np.isfinite(dt_used)
                                        and dt_used > 0 else None)
                    else:  # disk_restore: rewind possibly many steps
                        from .io import load_checkpoint
                        load_checkpoint(self.ckpt_dir, sim)
                        self.ring.clear()
                        self._reanchor()
                        if self.watchdog is not None:
                            # the window now describes steps FORWARD of
                            # the restored point — stale as a baseline
                            self.watchdog.reset()
                        retry_dt = None
                    self._emit(step=step0, verdict=v.reason,
                               action=action, dt=dt_used, rung=rung,
                               replayed=replayed)
                    self.recoveries += 1
                    # the retry itself verdicts SYNCHRONOUSLY —
                    # recovery is the cold path, the lag exists for
                    # the steady state
                    t0, s0 = sim.time, sim.step_count
                    exact_retry = action == "escalate"
                    trig = self._trigger_state()
                    diag = self._attempt(retry_dt, exact=exact_retry)
                    advanced = sim.time != t0
                    vals = _host_scalars(diag, _PULL_KEYS)
                    v2 = self._verdict_from(vals, s0)
                    p2 = _Pending(
                        step0=s0, t0=t0, diag=diag,
                        exact=bool(s0 < 10 or exact_retry),
                        dt_host=(sim.time - t0 if advanced else None),
                        advanced=advanced, trig=trig)
                    if v2.ok:
                        # recovered: take a FRESH anchor
                        # unconditionally (the replay list must
                        # restart from a clean base)
                        p2.snap = self._snapshot()
                        self._since_snap = 0
                        return self._commit(p2, vals)
                    v = v2
                    dt_used = self._dt_of(p2, vals)
                    rung += 1

    def _rewind_replay(self) -> int:
        """Restore the latest anchor, then replay the recorded good
        steps bit-exactly (same dts, same exact-solve and trigger
        branches, faults suspended, no verdict pulls) up to the failed
        step."""
        from .io import restore_snapshot_device
        restore_snapshot_device(self.sim, self.ring[-1])
        return self._replay_recorded()

    def _replay_recorded(self) -> int:
        """Replay the recorded good steps since the anchor (the loop
        half of :meth:`_rewind_replay`; the elastic path calls it after
        its own re-sharding restore — there the replay runs on the NEW
        mesh, so it reproduces the committed steps to the sharded-
        equality bound rather than bit-exactly)."""
        import contextlib
        sim = self.sim
        n = len(self._replay)
        if not n:
            return 0
        ctx = (self.faults.suspend() if self.faults is not None
               else contextlib.nullcontext())
        # replayed steps were already force-logged when they first ran
        # good — re-logging them would append duplicate rows with
        # rewound times to the force CSV
        cfe = getattr(sim, "compute_forces_every", None)
        if cfe is not None:
            sim.compute_forces_every = 0
        try:
            with ctx:
                for rdt, rexact, rtrig in self._replay:
                    t0 = sim.time
                    if rtrig is not None:
                        # the trigger inputs as-of this step's ORIGINAL
                        # dispatch: replay must take the same
                        # preconditioner branch
                        sim._coarse_on, sim._last_iters = rtrig
                        sim._last_iters_dev = None
                    if rexact:
                        sim._force_exact = True
                    try:
                        sim.step_once(dt=rdt)
                    finally:
                        if rexact:
                            sim._force_exact = False
                    if sim.time == t0:
                        # async driver: settle the clock from the
                        # recorded dt (the same float the original
                        # commit pulled)
                        sim.time = t0 + rdt
        finally:
            if cfe is not None:
                sim.compute_forces_every = cfe
        self.replayed_steps += n
        return n

    def _attempt(self, dt, exact: bool = False) -> dict:
        sim = self.sim
        self._last_fired = (self.faults.apply_pre_step(sim)
                            if self.faults is not None else ())
        if exact:
            sim._force_exact = True
        # enqueue-side span: on the async paths the dispatch returns
        # with the diag still in flight — this times the enqueue, the
        # verdict span times the fence (the pipeline it must not stall)
        with tracing.span("dispatch", step=int(sim.step_count)):
            try:
                return sim.step_once(dt=dt)
            finally:
                if exact:
                    sim._force_exact = False

    def _next_action(self, rung: int) -> str:
        if not self.recover:
            return "abort"
        if rung == 0:
            return "retry"
        if rung == 1:
            return "escalate"
        if rung == 2 and self._disk_available():
            return "disk_restore"
        return "abort"

    def _emit(self, event: str = "recovery", **fields) -> None:
        if self.event_log is not None:
            self.event_log.emit(event=event,
                                sim_time=float(self.sim.time), **fields)

    def _abort(self, step: int, v: StepVerdict, vals: dict,
               dt_used: float) -> None:
        """The last rung: post-mortem checkpoint + diagnostic dump of
        the dead state, force log closed, one final event — then raise.
        A dead run must always leave enough on disk to be diagnosed and
        (where the fault was environmental) resumed."""
        sim = self.sim
        pm = None
        if self.postmortem_dir:
            try:
                from .io import save_checkpoint
                save_checkpoint(self.postmortem_dir, sim)
                pm = self.postmortem_dir
            except Exception as e:   # the abort must not be masked
                print(f"cup2d_tpu: post-mortem checkpoint failed: {e}",
                      file=sys.stderr)
        flog = getattr(sim, "force_log", None)
        if flog is not None and not flog.closed:
            flog.close()
        summary = {k: _as_float(vals[k])
                   for k in ("umax", "poisson_residual", "poisson_iters")
                   if k in vals}
        self._emit(step=step, verdict=v.reason, action="abort",
                   dt=dt_used, postmortem=pm, diag=summary)
        raise ResilienceAbort(
            f"step {step}: {v.reason}; recovery ladder exhausted"
            + (f" (post-mortem checkpoint: {pm})" if pm else ""))

    # -- elastic topology recovery (PR 7) ------------------------------
    def elastic_recover(self, topo: "TopologyGuard") -> None:
        """Re-mesh the survivors and resume in place after ``topo``
        declared a topology loss — no process relaunch.

        Sequence (every stage one JSONL event):

        1. every in-flight dispatch is garbage — it was issued against
           the LOST topology (on a real pod its collectives would hang;
           even verdicted-good pendings are dropped so the resume point
           is a CONFIRMED anchor) — discard + refund its fault counts,
           exactly like the ladder's discard;
        2. survivors (deterministic on every process — same evidence,
           same rule, see TopologyGuard) become a fresh 1-D mesh and
           ``sim.remesh`` rebuilds placement/tables/step executables
           over it (the SFC block partition is device-count-parametric,
           so the forest re-partitions by construction);
        3. state, down a four-rung ladder:

           - **ring** — the latest anchor whose OWN shards still cover
             the survivor set (``io.snapshot_covers`` with the mirror
             tier masked off; a shard_loss drill voids this rung by
             construction — the owner bytes are destroyed) —
             re-sharded onto the new mesh by
             ``io.restore_snapshot_resharded``, then the recorded
             steps since the anchor replayed on the new mesh;
           - **mirror** — the anchor carries a host-redundant mirror
             and every lost host's ring neighbor survived
             (mirror-aware ``snapshot_covers``): the neighbor-held
             blocks are checksum-verified (``io.verify_mirror``; a
             torn/corrupt mirror emits one ``mirror_reject`` event and
             falls through rather than installing bad bytes),
             realigned over the lost columns
             (``io.restore_snapshot_mirrored``), and replayed exactly
             like the ring rung — same trajectory, in-HBM resume;
           - **disk** — the last checkpoint, watchdog baseline reset;
           - **abort** — standard post-mortem machinery.

        The ring is re-anchored on the new topology afterwards (old
        entries carry lost-mesh placement and must never be restored),
        and the mirror tier is resized to the surviving host count
        (disabled when fewer than two hosts remain — no neighbor left
        to hold a mirror).
        """
        with tracing.span("remesh", step=int(self.sim.step_count),
                          epoch=int(topo.epoch)):
            return self._elastic_recover(topo)

    def _elastic_recover(self, topo: "TopologyGuard") -> None:
        import time as _time

        sim = self.sim
        t0 = _time.perf_counter()
        import jax
        if jax.default_backend() == "cpu":
            # recovery fence, CPU ONLY: dispatched-but-unverdicted
            # steps may still be executing, and their halo collectives
            # share devices with the recovery launches (verify sums,
            # mirror realign). The CPU client honors no cross-launch
            # device order, so racing them can deadlock at rendezvous
            # (io.mirror_snapshot documents the capture-side twin).
            # Settle everything in flight before the first recovery
            # launch; TPU's enqueue-ordered streams don't need this.
            for a in jax.tree_util.tree_leaves(
                    [(p.snap, p.diag) for p in self._pendings]
                    + [getattr(sim, "state", None)]):
                if hasattr(a, "block_until_ready"):
                    a.block_until_ready()
        # stage 1: discard + refund (the ladder's garbage-dispatch rule)
        self._discard_pendings()
        survivors = topo.survivor_devices()
        from .io import load_checkpoint, restore_snapshot_resharded, \
            restore_snapshot_mirrored, snapshot_covers, verify_mirror, \
            destroy_shards
        # real-loss honesty (shard_loss drill): zero the destroyed
        # hosts' shard slices — live state, every held snapshot payload
        # AND the physical mirror slices they held — BEFORE choosing a
        # rung, so a successful resume provably sourced the survivors'
        # mirror copies, not the "lost" originals
        destroyed = tuple(topo.destroyed_hosts())
        lost_hosts = tuple(topo.lost_host_indices())
        if destroyed:
            wiped = destroy_shards(sim, list(self.ring), destroyed,
                                   topo.n_hosts)
            self.ring.clear()
            self.ring.extend(wiped)
        anchor = self.ring[-1] if self.ring else None
        lost_p = topo.lost_process_indices()
        use_ring = anchor is not None and not destroyed \
            and snapshot_covers(anchor, lost_p, mirror=False)
        use_mirror = False
        if not use_ring and anchor is not None and snapshot_covers(
                anchor, lost_p, lost_hosts=lost_hosts,
                shards_destroyed=bool(destroyed)):
            dead = tuple(sorted(set(lost_hosts) | set(lost_p)))
            bad = verify_mirror(anchor, dead)
            if bad:
                # torn/corrupt mirror: never install it — reject loudly
                # (durable event) and fall through to disk
                if self.event_log is not None:
                    self.event_log.emit(
                        event="mirror_reject", step=int(sim.step_count),
                        n_rejects=len(bad), rejects=bad[:8])
            else:
                use_mirror = True
        if not use_ring and not use_mirror and not self._disk_available():
            v = StepVerdict(False, "topology_lost")
            self._abort(sim.step_count, v,
                        {}, float("nan"))
        if not survivors:
            raise ResilienceAbort("topology loss left no survivor "
                                  "devices — nothing to re-mesh onto")
        # stage 2: re-mesh (lazy import: resilience must not drag the
        # sharded stack into single-device library users)
        from .parallel.mesh import make_mesh
        mesh = make_mesh(devices=survivors)
        sim.remesh(mesh)
        # stage 3: resume
        replayed = 0
        if use_ring:
            restore_snapshot_resharded(sim, anchor)
            replayed = self._replay_recorded()
            source = "ring"
        elif use_mirror:
            dead = tuple(sorted(set(lost_hosts) | set(lost_p)))
            restore_snapshot_mirrored(sim, anchor, dead)
            replayed = self._replay_recorded()
            source = "mirror"
        else:
            load_checkpoint(self.ckpt_dir, sim)
            if self.watchdog is not None:
                # the window describes steps forward of the restored
                # point — stale as a baseline (same rule as the ladder's
                # disk rung; the ring/mirror paths resume the SAME
                # trajectory, so its window stays valid)
                self.watchdog.reset()
            source = "disk"
        self.restore_source = source
        # resize the mirror tier to the surviving hosts: below two
        # there is no neighbor left to hold a mirror
        if self.mirror_hosts is not None:
            alive = topo.alive_host_count()
            self.mirror_hosts = alive if alive >= 2 else None
        self.ring.clear()
        self._reanchor()
        self.topology_epoch = int(topo.epoch)
        self.remesh_count += 1
        ms = 1e3 * (_time.perf_counter() - t0)
        self.remesh_ms_total += ms
        self.recoveries += 1
        if self.event_log is not None:
            self.event_log.emit(
                event="remesh", epoch=int(topo.epoch), source=source,
                devices=len(survivors), step=int(sim.step_count),
                sim_time=float(sim.time), replayed=replayed,
                ms=round(ms, 3))


# ---------------------------------------------------------------------------
# per-member supervision for the fleet-batched driver (fleet.py)
# ---------------------------------------------------------------------------

class FleetStepGuard(StepGuard):
    """Vectorized verdicts + per-member recovery for ``FleetSim``.

    The fused fleet dispatch is the hot path: ONE batched pull carries
    [B] diag vectors, every member is classified independently (the
    same ``health_verdict`` policy per member, plus an independent
    :class:`PhysicsWatchdog` clone per member — pass one prototype via
    ``watchdog=`` and it is deep-copied B times). Recovery is the cold
    path and PER MEMBER:

    - a bad member restores ONLY its slice of the latest device
      snapshot (``FleetSim.set_member_state`` — every other member's
      values pass through bit-unchanged), replays its recorded
      per-member dts solo through ``member_step_once`` (faults
      suspended, exact-solve branches reproduced), then retries the
      failed step at dt/2 and, on a second failure, with the exact
      Poisson solve;
    - HEALTHY MEMBERS NEVER REWIND: their step-N states from the fused
      dispatch commit as usual, bit-identical to an unfaulted run
      (tests/test_fleet.py pins this with a per-member NaN drill);
    - the per-member ladder has NO disk rung — a disk restore would
      rewind every member (healthy trajectories included), so it goes
      retry -> escalate -> abort, and whole-fleet disk restore remains
      the operator-level restart path.

    Solo replay note: the solo executable deviates from the fused
    member slice by the documented ~1e-16..1e-13 MG FMA-contraction
    noise (fleet.py module docstring), so a replayed member is
    equal-to-solo, not bit-equal-to-fused; the default ``snap_every=1``
    keeps fleet replays at zero steps unless a cadence is requested.

    The fleet verdict is EAGER (``lag`` is forced off): under the
    one-step-lagged verdict a dispatch stacked on an undetected-bad
    step N is discarded wholesale — but a FLEET dispatch of step N+1
    is garbage only in the bad member's slice and a perfectly good
    step N+1 for the other B-1 members, so discarding it would either
    rewind healthy members (recomputing their trajectories — exactly
    what per-member recovery forbids) or fork a per-member step-count
    catch-up. Verdicting eagerly costs NO extra pull: it is the same
    ONE batched device_get per step the sync fleet driver already
    pays for the whole fleet — the fleet's throughput lever is
    dispatch amortization across members, which is orthogonal to the
    lag (a latency lever for the single-case drivers).

    Injected ``poisson_giveup`` faults flag member 0 (the same member
    ``faults.poison_velocity``/``scale_velocity`` target on a fleet).

    Serving mode (``on_member_abort=``, wired by ``fleet.FleetServer``):
    the exhausted ladder EVICTS the one bad member — ``member_aborted``
    event, callback frees the slot, the fleet lives on — instead of
    raising :class:`ResilienceAbort`. Slots masked inactive by the
    server are skipped by the per-member verdicts and watchdogs (their
    lanes are select-frozen identity; classifying a parked slot's
    stale diag would evict ghosts).
    """

    def __init__(self, sim, *, watchdog=None, on_member_abort=None,
                 **kw):
        kw["lag"] = False     # eager by design — see the docstring
        super().__init__(sim, watchdog=None, **kw)
        import copy
        self._watchdog_proto = watchdog
        self.member_watchdogs = (
            [copy.deepcopy(watchdog) for _ in range(sim.members)]
            if watchdog is not None else None)
        self.on_member_abort = on_member_abort
        self.evictions = 0

    def _member_active(self, m: int) -> bool:
        act = getattr(self.sim, "active_mask", None)
        return True if act is None else bool(act[m])

    def reset_member_watchdog(self, m: int) -> None:
        """Fresh watchdog clone for slot ``m`` (server admission: the
        slot's history belongs to the previous occupant)."""
        if self.member_watchdogs is not None:
            import copy
            self.member_watchdogs[m] = copy.deepcopy(self._watchdog_proto)

    def reanchor(self) -> None:
        """Fresh snapshot anchor + clean replay base. The server calls
        this after an admission batch so a later per-member rewind can
        never restore PRE-admit slot contents (the eager fleet verdict
        guarantees no dispatch is in flight between steps)."""
        self.ring.append(self._snapshot())
        self._replay.clear()
        self._since_snap = 0

    # -- vectorized verdict -------------------------------------------
    def _resolve_oldest(self) -> dict:
        pend = self._pendings.pop(0)
        with tracing.span("verdict", step=int(pend.step0)):
            vals = _host_scalars(pend.diag, _PULL_KEYS)   # [B] vectors
            verdicts = self._member_verdicts(vals, pend.step0)
            bad = [m for m, v in enumerate(verdicts) if not v.ok]
        if not bad:
            return self._commit(pend, vals)
        return self._recover_members(pend, vals, verdicts, bad)

    def _one_member_verdict(self, m: int, mv: dict,
                            step: int) -> StepVerdict:
        """THE per-member verdict policy — shared by the fused-dispatch
        classification and the solo retry, so a policy change can never
        drift between them: health -> per-member watchdog -> member-0
        giveup injection."""
        tol = float(getattr(self.sim.cfg, "poisson_tol", 0.0))
        v = health_verdict(mv,
                           residual_ok=(100.0 * tol if tol > 0 else None))
        if v.ok and self.member_watchdogs is not None:
            reason = self.member_watchdogs[m].check(mv)
            if reason is not None:
                v = StepVerdict(False, reason)
        if v.ok and m == 0 and self.faults is not None \
                and self.faults.poisson_giveup_at(step):
            v = StepVerdict(False, "poisson_giveup(injected)")
        return v

    def _member_verdicts(self, vals: dict, step: int) -> list:
        return [
            self._one_member_verdict(
                m, {k: v[m] for k, v in vals.items() if np.ndim(v) >= 1},
                step)
            if self._member_active(m)
            # parked slot: its lane is select-frozen identity — always
            # healthy by construction, never classified
            else StepVerdict(True, "inactive")
            for m in range(self.sim.members)]

    def _commit(self, pend: _Pending, vals: dict) -> dict:
        sim = self.sim
        dts = np.asarray(vals["dt"], np.float64)
        if not pend.advanced:
            # async path: settle every member's clock from the pulled
            # per-member dt vector (commits run in step order; a dead
            # slot's pulled dt is exactly 0.0 — its clock freezes)
            sim.times = sim.times + dts
            sim.time = sim._fleet_time()
        if self.member_watchdogs is not None:
            for m in range(sim.members):
                if self._member_active(m):
                    self.member_watchdogs[m].observe(
                        {k: v[m] for k, v in vals.items()})
        if pend.snap is not None:
            # capture-time clocks were lagged — settle them now
            pend.snap.meta["time"] = sim.time
            pend.snap.meta["times"] = np.array(sim.times)
            self.ring.append(pend.snap)
            self._replay.clear()
        else:
            self._replay.append((dts, pend.exact, None))
        if self.faults is not None:
            self.faults.fire_post_step(pend.step0 + 1)
        rec = {**pend.diag, **vals, "step": pend.step0 + 1,
               "t": sim.time, "dt": dts}
        if pend.mode is not None:
            rec["poisson_mode"] = pend.mode   # dispatch-time label
        if pend.tier is not None:
            rec["kernel_tier"] = pend.tier    # dispatch-time label
        return rec

    # -- per-member recovery ------------------------------------------
    def _recover_members(self, pend: _Pending, vals: dict,
                         verdicts: list, bad: list) -> dict:
        sim = self.sim
        # discard (and refund) any dispatch stacked on the bad step
        self._discard_pendings()
        # the optimistic post-step snapshot contains the bad slices —
        # it must never become an anchor
        pend.snap = None
        vals = {k: np.array(v) for k, v in vals.items()}   # writable
        dts = np.asarray(vals["dt"], np.float64)
        if not pend.advanced:
            # commit the HEALTHY members' step N (their fused results
            # are good; they never rewind)
            for m in range(sim.members):
                if verdicts[m].ok:
                    sim.times[m] += dts[m]
        # the dt cache may hold a discarded garbage dispatch's dt_next
        # (lagged mode dispatched N+1 on top of the bad N): re-anchor
        # EVERY member on step N's pulled dt_next — the same floats the
        # unfaulted run keeps on device, so healthy trajectories stay
        # bit-identical
        import jax.numpy as jnp
        sim._next_dt = jnp.asarray(np.asarray(vals["dt_next"]),
                                   sim.grid.dtype)
        anchor = self.ring[-1]
        for m in bad:
            mv = self._recover_member(m, anchor, pend.step0, vals,
                                      verdicts[m])
            # the record reflects what actually committed for m
            for k, val in mv.items():
                if k in vals and np.ndim(vals[k]) >= 1:
                    vals[k][m] = val
        if self.member_watchdogs is not None:
            for m in range(sim.members):
                if verdicts[m].ok and self._member_active(m):
                    self.member_watchdogs[m].observe(
                        {k: v[m] for k, v in vals.items()})
        sim.time = sim._fleet_time()
        # every member healthy again: fresh anchor, clean replay base
        self.ring.append(self._snapshot())
        self._replay.clear()
        self._since_snap = 0
        if self.faults is not None:
            self.faults.fire_post_step(pend.step0 + 1)
        return {**pend.diag, **vals, "step": pend.step0 + 1,
                "t": sim.time, "dt": np.asarray(vals["dt"])}

    def _recover_member(self, m: int, anchor, step0: int, vals: dict,
                        v: StepVerdict) -> dict:
        sim = self.sim
        dt_used = float(np.asarray(vals["dt"])[m])
        rung = 0
        with tracing.span("recover", step=int(step0), member=m,
                          verdict=v.reason):
            while True:
                if not self.recover or rung >= 2:
                    # serving mode nests the server's client-attributed
                    # "evict" span here (the on_member_abort callback)
                    self._abort_member(m, step0, v, vals, dt_used)
                    # eviction (serving mode): the slot is free, the
                    # fleet lives on — patch the record with an inert
                    # lane so the fold aggregates don't carry the dead
                    # member's NaNs
                    return {"dt": 0.0, "dt_next": 1.0, "finite": True,
                            "umax": 0.0, "energy": 0.0,
                            "div_linf": 0.0, "poisson_iters": 0,
                            "poisson_residual": 0.0,
                            "poisson_stalled": False,
                            "poisson_converged": True,
                            "precond_cycles": 0}
                action = "retry" if rung == 0 else "escalate"
                with tracing.span(action, step=int(step0), member=m,
                                  rung=rung):
                    replayed = self._rewind_member(m, anchor)
                    exact = rung == 1
                    retry_dt = (0.5 * dt_used
                                if rung == 0 and np.isfinite(dt_used)
                                and dt_used > 0 else None)
                    self._emit(step=step0, member=m, verdict=v.reason,
                               action=action, dt=dt_used, rung=rung,
                               replayed=replayed)
                    self.recoveries += 1
                    # the retry is a FRESH attempt of step0: armed *K
                    # faults re-fire (looked up by the step being
                    # retried — the SHARED fleet counter already
                    # advanced past it)
                    self._last_fired = (
                        self.faults.apply_pre_step(sim, step=step0)
                        if self.faults is not None else ())
                    diag = sim.member_step_once(
                        m, dt=retry_dt, exact=(exact or step0 < 10))
                    mv = _host_scalars(diag, _PULL_KEYS)
                    v2 = self._one_member_verdict(m, mv, step0)
                    if v2.ok:
                        sim.times[m] += float(mv["dt"])
                        sim.time = float(sim.times.min())
                        sim.set_member_next_dt(m, mv["dt_next"])
                        if self.member_watchdogs is not None:
                            self.member_watchdogs[m].observe(mv)
                        return mv
                    v = v2
                    dt_used = float(mv["dt"])
                    rung += 1

    def _rewind_member(self, m: int, anchor) -> int:
        """Restore member ``m``'s slice from the anchor snapshot, then
        replay its recorded per-member dts solo (faults suspended, no
        verdict pulls) up to the failed step."""
        import contextlib
        sim = self.sim
        sim.set_member_state(m, type(sim.state)(
            *(anchor.payload[k][m] for k in sim.state._fields)))
        sim.times[m] = float(np.asarray(anchor.meta["times"])[m])
        n = 0
        ctx = (self.faults.suspend() if self.faults is not None
               else contextlib.nullcontext())
        with ctx:
            for rdts, rexact, _ in self._replay:
                rdt = float(np.asarray(rdts)[m])
                if rdt == 0.0:
                    # the member sat parked (masked dead) for this
                    # recorded step: its lane was frozen identity, so
                    # replay is a no-op for it
                    continue
                sim.member_step_once(m, dt=rdt, exact=rexact)
                sim.times[m] += rdt
                n += 1
        self.replayed_steps += n
        return n

    def _abort_member(self, m: int, step: int, v: StepVerdict,
                      vals: dict, dt_used: float) -> None:
        sim = self.sim
        summary = {k: _as_float(np.asarray(vals[k])[m])
                   for k in ("umax", "poisson_residual", "poisson_iters")
                   if k in vals}
        if self.on_member_abort is not None:
            # serving mode: EVICT the one bad member. The callback
            # (FleetServer._on_member_abort) zeroes the slot and masks
            # it dead; scrubbing the dt cache keeps the evicted lane's
            # NaN out of the next dispatch's operands (the masked step
            # would sanitize it anyway — this keeps the cache clean for
            # the host side too). Healthy members never rewound, and
            # _recover_members re-anchors on the post-eviction state.
            self._emit(event="member_aborted", step=step, member=m,
                       verdict=v.reason, action="evict", dt=dt_used,
                       diag=summary)
            self.evictions += 1
            self.on_member_abort(m, v.reason, step)
            sim.set_member_next_dt(m, 1.0)
            return
        pm = None
        if self.postmortem_dir:
            try:
                from .io import save_checkpoint
                save_checkpoint(self.postmortem_dir, sim)
                pm = self.postmortem_dir
            except Exception as e:   # the abort must not be masked
                print(f"cup2d_tpu: post-mortem checkpoint failed: {e}",
                      file=sys.stderr)
        flog = getattr(sim, "force_log", None)
        if flog is not None and not flog.closed:
            flog.close()
        self._emit(step=step, member=m, verdict=v.reason,
                   action="abort", dt=dt_used, postmortem=pm,
                   diag=summary)
        raise ResilienceAbort(
            f"step {step}, member {m}: {v.reason}; per-member ladder "
            "exhausted"
            + (f" (post-mortem checkpoint: {pm})" if pm else ""))


def _on_device(diag: dict) -> bool:
    import jax
    return any(isinstance(v, jax.Array) for v in diag.values())


def _as_float(x) -> float:
    try:
        return float(np.asarray(x))
    except Exception:
        return float("nan")


# ---------------------------------------------------------------------------
# preemption-safe shutdown
# ---------------------------------------------------------------------------

class PreemptionGuard:
    """Latches SIGTERM (and optionally other signals) into a flag the
    driver loop polls at step boundaries. Installing mid-collective-safe
    shutdown any other way is not possible: the handler must not touch
    device state, so it only sets the flag."""

    def __init__(self):
        self.triggered = False
        self.signum: Optional[int] = None
        self._prev: dict = {}

    def install(self, signums=None) -> "PreemptionGuard":
        import signal
        if signums is None:
            signums = (signal.SIGTERM,)

        def _handler(signum, frame):
            self.triggered = True
            self.signum = signum

        for s in signums:
            self._prev[s] = signal.signal(s, _handler)
        return self

    def agree(self) -> bool:
        """Cross-process agreement on the latch (the former ROADMAP pod
        gap (a)): hosts preempted at different instants must not enter
        MISMATCHED collectives — one stepping while another starts the
        collective checkpoint save hangs the SPMD program out its grace
        window. The flag itself stays per-process (a signal handler
        cannot run collectives); the DECISION is made here: at every
        step boundary each process contributes its local flag to a tiny
        min-allreduce (an allgather of one int32 — the cheap dedicated
        collective; on pods it rides DCN in microseconds against a
        multi-ms step), and the checkpoint fires only once EVERY
        process has latched — so all hosts enter the collective save at
        the SAME step boundary. A lone signal on one host keeps the run
        alive by design: real preemption notifies every worker, and
        stopping on ANY flag would turn a stray operator signal into a
        fleet-wide shutdown. Call it at the same loop point on every
        process — it is a collective on pods. Single-host (or before
        distributed init): just the local flag, no device/collective
        cost. Drilled with skewed sigterm@N delivery by the multihost
        harness (tests/_multihost_worker.py).

        Pre-init / single-process FAST PATH: before the distributed
        runtime is up (or when it was never brought up) this is just
        the local flag — no collective, no device touch, no backend
        probe (the version-safe :func:`dist_initialized` check). Unit-
        tested in tests/test_elastic.py."""
        import jax
        if not dist_initialized() or jax.process_count() == 1:
            return self.triggered
        from jax.experimental import multihost_utils
        flags = multihost_utils.process_allgather(
            np.asarray([1 if self.triggered else 0], np.int32))
        return bool(np.min(flags) > 0)

    def uninstall(self) -> None:
        import signal
        for s, h in self._prev.items():
            signal.signal(s, h)
        self._prev.clear()


# ---------------------------------------------------------------------------
# elastic topology detection + agreement (PR 7)
# ---------------------------------------------------------------------------

def bounded_call(fn, timeout: float):
    """Run ``fn()`` with a deadline: returns ``(True, result)`` when it
    completes within ``timeout`` seconds, ``(False, None)`` when it is
    still blocked at the deadline — the hang watchdog for collectives
    (a peer that died mid-step leaves the survivors' next allgather
    blocked forever; this turns the infinite hang into evidence). The
    worker thread is a daemon: a genuinely hung collective cannot be
    cancelled, only observed — its thread is abandoned with the dying
    world. An exception inside ``fn`` is re-raised here."""
    import threading
    box: dict = {}

    def _run():
        try:
            box["result"] = fn()
        except BaseException as e:   # surfaced to the caller below
            box["error"] = e

    th = threading.Thread(target=_run, daemon=True)
    th.start()
    th.join(timeout)
    if th.is_alive():
        return False, None
    if "error" in box:
        raise box["error"]
    return True, box.get("result")


class Beat(NamedTuple):
    """One step-boundary heartbeat result (TopologyGuard.step_boundary)."""

    stop: bool          # SIGTERM agreement (PreemptionGuard semantics)
    lost: tuple         # hosts DECLARED lost at this beat (may be empty)
    self_lost: bool     # real mode: THIS process was told to die
    hung: bool          # the bounded collective missed its deadline


class TopologyGuard:
    """Detection + agreement half of the elastic recovery subsystem.

    Two modes share one protocol:

    - **Simulated** (``sim_hosts=H``, single process): the device list
      is grouped into H contiguous "hosts" (the same contiguous-range
      layout a real pod has — parallel/launch.global_mesh). Losses are
      injected by ``faults.py`` ``host_exit@N`` / ``host_hang@N``
      directives: the directive marks the highest-index alive host
      dead at step N's boundary, and each subsequent :meth:`poll` is
      one missed beat — after ``miss_k`` consecutive misses the host
      is DECLARED lost and the epoch bumps. This is the tier-1 drill
      mode: the virtual devices all remain addressable, so the
      snapshot-ring resume path runs end-to-end in one process.
    - **Real** (multi-process): the heartbeat piggybacks on the
      step-boundary collective :meth:`PreemptionGuard.agree` already
      pays — ONE allgather of ``[sigterm, epoch, exiting]`` int32s per
      process, run under ``timeout`` via :func:`bounded_call`. A
      graceful loss (``host_exit@N`` on that process) announces itself
      in its final beat (``exiting=1``), so every survivor sees the
      same evidence vector and computes the same survivor set + epoch
      — agreement by construction, no extra round. A hard loss
      (``host_hang@N``, a kill) surfaces as the next beat's deadline
      miss: the world's collectives are unusable from that instant, so
      in-place recovery additionally needs a runtime re-init
      (``parallel.launch.reinit_distributed``) before any further
      collective — the slow-marked 2-process drill's path.

    The DECISION rule is deterministic on identical evidence: survivors
    = alive hosts in original order, epoch += 1 per declaration batch.
    Every declaration emits one ``topology_lost`` JSONL event.
    """

    def __init__(self, devices=None, *, sim_hosts: Optional[int] = None,
                 miss_k: int = 3, timeout: float = 10.0,
                 faults=None, event_log=None):
        import jax
        self.devices = list(devices) if devices is not None \
            else list(jax.devices())
        self.miss_k = max(1, int(miss_k))
        self.timeout = float(timeout)
        self.faults = faults
        self.event_log = event_log
        self.epoch = 0
        self.hung = False
        self._exiting = False
        self._lost_processes: set = set()
        if sim_hosts is not None:
            h = int(sim_hosts)
            if h < 2 or len(self.devices) % h:
                raise ValueError(
                    f"sim_hosts={h}: need >= 2 simulated hosts (losing "
                    "the only host leaves nothing to re-mesh onto) "
                    f"dividing the {len(self.devices)}-device set into "
                    "equal contiguous groups")
            self.sim_hosts = h
        else:
            self.sim_hosts = None
        n = self.n_hosts
        self.alive = [True] * n
        self._dead: dict = {}      # host -> fault kind (not yet declared)
        self._missed: dict = {}    # host -> consecutive missed beats
        # hosts whose shard slices died WITH them (shard_loss@N paired
        # with the loss token — the simulated real-loss semantics; real
        # process losses carry this implicitly via lost_process_indices)
        self._destroyed: set = set()

    # -- topology bookkeeping -----------------------------------------
    @property
    def n_hosts(self) -> int:
        if self.sim_hosts is not None:
            return self.sim_hosts
        import jax
        return jax.process_count() if dist_initialized() else 1

    def _host_of(self, idx: int) -> int:
        """Host owning device index ``idx`` (contiguous groups)."""
        if self.sim_hosts is not None:
            return idx * self.sim_hosts // len(self.devices)
        return int(getattr(self.devices[idx], "process_index", 0))

    def survivor_devices(self) -> list:
        """Devices of the alive hosts, in original (SFC-contiguous)
        order — identical on every survivor by the determinism rule."""
        return [d for i, d in enumerate(self.devices)
                if self.alive[self._host_of(i)]]

    def lost_process_indices(self) -> tuple:
        """Process indices declared lost (REAL mode; empty for
        simulated hosts — the single process survives them all), for
        ``io.snapshot_covers``."""
        return tuple(sorted(self._lost_processes))

    def lost_host_indices(self) -> tuple:
        """Ring indices of every declared-lost host, BOTH modes (the
        mirror-coverage input: simulated hosts and real processes ride
        the same contiguous-block ring)."""
        return tuple(h for h in range(len(self.alive))
                     if not self.alive[h])

    def destroyed_hosts(self) -> tuple:
        """Declared-lost hosts whose shard slices died with them
        (``shard_loss@N`` consumed at the loss boundary) — the
        simulated real-loss set ``elastic_recover`` zeroes via
        ``io.destroy_shards`` before choosing a resume rung."""
        return tuple(sorted(h for h in self._destroyed
                            if not self.alive[h]))

    def alive_host_count(self) -> int:
        return sum(1 for a in self.alive if a)

    # -- detection -----------------------------------------------------
    def poll(self, step: int) -> tuple:
        """One simulated-mode heartbeat at the boundary of ``step``:
        consume any host-loss fault armed for this step, count one
        missed beat per dead-but-undeclared host, and DECLARE the ones
        that reached ``miss_k`` misses. Returns the hosts declared at
        THIS beat (empty tuple almost always)."""
        if self.faults is not None:
            for kind in self.faults.host_loss_at(step):
                h = self._highest_alive_undead()
                if h is not None:
                    self._dead[h] = kind
                    if self.faults.shard_loss_at(step):
                        # the loss takes its shard slices with it (the
                        # simulated real-loss semantics; zeroed by
                        # elastic_recover via io.destroy_shards)
                        self._destroyed.add(h)
        newly = []
        for h, kind in self._dead.items():
            if not self.alive[h]:
                continue
            self._missed[h] = self._missed.get(h, 0) + 1
            if self._missed[h] >= self.miss_k:
                newly.append(h)
        if newly:
            self._declare(newly, step)
        return tuple(newly)

    def _highest_alive_undead(self):
        for h in range(self.n_hosts - 1, -1, -1):
            if self.alive[h] and h not in self._dead:
                return h
        return None

    def _declare(self, hosts, step) -> None:
        for h in hosts:
            self.alive[h] = False
            if self.sim_hosts is None:
                self._lost_processes.add(h)
        self.epoch += 1
        if self.event_log is not None:
            self.event_log.emit(
                event="topology_lost", epoch=self.epoch,
                hosts=[int(h) for h in hosts],
                kinds=[str(self._dead.get(h, "?")) for h in hosts],
                step=int(step), miss_k=self.miss_k,
                survivors=len(self.survivor_devices()))

    # -- the piggybacked step-boundary collective ---------------------
    def step_boundary(self, stop: PreemptionGuard, step: int) -> Beat:
        """The combined step-boundary call: SIGTERM agreement AND
        heartbeat in the ONE small collective the loop already paid for
        ``PreemptionGuard.agree`` (real mode), or the local fast path +
        simulated poll (single process)."""
        import jax
        if self.sim_hosts is not None or not dist_initialized() \
                or jax.process_count() == 1:
            return Beat(stop=stop.agree(), lost=self.poll(step),
                        self_lost=False, hung=False)
        # real mode: host-loss directives are PROCESS-scoped here (the
        # same env-latched plan, a different consumer than the
        # simulated poll — sigterm@N precedent)
        self_kind = None
        if self.faults is not None:
            kinds = self.faults.host_loss_at(step)
            if kinds:
                self_kind = kinds[-1]
                if self_kind == "exit":
                    # announce in this (final) beat so the survivors'
                    # evidence is complete BEFORE the process dies
                    self._exiting = True
        from jax.experimental import multihost_utils
        payload = np.asarray(
            [1 if stop.triggered else 0, self.epoch,
             1 if self._exiting else 0], np.int32)
        done, flags = bounded_call(
            lambda: multihost_utils.process_allgather(payload),
            self.timeout)
        if not done:
            # the collective itself blocked past its deadline: a peer
            # died mid-step. The old world's collectives are unusable;
            # the caller must re-init the runtime before re-meshing.
            self.hung = True
            if self.event_log is not None:
                self.event_log.emit(event="topology_hang", step=int(step),
                                    timeout_s=self.timeout,
                                    epoch=self.epoch)
            return Beat(stop=False, lost=(), self_lost=False, hung=True)
        flags = np.asarray(flags).reshape(-1, 3)
        exiting = [p for p in range(flags.shape[0])
                   if flags[p, 2] and self.alive[p]
                   and p != jax.process_index()]
        if exiting:
            for h in exiting:
                self._dead[h] = "exit"
            self._declare(exiting, step)
        alive_rows = [p for p in range(flags.shape[0]) if self.alive[p]]
        stop_agreed = bool(np.min(flags[alive_rows, 0]) > 0)
        if self_kind == "hang":
            # simulate the hard-loss flavor: stop beating, keep the
            # process (the survivors' NEXT beat hits the deadline)
            time.sleep(1e9)
        return Beat(stop=stop_agreed, lost=tuple(exiting),
                    self_lost=(self_kind == "exit"), hung=False)
