/* AMR host-runtime kernels in C.
 *
 * The reference's regrid bookkeeping is C++ (state fixing + tree walks,
 * main.cpp:4717-4861 inside adapt()); this is the TPU build's native
 * equivalent for the host-side hot loops that scale with block count.
 * The Python fallback in amr.py implements identical semantics; the
 * test suite asserts equality on randomized forests.
 *
 * Exposed via ctypes (no pybind11 in the image); compiled lazily by
 * cup2d_tpu/native/__init__.py with `cc -O2 -shared -fPIC`.
 *
 * fix_states: the 2:1-balance sweeps over all active blocks, finest
 * level first. Blocks are given as parallel arrays (level, i, j) with a
 * state byte (1 = refine, 0 = leave, -1 = compress), mutated in place:
 *   - a block whose finer face/corner neighbor region contains a
 *     refining block must refine;
 *   - a compressing block next to a finer region stays;
 *   - a compressing block next to a same-level refining block stays.
 * The fixpoint is iteration-order independent (promotions only read
 * finalized finer-level states or are monotone), matching amr.py.
 */

#include <stdint.h>
#include <stdlib.h>
#include <string.h>

/* open-addressing hash map: packed (level, i, j) -> block index */
typedef struct {
    uint64_t *keys;
    int64_t *vals;
    uint64_t mask;
} map_t;

#define EMPTY UINT64_MAX

static inline uint64_t pack(int64_t l, int64_t i, int64_t j)
{
    /* level < 32, i/j < 2^29 (levelMax 8 x bpd 2 needs 12 bits) */
    return ((uint64_t)l << 58)
        | (((uint64_t)i & ((1ULL << 29) - 1)) << 29)
        | ((uint64_t)j & ((1ULL << 29) - 1));
}

static inline uint64_t hash64(uint64_t x)
{
    x ^= x >> 33; x *= 0xff51afd7ed558ccdULL;
    x ^= x >> 33; x *= 0xc4ceb9fe1a85ec53ULL;
    x ^= x >> 33;
    return x;
}

static int map_init(map_t *m, int64_t n)
{
    uint64_t cap = 16;
    while ((int64_t)cap < 2 * n + 8)
        cap <<= 1;
    m->keys = (uint64_t *)malloc(cap * sizeof(uint64_t));
    m->vals = (int64_t *)malloc(cap * sizeof(int64_t));
    if (!m->keys || !m->vals) {
        free(m->keys);
        free(m->vals);
        return -1;
    }
    memset(m->keys, 0xFF, cap * sizeof(uint64_t));  /* all EMPTY */
    m->mask = cap - 1;
    return 0;
}

static void map_free(map_t *m)
{
    free(m->keys);
    free(m->vals);
}

static void map_put(map_t *m, uint64_t key, int64_t val)
{
    uint64_t h = hash64(key) & m->mask;
    while (m->keys[h] != EMPTY)
        h = (h + 1) & m->mask;
    m->keys[h] = key;
    m->vals[h] = val;
}

static int64_t map_get(const map_t *m, uint64_t key)
{
    uint64_t h = hash64(key) & m->mask;
    while (m->keys[h] != EMPTY) {
        if (m->keys[h] == key)
            return m->vals[h];
        h = (h + 1) & m->mask;
    }
    return -1;
}

/* any child of (l, i, j) active => the region is refined (the forest's
 * owner_relation == -1 for positions not themselves active) */
static int region_refined(const map_t *m, int64_t l, int64_t i, int64_t j)
{
    return map_get(m, pack(l + 1, 2 * i, 2 * j)) >= 0
        || map_get(m, pack(l + 1, 2 * i + 1, 2 * j)) >= 0
        || map_get(m, pack(l + 1, 2 * i, 2 * j + 1)) >= 0
        || map_get(m, pack(l + 1, 2 * i + 1, 2 * j + 1)) >= 0;
}

int fix_states(int64_t n, const int32_t *lvl, const int32_t *bi,
               const int32_t *bj, int8_t *state, int32_t level_max,
               int32_t bpdx, int32_t bpdy)
{
    map_t m;
    if (map_init(&m, n) != 0)
        return -1;
    for (int64_t k = 0; k < n; ++k)
        map_put(&m, pack(lvl[k], bi[k], bj[k]), k);

    for (int32_t mlev = level_max - 1; mlev >= 0; --mlev) {
        /* sweep 1: refining finer neighbors force refinement;
         * compressing next to ANY finer region must stay */
        for (int64_t k = 0; k < n; ++k) {
            if (lvl[k] != mlev || state[k] == 1 || lvl[k] == level_max - 1)
                continue;
            int64_t l = lvl[k], i = bi[k], j = bj[k];
            int64_t nbx = (int64_t)bpdx << l, nby = (int64_t)bpdy << l;
            for (int cx = -1; cx <= 1 && state[k] != 1; ++cx) {
                for (int cy = -1; cy <= 1; ++cy) {
                    if (cx == 0 && cy == 0)
                        continue;
                    int64_t ni = i + cx, nj = j + cy;
                    if (ni < 0 || ni >= nbx || nj < 0 || nj >= nby)
                        continue;
                    if (map_get(&m, pack(l, ni, nj)) >= 0)
                        continue;            /* same-level active: rel 0 */
                    if (!region_refined(&m, l, ni, nj))
                        continue;            /* rel != -1 */
                    if (state[k] == -1)
                        state[k] = 0;
                    for (int a = 0; a < 2 && state[k] != 1; ++a)
                        for (int b = 0; b < 2; ++b) {
                            int64_t ck = map_get(
                                &m, pack(l + 1, 2 * ni + a, 2 * nj + b));
                            if (ck >= 0 && state[ck] == 1) {
                                state[k] = 1;
                                break;
                            }
                        }
                    if (state[k] == 1)
                        break;
                }
            }
        }
        /* sweep 2: compressing next to a same-level refining block */
        for (int64_t k = 0; k < n; ++k) {
            if (lvl[k] != mlev || state[k] != -1)
                continue;
            int64_t l = lvl[k], i = bi[k], j = bj[k];
            int done = 0;
            for (int cx = -1; cx <= 1 && !done; ++cx)
                for (int cy = -1; cy <= 1; ++cy) {
                    if (cx == 0 && cy == 0)
                        continue;
                    int64_t ck = map_get(&m, pack(l, i + cx, j + cy));
                    if (ck >= 0 && state[ck] == 1) {
                        state[k] = 0;
                        done = 1;
                        break;
                    }
                }
        }
    }
    map_free(&m);
    return 0;
}
