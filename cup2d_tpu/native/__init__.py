"""Native (C) host-runtime kernels, loaded via ctypes.

The reference implements its regrid bookkeeping in C++ inside adapt()
(main.cpp:4717-4861); `amr_host.c` is this build's native equivalent.
No pybind11 exists in the image, so the shared object is compiled
lazily with the system compiler into a content-hashed cache path and
bound with ctypes; any failure (no compiler, sandboxed tmp, exotic
platform) degrades silently to the pure-Python implementations in
amr.py, which are semantically identical (tests assert equality).

Measured honestly: at 2.7k blocks the Python sweep already costs only
~7 ms, so the native path wins ~1.2x there (marshalling-bound); the
gap is asymptotic — at the 1e5-block scale of fully developed
canonical runs the Python dict sweeps are ~0.3 s/regrid vs ~20 ms
native.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "amr_host.c")

_lib = None
_poisoned = False


def available() -> bool:
    """True when the native library loads (compiling it on first use)."""
    return _load() is not None


def _load():
    global _lib, _poisoned
    if _lib is not None or _poisoned:
        return _lib
    try:
        with open(_SRC, "rb") as f:
            src = f.read()
        key = hashlib.sha256(src).hexdigest()[:16]
        cache = os.environ.get(
            "CUP2D_NATIVE_CACHE",
            os.path.expanduser("~/.cache/cup2d_tpu_native"))
        os.makedirs(cache, exist_ok=True)
        so = os.path.join(cache, f"amr_host_{key}.so")
        if not os.path.exists(so):
            cc = os.environ.get("CC", "cc")
            tmp = so + f".tmp{os.getpid()}"
            subprocess.run(
                [cc, "-O2", "-shared", "-fPIC", _SRC, "-o", tmp],
                check=True, capture_output=True)
            os.replace(tmp, so)   # atomic: concurrent builders race safely
        lib = ctypes.CDLL(so)
        lib.fix_states.restype = ctypes.c_int
        lib.fix_states.argtypes = [
            ctypes.c_int64,
            np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(np.int8, flags="C_CONTIGUOUS"),
            ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,
        ]
        _lib = lib
    except Exception:
        _lib = None
        _poisoned = True   # don't retry the compile every call
    return _lib


def fix_states(lvl: np.ndarray, bi: np.ndarray, bj: np.ndarray,
               state: np.ndarray, level_max: int, bpdx: int,
               bpdy: int) -> bool:
    """In-place 2:1-balance state fixing; returns False if the native
    library is unavailable (caller falls back to Python)."""
    lib = _load()
    if lib is None:
        return False
    # pack() keys carry 29 bits per coordinate: degrade safely (not
    # silently-wrong) for configs beyond that
    if level_max >= 29 or (max(bpdx, bpdy) << level_max) >= (1 << 29):
        return False
    assert state.dtype == np.int8 and state.flags.c_contiguous, \
        "state must be a contiguous int8 array (mutated in place)"
    rc = lib.fix_states(
        len(lvl),
        np.ascontiguousarray(lvl, np.int32),
        np.ascontiguousarray(bi, np.int32),
        np.ascontiguousarray(bj, np.int32),
        state, level_max, bpdx, bpdy)
    return rc == 0
