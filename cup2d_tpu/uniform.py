"""Uniform-grid execution path: the whole state is dense global arrays.

When every block sits at one level (the reference's levelMax=1 degenerate
case, and the oracle configuration for the AMR path), the TPU-idiomatic
representation is NOT a block forest but plain `[Ny, Nx]` arrays: stencils
become shifted slices XLA fuses into a few kernels, the Poisson solve is
matrix-free over the same arrays, and sharding is a one-line
`NamedSharding` over rows. This module is that path, end-to-end jitted.

It reproduces the reference timestep (`/root/reference/main.cpp:6576-7290`):
CFL dt control, two-stage Heun advection-diffusion (WENO5 + central
diffusion), Brinkman penalization, pressure projection with the deltap
formulation (initial guess = old pressure, main.cpp:7007-7027), and
free-slip / Neumann box boundaries (main.cpp:3126-3256).
"""

from __future__ import annotations

import os
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .bc import (
    FREE_SLIP,
    BCTable,
    divergence_affine_bc,
    divergence_coeffs,
    pad_vector_bc,
    periodic_axes,
    pressure_signs,
)
from .config import SimConfig
from .ops.stencil import (
    advect_diffuse_rhs,
    divergence_bc,
    divergence_freeslip,
    divergence_rhs_fused,
    dt_from_umax,
    heun_substage,
    laplacian5_bc,
    laplacian5_neumann,
    vorticity,
)
from .poisson import (
    FFTDiagPlan,
    MultigridPreconditioner,
    apply_block_precond,
    bicgstab,
    block_precond_matrix,
    fft_diag_solve,
    mg_solve,
    project_correct,
)


# ---------------------------------------------------------------------------
# Ghost padding with the reference's physical BCs (main.cpp:3126-3256):
#  - vector: free-slip mirror — ghost takes the wall-adjacent cell's value
#    with the normal component negated (zeroth-order, like the reference)
#  - scalar: zero-Neumann copy of the wall-adjacent cell
# ---------------------------------------------------------------------------

def pad_scalar(p: jnp.ndarray, g: int) -> jnp.ndarray:
    """[..., Ny, Nx] -> [..., Ny+2g, Nx+2g], Neumann copy (ScalarLab)."""
    pad = [(0, 0)] * (p.ndim - 2) + [(g, g), (g, g)]
    return jnp.pad(p, pad, mode="edge")


def pad_vector(v: jnp.ndarray, g: int) -> jnp.ndarray:
    """[..., 2, Ny, Nx] -> [..., 2, Ny+2g, Nx+2g], free-slip mirror
    (VectorLab::applyBCface): u flips sign in x-ghost columns, v flips
    in y-ghost rows; corners compose both flips — exactly the
    reference's two-pass face sweep. Built as a ZERO pad (a fusible pad
    HLO) plus ghost-strip writes of the sign-flipped edge lines: the
    edge-mode pad + per-component strip multiplies this replaces cost
    4.8x more standalone at 8192^2/g=3 (75 -> 16 ms — each integer-
    indexed strip update materialized a full copy)."""
    pad = [(0, 0)] * (v.ndim - 2) + [(g, g), (g, g)]
    out = jnp.pad(v, pad)
    # per-component SLICE-indexed strip writes: integer component
    # indices materialize full copies, and a [2]-element sign-vector
    # constant costs a ~0.09 ms DMA staging per use on this chip
    # (3.5 ms/step traced) — the negation belongs in the expression.
    # y-ghosts copy u, flip v; x-ghosts flip u, copy v.
    out = out.at[..., 0:1, :g, g:-g].set(v[..., 0:1, :1, :])
    out = out.at[..., 1:2, :g, g:-g].set(-v[..., 1:2, :1, :])
    out = out.at[..., 0:1, -g:, g:-g].set(v[..., 0:1, -1:, :])
    out = out.at[..., 1:2, -g:, g:-g].set(-v[..., 1:2, -1:, :])
    # x strips read the y-padded columns so corners compose both flips
    out = out.at[..., 0:1, :, :g].set(-out[..., 0:1, :, g:g + 1])
    out = out.at[..., 1:2, :, :g].set(out[..., 1:2, :, g:g + 1])
    out = out.at[..., 0:1, :, -g:].set(-out[..., 0:1, :, -g - 1:-g])
    out = out.at[..., 1:2, :, -g:].set(out[..., 1:2, :, -g - 1:-g])
    return out


class FlowState(NamedTuple):
    """Device-side per-step state (the reference's 7 field grids,
    main.cpp:3264-3278, minus the scratch fields XLA fuses away; the
    previous pressure — the reference's ``pold`` — is just ``pres`` at
    entry to step()).

    ``us`` is the full solid velocity (rigid + deformation) targeted by
    penalization (main.cpp:6974-6975); ``udef`` is the *deformation-only*
    part entering the pressure RHS's chi*div(udef) term (main.cpp:6980-7006
    accumulates only o->udef — rigid motion is divergence-free and dropped).
    """

    vel: jnp.ndarray    # [2, Ny, Nx]
    pres: jnp.ndarray   # [Ny, Nx]
    chi: jnp.ndarray    # [Ny, Nx]
    us: jnp.ndarray     # [2, Ny, Nx]
    udef: jnp.ndarray   # [2, Ny, Nx]


def taylor_green_state(grid) -> "FlowState":
    """Taylor–Green vortex compatible with the free-slip box: u = sin cos,
    v = -cos sin has zero normal velocity at all four walls and decays
    analytically as exp(-2 nu pi^2 (1/Lx^2 + 1/Ly^2) t) — the validation
    case SURVEY.md §4 prescribes. Shared by tests, bench.py and
    __graft_entry__.py."""
    x, y = grid.cell_centers()
    lx, ly = grid.cfg.extents
    u = np.sin(np.pi * x / lx) * np.cos(np.pi * y / ly)
    v = -(ly / lx) * np.cos(np.pi * x / lx) * np.sin(np.pi * y / ly)
    vel = jnp.asarray(np.stack([u, v]), dtype=grid.dtype)
    return grid.zero_state()._replace(vel=vel)


class UniformGrid:
    """Geometry + jitted operators for one uniform resolution.

    ``use_pallas`` (or env CUP2D_PALLAS=1) swaps the whole advection +
    projection-correction chain for the fused Pallas megakernel tier
    (ops/pallas_kernels.fused_advect_heun): one HBM read, one write per
    RK substage. CUP2D_PREC=bf16 additionally stores the advection
    operands bf16 (f32 accumulation). On non-TPU hosts the tier runs
    in Pallas interpret mode — validation, not speed. XLA remains the
    default tier."""

    def __init__(self, cfg: SimConfig, level: Optional[int] = None,
                 use_pallas: Optional[bool] = None,
                 spmd_safe: bool = False,
                 bc: Optional[BCTable] = None):
        # spmd_safe: the fused-BC stencil forms have a fast pad+slice
        # variant this image's GSPMD partitioner miscompiles on sharded
        # axes (see ops/stencil._zshift); sharded sims set True
        self.spmd_safe = spmd_safe
        self.cfg = cfg
        # per-face boundary-condition table (bc.py, ISSUE 12): the
        # single source of truth for the box-edge treatment. None/
        # FREE_SLIP keeps every consumer on the UNMODIFIED legacy
        # expressions (bit-identity pinned in tests/test_bc.py).
        self.bc = (FREE_SLIP if bc is None else bc).validate()
        lvl = cfg.level_start if level is None else level
        if use_pallas is None:
            use_pallas = os.environ.get("CUP2D_PALLAS", "") == "1"
        # storage-precision latch for the fused tier (the ONE sanctioned
        # CUP2D_PREC read site — tests/test_env_latch.py): bf16 is a
        # property of the megakernel's HBM operands, meaningless without
        # the tier, so requesting it tier-less fails loudly.
        prec = os.environ.get("CUP2D_PREC", "") or "f32"
        if prec not in ("f32", "bf16"):
            raise ValueError(f"CUP2D_PREC={prec!r}: expected f32|bf16")
        if prec == "bf16" and not use_pallas:
            raise ValueError(
                "CUP2D_PREC=bf16 selects the bf16-storage variant of the "
                "fused Pallas tier; set CUP2D_PALLAS=1 (or use_pallas=True)"
                " or drop CUP2D_PREC")
        tier = "xla"
        if use_pallas:
            # capability check (ISSUE 16 retired the two construction
            # refusals): every bc.py ghost kind now has an in-VMEM
            # synthesis, and the sharded x-split routes through the
            # halo-mode kernel (shard_halo.fused_advect_heun_sharded,
            # dispatched in advect_heun once a mesh is attached) — only
            # a genuinely unsupported future kind refuses, loudly and
            # naming the token.
            from .ops.pallas_kernels import kernel_supports
            kernel_supports(self.bc)
            ny = cfg.bpdy * cfg.bs << lvl
            nx = cfg.bpdx * cfg.bs << lvl
            from .ops.pallas_kernels import fused_tier_supported
            ok = (jnp.dtype(cfg.dtype) == jnp.float32
                  and fused_tier_supported(ny, nx, prec=prec))
            if ok:
                tier = "pallas-fused-bf16" if prec == "bf16" \
                    else "pallas-fused"
            elif prec == "bf16":
                raise ValueError(
                    f"CUP2D_PREC=bf16 unsupported for this grid "
                    f"({cfg.dtype} {ny}x{nx}): the bf16 tier needs f32 "
                    "state and sublane-aligned strips (ny % 16 == 0)")
            # f32 shape/dtype misses keep the historical silent-XLA
            # fallback (the tier is an optimization, not a semantic)
        self._kernel_tier = tier
        self.use_pallas = tier != "xla"   # back-compat bool alias
        # device mesh of the sharded x-split (attach_mesh): routes the
        # fused tier through the halo-mode kernel wrapper
        self._mesh = None
        # Poisson solve-path latch (read ONCE here, the AMRSim.__init__
        # pattern — tests/test_env_latch.py sanctions this site): the
        # uniform/fleet/sharded-uniform drivers accept "fas"/"fas-f"
        # (matrix-free FAS multigrid replacing Krylov on production
        # solves, poisson.mg_solve; -f opens each solve with an
        # F-cycle); the forest-only tokens (structured/tables/fft) are
        # valid but inert here so one latched env serves a mixed
        # process. A typo must fail loudly, not silently measure the
        # default on both A/B arms.
        # "fftd" (ISSUE 20): FFT-diagonalized DIRECT solve — rides
        # THIS sanctioned read, no new latch site (the graftlint
        # assertion in tests/test_analysis.py pins that).
        pois = os.environ.get("CUP2D_POIS", "")
        if pois not in ("", "structured", "tables", "fft",
                        "fas", "fas-f", "fftd"):
            raise ValueError(
                f"CUP2D_POIS={pois!r}: expected "
                "structured|tables|fft|fas|fas-f|fftd")
        self.solver_mode = ("fftd" if pois == "fftd"
                            else "fas" if pois in ("fas", "fas-f")
                            else "bicgstab")
        self.fas_fmg = pois == "fas-f"
        self.level = lvl
        self.nx = cfg.bpdx * cfg.bs << lvl
        self.ny = cfg.bpdy * cfg.bs << lvl
        self.h = cfg.h_at(lvl)
        self.dtype = jnp.dtype(cfg.dtype)
        self.p_inv = jnp.asarray(block_precond_matrix(cfg.bs), dtype=self.dtype)
        # derived per-face operator coefficients (None on the default
        # table => every consumer takes the legacy branch verbatim)
        if self.bc.is_free_slip:
            self._psigns = None
            self._dcoeffs = None
            self._div_affine = None
            self._paxes = (False, False)
        else:
            self._psigns = pressure_signs(self.bc)
            self._dcoeffs = divergence_coeffs(self.bc)
            self._div_affine = divergence_affine_bc(
                self.bc, self.ny, self.nx, self.dtype)
            # periodic axis flags (ISSUE 20): wrap shifts in the
            # operator/divergence/gradient stencils
            self._paxes = periodic_axes(self.bc)
        # FFT-diagonalized direct solve (CUP2D_POIS=fftd): the plan's
        # transforms/eigenvalues/tridiagonal elimination coefficients
        # are host-precomputed once per grid. Needs >= 1 periodic
        # direction — a wall-only box has nothing to diagonalize.
        if self.solver_mode == "fftd":
            px, py = self._paxes
            if not (px or py):
                raise ValueError(
                    f"CUP2D_POIS=fftd needs at least one periodic "
                    f"direction, got BCTable ({self.bc.token}): the "
                    "FFT diagonalizes a periodic axis's second "
                    "difference — run wall-only boxes under "
                    "bicgstab/fas")
            self._fft_plan = FFTDiagPlan(
                self.ny, self.nx, self.dtype, px, py, self._psigns)
        else:
            self._fft_plan = None
        # multigrid V-cycle preconditioner: O(1) Krylov iterations in N,
        # where the reference's single-level block-Jacobi (kept above for
        # the oracle/AMR paths) degrades linearly in N_1d/BS.
        # The FAS full-solver path runs the cycle at SOLVER precision:
        # as a preconditioner a bf16 cycle only shapes the error and
        # flexible BiCGSTAB absorbs the inexactness, but as THE solver
        # the cycle's floor caps the reachable residual (measured: f32
        # fields + bf16 cycles stall at ~2e-4 relative, above the 1e-4
        # bench target). f32 cycles double the per-cycle bytes; the
        # solve spends 2-4 cycles total vs Krylov's 2 M-applies x 8-11
        # iterations, so the byte TOTAL still drops.
        #
        # Memory-tiered FAS (ISSUE 19): the CUP2D_PREC/CUP2D_PALLAS
        # composition extends to the SOLVER side of the fas latch —
        # bf16 lives on the cycle's smoother/transfer LEGS only
        # (leg_dtype), while mg_solve's outer loop keeps the f32 true
        # residual (iterative refinement: the legs cannot floor the
        # solve the way the fully-bf16 solver above does), and the
        # Pallas latch arms the fused strip smoother (one HBM pass per
        # sweep chain). Both are demoted truthfully by the
        # MultigridPreconditioner shape gate; prec=bf16 without the
        # Pallas tier already refused above.
        self._fas_leg_dtype = (
            jnp.bfloat16
            if (prec == "bf16" and self.solver_mode == "fas")
            else None)
        self._mg_smoother = (
            "strip"
            if (self._kernel_tier != "xla"
                and self.solver_mode == "fas")
            else "xla")
        self.mg = MultigridPreconditioner(
            self.ny, self.nx, self.dtype, spmd_safe=spmd_safe,
            cycle_dtype=(self.dtype if self.solver_mode == "fas"
                         else None),
            edge_signs=self._psigns,
            leg_dtype=self._fas_leg_dtype,
            smoother=self._mg_smoother,
            periodic=self._paxes)
        # f64 dot-product accumulation when fields are f32 AND x64 is
        # available (the Krylov scalars are precision-critical, SURVEY.md §7
        # hard part 5). Without x64, XLA's tree reduction keeps f32 error at
        # ~log(N)*eps, which holds to the reference's 1e-3 tolerance.
        self.sum_dtype = (
            jnp.float64
            if (self.dtype == jnp.float32 and jax.config.jax_enable_x64)
            else None
        )

    # -- coordinate helpers (cell centers) --
    def cell_centers(self):
        x = (np.arange(self.nx) + 0.5) * self.h
        y = (np.arange(self.ny) + 0.5) * self.h
        return np.meshgrid(x, y, indexing="xy")  # X[j,i], Y[j,i] -> [Ny, Nx]

    def zero_state(self) -> FlowState:
        # distinct buffers per field: the stepping jits donate the state,
        # and donating one aliased buffer through several fields is a
        # runtime error ("donate the same buffer twice")
        def z():
            return jnp.zeros((self.ny, self.nx), dtype=self.dtype)

        def zv():
            return jnp.zeros((2, self.ny, self.nx), dtype=self.dtype)

        return FlowState(vel=zv(), pres=z(), chi=z(), us=zv(), udef=zv())

    # -- dt control (main.cpp:6579-6595) --
    def dt_from_umax(self, umax) -> jnp.ndarray:
        return dt_from_umax(
            jnp.asarray(umax, self.dtype),
            jnp.asarray(self.h, self.dtype), self.cfg.nu, self.cfg.cfl)

    def compute_dt(self, vel: jnp.ndarray) -> jnp.ndarray:
        return self.dt_from_umax(jnp.max(jnp.abs(vel)))

    # -- Poisson operator: undivided 5-point Laplacian with the table's
    # per-face pressure rows (fused-BC form: zero-ghost shifts + rank-1
    # edge correction — see ops/stencil.laplacian5_neumann/_bc). The
    # default table takes the legacy all-Neumann expression verbatim.
    def laplacian(self, p: jnp.ndarray) -> jnp.ndarray:
        if self._psigns is None:
            return laplacian5_neumann(p, self.spmd_safe)
        sx_lo, sx_hi, sy_lo, sy_hi = self._psigns
        px, py = self._paxes
        return laplacian5_bc(p, sx_lo, sx_hi, sy_lo, sy_hi,
                             self.spmd_safe, px, py)

    # -- BC-aware ghost paint + divergence, shared with fleet.py's
    # inlined member-batched step so the table dispatch cannot
    # desynchronize between the solo and fleet paths --
    def pad_vector_field(self, v: jnp.ndarray, g: int,
                         dt=None) -> jnp.ndarray:
        """Velocity ghost paint per the table; the default table is the
        legacy free-slip mirror (``pad_vector``) unchanged. ``dt``
        feeds the convective-outflow extrapolation speed (None degrades
        outflow to zeroth-order — diagnostics only)."""
        if self.bc.is_free_slip:
            return pad_vector(v, g)
        return pad_vector_bc(v, g, self.bc, self.h, dt)

    def poisson_rhs(self, vel, chi, udef, dt) -> jnp.ndarray:
        """(h/2dt)[div u* - chi div u_def] with the table's per-face
        edge coefficients + the prescribed wall-normal-velocity affine
        term (bc.divergence_affine_bc). ``chi=None`` drops the
        obstacle term. Default table = the legacy fused expressions
        bit-identically."""
        h = self.h
        if self._dcoeffs is None:
            if chi is None:
                return (0.5 * h / dt) * divergence_freeslip(
                    vel, self.spmd_safe)
            return divergence_rhs_fused(vel, udef, chi, h, dt,
                                        self.spmd_safe)
        fac = 0.5 * h / dt
        b = fac * divergence_bc(vel, *self._dcoeffs, self.spmd_safe,
                                *self._paxes)
        if self._div_affine is not None:
            b = b + fac * self._div_affine
        if chi is not None:
            b = b - (fac * chi) * divergence_bc(
                udef, *self._dcoeffs, self.spmd_safe, *self._paxes)
        return b

    def precond(self, r: jnp.ndarray) -> jnp.ndarray:
        return apply_block_precond(r, self.p_inv, self.cfg.bs)

    @property
    def poisson_mode(self) -> str:
        """The active solve-path latch, for the telemetry stream
        (schema v4 ``poisson_mode``; v12 adds the fftd vocabulary):
        ``fftd`` = pure spectral divide (both directions periodic),
        ``fftd+tridiag`` = per-mode Thomas systems (one periodic)."""
        if self.solver_mode == "fftd":
            return "fftd" if (self._paxes[0] and self._paxes[1]) \
                else "fftd+tridiag"
        if self.solver_mode == "fas":
            return "fas-f" if self.fas_fmg else "fas"
        return "bicgstab+mg" if self.cfg.precond else "bicgstab"

    @property
    def kernel_tier(self) -> str:
        """Active advection-kernel tier latch (telemetry schema v6):
        xla | pallas-fused | pallas-fused-bf16, with the BC token
        suffixed on BC'd fused tiers (ISSUE 16, e.g.
        ``pallas-fused+bc(in,out,fs,fs)``) — the suffix IS the
        executable identity (one compile per token). Internal
        dispatch compares the bare ``_kernel_tier`` latch."""
        if self._kernel_tier != "xla" and not self.bc.is_free_slip:
            return f"{self._kernel_tier}+bc({self.bc.token})"
        return self._kernel_tier

    @property
    def prec_mode(self) -> str:
        """Storage-precision contract of the advection hot loop
        (telemetry schema v6): the bf16 tier stores HBM operands bf16
        (f32 accumulation); otherwise the state dtype."""
        if self._kernel_tier == "pallas-fused-bf16":
            return "bf16"
        return {"float32": "f32", "float64": "f64"}.get(
            self.dtype.name, self.dtype.name)

    @property
    def smoother_tier(self) -> str:
        """Active smoother tier of the pressure hierarchy (telemetry
        schema v11): ``xla`` (sweep-chain lowered by XLA), ``strip``
        (fused Pallas strip pipeline, f32 legs), or ``strip+bf16``
        (strip pipeline over bf16-storage legs). Reported by the
        preconditioner itself so shape-gate demotions stay truthful."""
        return self.mg.smoother_tier

    @property
    def bc_table(self) -> str:
        """Compact per-face BC token string (telemetry schema v8)."""
        return self.bc.token

    def attach_mesh(self, mesh) -> None:
        """Record the device mesh of the sharded x-split. The fused
        advection tier then dispatches through the halo-mode kernel
        (shard_halo.fused_advect_heun_sharded: edge-column ppermutes
        issued before the strip pipeline); the FAS path additionally
        rebuilds its MG hierarchy so the finest-level smoothing sweeps
        use the explicit overlapped ppermute exchange
        (shard_halo.overlap_jacobi_sweeps). The default Krylov
        preconditioner cycles stay on the GSPMD form whose
        sharded==single equality is already pinned."""
        if self.solver_mode == "fftd":
            # documented refusal (ISSUE 20): the FFT transform and the
            # per-mode tridiagonal scan are whole-array sequential
            # along their axes — the mesh's x-split always shards one
            # of them (periodic x: the transform axis; periodic y
            # only: the scan axis), and neither has a shard_map form
            # (parallel/shard_halo.py). Sharded periodic cases run
            # under bicgstab/fas, whose wrap stencils GSPMD partitions
            # correctly.
            raise ValueError(
                "CUP2D_POIS=fftd cannot attach a device mesh: the "
                "x-split shards the FFT transform axis (periodic x) "
                "or the tridiagonal scan axis (periodic y) — run "
                "sharded periodic cases under bicgstab/fas")
        self._mesh = mesh
        if self.solver_mode == "fas":
            self.mg = MultigridPreconditioner(
                self.ny, self.nx, self.dtype,
                spmd_safe=self.spmd_safe, mesh=mesh,
                cycle_dtype=self.dtype,
                edge_signs=self._psigns,
                leg_dtype=self._fas_leg_dtype,
                smoother=self._mg_smoother)

    def pressure_solve(self, rhs: jnp.ndarray, exact: bool = False):
        """Solve lap(dp) = rhs (undivided). ``exact`` reproduces the
        reference's first-10-steps override — tol 0 with 100 restarts
        while the pold initial guess is cold (main.cpp:7028-7030). A
        literal tol 0 is unreachable in finite precision; instead of the
        r2 builds' hardcoded f32 relative floor (grid-dependent magic,
        VERDICT r2 #8) exact mode now runs at tol 0 and exits through
        the solver's stall detector at whatever the actual precision
        floor is, with a tight refresh cadence so the exit is prompt."""
        cfg = self.cfg
        if self.solver_mode == "fftd":
            # direct solve (CUP2D_POIS=fftd): exact to the precision
            # floor in ONE application — the tol-0 "exact" startup
            # request needs no escalation path, it simply reports the
            # floor through the benign stalled bit exactly like
            # bicgstab's tol-0 stall exit.
            return fft_diag_solve(
                self.laplacian, rhs, self._fft_plan,
                tol=0.0 if exact else cfg.poisson_tol,
                tol_rel=0.0 if exact else cfg.poisson_tol_rel,
            )
        if self.solver_mode == "fas" and not exact:
            # production solves as pure MG cycles (CUP2D_POIS=fas):
            # 1 A-apply + 1 V-cycle per iteration vs Krylov's 2 + 2.
            # Exact (tol-0 startup) and escalation solves keep the
            # Krylov path — its stall-out-at-the-precision-floor
            # pedigree (r2-r4) is the robustness backstop, and the
            # unbatched BiCGSTAB stays bit-unchanged.
            return mg_solve(
                self.laplacian, rhs, self.mg,
                tol=cfg.poisson_tol, tol_rel=cfg.poisson_tol_rel,
                max_cycles=cfg.max_poisson_iterations,
                fmg=self.fas_fmg,
            )
        return bicgstab(
            self.laplacian,
            rhs,
            M=self.mg if cfg.precond else None,
            tol=0.0 if exact else cfg.poisson_tol,
            tol_rel=0.0 if exact else cfg.poisson_tol_rel,
            max_iter=cfg.max_poisson_iterations,
            max_restarts=100 if exact else cfg.max_poisson_restarts,
            sum_dtype=self.sum_dtype,
            refresh_every=10 if exact else 50,
            stall_iters=20 if exact else 120,
            stall_rtol=0.99 if exact else 0.999,
        )

    # -- step stages, shared by the obstacle-free and Simulation paths --
    def advect_heun(self, vel: jnp.ndarray, dt) -> jnp.ndarray:
        """Advection-diffusion, 2-stage Heun (main.cpp:6607-6642).
        On the fused tier both substages run as Pallas megakernels
        (one HBM read/write per substage) instead of the
        pad -> WENO-RHS -> update dispatch chain."""
        if self._kernel_tier != "xla":
            bf16 = self._kernel_tier == "pallas-fused-bf16"
            bc = None if self.bc.is_free_slip else self.bc
            if self._mesh is not None:
                from .parallel.shard_halo import fused_advect_heun_sharded
                return fused_advect_heun_sharded(
                    vel, self.h, self.cfg.nu, dt, self._mesh,
                    bc=bc, bf16=bf16)
            from .ops.pallas_kernels import fused_advect_heun
            return fused_advect_heun(
                vel, self.h, self.cfg.nu, dt, bc=bc, bf16=bf16)
        ih2 = 1.0 / (self.h * self.h)
        vold = vel
        for c in (0.5, 1.0):
            lab = self.pad_vector_field(vel, 3, dt)
            rhs = advect_diffuse_rhs(lab, 3, self.h, self.cfg.nu, dt)
            vel = heun_substage(vold, c, rhs, ih2)
        return vel

    def project(self, vel, pres_old, chi, udef, dt, exact_poisson=False):
        """deltap pressure solve + velocity correction
        (main.cpp:7007-7187): b = (h/2dt)[div u* - chi div u_def] -
        lap(pold); p = dp + pold (both mean-free); u += -dt/(2h) grad p.
        Returns (vel, pres, solver_result, div_linf). ``chi=None``
        (obstacle-free callers) drops the identically-zero
        chi*div(u_def) term. ``div_linf`` is max |∇·(u* − χ u_def)| of
        the pre-projection velocity — the divergence field the step
        already forms as the Poisson RHS, rescaled to physical units
        (zero extra field passes; the telemetry watchdog's second
        invariant, resilience.PhysicsWatchdog)."""
        h = self.h
        ih2 = 1.0 / (h * h)
        b = self.poisson_rhs(vel, chi, udef, dt)
        # |b| = (h/2dt) * |undivided div|; physical div = undivided/(2h)
        div_linf = jnp.max(jnp.abs(b)) * (dt / (h * h))
        b = b - self.laplacian(pres_old)
        res = self.pressure_solve(b, exact=exact_poisson)
        # any-Dirichlet tables (outflow face) pin the pressure level:
        # the operator is non-singular and the legacy mean removal
        # would shift the anchored solution — skip it (bc.py docs)
        # the fused correction kernel has no halo-mode form (its
        # stencil is purely local, but the strip DMA cannot be GSPMD-
        # partitioned) — mesh-attached grids keep the XLA epilogue,
        # whose sharded==single equality is pinned
        corr_tier = "xla" if self._mesh is not None else self._kernel_tier
        vel, pres = project_correct(
            res.x, pres_old, vel, h, dt,
            spmd_safe=self.spmd_safe, tier=corr_tier,
            remove_mean=self.bc.all_neumann, grad_signs=self._psigns,
            periodic=self._paxes)
        return vel, pres, res, div_linf

    def precond_cycles(self, res, exact):
        """Preconditioner/MG cycle count of one solve (telemetry
        schema v4), shared by the solo and fleet diag producers so the
        accounting convention cannot desynchronize between them: FAS
        iterations ARE cycles; flexible BiCGSTAB applies M twice per
        iteration; block-Jacobi-only solves report 0 (no hierarchy
        cycles). A host-derived count would desynchronize from the
        device iters under the lagged verdict, so this rides the same
        diag pull as the iters themselves."""
        if self.solver_mode == "fftd":
            # direct solve: no hierarchy cycles at all
            return jnp.zeros_like(res.iters)
        if self.solver_mode == "fas" and not exact:
            return res.iters
        if self.cfg.precond:
            return 2 * res.iters
        return jnp.zeros_like(res.iters)

    def step_diag(self, vel, pres, res, div_linf=None,
                  exact=False) -> dict:
        umax = jnp.max(jnp.abs(vel))
        # kinetic energy: the telemetry watchdog's first invariant —
        # one extra fused reduction over a field the diag pass reads
        # anyway (umax); accumulated in sum_dtype like the Krylov dots
        vv = vel.astype(self.sum_dtype) if self.sum_dtype is not None \
            else vel
        energy = 0.5 * self.h * self.h * jnp.sum(vv * vv)
        return {
            "poisson_iters": res.iters,
            "poisson_residual": res.residual,
            "poisson_stalled": res.stalled,
            # the solver has always computed `converged`; surfacing it
            # here lets the resilience verdict consume it for free
            # (resilience.health_verdict — PR 2)
            "poisson_converged": res.converged,
            # fused isfinite reduction over vel AND pres: the health
            # verdict's cheap NaN/Inf detector, riding the same device
            # call (umax alone misses a NaN confined to the pressure)
            "finite": jnp.all(jnp.isfinite(vel))
            & jnp.all(jnp.isfinite(pres)),
            "umax": umax,
            # physics invariants for the watchdog + metrics stream,
            # riding the same batched diag pull (PR 3)
            "energy": energy,
            "div_linf": div_linf,
            "precond_cycles": self.precond_cycles(res, exact),
            # next step's dt rides the same device call (no separate
            # dt round trip, r1 weak #10)
            "dt_next": self.dt_from_umax(umax),
        }

    # -- one full projection step (the reference hot loop 6576-7290) --
    def step(self, state: FlowState, dt: jnp.ndarray,
             exact_poisson: bool = False,
             obstacle_terms: bool = True) -> tuple[FlowState, dict]:
        """``obstacle_terms=False`` statically drops the penalization
        update and the chi*div(u_def) RHS term — they are identically
        zero without shapes, but XLA cannot know that and spends ~4 ms
        of full-field passes on them at 8192^2. The obstacle-free
        drivers (UniformSim, Simulation's empty branch, bench.py) pass
        False; the shaped path never calls this (it penalizes in
        Simulation._flow_step_impl)."""
        cfg = self.cfg
        vel = self.advect_heun(state.vel, dt)

        if obstacle_terms:
            # Brinkman penalization implicit update (main.cpp:6961-6977):
            # alpha = chi>0.5 ? 1/(1+lambda dt) : 1; u <- alpha u + (1-alpha) u_s
            alpha = jnp.where(state.chi > 0.5, 1.0 / (1.0 + cfg.lam * dt), 1.0)
            vel = alpha * vel + (1.0 - alpha) * state.us

        vel, pres, res, div_linf = self.project(
            vel, state.pres,
            state.chi if obstacle_terms else None,
            state.udef if obstacle_terms else None, dt, exact_poisson)
        return state._replace(vel=vel, pres=pres), \
            self.step_diag(vel, pres, res, div_linf,
                           exact=exact_poisson)

    def vorticity_field(self, vel: jnp.ndarray) -> jnp.ndarray:
        return vorticity(self.pad_vector_field(vel, 1), 1, self.h)


class UniformSim:
    """Host-side driver: owns time/step counters, jits the device step."""

    def __init__(self, cfg: SimConfig, level: Optional[int] = None,
                 spmd_safe: bool = False,
                 bc: Optional[BCTable] = None):
        self.grid = UniformGrid(cfg, level, spmd_safe=spmd_safe, bc=bc)
        self.cfg = cfg
        self.state = self.grid.zero_state()
        self.time = 0.0
        self.step_count = 0
        self.shapes: list = []          # obstacle-free by construction
        self.case: Optional[str] = None  # case-registry tag (cases.py)
        self.timers = None
        self.force_log = None
        self._next_dt = None            # cached end-state dt_next
        # supervision hooks (resilience.StepGuard): escalation-rung
        # exact solve + the lagged-verdict device-diag mode — see
        # sim.Simulation for the contract
        self._force_exact = False
        self.async_diag = False
        # donate the state: without it XLA copies the pass-through
        # fields (us/udef/chi) every step — 3.3 ms/step of dead copies
        # at 8192^2 (round-4 trace). Callers read the NEW state from the
        # return value; the donated input buffers are invalidated.
        # UniformSim is the obstacle-free driver, so the obstacle terms
        # are statically dropped.
        from . import tracing
        self._step = tracing.named_jit(
            "uniform.step", jax.jit(
                self.grid.step, donate_argnums=(0,),
                static_argnames=("exact_poisson", "obstacle_terms")),
            variant=("exact_poisson",))
        self._dt = tracing.named_jit(
            "uniform.dt", jax.jit(self.grid.compute_dt))

    @property
    def poisson_mode(self) -> str:
        """Active solve-path latch (telemetry schema v4)."""
        return self.grid.poisson_mode

    @property
    def kernel_tier(self) -> str:
        """Active advection-kernel tier (telemetry schema v6)."""
        return self.grid.kernel_tier

    @property
    def prec_mode(self) -> str:
        """Hot-loop storage precision (telemetry schema v6)."""
        return self.grid.prec_mode

    @property
    def smoother_tier(self) -> str:
        """Pressure-hierarchy smoother tier (telemetry schema v11)."""
        return self.grid.smoother_tier

    @property
    def bc_table(self) -> str:
        """Per-face BC token string (telemetry schema v8)."""
        return self.grid.bc_table

    def step_once(self, dt: Optional[float] = None):
        """One supervised-loop-compatible step (the StepGuard driver
        contract shared with Simulation/AMRSim): cached device dt_next,
        one batched diag pull — or, under ``async_diag``, no pull at
        all: the diag (incl. the dt used) stays on device and the
        guard's lagged verdict settles the clock."""
        g = self.grid
        if dt is None:
            if self._next_dt is not None:
                dt = self._next_dt
            else:
                dt = float(self._dt(self.state.vel))
        exact = self.step_count < 10 or self._force_exact
        dt_dev = jnp.asarray(dt, g.dtype)
        self.state, diag = self._step(
            self.state, dt_dev,
            exact_poisson=exact, obstacle_terms=False)
        if self.async_diag:
            diag = dict(diag)
            diag["dt"] = dt_dev
            self._next_dt = diag["dt_next"]
            self.step_count += 1
            return diag
        diag = jax.device_get(diag)
        diag["dt"] = float(dt)   # exact dt for the guard's replay record
        self._next_dt = float(diag["dt_next"])
        self.time += dt
        self.step_count += 1
        return diag

    def advance(self, n_steps: int = 1, tend: Optional[float] = None,
                exact_first_steps: bool = False):
        """``exact_first_steps`` mirrors the reference's tol-0 solve for
        steps < 10 (main.cpp:7028-7030); off by default because obstacle-free
        validation runs don't need the cold-start treatment."""
        diag = {}
        for _ in range(n_steps):
            if tend is not None and self.time >= tend:
                break
            dt = float(self._dt(self.state.vel))
            if tend is not None:
                dt = min(dt, tend - self.time + 1e-15)
            exact = exact_first_steps and self.step_count < 10
            self.state, diag = self._step(
                self.state, jnp.asarray(dt, self.grid.dtype),
                exact_poisson=exact, obstacle_terms=False,
            )
            self.time += dt
            self.step_count += 1
        return diag
