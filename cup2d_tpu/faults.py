"""Fault injection for the resilience subsystem (tests + chaos drills).

The supervised run loop (`resilience.StepGuard`) promises a bounded
recovery ladder for the failures the reference simply dies on. A
promise like that is only real if every rung can be *exercised*; this
module provides the controlled failures that do it, both from tests
(construct a :class:`FaultPlan` directly) and from the CLI/environment
(``CUP2D_FAULTS``, latched ONCE at plan construction — this module is a
SANCTIONED env latch point, enforced by ``tests/test_env_latch.py``).

Spec syntax — comma-separated directives, ``name[@STEP][*COUNT]``::

    nan_vel@N[*K]         poison the velocity with NaN before (up to K)
                          attempts of step N — the verdict's isfinite
                          reduction must catch it and the guard rewind
    inf_vel@N[*K]         same with +Inf (the pre-guard driver check
                          ``umax != umax`` famously missed Inf)
    scale_vel@N[*K]       wrong-but-FINITE corruption: scale the whole
                          velocity field x10 before step N — every
                          number stays finite, so the isfinite verdict
                          passes; only the physics-invariant watchdog
                          (resilience.PhysicsWatchdog: x10 umax,
                          x100 energy) catches it
    poisson_giveup@N[*K]  report step N's pressure solve as failed
                          (forced BiCGSTAB give-up seen by the verdict)
    sigterm@N             deliver SIGTERM to this process after step N
                          completes (preemption mid-run)
    crash_in_save         raise :class:`InjectedCrash` between the
                          checkpoint park and install renames
                          (io.save_checkpoint's crash window)
    host_exit@N           topology loss (graceful flavor): a host leaves
                          the SPMD program at step N's boundary. On a
                          SIMULATED topology (resilience.TopologyGuard
                          with sim_hosts=H) the highest-index alive
                          simulated host is marked dead — the tier-1
                          elastic drill; in a REAL multi-process run
                          the directive is process-scoped like
                          sigterm@N: THIS process announces exit in its
                          final heartbeat, then hard-exits (os._exit —
                          a dead host writes nothing)
    host_hang@N           topology loss (hard flavor): the host stops
                          heartbeating without an announcement —
                          simulated hosts just miss beats; a real
                          process blocks forever inside its next step
                          boundary, so the survivors' bounded
                          collective hits its deadline (the watchdog
                          path). Host-loss tokens are CONSUMED by the
                          TopologyGuard (resilience.py); without an
                          elastic guard they never fire.
    shard_loss@N          real-loss semantics for a SIMULATED host loss
                          at step N: the dead host's shard slices —
                          live state, every snapshot-ring payload, and
                          the mirror slices it physically held — are
                          ZEROED before recovery runs
                          (io.destroy_shards), exactly what a real
                          host loss takes with it. Pairs with
                          host_exit@N/host_hang@N; this is what makes
                          the CPU mirrored-ring drill honest (a
                          resumed trajectory provably came from the
                          neighbor's mirror, not the "lost" originals).
                          Consumed by the TopologyGuard at the same
                          boundary as the host-loss token.
    mirror_corrupt@N      flip one element's bit pattern in every host
                          block of every held mirror at step N's
                          dispatch (io.corrupt_mirror) WITHOUT updating
                          the stored checksums — drives the
                          checksum-reject path: the mirrored-ring rung
                          must detect the corruption (mirror_reject
                          event) and degrade to the disk rung rather
                          than install torn bytes. Consumed by the
                          StepGuard.

``*K`` repeats the fault for K consecutive attempts of that step, which
is how a test climbs the ladder: ``*1`` recovers at the rewind-retry
rung, ``*2`` forces the exact-Poisson escalation, ``*3`` the disk
restore, ``*4`` (with no disk checkpoint: ``*2``) the abort rung.

A typo'd directive raises instead of silently arming nothing — the
same principle as the CUP2D_POIS/CUP2D_TWOLEVEL gate validation
(a fault drill that never fires measures nothing).
"""

from __future__ import annotations

import contextlib
import os
import signal
from typing import Optional


class InjectedCrash(RuntimeError):
    """Raised at an armed crash point (stands in for a hard kill)."""


class FaultPlan:
    """Parsed, consumable fault schedule. Each directive is consumed as
    it fires (a decrementing count), so a recovered retry does not
    re-fault unless the spec asked for it with ``*K``."""

    _POISON = {"nan_vel": float("nan"), "inf_vel": float("inf")}
    _SCALE = 10.0      # scale_vel factor (x100 in energy)

    def __init__(self, spec: str = ""):
        self.vel_poison: dict[int, list] = {}   # step -> [value, count]
        self.vel_scale: dict[int, list] = {}    # step -> [factor, count]
        self.giveup: dict[int, int] = {}        # step -> count
        self.sigterm_steps: set[int] = set()
        self.crash_points: dict[str, int] = {}  # name -> count
        self.host_loss: dict[int, list] = {}    # step -> ["exit"|"hang"]
        self.shard_loss: dict[int, int] = {}    # step -> count
        self.mirror_corrupt: dict[int, int] = {}  # step -> count
        # replay suspension (StepGuard.snapshot-cadence recovery): a
        # restore-and-replay re-runs ALREADY-VERDICTED-GOOD steps, so
        # an armed *K fault whose step lands mid-replay must not fire
        # into it — replay is bit-exact reproduction, not a fresh
        # attempt. The guard wraps the replay in suspend().
        self._suspended = 0
        for tok in (spec or "").split(","):
            tok = tok.strip()
            if not tok:
                continue
            count = 1
            if "*" in tok:
                tok, c = tok.split("*", 1)
                count = int(c)
            if "@" in tok:
                name, s = tok.split("@", 1)
                step: Optional[int] = int(s)
            else:
                name, step = tok, None
            if name in self._POISON:
                if step is None:
                    raise ValueError(f"{name} needs @STEP")
                self.vel_poison[step] = [self._POISON[name], count]
            elif name == "scale_vel":
                if step is None:
                    raise ValueError("scale_vel needs @STEP")
                self.vel_scale[step] = [self._SCALE, count]
            elif name == "poisson_giveup":
                if step is None:
                    raise ValueError("poisson_giveup needs @STEP")
                self.giveup[step] = count
            elif name == "sigterm":
                if step is None:
                    raise ValueError("sigterm needs @STEP")
                self.sigterm_steps.add(step)
            elif name == "crash_in_save":
                self.crash_points["checkpoint_install"] = count
            elif name in ("host_exit", "host_hang"):
                if step is None:
                    raise ValueError(f"{name} needs @STEP")
                self.host_loss.setdefault(step, []).append(
                    name.split("_", 1)[1])
            elif name == "shard_loss":
                if step is None:
                    raise ValueError("shard_loss needs @STEP")
                self.shard_loss[step] = count
            elif name == "mirror_corrupt":
                if step is None:
                    raise ValueError("mirror_corrupt needs @STEP")
                self.mirror_corrupt[step] = count
            else:
                raise ValueError(
                    f"unknown fault directive {name!r} "
                    "(expected nan_vel|inf_vel|scale_vel|poisson_giveup|"
                    "sigterm|crash_in_save|host_exit|host_hang|"
                    "shard_loss|mirror_corrupt)")

    @classmethod
    def from_env(cls) -> "FaultPlan":
        """Latch CUP2D_FAULTS once (the sanctioned read site)."""
        return cls(os.environ.get("CUP2D_FAULTS", ""))

    def __bool__(self) -> bool:
        return bool(self.vel_poison or self.vel_scale or self.giveup
                    or self.sigterm_steps or self.crash_points
                    or self.host_loss or self.shard_loss
                    or self.mirror_corrupt)

    # -- replay suspension --------------------------------------------
    @contextlib.contextmanager
    def suspend(self):
        """Context manager: no fault fires inside (guard replay)."""
        self._suspended += 1
        try:
            yield
        finally:
            self._suspended -= 1

    # -- hooks consulted by the guard / io ----------------------------
    def apply_pre_step(self, sim, step: Optional[int] = None) -> list:
        """Poison or scale the velocity before an attempt of the
        current step. Returns the consumed [value, count] entries
        (truthy when anything fired) so the StepGuard can REFUND a
        dispatch it later discards: under the lagged verdict, a step
        dispatched on top of a not-yet-detected bad step is thrown
        away and re-dispatched after recovery — a fault armed for it
        must fire at the real dispatch, not be eaten by the garbage
        one. ``step`` overrides the counter lookup: the fleet guard's
        per-member retry re-attempts step N while the SHARED fleet
        counter already sits at N+1 (member recovery never rewinds the
        counter), so it must name the step it is retrying."""
        if self._suspended:
            return []
        if step is None:
            step = sim.step_count
        fired = []
        ent = self.vel_poison.get(step)
        if ent and ent[1] > 0:
            ent[1] -= 1
            poison_velocity(sim, ent[0])
            fired.append(ent)
        ent = self.vel_scale.get(step)
        if ent and ent[1] > 0:
            ent[1] -= 1
            scale_velocity(sim, ent[0])
            fired.append(ent)
        return fired

    def poisson_giveup_at(self, step: int) -> bool:
        """Consume one forced-give-up count for ``step`` if armed."""
        if self._suspended:
            return False
        c = self.giveup.get(step, 0)
        if c <= 0:
            return False
        self.giveup[step] = c - 1
        return True

    def fire_post_step(self, step: int) -> None:
        """Post-step faults: SIGTERM delivery (preemption)."""
        if self._suspended:
            return
        if step in self.sigterm_steps:
            self.sigterm_steps.discard(step)
            os.kill(os.getpid(), signal.SIGTERM)

    def host_loss_at(self, step: int) -> list:
        """Consume the host-loss directives armed for ``step`` (the
        TopologyGuard's per-boundary lookup — 'exit'/'hang' kinds).
        Suspended during guard replay like every other injector: a
        restore-and-replay must not lose a host twice."""
        if self._suspended:
            return []
        return self.host_loss.pop(step, [])

    def shard_loss_at(self, step: int) -> bool:
        """Consume one shard-destruction count for ``step`` if armed
        (the TopologyGuard's companion lookup to host_loss_at: the loss
        declared at this boundary takes its shards with it). Suspended
        during guard replay like every other injector."""
        if self._suspended:
            return False
        c = self.shard_loss.get(step, 0)
        if c <= 0:
            return False
        self.shard_loss[step] = c - 1
        return True

    def mirror_corrupt_at(self, step: int) -> bool:
        """Consume one mirror-corruption count for ``step`` if armed
        (the StepGuard's per-dispatch lookup). Suspended during guard
        replay: a replay must not re-corrupt a repaired ring."""
        if self._suspended:
            return False
        c = self.mirror_corrupt.get(step, 0)
        if c <= 0:
            return False
        self.mirror_corrupt[step] = c - 1
        return True

    def fire_crash_point(self, name: str) -> None:
        c = self.crash_points.get(name, 0)
        if c > 0:
            self.crash_points[name] = c - 1
            raise InjectedCrash(name)


def poison_velocity(sim, value: float) -> None:
    """Write ``value`` into one velocity cell of a REAL block/cell
    through each driver's supported write path (the ordered working
    state on the forest — slot writes between steps would trip the
    _ord_dirty guard; the FlowState on the uniform drivers). On a
    FLEET state ([B, 2, Ny, Nx], fleet.FleetSim) only MEMBER 0 is
    poisoned — the per-member recovery drill: the guard must rewind
    only that member while the others' trajectories stay
    bit-identical."""
    if hasattr(sim, "forest"):
        ordf = sim._ordered_state()
        sim._set_ordered(vel=ordf["vel"].at[0, 0, 0, 0].set(value))
    else:
        vel = sim.state.vel
        if vel.ndim == 4:   # fleet [B, 2, Ny, Nx]: member 0 only
            sim.state = sim.state._replace(
                vel=vel.at[0, 0, 0, 0].set(value))
        else:
            sim.state = sim.state._replace(
                vel=vel.at[0, 0, 0].set(value))


def scale_velocity(sim, factor: float) -> None:
    """Multiply the whole velocity field by ``factor`` — every value
    stays finite (the wrong-but-finite corruption class the isfinite
    verdict cannot see), through the same supported write paths as
    :func:`poison_velocity` (member 0 only on a fleet)."""
    if hasattr(sim, "forest"):
        ordf = sim._ordered_state()
        sim._set_ordered(vel=ordf["vel"] * factor)
    else:
        vel = sim.state.vel
        if vel.ndim == 4:   # fleet: corrupt member 0, leave the rest
            sim.state = sim.state._replace(
                vel=vel.at[0].set(vel[0] * factor))
        else:
            sim.state = sim.state._replace(vel=vel * factor)


# -- process-wide plan (the CLI arms it; io.py's crash window asks) ---
_ACTIVE: Optional[FaultPlan] = None


def install(plan: Optional[FaultPlan]) -> None:
    global _ACTIVE
    _ACTIVE = plan


def active() -> Optional[FaultPlan]:
    return _ACTIVE


def crash_point(name: str) -> None:
    """No-op unless a plan armed this crash point (io.py calls this
    between the checkpoint park and install renames)."""
    if _ACTIVE is not None:
        _ACTIVE.fire_crash_point(name)
