"""Flight recorder: run-wide observability riding existing sync points.

Four instruments, one discipline — ZERO new device pulls on the hot
path (the PR-3 contract: a recorder-on run is bit-identical to a
recorder-off run with equal ``HostCounters.device_gets`` AND equal
``jit_compiles``; tests/test_tracing.py pins both on UniformSim and
FleetServer churn):

1. **Span timeline** — hierarchical wall-clock spans (``span("step")``
   nesting ``dispatch``/``verdict``/``snapshot``/``mirror``/
   ``recover``/``remesh``/``admit``/``evict``/``regrid``) recorded
   lock-free per process into a bounded ring, flushed through the
   EventLog writer (cold path: shutdown or ring-full), exported to a
   Chrome/Perfetto ``trace.json`` by ``python -m cup2d_tpu.post
   --trace``. Spans are host-clock intervals between points the run
   already passes through: where a phase already fences (the verdict's
   batched pull, the snapshot's host gather) the span is
   fence-accurate; a ``dispatch`` span times enqueue cost only — the
   async dispatch pipeline is exactly what it must not perturb.

2. **Compile attribution** — ``profiling._on_compile`` (the
   jax.monitoring listener that counts ``jit_compiles``) forwards each
   backend-compile duration here; :func:`named_jit` wraps the
   package's jit entry points (uniform/fleet/amr/io) with a label
   pushed onto a stack for the duration of the call, so a compile
   fired by tracing inside that call lands on the innermost label.
   The ledger row carries count, total ms, trigger step
   (:func:`note_step`), latch token (:func:`note_token` — the
   dispatch-time poisson-mode/kernel-tier label), and the Poisson-path
   components observed at trace time (:func:`note_component` from
   ``poisson.mg_solve``/``bicgstab``). The ``jit_compiles==0`` CI pin
   thereby fails WITH a blame report instead of a bare count.

3. **HBM memory ledger** — after a call that triggered a compile, the
   executable is re-lowered from the abstract signature (donated
   arrays keep ``.shape``/``.dtype`` after deletion) and
   ``compiled.memory_analysis()`` records argument/output/temp/
   generated-code bytes per label. The re-lower fires one extra
   backend compile (served from the persistent compilation cache when
   armed); :func:`compiles_suppressed` hides it from HostCounters and
   from the ledger itself, preserving the equal-compile-count
   contract.

4. **Serving latency histograms** — :class:`ServingLatency` collects
   per-request queue-wait, admit-to-first-step, and per-step wall
   latency into fixed-bucket log2 :class:`LatencyHistogram`\\ s, per
   client and pool-wide; ``FleetServer`` drives it from its existing
   submit/admit/step boundaries (host clocks only).

Import discipline: this module imports nothing from the package at
module level (resilience/fleet/profiling all import it), and jax only
inside the cold-path memory capture.
"""

from __future__ import annotations

import math
import os
import time
from collections import deque
from contextlib import nullcontext
from typing import Optional

# ---------------------------------------------------------------------------
# module state: the active recorder + attribution stacks
# ---------------------------------------------------------------------------

_RECORDER: Optional["FlightRecorder"] = None
_LABEL_STACK: list = []     # innermost active named_jit label
_SUPPRESS = [0]             # >0: backend compiles are ledger-internal
_NULL = nullcontext()       # shared, reentrant — the recorder-off span


def recorder() -> Optional["FlightRecorder"]:
    """The active flight recorder, or None (library default)."""
    return _RECORDER


def compiles_suppressed() -> bool:
    """True while a ledger-internal re-lower is compiling — the
    profiling listener must count neither in HostCounters nor here."""
    return _SUPPRESS[0] > 0


def span(name: str, **attrs):
    """A timeline span context. Free when no recorder is installed
    (returns a shared ``nullcontext``); otherwise records one ring
    entry at exit — host clocks only, no device interaction."""
    r = _RECORDER
    if r is None or not r.spans_on:
        return _NULL
    return _SpanCtx(r, name, attrs)


def note_step(n) -> None:
    """Current driver step — stamped onto compiles as the trigger step
    (called from StepGuard's dispatch path; a no-op attribute write)."""
    r = _RECORDER
    if r is not None:
        r._step = int(n)


def note_token(token) -> None:
    """Current latch token (dispatch-time poisson-mode/kernel-tier
    label) — stamped onto compiles whose entry has no static token."""
    r = _RECORDER
    if r is not None:
        r._token = token


def note_component(name: str) -> None:
    """Record a trace-time component (e.g. ``poisson.mg_solve``) onto
    the innermost compiling executable's ledger row. Runs only while a
    jit body is being TRACED — compiled dispatches never re-enter the
    Python body, so this costs nothing in steady state."""
    r = _RECORDER
    if r is None or not r.compile_attr or not _LABEL_STACK:
        return
    ent = r.ledger.get(_LABEL_STACK[-1])
    if ent is not None:
        ent["components"].add(name)


def _note_compile(duration_s: float) -> None:
    """Entry point for profiling._on_compile: attribute one backend
    compile to the innermost active label."""
    r = _RECORDER
    if r is not None and r.compile_attr:
        r._on_compile_event(
            _LABEL_STACK[-1] if _LABEL_STACK else None, duration_s)


# ---------------------------------------------------------------------------
# spans
# ---------------------------------------------------------------------------

class _SpanCtx:
    """One live span frame. Entry/exit are a few host clock reads and
    list ops; the record lands in the recorder's ring at exit (LIFO —
    spans close in nesting order, enforced by ``with`` scoping)."""

    __slots__ = ("_r", "name", "attrs", "_wall", "_t0")

    def __init__(self, r: "FlightRecorder", name: str, attrs: dict):
        self._r = r
        self.name = name
        self.attrs = attrs

    def __enter__(self):
        self._r._stack.append(self)
        self._wall = time.time()          # cross-process alignment
        self._t0 = time.perf_counter()    # duration
        return self

    def __exit__(self, etype, _exc, _tb):
        dur = time.perf_counter() - self._t0
        r = self._r
        r._stack.pop()
        attrs = self.attrs
        if etype is not None:
            # an aborting rung propagates through its spans — keep the
            # interval and mark it, so the timeline shows WHERE it died
            attrs = {**attrs, "error": etype.__name__}
        r._record(self.name, self._wall, dur, len(r._stack), attrs)
        return False


# ---------------------------------------------------------------------------
# compile attribution: the named-jit label registry
# ---------------------------------------------------------------------------

class NamedJit:
    """A jitted callable with a ledger label. ``__call__`` pushes the
    label for the duration of the dispatch (compiles happen
    synchronously inside it, so the monitoring listener attributes the
    duration to the innermost label) and, when a compile fired,
    captures the executable's ``memory_analysis`` from the abstract
    signature. Recorder off: one ``is None`` check, then passthrough.

    ``variant`` names static kwargs whose values split the label
    (``step[exact_poisson=True]`` is a different executable than the
    production solve — the blame report must say which one compiled).
    ``token`` is an optional static latch token; without one the
    recorder's current :func:`note_token` value stamps at compile
    time. All other attribute access (``.lower``, ``.__wrapped__``)
    passes through to the underlying jit."""

    def __init__(self, label: str, fn, *, token=None, variant=()):
        self._label = label
        self._fn = fn
        self._token = token
        self._variant = tuple(variant)

    def __call__(self, *args, **kwargs):
        r = _RECORDER
        if r is None or not r.compile_attr:
            return self._fn(*args, **kwargs)
        label = self._label
        for k in self._variant:
            if k in kwargs:
                label = f"{label}[{k}={kwargs[k]}]"
        ent = r._ledger_entry(label, self._token)
        n0 = ent["count"]
        _LABEL_STACK.append(label)
        try:
            out = self._fn(*args, **kwargs)
        finally:
            _LABEL_STACK.pop()
        if ent["count"] > n0 and r.capture_memory and ent["mem"] is None:
            ent["mem"] = _memory_analysis(self._fn, args, kwargs)
        return out

    def __getattr__(self, name):
        return getattr(self._fn, name)

    def __repr__(self):
        return f"NamedJit({self._label!r}, {self._fn!r})"


def named_jit(label: str, fn, *, token=None, variant=()) -> NamedJit:
    """Wrap a ``jax.jit`` result with a compile-ledger label (see
    :class:`NamedJit`). graftlint's donation/retrace rules unwrap this
    call to keep seeing the inner jit's donate/static declarations."""
    return NamedJit(label, fn, token=token, variant=variant)


def _memory_analysis(fn, args, kwargs) -> dict:
    """Cold-path HBM ledger capture: re-lower ``fn`` from the abstract
    signature of the call that just compiled and read the executable's
    ``memory_analysis``. Donated operands are already deleted by the
    time this runs — only ``.shape``/``.dtype`` are read, which
    survive deletion. The re-lower's own backend compile is suppressed
    from HostCounters and the ledger (equal-compile-count contract);
    with the persistent compilation cache armed it is a cache hit.
    Sanctioned host-sync scope (policy.HOST_SYNC_SITES)."""
    import jax
    import numpy as np

    def _abstract(x):
        if isinstance(x, (jax.Array, np.ndarray)):
            return jax.ShapeDtypeStruct(x.shape, x.dtype)
        return x

    try:
        aargs, akw = jax.tree_util.tree_map(_abstract, (args, kwargs))
        _SUPPRESS[0] += 1
        try:
            compiled = fn.lower(*aargs, **akw).compile()
        finally:
            _SUPPRESS[0] -= 1
        ma = compiled.memory_analysis()
        return {
            "argument_bytes": int(ma.argument_size_in_bytes),
            "output_bytes": int(ma.output_size_in_bytes),
            "temp_bytes": int(ma.temp_size_in_bytes),
            "generated_code_bytes": int(ma.generated_code_size_in_bytes),
        }
    except Exception as e:         # never let the ledger kill a run
        return {"error": str(e)[:200]}


def _mem_total(mem: Optional[dict]) -> int:
    if not mem or "error" in mem:
        return 0
    return sum(int(v) for v in mem.values())


# ---------------------------------------------------------------------------
# serving latency histograms
# ---------------------------------------------------------------------------

class LatencyHistogram:
    """Fixed-bucket log2 histogram of durations. Bucket ``i`` counts
    samples in ``[2^i, 2^(i+1))`` microseconds (bucket 0 absorbs
    sub-2µs); 40 buckets reach ~18 minutes. O(1) memory and update —
    no per-sample storage on the serving path. Percentiles report the
    upper edge of the bucket holding the rank, clamped to the observed
    max: a conservative (never under-reporting) estimate within one
    bucket (2x) of resolution."""

    NBUCKETS = 40

    __slots__ = ("counts", "n", "sum_us", "max_us")

    def __init__(self):
        self.counts = [0] * self.NBUCKETS
        self.n = 0
        self.sum_us = 0.0
        self.max_us = 0.0

    def add(self, seconds: float) -> None:
        us = seconds * 1e6
        if us < 0.0:
            us = 0.0
        i = max(int(us), 1).bit_length() - 1
        if i >= self.NBUCKETS:
            i = self.NBUCKETS - 1
        self.counts[i] += 1
        self.n += 1
        self.sum_us += us
        if us > self.max_us:
            self.max_us = us

    def percentile(self, q: float) -> Optional[float]:
        """q-quantile in milliseconds (bucket upper edge, clamped to
        the observed max), or None when empty."""
        if self.n == 0:
            return None
        target = max(int(math.ceil(q * self.n)), 1)
        seen = 0
        for i, c in enumerate(self.counts):
            seen += c
            if seen >= target:
                if i == self.NBUCKETS - 1:
                    # the overflow bucket has no real upper edge — the
                    # observed max is the only honest bound
                    return round(self.max_us / 1e3, 3)
                return round(min(float(1 << (i + 1)),
                                 self.max_us) / 1e3, 3)
        return round(self.max_us / 1e3, 3)

    def report(self) -> dict:
        if self.n == 0:
            return {"count": 0}
        return {"count": self.n,
                "mean_ms": round(self.sum_us / self.n / 1e3, 3),
                "p50_ms": self.percentile(0.50),
                "p90_ms": self.percentile(0.90),
                "p99_ms": self.percentile(0.99),
                "max_ms": round(self.max_us / 1e3, 3)}


class ServingLatency:
    """Per-request latency collector for ``FleetServer`` — host clocks
    at the server's existing submit/admit/step boundaries, so arming
    it adds no device interaction and no extra dispatches.

    Three distributions, pool-wide and per client:

    - ``queue_wait``: submit() -> the admit that seats the request;
    - ``admit_to_first_step``: admit -> end of the first fused step
      that carried the client;
    - ``step``: wall time of each fused step, attributed to every
      client it carried (the slot pool dispatches all occupants
      together — a member's step latency IS the fused latency).

    Per-client tracking caps at ``MAX_CLIENTS`` distinct ids (the
    pool-wide histograms keep counting; dropped ids are reported as
    ``untracked_clients``)."""

    KINDS = ("queue_wait", "admit_to_first_step", "step")
    MAX_CLIENTS = 512

    def __init__(self):
        self.pool = {k: LatencyHistogram() for k in self.KINDS}
        self.clients: dict = {}
        self._submitted: dict = {}
        self._admitted: dict = {}
        self._dropped: set = set()

    def _client(self, cid) -> Optional[dict]:
        h = self.clients.get(cid)
        if h is None:
            if len(self.clients) >= self.MAX_CLIENTS:
                self._dropped.add(cid)
                return None
            h = {k: LatencyHistogram() for k in self.KINDS}
            self.clients[cid] = h
        return h

    def _observe(self, kind: str, cid, seconds: float) -> None:
        self.pool[kind].add(seconds)
        h = self._client(cid)
        if h is not None:
            h[kind].add(seconds)

    def on_submit(self, cid) -> None:
        self._submitted[cid] = time.perf_counter()

    def on_admit(self, cid) -> None:
        now = time.perf_counter()
        t0 = self._submitted.pop(cid, None)
        if t0 is not None:
            self._observe("queue_wait", cid, now - t0)
        self._admitted[cid] = now

    def on_step(self, cids, seconds: float) -> None:
        """One fused step of duration ``seconds`` carried ``cids``."""
        now = time.perf_counter()
        for cid in cids:
            if cid is None:
                continue
            self._observe("step", cid, seconds)
            t0 = self._admitted.pop(cid, None)
            if t0 is not None:
                self._observe("admit_to_first_step", cid, now - t0)

    def report(self) -> dict:
        out = {"pool": {k: self.pool[k].report() for k in self.KINDS}}
        if self.clients:
            out["clients"] = {
                str(cid): {k: h[k].report() for k in self.KINDS}
                for cid, h in self.clients.items()}
        if self._dropped:
            out["untracked_clients"] = len(self._dropped)
        return out


# ---------------------------------------------------------------------------
# the recorder
# ---------------------------------------------------------------------------

class FlightRecorder:
    """Per-process flight recorder: span ring + compile/memory ledger.
    Install exactly one (:meth:`install` registers it module-wide and
    arms the profiling compile listener); ``close()`` flushes and
    deregisters. All state is plain host data — the recorder never
    touches the device outside the sanctioned cold-path scopes."""

    def __init__(self, *, spans: bool = True, compile_attr: bool = True,
                 capture_memory: bool = True, max_spans: int = 65536,
                 sink=None):
        self.spans_on = bool(spans)
        self.compile_attr = bool(compile_attr)
        self.capture_memory = bool(capture_memory)
        self.max_spans = int(max_spans)
        self.sink = sink                  # EventLog-like (.emit(**row))
        self.pid = 0
        self._buf: deque = deque()
        self._stack: list = []
        self.span_count = 0               # cumulative, survives flushes
        self.spans_dropped = 0
        self.ledger: dict = {}            # label -> entry dict
        self.compile_ms_total = 0.0
        self._step = None                 # note_step
        self._token = None                # note_token

    @classmethod
    def from_env(cls, **kw) -> "FlightRecorder":
        """Construction-time latch of ``CUP2D_SPANS`` (the ONE read,
        policy.ENV_LATCH_SITES): ``"0"`` disables the span instrument
        (ledger instruments stay on), an integer overrides the ring
        capacity, unset/empty keeps the caller's settings."""
        raw = os.environ.get("CUP2D_SPANS", "").strip()
        on = kw.pop("spans", True)
        if raw == "0":
            on = False
        elif raw:
            try:
                kw["max_spans"] = max(int(raw), 16)
            except ValueError:
                pass
        return cls(spans=on, **kw)

    # -- lifecycle -----------------------------------------------------
    def install(self) -> "FlightRecorder":
        global _RECORDER
        _RECORDER = self
        from . import profiling
        profiling._install_hooks()    # arm the compile listener
        try:
            import jax
            from .resilience import dist_initialized
            self.pid = (jax.process_index() if dist_initialized()
                        else 0)
        except Exception:
            self.pid = 0
        return self

    def uninstall(self) -> None:
        global _RECORDER
        if _RECORDER is self:
            _RECORDER = None

    def close(self) -> None:
        self.flush()
        self.uninstall()

    # -- span ring -----------------------------------------------------
    def _record(self, name, wall, dur, depth, attrs) -> None:
        self.span_count += 1
        buf = self._buf
        if len(buf) >= self.max_spans:
            if self.sink is not None:
                self.flush()       # cold path: ring-full write burst
            else:
                buf.popleft()
                self.spans_dropped += 1
        buf.append((name, wall, dur, depth, attrs))

    def flush(self) -> None:
        """Drain the span ring into the attached EventLog sink — cold
        path (shutdown / ring-full), one JSONL row per span."""
        sink = self.sink
        if sink is None:
            return
        buf = self._buf
        while buf:
            name, wall, dur, depth, attrs = buf.popleft()
            row = {"event": "span", "name": name,
                   "ts_us": int(wall * 1e6),
                   "dur_us": max(int(dur * 1e6), 1),
                   "depth": depth, "pid": self.pid}
            for k, v in attrs.items():
                if k not in row:
                    row[k] = v
            sink.emit(**row)

    # -- compile / memory ledger ----------------------------------------
    def _ledger_entry(self, label: str, token=None) -> dict:
        ent = self.ledger.get(label)
        if ent is None:
            ent = {"label": label, "count": 0, "ms": 0.0,
                   "first_step": None, "last_step": None,
                   "token": token, "components": set(), "mem": None}
            self.ledger[label] = ent
        elif token is not None and ent["token"] is None:
            ent["token"] = token
        return ent

    def _on_compile_event(self, label: Optional[str],
                          duration_s: float) -> None:
        ent = self._ledger_entry(label or "<unattributed>")
        ent["count"] += 1
        ent["ms"] += duration_s * 1e3
        if ent["first_step"] is None:
            ent["first_step"] = self._step
        ent["last_step"] = self._step
        if ent["token"] is None:
            ent["token"] = self._token
        self.compile_ms_total += duration_s * 1e3

    def hbm_exec_bytes(self) -> int:
        """Summed memory_analysis footprint (argument+output+temp+
        generated code) over every executable with a captured row."""
        return sum(_mem_total(e["mem"]) for e in self.ledger.values())

    def ledger_report(self) -> dict:
        """The compile blame report: one row per named executable."""
        rows = []
        for label in sorted(self.ledger):
            e = self.ledger[label]
            rows.append({
                "label": label,
                "compiles": e["count"],
                "ms": round(e["ms"], 3),
                "first_step": e["first_step"],
                "last_step": e["last_step"],
                "token": e["token"],
                "components": sorted(e["components"]) or None,
                "memory": e["mem"],
            })
        return {
            "compiles": sum(r["compiles"] for r in rows),
            "compile_ms_total": round(self.compile_ms_total, 3),
            "hbm_exec_bytes": self.hbm_exec_bytes() or None,
            "executables": rows,
        }


# ---------------------------------------------------------------------------
# Perfetto export
# ---------------------------------------------------------------------------

_CLIENT_PID_BASE = 1 << 20    # client tracks live above any process id


def spans_to_perfetto(rows) -> dict:
    """Chrome/Perfetto trace-event JSON from flushed span rows: one
    track per process (pid = process index) plus one synthesized track
    per client session (spans carrying a ``client`` attr — admit/
    retire/evict — are mirrored onto the client's track under a
    ``session`` envelope spanning first-to-last appearance). Load the
    result at https://ui.perfetto.dev or chrome://tracing."""
    events = []
    pids = set()
    clients: dict = {}
    for r in rows:
        if r.get("event") != "span":
            continue
        pid = int(r.get("pid", 0))
        pids.add(pid)
        args = {k: v for k, v in r.items()
                if k not in ("event", "name", "ts_us", "dur_us",
                             "depth", "pid", "wall")}
        ev = {"name": str(r["name"]), "ph": "X", "ts": int(r["ts_us"]),
              "dur": int(r["dur_us"]), "pid": pid, "tid": 0,
              "args": args}
        events.append(ev)
        cid = r.get("client")
        if cid is not None:
            info = clients.setdefault(
                str(cid), {"first": ev["ts"], "last": ev["ts"],
                           "spans": []})
            info["first"] = min(info["first"], ev["ts"])
            info["last"] = max(info["last"], ev["ts"] + ev["dur"])
            info["spans"].append(ev)
    meta = []
    for pid in sorted(pids):
        meta.append({"name": "process_name", "ph": "M", "pid": pid,
                     "tid": 0, "args": {"name": f"process {pid}"}})
        meta.append({"name": "thread_name", "ph": "M", "pid": pid,
                     "tid": 0, "args": {"name": "guard"}})
    for i, cid in enumerate(sorted(clients,
                                   key=lambda c: clients[c]["first"])):
        cpid = _CLIENT_PID_BASE + i
        info = clients[cid]
        meta.append({"name": "process_name", "ph": "M", "pid": cpid,
                     "tid": 0, "args": {"name": f"client {cid}"}})
        events.append({"name": "session", "ph": "X",
                       "ts": info["first"],
                       "dur": max(info["last"] - info["first"], 1),
                       "pid": cpid, "tid": 0, "args": {"client": cid}})
        for ev in info["spans"]:
            events.append({**ev, "pid": cpid})
    return {"traceEvents": meta + events, "displayTimeUnit": "ms"}
