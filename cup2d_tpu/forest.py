"""Block forest: structure-of-arrays AMR grid.

TPU-native inversion of the reference's pointer forest
(`/root/reference/main.cpp:504-738` Info/treef/getf per-block mallocs):
every field lives in ONE dense device array `[capacity, dim, BS, BS]`
addressed by slot; the topology (level, block index, active mask, the
(level, i, j) -> slot map) is small host-side numpy/dict state that only
changes at regrid time. Device kernels always run over the full padded
capacity — XLA sees static shapes; inactive slots hold zeros and are
masked out of reductions.

Blocks are kept in Hilbert-SFC order across levels (the reference's
``id2`` ordering via SpaceCurve::Encode, main.cpp:422-446) so that the
sharded multi-device path can split contiguous SFC ranges exactly like
the reference partitions ranks.
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax.numpy as jnp
import numpy as np

from .config import SimConfig
from .curve import SpaceCurve


class _FieldsDict(dict):
    """Field store with a write-version counter.

    The AMR driver keeps an SFC-ordered compact copy of the fields as
    its per-step working state (amr.AMRSim._ordered_state) and syncs it
    back lazily; ``wver`` lets it detect any external write to the
    slot-layout dict (tests seeding a field, checkpoint restore) so a
    stale ordered cache is never used."""

    def __init__(self, *a, **k):
        super().__init__(*a, **k)
        self.wver = 0

    # wver moves only when a mutation actually happens: a spurious bump
    # either aborts the next _ordered_state() (when the ordered cache
    # is dirty) or silently drops the cached end-state dt — so the
    # no-op forms (setdefault on a present key, pop of a missing key
    # with default, failed del) must NOT count as writes.
    def __setitem__(self, key, value):
        self.wver += 1
        super().__setitem__(key, value)

    def update(self, *a, **k):
        # len() covers mappings and sequences; a bare iterator can't be
        # emptiness-tested without consuming it, so it counts as a write
        if k or (a and (not hasattr(a[0], "__len__") or len(a[0]))):
            self.wver += 1
        super().update(*a, **k)

    def __ior__(self, other):
        # `fields |= {...}` does NOT route through update() in CPython
        self.update(other)
        return self

    def __delitem__(self, key):
        super().__delitem__(key)
        self.wver += 1

    def pop(self, key, *default):
        existed = key in self
        val = super().pop(key, *default)
        if existed:
            self.wver += 1
        return val

    def popitem(self):
        item = super().popitem()
        self.wver += 1
        return item

    def setdefault(self, key, default=None):
        if key not in self:
            self.wver += 1
        return super().setdefault(key, default)

    def clear(self):
        if self:
            self.wver += 1
        super().clear()


class Forest:
    """Host topology + device field storage for one AMR run.

    All fields share one topology (the reference keeps 7 independent
    grids in lock-step, main.cpp:3264-3278 — here lock-step is free
    because there is only one tree).
    """

    def __init__(self, cfg: SimConfig, capacity: int = 0,
                 dtype=None):
        self.cfg = cfg
        self.bs = cfg.bs
        self.dtype = jnp.dtype(dtype or cfg.dtype)
        self.curve = SpaceCurve(cfg.bpdx, cfg.bpdy, cfg.level_max)
        nb0 = cfg.bpdx * cfg.bpdy
        n_init = nb0 << (2 * cfg.level_start)
        self.capacity = capacity or max(
            64, 4 * n_init,
            4 * nb0 << (2 * min(cfg.level_max - 1, 3)))
        # multiple of 64 so the slot axis divides device meshes up to 64
        # chips (parallel/forest_mesh.py shards it); _grow doubles, so
        # divisibility is preserved
        self.capacity = -(-self.capacity // 64) * 64
        self.blocks: Dict[Tuple[int, int, int], int] = {}
        self.level = np.zeros(self.capacity, np.int32)
        self.bi = np.zeros(self.capacity, np.int32)
        self.bj = np.zeros(self.capacity, np.int32)
        self.active = np.zeros(self.capacity, bool)
        self._free = list(range(self.capacity - 1, -1, -1))
        self.fields: Dict[str, jnp.ndarray] = _FieldsDict()
        self.version = 0   # bumped on every topology change

        # initial uniform partition at level_start (main.cpp:6494-6541)
        lvl = cfg.level_start
        nbx, nby = cfg.bpdx << lvl, cfg.bpdy << lvl
        for j in range(nby):
            for i in range(nbx):
                self.allocate(lvl, i, j)

    # -- slot management ------------------------------------------------
    def _grow(self):
        """Double the slot capacity in place: pad the metadata arrays and
        every field (the reference mallocs per block, main.cpp:2162; a
        dense SoA pays one realloc + device pad instead)."""
        old = self.capacity
        new = old * 2
        self.level = np.concatenate([self.level, np.zeros(old, np.int32)])
        self.bi = np.concatenate([self.bi, np.zeros(old, np.int32)])
        self.bj = np.concatenate([self.bj, np.zeros(old, np.int32)])
        self.active = np.concatenate([self.active, np.zeros(old, bool)])
        for name, fld in self.fields.items():
            pad = jnp.zeros((old,) + fld.shape[1:], fld.dtype)
            self.fields[name] = jnp.concatenate([fld, pad], axis=0)
        self._free.extend(range(new - 1, old - 1, -1))
        self.capacity = new

    def allocate(self, l: int, i: int, j: int) -> int:
        if not self._free:
            self._grow()
        s = self._free.pop()
        self.blocks[(l, i, j)] = s
        self.level[s] = l
        self.bi[s] = i
        self.bj[s] = j
        self.active[s] = True
        self.version += 1
        return s

    def release(self, l: int, i: int, j: int) -> int:
        s = self.blocks.pop((l, i, j))
        self.active[s] = False
        self._free.append(s)
        self.version += 1
        return s

    def add_field(self, name: str, dim: int):
        self.fields[name] = jnp.zeros(
            (self.capacity, dim, self.bs, self.bs), dtype=self.dtype)

    # -- queries --------------------------------------------------------
    def nblocks_at(self, l: int) -> Tuple[int, int]:
        return self.cfg.bpdx << l, self.cfg.bpdy << l

    def h_at(self, l: int) -> float:
        return self.cfg.h_at(l)

    def slot(self, l: int, i: int, j: int) -> int:
        return self.blocks.get((l, i, j), -1)

    def order(self) -> np.ndarray:
        """Active slots sorted by the level-aware SFC id (the reference's
        id2/Encode order, main.cpp:422-446). One vectorized encode over
        all blocks — a per-block Python loop costs ~100 ms at 1e4 blocks
        of per-regrid host time."""
        if not self.blocks:
            return np.empty(0, np.int32)
        slots = np.fromiter(self.blocks.values(), np.int32,
                            len(self.blocks))
        ids = self.curve.encode(
            self.level[slots], self.bi[slots], self.bj[slots])
        return slots[np.argsort(ids, kind="stable")]

    def origin(self, s: int) -> Tuple[float, float]:
        h = self.h_at(int(self.level[s]))
        return (float(self.bi[s]) * self.bs * h,
                float(self.bj[s]) * self.bs * h)

    def h_per_block(self, order: np.ndarray) -> np.ndarray:
        return self.cfg.h0 / (1 << self.level[order]).astype(np.float64)

    # -- cell ownership (the reference's treef queries) -----------------
    def owner_relation(self, l: int, i: int, j: int) -> int:
        """For block (l,i,j): 0 = active here, -1 = region is refined
        (finer blocks cover it), -2 = coarser parent active, -3 = nothing
        (the reference tree codes, main.cpp:672-688; its treef keeps the
        parent entry at -1 through arbitrarily deep refinement). Any of
        the four children existing means refined — a child may itself be
        refined deeper, but 2:1 balance guarantees the face-adjacent
        children a caller needs do exist."""
        if (l, i, j) in self.blocks:
            return 0
        i2, j2 = 2 * i, 2 * j
        b = self.blocks
        if (l + 1, i2, j2) in b or (l + 1, i2 + 1, j2) in b \
                or (l + 1, i2, j2 + 1) in b or (l + 1, i2 + 1, j2 + 1) in b:
            return -1
        if (l - 1, i // 2, j // 2) in self.blocks:
            return -2
        return -3
