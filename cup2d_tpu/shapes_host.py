"""Host-side shape bookkeeping shared by the uniform (`sim.Simulation`)
and adaptive (`amr.AMRSim`) drivers: CoM/inertia sync after
rasterization, the deforming-body dt cap, and force-diagnostic logging.
The device kernels differ by storage layout; these pieces are layout-free
and must stay identical between the two paths."""

from __future__ import annotations

import jax
import numpy as np

from .ops.forces import FORCE_KEYS


class ShapeHostMixin:
    """Requires: self.shapes, self.time, self.force_log."""

    def _sync_shape_scalars(self, obs):
        """CoM correction + M/J/d_gm bookkeeping (main.cpp:4480-4541).
        One batched device_get — separate np.asarray pulls each pay the
        full device->host latency (~100 ms through the TPU tunnel)."""
        self._sync_shape_scalars_np(*jax.device_get(
            (obs.com, obs.mass, obs.inertia)))

    def _sync_shape_scalars_np(self, com, mass, inertia):
        """Same, from already-fetched host arrays (fused-step path)."""
        com = np.asarray(com, dtype=np.float64)
        mass = np.asarray(mass, dtype=np.float64)
        inertia = np.asarray(inertia, dtype=np.float64)
        for k, s in enumerate(self.shapes):
            s.com[:] = com[k]
            s.M = float(mass[k])
            s.J = float(inertia[k])
            dc = s.center - s.com
            cth, sth = np.cos(s.orientation), np.sin(s.orientation)
            s.d_gm[0] = dc[0] * cth + dc[1] * sth
            s.d_gm[1] = -dc[0] * sth + dc[1] * cth

    def _kinematic_dt_cap(self) -> float:
        """Deforming bodies need dt well under their gait period: the
        grid-umax CFL (main.cpp:6579-6595) cannot see the midline's
        future motion when the flow is still quiescent (the curvature
        scheduler ramps from zero), and on coarse grids the diffusive dt
        limit 0.25 h^2/nu can exceed the period itself — advancing the
        kinematics by O(period) per step is meaningless and blows up the
        penalization. The reference dodges this only by always running
        fine grids (h <= 1/1024 keeps the diffusive cap small). 1/20th
        of the fastest period resolves the gait; obstacle-free and
        rigid-shape runs are uncapped, exactly like the reference."""
        periods = [float(s.current_period) for s in self.shapes
                   if getattr(s, "current_period", 0.0) > 0.0]
        return 0.05 * min(periods) if periods else float("inf")

    @staticmethod
    def force_log_header() -> str:
        return ",".join(["time", "shape"] + list(FORCE_KEYS))

    def _record_forces(self, results):
        """Store the 19 diagnostics on each shape + append CSV rows.
        device_get fetches all S x 19 device scalars in one transfer —
        per-scalar float() pulls cost S x 19 round trips."""
        results = jax.device_get(results)
        for k, (s, r) in enumerate(zip(self.shapes, results)):
            s.forces = {key: float(r[key]) for key in FORCE_KEYS}
            if self.force_log is not None:
                row = [f"{self.time:.8g}", str(k)] + [
                    f"{s.forces[key]:.8g}" for key in FORCE_KEYS]
                self.force_log.write(",".join(row) + "\n")
