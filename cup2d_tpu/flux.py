"""Coarse-fine conservation: the makeFlux Poisson closure + flux correction.

Two pieces the reference treats as correctness invariants at AMR level
interfaces, re-expressed as gather tables / index tables so the per-step
device work stays branch-free:

1. **Variable-resolution Poisson closure** (`/root/reference/main.cpp:
   5916-5997` interpolate/makeFlux/D1/D2, assembled into COO rows at
   `7031-7115`). The reference builds one sparse row per cell; every row
   is "sum over the 4 faces of (ghost - this)" where the ghost at a
   level interface is the 8/15, 2/3, -1/5 interpolation with D1/D2
   tangential Taylor corrections (fine side) or the flux-replacement sum
   over the two fine subfaces (coarse side). Both are LINEAR in stored
   cell values, so the whole operator is `laplacian5` applied to a lab
   whose interface ghosts encode those rows — built here as a drop-in
   builder for `halo.build_tables`. The resulting operator is exactly
   the reference's matrix: consistent, and conservative (the flux a fine
   cell pair sees is minus the flux the coarse cell sees, D-terms
   included — the D1 terms cancel pairwise across a subface pair).

2. **Flux correction for stencil kernels** (`main.cpp:513-517 BlockCase,
   1392-1849 prepare0/fillcases`). Reference kernels deposit each
   block-face's *linear* flux (diffusive flux for advection-diffusion,
   face velocity for the divergence RHS, pressure gradient for the
   projection; the WENO advective part is never corrected) into per-face
   stores; `fillcases` then ADDS [own coarse deposit + paired sums of
   the fine deposits] to the coarse edge cells, which — because a
   deposit is defined as minus the face's contribution to the written
   value — replaces the coarse face's term with minus the fine side's:
   discrete conservation. Here every block computes its 4 face-deposit
   vectors from the already-assembled labs (vectorized over all blocks),
   and a topology-only index table (built once per regrid) gathers
   [coarse deposit + fine pair] into the affected cells.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from .forest import Forest
from .halo import Expr, HaloTables, _TopoIndex, build_tables

# face order = the reference's BlockCase d[0..3] (main.cpp:513-517)
_FACES = ((-1, 0), (1, 0), (0, -1), (0, 1))  # Xm, Xp, Ym, Yp


# ---------------------------------------------------------------------------
# 1. Poisson closure as a lab-ghost builder
# ---------------------------------------------------------------------------

# D1/D2 tangential stencils at a coarse cell (main.cpp:5916-5959): keyed
# by (is_backward, is_forward); offsets are tangential steps within the
# coarse block. The BS/2 splits keep the stencil inside the half-face a
# single fine block abuts.
_D1 = {
    "bd": ((-2, 1.0 / 8.0), (-1, -1.0 / 2.0), (0, 3.0 / 8.0)),
    "fd": ((2, -1.0 / 8.0), (1, 1.0 / 2.0), (0, -3.0 / 8.0)),
    "ct": ((-1, -1.0 / 8.0), (1, 1.0 / 8.0)),
}
_D2 = {
    "bd": ((-2, 1.0 / 32.0), (-1, -1.0 / 16.0), (0, 1.0 / 32.0)),
    "fd": ((2, 1.0 / 32.0), (1, -1.0 / 16.0), (0, 1.0 / 32.0)),
    "ct": ((-1, 1.0 / 32.0), (1, 1.0 / 32.0), (0, -1.0 / 16.0)),
}


def _dkind(t: int, bs: int) -> str:
    if t == bs - 1 or t == bs // 2 - 1:
        return "bd"
    if t == 0 or t == bs // 2:
        return "fd"
    return "ct"


def _fine_subface(cx: int, cy: int, l: int, bi: int, bj: int, t: int,
                  bs: int):
    """For coarse block (l, bi, bj), face (cx, cy), face cell t: the
    finer neighbor block key covering that cell and the tangential index
    of the first of its two subface cells (the reference's Zchild +
    neiFine1/neiFine2 addressing, main.cpp:5825-5914). Shared by the
    Poisson closure and the flux-correction table so the two stay
    index-consistent by construction."""
    half = 1 if t >= bs // 2 else 0
    if cx != 0:
        a = 1 if cx < 0 else 0
        fb = (l + 1, 2 * (bi + cx) + a, 2 * bj + half)
    else:
        b_ = 1 if cy < 0 else 0
        fb = (l + 1, 2 * bi + half, 2 * (bj + cy) + b_)
    return fb, 2 * (t % (bs // 2))


class _PoissonLabBuilder:
    """Ghost expressions making `laplacian5(lab)` the reference's
    variable-resolution Poisson operator. Same constructor/`block_ghosts`
    contract as `halo._LabBuilder` so `build_tables` grouping reuses it.
    """

    def __init__(self, forest, g: int, tensorial: bool, dim: int):
        assert g == 1 and dim == 1
        self.f = forest
        self.bs = forest.bs
        self.g = 1
        self.dim = 1

    def _cell(self, slot, cy, cx, w=1.0):
        return Expr({(slot, cy, cx): np.full(1, w)})

    def _tang(self, slot, edge_n, tc, table, xface: bool) -> Expr:
        """D1/D2 expression at coarse cell (normal index edge_n,
        tangential index tc), tangential steps within block `slot`."""
        e = Expr()
        for d, w in table[_dkind(tc, self.bs)]:
            cy, cx = (tc + d, edge_n) if xface else (edge_n, tc + d)
            e.add(self._cell(slot, cy, cx), w)
        return e

    def block_ghosts(self, slot: int):
        f = self.f
        bs = self.bs
        l = int(f.level[slot])
        bi = int(f.bi[slot])
        bj = int(f.bj[slot])
        nbx, nby = f.nblocks_at(l)
        out: dict[tuple[int, int], Expr] = {}

        for face, (cx, cy) in enumerate(_FACES):
            xface = cx != 0
            ni, nj = bi + cx, bj + cy
            wall = not (0 <= ni < nbx and 0 <= nj < nby)
            # own edge coords along the face, as (cy, cx) builders
            edge_n = (0 if cx < 0 else bs - 1) if xface else \
                     (0 if cy < 0 else bs - 1)

            def own(t, depth=0):
                n = edge_n + (1 if (cx < 0 or cy < 0) else -1) * depth
                return (t, n) if xface else (n, t)

            def lab_of(t):
                if xface:
                    lx = 0 if cx < 0 else bs + 1
                    return (t + 1, lx)
                ly = 0 if cy < 0 else bs + 1
                return (ly, t + 1)

            if wall:
                # zero-Neumann wall: ghost = edge cell, flux = 0
                # (the reference skips boundary faces entirely,
                # main.cpp:7104 isBoundary)
                for t in range(bs):
                    oy, ox = own(t)
                    out[lab_of(t)] = self._cell(slot, oy, ox)
                continue

            rel = f.owner_relation(l, ni, nj)
            if rel == 0:
                ns = f.slot(l, ni, nj)
                n_edge = (bs - 1 if cx < 0 else 0) if xface else \
                         (bs - 1 if cy < 0 else 0)
                for t in range(bs):
                    cyx = (t, n_edge) if xface else (n_edge, t)
                    out[lab_of(t)] = self._cell(ns, *cyx)
            elif rel == -2:
                # fine side of a fine-coarse interface: interpolated
                # ghost (interpolate(), signInt=+1, main.cpp:5943-5960)
                cs = f.slot(l - 1, ni // 2, nj // 2)
                assert cs >= 0
                c_edge = (bs - 1 if cx < 0 else 0) if xface else \
                         (bs - 1 if cy < 0 else 0)
                par = (bj & 1) if xface else (bi & 1)
                for t in range(bs):
                    tc = t // 2 + par * (bs // 2)
                    ccyx = (tc, c_edge) if xface else (c_edge, tc)
                    st = -1.0 if t % 2 == 0 else 1.0
                    e = Expr()
                    e.add(self._cell(slot, *own(t)), 2.0 / 3.0)
                    e.add(self._cell(slot, *own(t, 1)), -1.0 / 5.0)
                    e.add(self._cell(cs, *ccyx), 8.0 / 15.0)
                    e.add(self._tang(cs, c_edge, tc, _D1, xface),
                          st * 8.0 / 15.0)
                    e.add(self._tang(cs, c_edge, tc, _D2, xface),
                          8.0 / 15.0)
                    out[lab_of(t)] = e
            elif rel == -1:
                # coarse side: flux replacement by the two fine subfaces
                # (makeFlux -1 branch; the paired D1 terms cancel,
                # leaving -16/15 D2, main.cpp:5997-6013)
                fe_close = bs - 1 if (cx < 0 or cy < 0) else 0
                fe_far = fe_close + (-1 if fe_close == bs - 1 else 1)
                for t in range(bs):
                    fb, tf0 = _fine_subface(cx, cy, l, bi, bj, t, bs)
                    fs = f.slot(*fb)
                    assert fs >= 0
                    e = Expr()
                    e.add(self._cell(slot, *own(t)), 1.0 - 16.0 / 15.0)
                    for tf in (tf0, tf0 + 1):
                        ccyx = (tf, fe_close) if xface else (fe_close, tf)
                        fcyx = (tf, fe_far) if xface else (fe_far, tf)
                        e.add(self._cell(fs, *ccyx), 1.0 / 3.0)
                        e.add(self._cell(fs, *fcyx), 1.0 / 5.0)
                    e.add(self._tang(slot, edge_n, t, _D2, xface),
                          -16.0 / 15.0)
                    out[lab_of(t)] = e
            else:  # pragma: no cover - 2:1 balance guarantees a neighbor
                raise AssertionError("missing neighbor on balanced forest")
        return out


def build_poisson_tables(forest: Forest, order: np.ndarray,
                         topo=None) -> HaloTables:
    """g=1 scalar tables: `laplacian5(assemble_labs_ordered(x, t), 1)`
    is the reference's variable-resolution Poisson matrix A."""
    return build_tables(forest, order, 1, False, 1, topo=topo,
                        builder_cls=_PoissonLabBuilder)


# ---------------------------------------------------------------------------
# 1b. The same Poisson operator in structured (gather-free-rows) form
#
# Every ghost of the makeFlux closure is FACE-LOCAL: it combines (a) the
# block's own edge/next-to-edge strips, (b) ONE face neighbor's edge
# strip (same-level or coarse), or (c) TWO finer neighbors' edge strips
# — and all tangential D1/D2 arithmetic is a fixed linear map on those
# 8-vectors. So instead of per-ghost-cell gather rows (whose scatter
# lowering serializes on TPU — the r5 1e4-block trace put the in-loop
# lab assemblies among the top costs), the operator needs only 2
# block-row gathers per face (embedding-style, one block = one 256 B
# row) plus per-face [BS, BS] matmuls built ONCE from the same _D1/_D2
# tables as the lab builder. Case selection (wall / same-level / coarse
# / fine) is a host-built one-hot mask per face.
#
# The lab-table path stays as the A/B reference (CUP2D_POIS=tables) and
# the equivalence test (tests/test_flux.py) pins the two forms against
# each other so the constants can never diverge. On a device mesh the
# same per-face gathers run per shard against [own ++ received surface]
# rows (parallel.shard_halo.ShardPoissonOp) — the strip math below is
# shared verbatim through _structured_lap.
# ---------------------------------------------------------------------------


class PoissonOp(NamedTuple):
    """Structured makeFlux operator tables (single-device hot path).

    Per face f in the _FACES order, arrays over the padded ordered
    block axis: ``nba[f]``/``nbb[f]`` gather source rows (fine-case
    halves; equal otherwise), ``m_same/m_coarse/m_fine/m_wall[f]`` the
    case one-hots, ``par[f]`` the coarse-interpolation parity. The
    static [BS, BS] tangential matrices ride along so the jitted apply
    is self-contained."""

    nba: jnp.ndarray       # [4, n_pad] int32 ordered positions
    nbb: jnp.ndarray       # [4, n_pad]
    m_same: jnp.ndarray    # [4, n_pad] dtype
    m_coarse: jnp.ndarray  # [4, n_pad]
    m_fine: jnp.ndarray    # [4, n_pad]
    m_wall: jnp.ndarray    # [4, n_pad]
    par: jnp.ndarray       # [4, n_pad] dtype (0.0 / 1.0)
    wc0: jnp.ndarray       # [BS, BS] coarse-ghost strip map, parity 0
    wc1: jnp.ndarray       # [BS, BS] parity 1
    mcl: jnp.ndarray       # [2, BS, BS] fine close-col maps per half
    mfr: jnp.ndarray       # [2, BS, BS] fine far-col maps per half
    d2own: jnp.ndarray     # [BS, BS] own-edge D2 map (coarse side)


jax.tree_util.register_pytree_node(
    PoissonOp,
    lambda t: (tuple(t), ()),
    lambda aux, ch: PoissonOp(*ch),
)


def _structured_matrices(bs: int):
    """The static tangential maps of the makeFlux closure, from the
    SAME _D1/_D2 tables as _PoissonLabBuilder (shared constants by
    construction). Row t of each matrix holds the weights over the
    gathered 8-strip for ghost cell t."""
    wc = np.zeros((2, bs, bs))
    for par in (0, 1):
        for t in range(bs):
            tc = t // 2 + par * (bs // 2)
            st = -1.0 if t % 2 == 0 else 1.0
            wc[par, t, tc] += 8.0 / 15.0
            for d, w in _D1[_dkind(tc, bs)]:
                wc[par, t, tc + d] += st * (8.0 / 15.0) * w
            for d, w in _D2[_dkind(tc, bs)]:
                wc[par, t, tc + d] += (8.0 / 15.0) * w
    mcl = np.zeros((2, bs, bs))
    mfr = np.zeros((2, bs, bs))
    for half in (0, 1):
        for t in range(half * (bs // 2), (half + 1) * (bs // 2)):
            tf0 = 2 * (t % (bs // 2))
            for tf in (tf0, tf0 + 1):
                mcl[half, t, tf] += 1.0 / 3.0
                mfr[half, t, tf] += 1.0 / 5.0
    d2own = np.zeros((bs, bs))
    for t in range(bs):
        for d, w in _D2[_dkind(t, bs)]:
            d2own[t, t + d] += w
    return wc[0], wc[1], mcl, mfr, d2own


def build_poisson_structured(forest: Forest, order: np.ndarray,
                             n_pad: int, topo=None) -> PoissonOp:
    """Host build of the structured operator (vectorized over the dense
    topology index; a few [n_pad] arrays per face — no per-cell rows)."""
    bs = forest.bs
    n_real = len(order)
    assert n_pad > n_real
    if topo is None:
        topo = _TopoIndex(forest, order)
    lv = forest.level[order].astype(np.int64)
    biv = forest.bi[order].astype(np.int64)
    bjv = forest.bj[order].astype(np.int64)
    ordpos_of = np.full(forest.capacity, n_real, np.int64)
    ordpos_of[order] = np.arange(n_real)
    fdt = np.dtype(jnp.dtype(forest.dtype).name)

    nba = np.full((4, n_pad), n_real, np.int32)
    nbb = np.full((4, n_pad), n_real, np.int32)
    masks = np.zeros((4, 4, n_pad), fdt)   # [case, face, n_pad]
    par = np.zeros((4, n_pad), fdt)
    for face, (cx, cy) in enumerate(_FACES):
        rel = topo.rel_at(lv, biv + cx, bjv + cy)
        wall = rel == -3          # off-domain: zero-flux face
        same = rel == 0
        coarse = rel == -2
        fine = rel == -1
        masks[3, face, :n_real][wall] = 1.0
        masks[0, face, :n_real][same] = 1.0
        masks[1, face, :n_real][coarse] = 1.0
        masks[2, face, :n_real][fine] = 1.0
        s_same = topo.slot_at(lv, biv + cx, bjv + cy)
        s_coarse = topo.slot_at(lv - 1, (biv + cx) >> 1, (bjv + cy) >> 1)
        if cx != 0:
            a = 1 if cx < 0 else 0
            fa_i = 2 * (biv + cx) + a
            fa_j = 2 * bjv
            fb_j = 2 * bjv + 1
            s_fa = topo.slot_at(lv + 1, fa_i, fa_j)
            s_fb = topo.slot_at(lv + 1, fa_i, fb_j)
            par[face, :n_real] = (bjv & 1).astype(fdt)
        else:
            b_ = 1 if cy < 0 else 0
            fa_j = 2 * (bjv + cy) + b_
            s_fa = topo.slot_at(lv + 1, 2 * biv, fa_j)
            s_fb = topo.slot_at(lv + 1, 2 * biv + 1, fa_j)
            par[face, :n_real] = (biv & 1).astype(fdt)
        a_slot = np.where(same, s_same,
                          np.where(coarse, s_coarse,
                                   np.where(fine, s_fa, -1)))
        b_slot = np.where(fine, s_fb, a_slot)
        nba[face, :n_real] = np.where(
            a_slot >= 0, ordpos_of[np.maximum(a_slot, 0)], n_real)
        nbb[face, :n_real] = np.where(
            b_slot >= 0, ordpos_of[np.maximum(b_slot, 0)], n_real)

    wc0, wc1, mcl, mfr, d2own = _structured_matrices(bs)
    # numpy leaves on purpose: the caller device_puts the whole op in
    # ONE async transfer (per-leaf jnp.asarray costs one synchronous
    # tunnel round trip each — the same ~14 s/regrid lesson as
    # halo.pad_tables)
    return PoissonOp(
        nba=nba, nbb=nbb,
        m_same=masks[0], m_coarse=masks[1],
        m_fine=masks[2], m_wall=masks[3],
        par=par,
        wc0=wc0.astype(fdt), wc1=wc1.astype(fdt),
        mcl=mcl.astype(fdt), mfr=mfr.astype(fdt),
        d2own=d2own.astype(fdt),
    )


def poisson_apply_structured(x: jnp.ndarray, op) -> jnp.ndarray:
    """A(x) for [n_pad, BS, BS] ordered x: within-block 5-point part
    plus the four per-face ghost strips (case-selected linear maps of
    gathered neighbor strips). Equivalent (same weights, slightly
    different f32 summation order) to
    `laplacian5(assemble_labs_ordered(x, tpois), 1)[:, 0]`.

    Dispatches to the shard-local apply when given a per-device
    operator (parallel.shard_halo.ShardPoissonOp — same strip math via
    `_structured_lap`, gather sources remapped into [own ++ received
    surface] space behind an explicit ppermute exchange)."""
    if hasattr(op, "apply"):
        return op.apply(x)
    return _structured_lap(
        x, x, op.nba, op.nbb, op.m_same, op.m_coarse, op.m_fine,
        op.m_wall, op.par, (op.wc0, op.wc1, op.mcl, op.mfr, op.d2own))


def _structured_lap(x_own: jnp.ndarray, x_src: jnp.ndarray,
                    nba, nbb, m_same, m_coarse, m_fine, m_wall, par,
                    mats) -> jnp.ndarray:
    """The ONE strip-math body of the structured makeFlux operator.

    ``x_own`` [N, BS, BS] holds the rows the laplacian is computed for;
    ``x_src`` [M, BS, BS] is the gather space ``nba``/``nbb`` index —
    x_own itself on a single device, [own blocks ++ received surface
    blocks] on a shard. Every tangential map reduces over BS only
    (elementwise in N), so the sharded per-device apply is bit-identical
    to the single-device one per block row by construction.

    Layout discipline (the round-5 lever): all strip/stencil math runs
    BLOCKS-LAST — strips are [BS, N] (full 128-lane rows instead of the
    16x-padded [N, BS]), the shifted-neighbor fields are built by
    concatenation along the major cell axes of a [BS, BS, N] transpose,
    and the tangential maps apply as [BS, BS] @ [BS, N] MXU matmuls at
    HIGHEST precision (the default bf16 pass truncates the D1/D2
    weights enough to destroy the two-level correction — measured
    8 -> 121 Krylov iterations). Only the neighbor-block gathers stay
    block-major (one block = one 256 B row, the fast gather pattern),
    paying one explicit [N,8,8] -> [8,8,N] relayout each."""
    wc0, wc1, mcl, mfr, d2own = mats
    bs = x_own.shape[1]
    xt = x_own.transpose(1, 2, 0)                 # [y, x, N]

    def mm(a, b):
        return jnp.matmul(a, b, precision=jax.lax.Precision.HIGHEST)

    c23, c15, c1615 = 2.0 / 3.0, 1.0 / 5.0, 16.0 / 15.0

    def ghost(face):
        """[BS, N] ghost strip (tangential index first)."""
        cx, cy = _FACES[face]
        At = x_src[nba[face]].transpose(1, 2, 0)  # [y, x, N]
        Bt = x_src[nbb[face]].transpose(1, 2, 0)
        if cx != 0:
            own_e = xt[:, 0, :] if cx < 0 else xt[:, bs - 1, :]
            own_e1 = xt[:, 1, :] if cx < 0 else xt[:, bs - 2, :]
            n_edge = bs - 1 if cx < 0 else 0
            far = bs - 2 if cx < 0 else 1
            sA = At[:, n_edge, :]
            far_a = At[:, far, :]
            close_b, far_b = Bt[:, n_edge, :], Bt[:, far, :]
        else:
            own_e = xt[0, :, :] if cy < 0 else xt[bs - 1, :, :]
            own_e1 = xt[1, :, :] if cy < 0 else xt[bs - 2, :, :]
            n_edge = bs - 1 if cy < 0 else 0
            far = bs - 2 if cy < 0 else 1
            sA = At[n_edge, :, :]
            far_a = At[far, :, :]
            close_b, far_b = Bt[n_edge, :, :], Bt[far, :, :]
        # same-level copy
        g_same = sA
        # fine side of a coarse neighbor: strip map per parity
        gc0 = mm(wc0, sA)
        gc1 = mm(wc1, sA)
        pf = par[face][None, :]
        g_coarse = (c23 * own_e - c15 * own_e1
                    + (1.0 - pf) * gc0 + pf * gc1)
        # coarse side of finer neighbors: subface sums + own D2
        # sA doubles as the fine close-column (same edge slice)
        g_fine = ((1.0 - c1615) * own_e
                  + mm(mcl[0], sA) + mm(mfr[0], far_a)
                  + mm(mcl[1], close_b) + mm(mfr[1], far_b)
                  - c1615 * mm(d2own, own_e))
        return (m_same[face][None, :] * g_same
                + m_coarse[face][None, :] * g_coarse
                + m_fine[face][None, :] * g_fine
                + m_wall[face][None, :] * own_e)

    gw, ge, gs, gn = ghost(0), ghost(1), ghost(2), ghost(3)
    xw = jnp.concatenate([gw[:, None, :], xt[:, :-1, :]], axis=1)
    xe = jnp.concatenate([xt[:, 1:, :], ge[:, None, :]], axis=1)
    xs_ = jnp.concatenate([gs[None, :, :], xt[:-1, :, :]], axis=0)
    xn = jnp.concatenate([xt[1:, :, :], gn[None, :, :]], axis=0)
    lapt = xw + xe + xs_ + xn - 4.0 * xt
    return lapt.transpose(2, 0, 1)



# ---------------------------------------------------------------------------
# 2. Flux-correction index tables + per-kernel face deposits
# ---------------------------------------------------------------------------

class FluxCorrTables(NamedTuple):
    """Correction rows: value[dest] += valid * (D[cidx] + D[fidx1] +
    D[fidx2]), where D is a [n_active * 4 * BS, dim] face-deposit array.
    One row per coarse edge cell whose face abuts a finer neighbor (the
    reference's fillcase0+fillcase1 combination). Rows are padded to
    power-of-two buckets (``valid`` = 0, dest pointing at a dead pad-row
    cell) so the jitted step's argument shapes survive regrids — same
    rationale as halo.pad_tables."""

    dest: jnp.ndarray    # [M] into ordered cell layout [n_active*BS*BS]
    cidx: jnp.ndarray    # [M] coarse block's own face deposit
    fidx1: jnp.ndarray   # [M] fine subface deposits (the pair)
    fidx2: jnp.ndarray   # [M]
    valid: jnp.ndarray   # [M] 1.0 real row / 0.0 padding


jax.tree_util.register_pytree_node(
    FluxCorrTables,
    lambda t: ((t.dest, t.cidx, t.fidx1, t.fidx2, t.valid), ()),
    lambda aux, ch: FluxCorrTables(*ch),
)


def build_flux_corr(forest: Forest, order: np.ndarray,
                    n_pad: int = 0, topo=None) -> FluxCorrTables:
    """Topology-only; shared by every corrected kernel (the per-kernel
    physics lives in the deposit arrays). ``n_pad`` > len(order) enables
    shape-stable row padding (pad rows target the first pad block's
    cell 0, which the caller's mask discards). Rows are built vectorized
    per face over the dense topology index (the per-block Python loop
    was O(blocks*faces*BS) host time per regrid); index math mirrors
    `_fine_subface`, asserted equal by tests/test_flux.py."""
    bs = forest.bs
    n_real = len(order)
    if topo is None:
        topo = _TopoIndex(forest, order)
    lv = forest.level[order].astype(np.int64)
    biv = forest.bi[order].astype(np.int64)
    bjv = forest.bj[order].astype(np.int64)
    ordpos_of = np.full(forest.capacity, -1, np.int64)
    ordpos_of[order] = np.arange(n_real)
    k_arr = np.arange(n_real, dtype=np.int64)
    t = np.arange(bs, dtype=np.int64)
    half = (t >= bs // 2).astype(np.int64)
    tf0 = 2 * (t % (bs // 2))
    dest_p, cidx_p, f1_p = [], [], []
    for face, (cx, cy) in enumerate(_FACES):
        finer = topo.rel_at(lv, biv + cx, bjv + cy) == -1
        if not finer.any():
            continue
        km = k_arr[finer]
        lm, bim, bjm = lv[finer], biv[finer], bjv[finer]
        # fine neighbor block per (member, t) — _fine_subface vectorized
        if cx != 0:
            fbi = 2 * (bim[:, None] + cx) + (1 if cx < 0 else 0)
            fbj = 2 * bjm[:, None] + half[None, :]
            cell = t[None, :] * bs + (0 if face == 0 else bs - 1)
        else:
            fbi = 2 * bim[:, None] + half[None, :]
            fbj = 2 * (bjm[:, None] + cy) + (1 if cy < 0 else 0)
            cell = (0 if face == 2 else bs - 1) * bs + t[None, :]
        slots = topo.slot_at(lm[:, None] + 1, fbi, fbj)
        assert (slots >= 0).all(), "2:1 balance violated at a face"
        kf = ordpos_of[slots]
        opp = face ^ 1
        dest_p.append((km[:, None] * (bs * bs) + cell).ravel())
        cidx_p.append(((km[:, None] * 4 + face) * bs + t[None, :]).ravel())
        f1_p.append(((kf * 4 + opp) * bs + tf0[None, :]).ravel())
    cat = (lambda ps: np.concatenate(ps)
           if ps else np.zeros(0, np.int64))
    dest, cidx, f1 = cat(dest_p), cat(cidx_p), cat(f1_p)
    m_real = len(dest)
    if n_pad:
        assert n_pad > n_real
        m = max(64, 1 << max(0, (m_real - 1)).bit_length())
        dead = n_real * bs * bs
        dest = np.concatenate([dest, np.full(m - m_real, dead, np.int64)])
        cidx = np.concatenate([cidx, np.zeros(m - m_real, np.int64)])
        f1 = np.concatenate([f1, np.zeros(m - m_real, np.int64)])
    valid = np.zeros(len(dest), np.float32)
    valid[:m_real] = 1.0
    as_i = lambda a: jnp.asarray(np.asarray(a, np.int32))
    return FluxCorrTables(
        dest=as_i(dest), cidx=as_i(cidx), fidx1=as_i(f1),
        fidx2=as_i(f1 + 1), valid=jnp.asarray(valid),
    )


def apply_flux_corr(values: jnp.ndarray, deposits: jnp.ndarray,
                    t) -> jnp.ndarray:
    """values: [N, BS, BS] or [N, dim, BS, BS] kernel output (ordered);
    deposits: [N, 4, BS] or [N, 4, BS, dim] from a `*_deposits` helper.
    Returns corrected values (the reference's fillcases add).
    Dispatches to the shard-local apply for per-device correction rows
    (parallel.shard_halo.ShardFluxCorr)."""
    if hasattr(t, "apply"):
        return t.apply(values, deposits)
    valid = t.valid.astype(values.dtype)
    if values.ndim == 3:
        flat = values.reshape(-1)
        d = deposits.reshape(-1)
        corr = valid * (d[t.cidx] + d[t.fidx1] + d[t.fidx2])
        return flat.at[t.dest].add(corr).reshape(values.shape)
    n, dim, bs, _ = values.shape
    flat = values.transpose(0, 2, 3, 1).reshape(-1, dim)
    d = deposits.reshape(-1, dim)
    corr = valid[:, None] * (d[t.cidx] + d[t.fidx1] + d[t.fidx2])
    out = flat.at[t.dest].add(corr)
    return out.reshape(n, bs, bs, dim).transpose(0, 3, 1, 2)


def _face_pairs(lab: jnp.ndarray, g: int, bs: int):
    """(this, ghost) slices per face of [..., L, L] labs; the face axis
    runs along the block edge (length BS)."""
    return (
        (lab[..., g:g + bs, g], lab[..., g:g + bs, g - 1]),        # Xm
        (lab[..., g:g + bs, g + bs - 1], lab[..., g:g + bs, g + bs]),  # Xp
        (lab[..., g, g:g + bs], lab[..., g - 1, g:g + bs]),        # Ym
        (lab[..., g + bs - 1, g:g + bs], lab[..., g + bs, g:g + bs]),  # Yp
    )


def diffusive_deposits(vlab: jnp.ndarray, g: int, dfac) -> jnp.ndarray:
    """KernelAdvectDiffuse deposits (main.cpp:5504-5570): dfac*(this -
    ghost) per component; only the diffusive flux is corrected, the WENO
    advective term is not. vlab [N, 2, L, L] -> [N, 4, BS, 2]."""
    bs = vlab.shape[-1] - 2 * g
    rows = [dfac * (t - gh) for (t, gh) in _face_pairs(vlab, g, bs)]
    return jnp.stack(rows, axis=1).transpose(0, 1, 3, 2)  # [N,4,BS,2]


def divergence_deposits(vlab: jnp.ndarray, ulab, chi, facDiv) -> jnp.ndarray:
    """pressure_rhs deposits (main.cpp:6152-6207): +-facDiv*(vn_this +
    vn_ghost) minus the chi*udef counterpart; vn is the face-normal
    component. facDiv = 0.5*h/dt per block, shaped [N] (or scalar).
    vlab/ulab [N, 2, L, L], chi [N, BS, BS] -> [N, 4, BS]."""
    g = 1
    bs = vlab.shape[-1] - 2
    fd = jnp.asarray(facDiv)
    fd = fd.reshape(-1, 1) if fd.ndim else fd
    pairs = _face_pairs(vlab, g, bs)
    upairs = _face_pairs(ulab, g, bs) if ulab is not None else None
    chi_edge = (chi[:, :, 0], chi[:, :, bs - 1],
                chi[:, 0, :], chi[:, bs - 1, :]) if chi is not None else None
    rows = []
    for f in range(4):
        comp = 0 if f < 2 else 1
        sgn = 1.0 if f % 2 == 0 else -1.0
        t, gh = pairs[f]
        val = t[:, comp] + gh[:, comp]
        if upairs is not None:
            ut, ugh = upairs[f]
            val = val - chi_edge[f] * (ut[:, comp] + ugh[:, comp])
        rows.append(sgn * fd * val)
    return jnp.stack(rows, axis=1)


def laplacian_deposits(plab: jnp.ndarray) -> jnp.ndarray:
    """pressure_rhs1 deposits (main.cpp:6231-6286): ghost - this per
    face. plab [N, L, L] -> [N, 4, BS]."""
    bs = plab.shape[-1] - 2
    return jnp.stack(
        [gh - t for (t, gh) in _face_pairs(plab, 1, bs)], axis=1)


def gradient_deposits(plab: jnp.ndarray, pfac) -> jnp.ndarray:
    """pressureCorrectionKernel deposits (main.cpp:6055-6103):
    +-pfac*(this + ghost) in the face-normal component only; pfac =
    -0.5*dt*h per block [N]. plab [N, L, L] -> [N, 4, BS, 2]."""
    bs = plab.shape[-1] - 2
    pf = jnp.asarray(pfac)
    pf = pf.reshape(-1, 1) if pf.ndim else pf
    out = []
    for f, (t, gh) in enumerate(_face_pairs(plab, 1, bs)):
        sgn = 1.0 if f % 2 == 0 else -1.0
        val = sgn * pf * (t + gh)
        zero = jnp.zeros_like(val)
        out.append(jnp.stack([val, zero] if f < 2 else [zero, val],
                             axis=-1))
    return jnp.stack(out, axis=1)  # [N, 4, BS, 2]
