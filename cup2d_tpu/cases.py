"""Validation-case catalog (ISSUE 12): named, runnable, serveable
workloads built on the per-face BC engine (bc.py).

Each case bundles the THREE things that define a workload — a
SimConfig, a BCTable and the initial/obstacle state — behind one name,
so the same case runs identically from the CLI (``-case cavity``), the
validation probes (validation/cavity.py, validation/channel.py), tests
and the fleet/serving layer. The registry is plain data + builder
functions: adding a case is one ``CaseSpec`` entry, no solver changes.

Catalog:

``cavity``
    Lid-driven cavity, THE canonical incompressible benchmark the
    free-slip-only box could never express: unit box, four no-slip
    walls, the y_hi lid translating at ``lid_u``. Obstacle-free
    (UniformSim family — also fleet-servable: the table is all-Neumann
    so the slot-pool solvers keep their mean-free contract). Validated
    against the Ghia et al. (1982) Re=100 centerline profiles
    (validation/cavity.py).

``channel``
    Channel flow past a FIXED cylinder: Dirichlet inflow at x_lo,
    convective outflow at x_hi, free-slip side walls, a prescribed-
    (0,0) disk in the stream, the whole domain impulsively started at
    the inflow velocity. The true inflow-outflow configuration the
    towed-cylinder case only approximates Galilean-ly. Validated by
    shedding Strouhal number vs the Williamson (1989) Re=200 band
    (validation/channel.py).

``cylinder``
    The legacy towed-cylinder drag/Strouhal case (free-slip box,
    prescribed (-U, 0) disk) folded into the registry so
    validation/cylinder.py runs through the same ``-case`` path it
    validates.

``tgv_periodic``
    Doubly-periodic Taylor-Green vortex (ISSUE 20): u = U sin(kx)
    cos(ky), v = -U cos(kx) sin(ky), k = 2pi/L on the unit box. The
    ONE periodic case with a closed-form answer — kinetic energy
    decays as exp(-4 nu k^2 t) — so it anchors both the wrap-ghost
    paint and the fftd direct solve against analysis, not another
    solver. Obstacle-free and fleet-servable (the sampled IC is
    discretely divergence-free under the centered stencils, and the
    all-periodic table keeps the mean-free pressure contract).

``shear_layer``
    Doubly-periodic double shear layer (Bell-Colella-Glaz): two tanh
    layers at y = 1/4 and 3/4 with a delta*sin(2pi x) vertical
    perturbation that rolls them up into the classic vortex pairs.
    The standard stress test for periodic advection + projection.

``turb2d``
    Seeded decaying 2D turbulence: random-phase vorticity spectrum
    E(k) ~ k / (1 + (k/k0)^4), velocity synthesized host-side from
    the streamfunction by CENTERED differences (discretely
    divergence-free by construction — Dx Dy psi == Dy Dx psi).
    Deterministic per seed; fleet members get seed + slot so a
    member-batched fleet serves an ensemble.

No environment reads here — cases parameterize through arguments only
(tests/test_env_latch.py walks this package)."""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Optional, Tuple

from .bc import (BCTable, FREE_SLIP, convective_outflow,
                 dirichlet_inflow, free_slip, no_slip, periodic)
from .config import SimConfig


@dataclass(frozen=True)
class CaseSpec:
    """One catalog entry: ``build(**kw)`` returns a ready-to-step
    driver with ``sim.case`` set; ``default_level`` is the validation
    resolution (CLI ``-level`` overrides); ``fleet_ok`` marks cases
    whose obstacle-free state can ride the fleet slot pool."""

    name: str
    describe: str
    build: Callable
    default_level: int
    fleet_ok: bool = False


def cavity_table(lid_u: float = 1.0) -> BCTable:
    """Four no-slip walls, the y_hi lid moving at (+lid_u, 0)."""
    return BCTable(no_slip(), no_slip(), no_slip(), no_slip(lid_u, 0.0))


def channel_table(u_in: float, profile: str = "uniform") -> BCTable:
    """Dirichlet inflow at x_lo, convective outflow at x_hi, free-slip
    side walls."""
    return BCTable(dirichlet_inflow(u_in, profile=profile),
                   convective_outflow(), free_slip(), free_slip())


def periodic_table() -> BCTable:
    """Doubly-periodic box (all four faces wrap)."""
    return BCTable(periodic(), periodic(), periodic(), periodic())


def periodic_channel_table() -> BCTable:
    """Periodic in x, no-slip walls in y — the mixed table the
    fftd+tridiag solve (and its bench arm) exercises."""
    return BCTable(periodic(), periodic(), no_slip(), no_slip())


def _periodic_sim(cfg: SimConfig, lvl: int, mesh, members: int):
    """Shared driver dispatch for the obstacle-free periodic cases
    (the build_cavity pattern: fleet > sharded > solo)."""
    bc = periodic_table()
    if members > 0:
        from .fleet import FleetSim
        return FleetSim(cfg, level=lvl, members=members, mesh=mesh,
                        bc=bc)
    if mesh is not None:
        from .parallel.mesh import ShardedUniformSim
        return ShardedUniformSim(cfg, mesh, level=lvl, bc=bc)
    from .uniform import UniformSim
    return UniformSim(cfg, level=lvl, bc=bc)


def _install_vel(sim, members: int, vel_fn):
    """Overwrite the zero-state velocity with ``vel_fn(m) ->
    [2, Ny, Nx]`` (numpy), broadcast/stacked over fleet slots."""
    import jax.numpy as jnp
    import numpy as np

    g = sim.grid
    if members > 0:
        v = np.stack([vel_fn(m) for m in range(members)])
    else:
        v = vel_fn(0)
    sim.state = sim.state._replace(
        vel=jnp.asarray(v, dtype=g.dtype))


def build_tgv_periodic(level: Optional[int] = None, nu: float = 1e-3,
                       u0: float = 1.0, dtype: str = "float32",
                       mesh=None, members: int = 0, cfl: float = 0.4):
    """Doubly-periodic Taylor-Green vortex on the unit box:
    u = u0 sin(kx) cos(ky), v = -u0 cos(kx) sin(ky), k = 2pi.

    The nonlinear term of this field is a pure gradient (absorbed by
    the pressure), so the exact solution is self-similar decay —
    KE(t) = KE(0) * exp(-4 nu k^2 t) — and the discrete IC sampled at
    cell centers is divergence-free under the centered divergence
    (the du/dx and dv/dy terms cancel mode-wise). Validation anchor
    for the periodic BC + fftd stack (tests/test_cases.py)."""
    lvl = 4 if level is None else level
    cfg = SimConfig(bpdx=1, bpdy=1, level_max=1, level_start=0,
                    extent=1.0, dtype=dtype, nu=nu, cfl=cfl,
                    poisson_tol=1e-4, poisson_tol_rel=1e-3)
    sim = _periodic_sim(cfg, lvl, mesh, members)

    import numpy as np
    x, y = sim.grid.cell_centers()
    k = 2.0 * np.pi / cfg.extent
    u = u0 * np.sin(k * x) * np.cos(k * y)
    v = -u0 * np.cos(k * x) * np.sin(k * y)
    _install_vel(sim, members, lambda m: np.stack([u, v]))
    sim.case = "tgv_periodic"
    return sim


def build_shear_layer(level: Optional[int] = None, nu: float = 2e-4,
                      rho: float = 30.0, delta: float = 0.05,
                      u0: float = 1.0, dtype: str = "float32",
                      mesh=None, members: int = 0, cfl: float = 0.4):
    """Doubly-periodic double shear layer (Bell-Colella-Glaz 1989):
    two tanh layers of width ~1/rho at y = 1/4 and y = 3/4, kicked by
    a delta*sin(2pi x) vertical velocity that rolls each layer up
    into the classic vortex pair."""
    lvl = 4 if level is None else level
    cfg = SimConfig(bpdx=1, bpdy=1, level_max=1, level_start=0,
                    extent=1.0, dtype=dtype, nu=nu, cfl=cfl,
                    poisson_tol=1e-4, poisson_tol_rel=1e-3)
    sim = _periodic_sim(cfg, lvl, mesh, members)

    import numpy as np
    x, y = sim.grid.cell_centers()
    L = cfg.extent
    u = u0 * np.where(y <= 0.5 * L,
                      np.tanh(rho * (y / L - 0.25)),
                      np.tanh(rho * (0.75 - y / L)))
    v = delta * u0 * np.sin(2.0 * np.pi * x / L)
    _install_vel(sim, members, lambda m: np.stack([u, v]))
    sim.case = "shear_layer"
    return sim


def build_turb2d(level: Optional[int] = None, nu: float = 1e-4,
                 seed: int = 0, k0: float = 6.0, urms: float = 1.0,
                 dtype: str = "float32", mesh=None, members: int = 0,
                 cfl: float = 0.4):
    """Seeded decaying 2D turbulence on the doubly-periodic unit box.

    IC synthesis is host-side numpy (deterministic per seed, no
    device RNG): a random-phase streamfunction with energy spectrum
    E(k) ~ k / (1 + (k/k0)^4), inverse-FFT'd to the grid, then
    differenced CENTRALLY to velocity (u = D_y psi, v = -D_x psi) so
    the discrete centered divergence vanishes identically, and scaled
    to rms speed ``urms``. Fleet members draw seed + slot index — one
    member-batched fleet is a turbulence ensemble."""
    lvl = 4 if level is None else level
    cfg = SimConfig(bpdx=1, bpdy=1, level_max=1, level_start=0,
                    extent=1.0, dtype=dtype, nu=nu, cfl=cfl,
                    poisson_tol=1e-4, poisson_tol_rel=1e-3)
    sim = _periodic_sim(cfg, lvl, mesh, members)

    import numpy as np
    g = sim.grid
    ny, nx, h = g.ny, g.nx, g.h

    def vel_for(m: int):
        rng = np.random.default_rng(seed + m)
        kx = np.fft.fftfreq(nx, d=1.0 / nx)
        ky = np.fft.fftfreq(ny, d=1.0 / ny)
        KX, KY = np.meshgrid(kx, ky, indexing="xy")
        kk = np.sqrt(KX ** 2 + KY ** 2)
        with np.errstate(divide="ignore", invalid="ignore"):
            # E(k) ~ k/(1+(k/k0)^4); psi-hat amplitude
            # ~ sqrt(E(k)/k)/k (vorticity = k^2 psi-hat)
            amp = np.where(
                kk > 0,
                np.sqrt(kk / (1.0 + (kk / k0) ** 4)) / (kk ** 1.5),
                0.0)
        phase = np.exp(2j * np.pi * rng.random((ny, nx)))
        psi = np.fft.ifft2(amp * phase).real
        # centered differences on the wrap: discretely div-free
        u = (np.roll(psi, -1, axis=0) - np.roll(psi, 1, axis=0)) \
            / (2.0 * h)
        v = -(np.roll(psi, -1, axis=1) - np.roll(psi, 1, axis=1)) \
            / (2.0 * h)
        rms = np.sqrt(np.mean(u ** 2 + v ** 2))
        s = urms / rms if rms > 0 else 1.0
        return np.stack([u * s, v * s])

    _install_vel(sim, members, vel_for)
    sim.case = "turb2d"
    return sim


def build_cavity(level: Optional[int] = None, re: float = 100.0,
                 lid_u: float = 1.0, dtype: str = "float32",
                 mesh=None, members: int = 0, cfl: float = 0.4):
    """Lid-driven cavity at Reynolds number ``re`` = lid_u * L / nu on
    the unit box. Obstacle-free: UniformSim, or ShardedUniformSim over
    ``mesh``, or a ``members``-slot FleetSim (every member the same
    table — the pool contract)."""
    lvl = 4 if level is None else level
    cfg = SimConfig(bpdx=1, bpdy=1, level_max=1, level_start=0,
                    extent=1.0, dtype=dtype, nu=lid_u / re, cfl=cfl,
                    poisson_tol=1e-4, poisson_tol_rel=1e-3)
    bc = cavity_table(lid_u)
    if members > 0:
        from .fleet import FleetSim
        sim = FleetSim(cfg, level=lvl, members=members, mesh=mesh, bc=bc)
    elif mesh is not None:
        from .parallel.mesh import ShardedUniformSim
        sim = ShardedUniformSim(cfg, mesh, level=lvl, bc=bc)
    else:
        from .uniform import UniformSim
        sim = UniformSim(cfg, level=lvl, bc=bc)
    sim.case = "cavity"
    return sim


def build_channel(level: Optional[int] = None, re: float = 200.0,
                  u_in: float = 0.2, diameter: float = 0.1,
                  dtype: str = "float32", profile: str = "uniform",
                  xpos: float = 1.0):
    """Channel past a fixed cylinder: 4x1 domain, impulsive start at
    the inflow velocity, Re = u_in * diameter / nu. Returns a
    Simulation (the obstacle path) — run ``sim.initialize()`` before
    stepping, like any shaped case."""
    import jax.numpy as jnp

    from .models import DiskShape
    from .sim import Simulation

    lvl = 5 if level is None else level
    cfg = SimConfig(bpdx=4, bpdy=1, level_max=1, level_start=0,
                    extent=4.0, dtype=dtype, nu=u_in * diameter / re,
                    lam=1e6, cfl=0.5, max_poisson_iterations=200,
                    poisson_tol=1e-3, poisson_tol_rel=1e-2)
    bc = channel_table(u_in, profile)
    sim = Simulation(
        cfg, shapes=[DiskShape(diameter / 2, xpos, 0.5,
                               prescribed=(0.0, 0.0))],
        level=lvl, bc=bc)
    # impulsive start: the stream fills the domain at t=0 (the standard
    # setup for the literature Strouhal band)
    sim.state = sim.state._replace(
        vel=sim.state.vel.at[0].set(jnp.asarray(u_in, sim.grid.dtype)))
    sim.case = "channel"
    return sim


def build_cylinder(level: Optional[int] = None, D: float = 0.1,
                   U: float = 0.2, nu: float = 5e-4, xpos: float = 3.2,
                   bpdy: int = 1, dtype: str = "float32"):
    """Legacy towed-cylinder case (validation/cylinder.py's _build):
    free-slip box, prescribed (-U, 0) disk towed through still fluid —
    the Galilean twin of ``channel`` in the closed box."""
    from .models import DiskShape
    from .sim import Simulation

    lvl = 5 if level is None else level
    cfg = SimConfig(bpdx=4, bpdy=bpdy, level_max=1, level_start=0,
                    extent=4.0, dtype=dtype, nu=nu, lam=1e6, cfl=0.5,
                    max_poisson_iterations=200, poisson_tol=1e-3,
                    poisson_tol_rel=1e-2)
    sim = Simulation(
        cfg, shapes=[DiskShape(D / 2, xpos, 0.5 * bpdy,
                               prescribed=(-U, 0.0))],
        level=lvl, bc=FREE_SLIP)
    sim.case = "cylinder"
    return sim


CASES: Tuple[CaseSpec, ...] = (
    CaseSpec("cavity",
             "lid-driven cavity (4x no-slip, moving lid), Re=100",
             build_cavity, default_level=4, fleet_ok=True),
    CaseSpec("channel",
             "channel past a fixed cylinder (inflow/outflow), Re=200",
             build_channel, default_level=5),
    CaseSpec("cylinder",
             "towed cylinder in the free-slip box (legacy validation)",
             build_cylinder, default_level=5),
    CaseSpec("tgv_periodic",
             "doubly-periodic Taylor-Green vortex (analytic KE decay)",
             build_tgv_periodic, default_level=4, fleet_ok=True),
    CaseSpec("shear_layer",
             "doubly-periodic double shear layer roll-up (BCG 1989)",
             build_shear_layer, default_level=4, fleet_ok=True),
    CaseSpec("turb2d",
             "seeded decaying 2D turbulence, doubly-periodic",
             build_turb2d, default_level=4, fleet_ok=True),
)

REGISTRY = {c.name: c for c in CASES}


def case_names() -> Tuple[str, ...]:
    return tuple(c.name for c in CASES)


def make_sim(name: str, **kw):
    """Build a named case's driver. Unknown names fail loudly with the
    catalog listing (the CLI's ``-case`` error message)."""
    spec = REGISTRY.get(name)
    if spec is None:
        listing = ", ".join(
            f"{c.name} ({c.describe})" for c in CASES)
        raise ValueError(
            f"unknown case {name!r}; catalog: {listing}")
    return spec.build(**kw)
