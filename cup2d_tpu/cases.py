"""Validation-case catalog (ISSUE 12): named, runnable, serveable
workloads built on the per-face BC engine (bc.py).

Each case bundles the THREE things that define a workload — a
SimConfig, a BCTable and the initial/obstacle state — behind one name,
so the same case runs identically from the CLI (``-case cavity``), the
validation probes (validation/cavity.py, validation/channel.py), tests
and the fleet/serving layer. The registry is plain data + builder
functions: adding a case is one ``CaseSpec`` entry, no solver changes.

Catalog:

``cavity``
    Lid-driven cavity, THE canonical incompressible benchmark the
    free-slip-only box could never express: unit box, four no-slip
    walls, the y_hi lid translating at ``lid_u``. Obstacle-free
    (UniformSim family — also fleet-servable: the table is all-Neumann
    so the slot-pool solvers keep their mean-free contract). Validated
    against the Ghia et al. (1982) Re=100 centerline profiles
    (validation/cavity.py).

``channel``
    Channel flow past a FIXED cylinder: Dirichlet inflow at x_lo,
    convective outflow at x_hi, free-slip side walls, a prescribed-
    (0,0) disk in the stream, the whole domain impulsively started at
    the inflow velocity. The true inflow-outflow configuration the
    towed-cylinder case only approximates Galilean-ly. Validated by
    shedding Strouhal number vs the Williamson (1989) Re=200 band
    (validation/channel.py).

``cylinder``
    The legacy towed-cylinder drag/Strouhal case (free-slip box,
    prescribed (-U, 0) disk) folded into the registry so
    validation/cylinder.py runs through the same ``-case`` path it
    validates.

No environment reads here — cases parameterize through arguments only
(tests/test_env_latch.py walks this package)."""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Optional, Tuple

from .bc import (BCTable, FREE_SLIP, convective_outflow,
                 dirichlet_inflow, free_slip, no_slip)
from .config import SimConfig


@dataclass(frozen=True)
class CaseSpec:
    """One catalog entry: ``build(**kw)`` returns a ready-to-step
    driver with ``sim.case`` set; ``default_level`` is the validation
    resolution (CLI ``-level`` overrides); ``fleet_ok`` marks cases
    whose obstacle-free state can ride the fleet slot pool."""

    name: str
    describe: str
    build: Callable
    default_level: int
    fleet_ok: bool = False


def cavity_table(lid_u: float = 1.0) -> BCTable:
    """Four no-slip walls, the y_hi lid moving at (+lid_u, 0)."""
    return BCTable(no_slip(), no_slip(), no_slip(), no_slip(lid_u, 0.0))


def channel_table(u_in: float, profile: str = "uniform") -> BCTable:
    """Dirichlet inflow at x_lo, convective outflow at x_hi, free-slip
    side walls."""
    return BCTable(dirichlet_inflow(u_in, profile=profile),
                   convective_outflow(), free_slip(), free_slip())


def build_cavity(level: Optional[int] = None, re: float = 100.0,
                 lid_u: float = 1.0, dtype: str = "float32",
                 mesh=None, members: int = 0, cfl: float = 0.4):
    """Lid-driven cavity at Reynolds number ``re`` = lid_u * L / nu on
    the unit box. Obstacle-free: UniformSim, or ShardedUniformSim over
    ``mesh``, or a ``members``-slot FleetSim (every member the same
    table — the pool contract)."""
    lvl = 4 if level is None else level
    cfg = SimConfig(bpdx=1, bpdy=1, level_max=1, level_start=0,
                    extent=1.0, dtype=dtype, nu=lid_u / re, cfl=cfl,
                    poisson_tol=1e-4, poisson_tol_rel=1e-3)
    bc = cavity_table(lid_u)
    if members > 0:
        from .fleet import FleetSim
        sim = FleetSim(cfg, level=lvl, members=members, mesh=mesh, bc=bc)
    elif mesh is not None:
        from .parallel.mesh import ShardedUniformSim
        sim = ShardedUniformSim(cfg, mesh, level=lvl, bc=bc)
    else:
        from .uniform import UniformSim
        sim = UniformSim(cfg, level=lvl, bc=bc)
    sim.case = "cavity"
    return sim


def build_channel(level: Optional[int] = None, re: float = 200.0,
                  u_in: float = 0.2, diameter: float = 0.1,
                  dtype: str = "float32", profile: str = "uniform",
                  xpos: float = 1.0):
    """Channel past a fixed cylinder: 4x1 domain, impulsive start at
    the inflow velocity, Re = u_in * diameter / nu. Returns a
    Simulation (the obstacle path) — run ``sim.initialize()`` before
    stepping, like any shaped case."""
    import jax.numpy as jnp

    from .models import DiskShape
    from .sim import Simulation

    lvl = 5 if level is None else level
    cfg = SimConfig(bpdx=4, bpdy=1, level_max=1, level_start=0,
                    extent=4.0, dtype=dtype, nu=u_in * diameter / re,
                    lam=1e6, cfl=0.5, max_poisson_iterations=200,
                    poisson_tol=1e-3, poisson_tol_rel=1e-2)
    bc = channel_table(u_in, profile)
    sim = Simulation(
        cfg, shapes=[DiskShape(diameter / 2, xpos, 0.5,
                               prescribed=(0.0, 0.0))],
        level=lvl, bc=bc)
    # impulsive start: the stream fills the domain at t=0 (the standard
    # setup for the literature Strouhal band)
    sim.state = sim.state._replace(
        vel=sim.state.vel.at[0].set(jnp.asarray(u_in, sim.grid.dtype)))
    sim.case = "channel"
    return sim


def build_cylinder(level: Optional[int] = None, D: float = 0.1,
                   U: float = 0.2, nu: float = 5e-4, xpos: float = 3.2,
                   bpdy: int = 1, dtype: str = "float32"):
    """Legacy towed-cylinder case (validation/cylinder.py's _build):
    free-slip box, prescribed (-U, 0) disk towed through still fluid —
    the Galilean twin of ``channel`` in the closed box."""
    from .models import DiskShape
    from .sim import Simulation

    lvl = 5 if level is None else level
    cfg = SimConfig(bpdx=4, bpdy=bpdy, level_max=1, level_start=0,
                    extent=4.0, dtype=dtype, nu=nu, lam=1e6, cfl=0.5,
                    max_poisson_iterations=200, poisson_tol=1e-3,
                    poisson_tol_rel=1e-2)
    sim = Simulation(
        cfg, shapes=[DiskShape(D / 2, xpos, 0.5 * bpdy,
                               prescribed=(-U, 0.0))],
        level=lvl, bc=FREE_SLIP)
    sim.case = "cylinder"
    return sim


CASES: Tuple[CaseSpec, ...] = (
    CaseSpec("cavity",
             "lid-driven cavity (4x no-slip, moving lid), Re=100",
             build_cavity, default_level=4, fleet_ok=True),
    CaseSpec("channel",
             "channel past a fixed cylinder (inflow/outflow), Re=200",
             build_channel, default_level=5),
    CaseSpec("cylinder",
             "towed cylinder in the free-slip box (legacy validation)",
             build_cylinder, default_level=5),
)

REGISTRY = {c.name: c for c in CASES}


def case_names() -> Tuple[str, ...]:
    return tuple(c.name for c in CASES)


def make_sim(name: str, **kw):
    """Build a named case's driver. Unknown names fail loudly with the
    catalog listing (the CLI's ``-case`` error message)."""
    spec = REGISTRY.get(name)
    if spec is None:
        listing = ", ".join(
            f"{c.name} ({c.describe})" for c in CASES)
        raise ValueError(
            f"unknown case {name!r}; catalog: {listing}")
    return spec.build(**kw)
